#!/usr/bin/env python
"""Data-flywheel driver: mine captured serving traffic, run replay rounds.

Subcommands:

  mine   scan a ``--capture-dir`` for spilled shards, rank by hardness and
         write a ``mined-<digest>.json`` manifest (atomic tmp+rename).
  loop   run N capture->mine->train rounds; the train command (everything
         after ``--``) gets ``--replay-manifest <path>`` appended each
         round.  Serving replicas pick up the resulting checkpoints via
         ``--watch-checkpoints`` on their own.
  fleet  the distributed loop (ISSUE 17): merge per-member capture
         manifests, fold per-member rankings into one global top-K,
         train, then promote fleet-wide over ``--promote-to`` (the
         fabric router's /admin/reload) gated on the held-out
         eval-shard quality check; rounds repeat until a generation
         promotes, then continue only on score-distribution drift.

Each invocation prints one JSON line so smoke scripts can consume it.
The single-host ``mine``/``loop`` path is untouched by fleet mode.
"""

from __future__ import annotations

import argparse
import json
import sys

from mx_rcnn_tpu import telemetry
from mx_rcnn_tpu.flywheel import FlywheelLoop


def parse_args(argv=None):
    parser = argparse.ArgumentParser(description="Data flywheel driver")
    sub = parser.add_subparsers(dest="cmd", required=True)
    for name in ("mine", "loop", "fleet"):
        p = sub.add_parser(name)
        p.add_argument("--capture-dir", required=True,
                       help="dir the serve engine spills shards into")
        p.add_argument("--top-k", type=int, default=64,
                       help="hardest records kept per manifest")
        p.add_argument("--min-label-score", type=float, default=0.3,
                       help="records need one detection at or above this "
                            "to carry a usable pseudo-label")
        p.add_argument("--out-dir", default=None,
                       help="manifest output dir (default: capture dir)")
        p.add_argument("--telemetry-dir", default=None)
        if name in ("loop", "fleet"):
            p.add_argument("--rounds", type=int, default=1)
        if name == "fleet":
            p.add_argument("--promote-to", default=None,
                           help="fabric router address (host:port) the "
                                "promotion POSTs /admin/reload to")
            p.add_argument("--ckpt-prefix", default=None,
                           help="checkpoint prefix the trainer saves "
                                "under; newest committed save is the "
                                "promotion candidate")
            p.add_argument("--eval-every", type=int, default=4,
                           help="every Nth mined record is held out for "
                                "the promotion gate instead of trained on")
            p.add_argument("--quality-slack", type=float, default=0.0,
                           help="candidate may score this far below the "
                                "incumbent and still promote")
            p.add_argument("--drift-threshold", type=float, default=0.25)
            p.add_argument("--drift-window", type=int, default=64)
        if name in ("loop", "fleet"):
            p.add_argument("train_cmd", nargs=argparse.REMAINDER,
                           help="train command after --; gets "
                                "--replay-manifest appended per round")
    return parser.parse_args(argv)


def main(argv=None):
    args = parse_args(argv)
    if args.telemetry_dir:
        telemetry.configure(args.telemetry_dir, rank=0, world=1)
    train_cmd = None
    if args.cmd in ("loop", "fleet"):
        train_cmd = [t for t in args.train_cmd if t != "--"] or None
    if args.cmd == "fleet":
        from mx_rcnn_tpu.flywheel import FleetFlywheel
        fleet = FleetFlywheel(
            args.capture_dir, top_k=args.top_k,
            min_label_score=args.min_label_score, out_dir=args.out_dir,
            train_cmd=train_cmd, ckpt_prefix=args.ckpt_prefix,
            promote_to=args.promote_to, eval_every=args.eval_every,
            quality_slack=args.quality_slack,
            drift_threshold=args.drift_threshold,
            drift_window=args.drift_window)
        results = fleet.run(args.rounds)
        if args.telemetry_dir:
            telemetry.shutdown()
        last = results[-1]
        print(json.dumps({"cmd": "fleet", "rounds": len(results),
                          "mined": last["mined"],
                          "scanned": last["scanned"],
                          "eval": last.get("eval"),
                          "members": last["members"],
                          "mine_failed": last["mine_failed"],
                          "duplicates_dropped":
                              last.get("duplicates_dropped"),
                          "manifest": last["manifest"],
                          "train_rc": last["train_rc"],
                          "promoted": fleet.promoted_rounds,
                          "drift": last.get("drift")}))
        return 0 if fleet.promoted_rounds else 1
    loop = FlywheelLoop(args.capture_dir, top_k=args.top_k,
                        min_label_score=args.min_label_score,
                        out_dir=args.out_dir, train_cmd=train_cmd)
    if args.cmd == "mine":
        results = [loop.run_round(0)]
    else:
        results = loop.run(args.rounds)
    if args.telemetry_dir:
        telemetry.shutdown()
    last = results[-1]
    print(json.dumps({"cmd": args.cmd, "rounds": len(results),
                      "mined": last["mined"], "scanned": last["scanned"],
                      "manifest": last["manifest"],
                      "train_rc": last["train_rc"]}))
    if any(r["train_rc"] not in (None, 0) for r in results):
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
