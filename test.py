#!/usr/bin/env python
"""Detection evaluation driver (reference ``test.py`` → ``test_rcnn``):
load checkpoint → TestLoader → pred_eval (per-class NMS, max_per_image) →
imdb.evaluate_detections (VOC mAP / COCO AP)."""

from __future__ import annotations

import argparse

from mx_rcnn_tpu.data import TestLoader
from mx_rcnn_tpu.eval import Predictor, pred_eval
from mx_rcnn_tpu.logger import logger
from mx_rcnn_tpu.models import build_model
from mx_rcnn_tpu.tools.common import (add_common_args, config_from_args,
                                      get_imdb, load_eval_params, make_plan)


def parse_args():
    parser = argparse.ArgumentParser(description="Test a Faster R-CNN network")
    add_common_args(parser, train=False)
    parser.add_argument("--batch_images", type=int, default=1)
    parser.add_argument("--dets_cache", default="",
                        help="pickle all_boxes here for tools/reeval.py "
                             "(the reference's detections.pkl)")
    return parser.parse_args()


def test_rcnn(args):
    cfg = config_from_args(args, train=False)
    imdb = get_imdb(args, cfg, test=True)
    roidb = imdb.gt_roidb()
    model = build_model(cfg)
    params = load_eval_params(args, cfg, model)
    # data-parallel eval when >1 device: params replicate, batch rows shard
    # over the mesh (--batch_images stays the per-chip count, like train)
    plan = make_plan(args)
    predictor = Predictor(model, params, cfg, plan=plan)
    bs = args.batch_images * (plan.n_data if plan else 1)
    loader = TestLoader(roidb, cfg, batch_size=bs)
    stats = pred_eval(predictor, loader, imdb, thresh=args.thresh,
                      vis=args.vis, with_masks=cfg.network.HAS_MASK,
                      det_cache=args.dets_cache or None)

    def flat(d, prefix=""):
        out = {}
        for k, v in d.items():
            if isinstance(v, dict):
                out.update(flat(v, prefix + k + "/"))
            elif isinstance(v, (int, float)):
                out[prefix + k] = round(float(v), 4)
        return out

    logger.info("evaluation done: %s", flat(stats))
    return stats


if __name__ == "__main__":
    test_rcnn(parse_args())
