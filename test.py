#!/usr/bin/env python
"""Detection evaluation driver (reference ``test.py`` → ``test_rcnn``):
load checkpoint → TestLoader → pred_eval (per-class NMS, max_per_image) →
imdb.evaluate_detections (VOC mAP / COCO AP)."""

from __future__ import annotations

import argparse

from mx_rcnn_tpu.data import TestLoader
from mx_rcnn_tpu.eval import Predictor, pred_eval
from mx_rcnn_tpu.logger import logger
from mx_rcnn_tpu.models import build_model
from mx_rcnn_tpu.tools.common import (add_common_args, apply_program_cache,
                                      calibrate_from_args, config_from_args,
                                      get_imdb, load_eval_params, make_plan,
                                      start_observability)


def parse_args():
    parser = argparse.ArgumentParser(description="Test a Faster R-CNN network")
    add_common_args(parser, train=False)
    parser.add_argument("--batch_images", type=int, default=0,
                        help="GLOBAL images per eval step (like train's "
                             "flag; must divide by the mesh's data "
                             "dimension).  Default: 1 per data-parallel "
                             "chip.")
    parser.add_argument("--dets_cache", default="",
                        help="pickle all_boxes here for tools/reeval.py "
                             "(the reference's detections.pkl)")
    parser.add_argument("--eval-inflight", type=int, default=None,
                        help="overlapped-eval dispatch window (default "
                             "cfg.tpu.EVAL_INFLIGHT=2); 0 forces the "
                             "serial reference loop")
    parser.add_argument("--eval-host-workers", type=int, default=None,
                        help="host post-process thread-pool width "
                             "(default cfg.tpu.EVAL_HOST_WORKERS=2)")
    parser.add_argument("--prefetch", type=int, default=None,
                        help="TestLoader prefetch depth override "
                             "(default cfg.tpu.PREFETCH)")
    parser.add_argument("--device-postprocess", action="store_true",
                        help="fuse box decode + per-class NMS into the "
                             "forward program and read back only "
                             "max_per_image detections per image (opt-in: "
                             "exact score ties at the cap may resolve "
                             "differently from host NMS)")
    return parser.parse_args()


def test_rcnn(args):
    cfg = config_from_args(args, train=False)
    if args.device_postprocess and cfg.network.HAS_MASK \
            and cfg.TEST.MASK_PASTE == "native":
        # compact readbacks end to end: the same flag that fuses decode+NMS
        # moves mask paste onto the device (ops/mask_paste.py) so mask
        # responses ship packed bitplanes instead of (R, 28, 28) floats.
        # An explicit --cfg TEST__MASK_PASTE override still wins.
        import dataclasses

        cfg = cfg.replace(TEST=dataclasses.replace(cfg.TEST,
                                                   MASK_PASTE="device"))
    apply_program_cache(args)  # before the Predictor builds its registry
    imdb = get_imdb(args, cfg, test=True)
    roidb = imdb.gt_roidb()
    model = build_model(cfg)
    params = load_eval_params(args, cfg, model)
    # data-parallel eval when >1 device: params replicate, batch rows shard
    # over the mesh.  --batch_images is GLOBAL, matching train's flag
    # semantics (train_end2end.py uses it directly as the step batch);
    # defaulting it to n_data keeps the common single-flag invocation at
    # one image per data-parallel chip.
    plan = make_plan(args)
    n_data = plan.n_data if plan else 1
    bs = args.batch_images or n_data
    if bs % n_data:
        raise ValueError(
            f"--batch_images {bs} must divide by the mesh's data dimension "
            f"{n_data} (the flag is GLOBAL images per step, like train)")
    # --calibrate-shard (int8-activation only): scales from the FLOAT
    # params, persisted to the program cache before the variant cast
    act_scales = calibrate_from_args(args, cfg, model, params)
    predictor = Predictor(model, params, cfg, plan=plan,
                          dtype=args.infer_dtype, act_scales=act_scales)
    # eval is single-process (Predictor enforces it), so rank 0 / world 1
    # and the summary always belongs to this process; the plane owns the
    # sink lifecycle (and the /metrics endpoint when --obs-port is set)
    obs = start_observability(args, "test",
                              run_meta={"network": args.network,
                                        "batch_size": bs},
                              configure_telemetry=True)
    try:
        # --device-prep: the loader ships staged raw uint8 + sidecars and
        # the Predictor preps on device in its batch_put hook (mesh plans
        # raise at Predictor construction — host path only there)
        loader = TestLoader(roidb, cfg, batch_size=bs,
                            prefetch=args.prefetch,
                            device_prep=getattr(args, "device_prep", False))
        stats = pred_eval(predictor, loader, imdb, thresh=args.thresh,
                          vis=args.vis, with_masks=cfg.network.HAS_MASK,
                          det_cache=args.dets_cache or None,
                          inflight=args.eval_inflight,
                          host_workers=args.eval_host_workers,
                          device_postprocess=args.device_postprocess)
    finally:
        obs.close()

    def flat(d, prefix=""):
        out = {}
        for k, v in d.items():
            if isinstance(v, dict):
                out.update(flat(v, prefix + k + "/"))
            elif isinstance(v, (int, float)):
                out[prefix + k] = round(float(v), 4)
        return out

    logger.info("evaluation done: %s", flat(stats))
    return stats


if __name__ == "__main__":
    test_rcnn(parse_args())
