"""mx_rcnn_tpu — a TPU-native two-stage detection framework.

A from-scratch JAX/XLA/Pallas rebuild of the capability surface of the
reference MXNet Faster R-CNN framework (cepera-ang/mx-rcnn): Faster R-CNN /
Mask R-CNN with VGG-16 and ResNet-50/101 (+FPN) backbones, end-to-end and
4-step alternate training, PASCAL VOC and COCO datasets.

Design principles (TPU-first, not a port):
  * Everything in the training step is one jitted XLA program — the
    reference's per-step host round-trip (``ProposalTarget`` CustomOp,
    ``rcnn/symbol/proposal_target.py``) is replaced by in-graph, fixed-size
    masked ops driven by ``jax.random`` keys.
  * All ragged quantities (gt boxes, proposals, sampled RoIs, NMS output)
    are statically padded — the reference already proves this contract with
    its fixed post-NMS padding (2000 train / 300 test rows).
  * Data parallelism is a ``jax.sharding.Mesh`` + ``shard_map`` with
    ``lax.psum`` gradient reduction over the ICI axis, replacing
    ``KVStore('device')``.
  * Hot non-matmul ops (bitmask NMS, ROIAlign) have Pallas TPU kernels with
    pure-JAX fallbacks that share a signature and serve as test oracles.

Layer map (mirrors SURVEY.md §1 bottom-to-top):
  ops/      — numeric core: anchors, box codecs, IoU, NMS, target assignment
  kernels/  — Pallas TPU kernels for the hot ops
  models/   — flax backbones + heads + full detector graphs
  data/     — host-side dataset layer (VOC/COCO), static-shape batching
  train/    — losses, jitted train step, schedules, metrics, checkpoints
  eval/     — im_detect / pred_eval, VOC AP, in-repo COCO eval
  parallel/ — mesh construction and sharding rules
  utils/    — checkpoint load/save/combine helpers
  native/   — C++ CPU extension tier (RLE masks, host batch assembly)
"""

__version__ = "0.1.0"
