"""Stage tool: RPN proposal generation/eval (reference
``rcnn/tools/test_rpn.py`` — alternate-training steps 2 and 5): run the
RPN-only test graph over the roidb and cache per-image proposals."""

from __future__ import annotations

import argparse
import os

from mx_rcnn_tpu.data import TestLoader
from mx_rcnn_tpu.eval import Predictor, generate_proposals
from mx_rcnn_tpu.logger import logger
from mx_rcnn_tpu.models import build_model
from mx_rcnn_tpu.tools.common import (add_common_args, config_from_args,
                                      get_imdb, load_eval_params)


def test_rpn(args, cfg=None, params=None, imdb=None, roidb=None):
    cfg = cfg or config_from_args(args, train=False)
    if imdb is None:
        imdb = get_imdb(args, cfg)
    if roidb is None:
        roidb = imdb.gt_roidb()
    model = build_model(cfg)
    if params is None:
        params = load_eval_params(args, cfg, model)
    predictor = Predictor(model, params, cfg)
    loader = TestLoader(roidb, cfg, batch_size=1)
    cache = os.path.join(imdb.cache_path, f"{imdb.name}_rpn_proposals.pkl")
    roidb = generate_proposals(predictor, loader, imdb, roidb,
                               cache_path=cache)
    n = sum(len(r.get("proposals", ())) for r in roidb)
    logger.info("test_rpn: %d proposals over %d images", n, len(roidb))
    return roidb


def parse_args():
    parser = argparse.ArgumentParser(description="Generate RPN proposals")
    add_common_args(parser, train=False)
    return parser.parse_args()


if __name__ == "__main__":
    test_rpn(parse_args())
