"""Stage tool: Fast-RCNN training on cached proposals (reference
``rcnn/tools/train_rcnn.py`` — alternate-training steps 3 and 6): ROIIter
ships proposals; sampling happens in-graph (``FasterRCNN.rcnn_train``)."""

from __future__ import annotations

import argparse

from mx_rcnn_tpu.data import ROIIter
from mx_rcnn_tpu.logger import logger
from mx_rcnn_tpu.models import build_model
from mx_rcnn_tpu.tools.common import (CappedLoader, add_common_args,
                                      check_dist_loader, config_from_args,
                                      get_imdb, get_train_roidb,
                                      init_or_load_params, setup_parallel)
from mx_rcnn_tpu.train import ResilienceOptions, fit


def train_rcnn(args, cfg=None, params=None, roidb=None, frozen_shared=False):
    plan, pidx, pcount = setup_parallel(args)
    cfg = cfg or config_from_args(args, train=True)
    n_dev = plan.n_data if plan else 1
    batch_size = (getattr(args, "batch_images", None)
                  or n_dev * cfg.TRAIN.BATCH_IMAGES)
    if plan and batch_size % n_dev:
        raise ValueError(f"batch_images {batch_size} not divisible by "
                         f"mesh size {n_dev}")
    if roidb is None:
        imdb = get_imdb(args, cfg)
        source = getattr(args, "proposals", "")
        base = None
        if source == "selective_search":
            # legacy Fast-RCNN input (reference selective_search_roidb)
            if not hasattr(imdb, "selective_search_roidb"):
                raise ValueError(
                    f"--proposals selective_search is a PascalVOC input; "
                    f"{type(imdb).__name__} has no selective-search data")
            base = imdb.selective_search_roidb()
        elif source:  # a test_rpn .pkl cache path (aligned with gt_roidb)
            from mx_rcnn_tpu.utils.load_data import load_proposals

            base = load_proposals(imdb.gt_roidb(), source)
        # attach-then-flip: get_train_roidb mirrors the proposals key
        roidb = get_train_roidb(imdb, cfg, roidb=base)
    if not any("proposals" in r for r in roidb):
        raise ValueError("roidb has no cached proposals — run test_rpn, or "
                         "pass --proposals {selective_search|<cache.pkl>}")
    loader = ROIIter(roidb, cfg, batch_size, shuffle=cfg.TRAIN.SHUFFLE,
                     num_parts=pcount, part_index=pidx)
    check_dist_loader(plan, batch_size, pcount, pidx)
    if getattr(args, "num_steps", 0):
        loader = CappedLoader(loader, args.num_steps)
    model = build_model(cfg)
    if params is None:
        params = init_or_load_params(args, cfg, model, batch_size)
    fixed = (cfg.network.FIXED_PARAMS_SHARED if frozen_shared
             else cfg.network.FIXED_PARAMS)
    logger.info("train_rcnn: %d images, frozen=%s", len(roidb), fixed)
    state = fit(cfg, model, params, loader,
                begin_epoch=args.begin_epoch, end_epoch=args.end_epoch,
                plan=plan, prefix=getattr(args, "prefix", None), graph="rcnn",
                seed=getattr(args, "seed", 0),
                frequent=args.frequent, fixed_prefixes=fixed,
                telemetry_dir=getattr(args, "telemetry_dir", "") or None,
                steps_per_dispatch=getattr(args, "steps_per_dispatch", 1),
                resilience=ResilienceOptions.from_args(args))
    return state


def parse_args():
    parser = argparse.ArgumentParser(description="Train Fast R-CNN on proposals")
    add_common_args(parser, train=True)
    parser.add_argument("--proposals", default="",
                        help="proposal source: 'selective_search' (loads "
                             "root_path/selective_search_data/*.mat, the "
                             "legacy Fast-RCNN input) or a test_rpn .pkl "
                             "cache path; default expects proposals already "
                             "in the roidb")
    return parser.parse_args()


if __name__ == "__main__":
    train_rcnn(parse_args())
