"""Stage tools + shared CLI plumbing (reference ``rcnn/tools/`` —
train_rpn / test_rpn / train_rcnn / reeval — and the argv surface shared by
the root drivers train_end2end.py / test.py / demo.py)."""
