"""Stage tool: re-score cached detections (reference
``rcnn/tools/reeval.py``): load a pickled ``all_boxes`` and run
``imdb.evaluate_detections`` again — no model, no device."""

from __future__ import annotations

import argparse
import pickle

from mx_rcnn_tpu.logger import logger
from mx_rcnn_tpu.tools.common import add_common_args, config_from_args, get_imdb


def reeval(args):
    cfg = config_from_args(args, train=False)
    imdb = get_imdb(args, cfg, test=True)
    with open(args.detections, "rb") as f:
        all_boxes = pickle.load(f)
    stats = imdb.evaluate_detections(all_boxes)
    logger.info("reeval: %s", {k: round(float(v), 4) for k, v in stats.items()
                               if isinstance(v, (int, float))})
    return stats


def parse_args():
    parser = argparse.ArgumentParser(description="Re-evaluate cached detections")
    add_common_args(parser, train=False)
    parser.add_argument("--detections", required=True,
                        help="pickled all_boxes path")
    return parser.parse_args()


if __name__ == "__main__":
    reeval(parse_args())
