"""Shared driver plumbing: dataset construction, param init/loading, mesh
setup — the glue the reference spreads across ``train_end2end.py:train_net``
and ``rcnn/tools/*`` (load_param, generate_config calls, ctx parsing).
"""

from __future__ import annotations

import argparse
import dataclasses
import os
from typing import Optional, Tuple

import jax
import numpy as np

from mx_rcnn_tpu.config import Config, generate_config, list_datasets, list_networks
from mx_rcnn_tpu.data import SyntheticDataset
from mx_rcnn_tpu.data.pascal_voc import PascalVOC
from mx_rcnn_tpu.logger import logger
from mx_rcnn_tpu.models import build_model, init_params
from mx_rcnn_tpu.parallel import MeshPlan, make_mesh
from mx_rcnn_tpu.train.checkpoint import load_params_npz
from mx_rcnn_tpu.train.resilience import (add_resilience_args,
                                          inject_roidb_faults)


def add_common_args(parser: argparse.ArgumentParser, train: bool = True):
    """The reference's shared argparse surface (names kept; GPU-specific
    flags get TPU equivalents)."""
    parser.add_argument("--network", default="resnet101", choices=list_networks())
    parser.add_argument("--dataset", default="PascalVOC", choices=list_datasets())
    parser.add_argument("--image_set", default=None,
                        help="override the preset image set")
    parser.add_argument("--root_path", default="data")
    parser.add_argument("--dataset_path", default=None)
    parser.add_argument("--prefix", default="model/e2e",
                        help="checkpoint prefix (directory for orbax)")
    # TPU equivalents of --gpus/--ctx: how many mesh devices to use
    parser.add_argument("--devices", type=int, default=0,
                        help="data-mesh size; 0 = all visible devices")
    # zero-data-on-disk mode (no reference counterpart)
    parser.add_argument("--synthetic", action="store_true",
                        help="use the synthetic dataset (no files needed)")
    parser.add_argument("--synthetic_images", type=int, default=64)
    parser.add_argument("--cfg", action="append", default=[],
                        metavar="PATH=VALUE",
                        help="config override, repeatable; double-underscore "
                             "paths into the tree with python-literal values "
                             "(e.g. --cfg tpu__SCALES='((64,96),)' "
                             "--cfg TRAIN__BATCH_ROIS=32)")
    parser.add_argument("--loader-workers", type=int, default=None,
                        dest="loader_workers", metavar="N",
                        help="host input-pipeline worker processes "
                             "(data/workers.py): N > 0 fans decode/resize/"
                             "flip over N processes with shared-memory "
                             "handover, batches bit-identical to the "
                             "default serial producer (0)")
    parser.add_argument("--telemetry-dir", default="", dest="telemetry_dir",
                        help="stream structured run telemetry here (JSONL "
                             "events + summary JSON; per-rank files on "
                             "multi-host, summary from process 0 only — "
                             "fold with scripts/telemetry_report.py)")
    parser.add_argument("--obs-port", type=int, default=0, dest="obs_port",
                        metavar="PORT",
                        help="live observability endpoint: rank 0 serves "
                             "GET /metrics (Prometheus text, all ranks "
                             "folded from --telemetry-dir snapshots) and "
                             "/healthz on 127.0.0.1:PORT while the run is "
                             "alive (telemetry/obs.py; 0 = off, no "
                             "network bind)")
    if train:
        # multi-host (the reference's unscripted KVStore('dist_sync') tier,
        # scripted here — parallel/distributed.py): every process runs the
        # same command with its own --dist-process-id; --dist-auto on TPU
        # pods.  Train-only: eval drivers reject these at argparse level
        # (multi-process eval is not supported — run it single-process).
        parser.add_argument("--dist-auto", action="store_true",
                            help="join a TPU-pod distributed runtime "
                                 "(topology auto-detected)")
        parser.add_argument("--dist-coordinator", default=None,
                            metavar="HOST:PORT",
                            help="distributed coordinator address "
                                 "(non-pod multi-host)")
        parser.add_argument("--dist-num-processes", type=int, default=None)
        parser.add_argument("--dist-process-id", type=int, default=None)
        parser.add_argument("--pretrained", default="",
                            help=".npz backbone/params path (converted)")
        parser.add_argument("--pretrained_epoch", type=int, default=0)
        parser.add_argument("--begin_epoch", type=int, default=0)
        parser.add_argument("--end_epoch", type=int, default=10)
        parser.add_argument("--lr", type=float, default=None)
        parser.add_argument("--lr_step", default=None,
                            help="comma-separated epochs, e.g. '7'")
        parser.add_argument("--frequent", type=int, default=20)
        parser.add_argument("--no_flip", action="store_true")
        parser.add_argument("--no_shuffle", action="store_true")
        parser.add_argument("--resume", action="store_true")
        parser.add_argument("--batch_images", type=int, default=None,
                            help="GLOBAL images per step (default: 1 per device)")
        parser.add_argument("--seed", type=int, default=0,
                            help="train RNG seed (sampling streams + "
                                 "dropout); loader shuffle uses its own")
        parser.add_argument("--num-steps", type=int, default=0, dest="num_steps",
                            help="cap steps per epoch (smoke runs)")
        parser.add_argument("--steps-per-dispatch", type=int, default=1,
                            help="train steps per dispatched program "
                                 "(lax.scan grouping; >1 amortizes dispatch "
                                 "overhead and lets XLA compile the step as "
                                 "a loop body — see train/trainer.py fit "
                                 "docstring; applies to every fit-based "
                                 "driver, alternate stages included)")
        parser.add_argument("--prefetch", type=int, default=None,
                            metavar="DEPTH",
                            help="host→device prefetch queue depth "
                                 "(tpu.PREFETCH; default from config)")
        parser.add_argument("--device-prep", action="store_true",
                            dest="device_prep",
                            help="run the per-sample resize/flip/normalize/"
                                 "pad hot path on device as a jitted "
                                 "program (data/device_prep.py; default "
                                 "off = host numpy path, bit-identical to "
                                 "previous releases)")
        parser.add_argument("--tuned-pipeline", action="store_true",
                            dest="tuned_pipeline",
                            help="boot into the input-pipeline cell "
                                 "persisted by `bench.py --mode pipeline "
                                 "--auto-tune` (k steps/dispatch, loader "
                                 "workers, prefetch depth, device-prep); "
                                 "explicit flags win per field")
        # data flywheel replay (ISSUE 13): mix mined serving captures
        # into the epoch plan (data/replay.py); the mix is drawn from the
        # loader's plan RNG, so --auto-resume reproduces it bit-for-bit
        parser.add_argument("--replay-manifest", default="",
                            dest="replay_manifest",
                            help="mined-<digest>.json manifest from "
                                 "flywheel.py mine; enables replay mixing")
        parser.add_argument("--replay-ratio", type=float, default=0.25,
                            dest="replay_ratio",
                            help="fraction of each batch's slots "
                                 "substituted with replay records "
                                 "(in [0, 1); only with --replay-manifest)")
        parser.add_argument("--replay-thresh", type=float, default=0.5,
                            dest="replay_thresh",
                            help="min served detection score kept as a "
                                 "replay pseudo-label")
        # fault tolerance (train/resilience.py): --save-every-n-steps,
        # --auto-resume, --nan-policy on every fit-based driver
        add_resilience_args(parser)
    else:
        parser.add_argument("--epoch", type=int, default=10,
                            help="checkpoint epoch to load")
        parser.add_argument("--vis", action="store_true")
        parser.add_argument("--thresh", type=float, default=1e-3)
        parser.add_argument("--infer-dtype", default="float32",
                            dest="infer_dtype",
                            choices=["float32", "bfloat16", "int8",
                                     "int8-activation"],
                            help="inference variant: float32 (exact), "
                                 "bfloat16 (params cast, outputs back to "
                                 "f32 — tolerance-pinned parity vs f32), "
                                 "int8 (symmetric weight quantization), "
                                 "or int8-activation (weights int8 AND "
                                 "network-input activations fake-quantized"
                                 " against scales calibrated with "
                                 "--calibrate-shard).  Each dtype gets its"
                                 " own program-registry key space and "
                                 "persistent-cache dir")
        parser.add_argument("--calibrate-shard", type=int, default=0,
                            dest="calibrate_shard", metavar="N",
                            help="int8-activation calibration: run the "
                                 "FLOAT model over N held-out images "
                                 "(tail of the eval set; deterministic "
                                 "noise under --synthetic), record per-"
                                 "tensor activation absmax scales, and "
                                 "persist them next to the AOT marker "
                                 "manifest in the program cache (0 = use "
                                 "previously persisted scales, or degrade "
                                 "to weight-only int8)")
        parser.add_argument("--device-prep", action="store_true",
                            dest="device_prep",
                            help="run eval preprocessing (resize/"
                                 "normalize/pad) on device as a jitted "
                                 "program — the loader ships staged raw "
                                 "uint8 and the Predictor preps it in the "
                                 "prefetch-thread transfer hook (same "
                                 "host-bilinear parity pin as train; "
                                 "single-mesh only — mesh plans raise)")
        parser.add_argument("--program-cache", default="",
                            dest="program_cache", metavar="DIR",
                            help="persistent compiled-program cache base "
                                 "dir (same as the MXR_PROGRAM_CACHE env "
                                 "var): a second boot over a warm dir "
                                 "loads its XLA programs from disk "
                                 "instead of recompiling (machine-, "
                                 "jax-version- and dtype-keyed subdirs; "
                                 "see README 'Program registry')")
    return parser


def apply_program_cache(args) -> None:
    """Fold ``--program-cache`` into the ``MXR_PROGRAM_CACHE`` env var
    (the single knob the :class:`ProgramRegistry` reads) before any
    Predictor/registry is built.  The flag wins over an inherited env."""
    import os

    if getattr(args, "program_cache", ""):
        os.environ["MXR_PROGRAM_CACHE"] = args.program_cache


def parse_cfg_overrides(items) -> dict:
    """``--cfg PATH=VALUE`` (python-literal) → overrides dict.  Shared by
    the CLI drivers, bench.py and scripts/profile_step.py so the syntax
    and error messages stay identical everywhere."""
    import ast

    overrides = {}
    for item in items or []:
        key, _, val = item.partition("=")
        if not _:
            raise ValueError(f"--cfg expects PATH=VALUE, got '{item}'")
        try:
            overrides[key] = ast.literal_eval(val)
        except (ValueError, SyntaxError) as e:
            raise ValueError(
                f"--cfg {key}: value {val!r} is not a python literal "
                f"(strings need quotes, e.g. --cfg dataset__IMAGE_SET="
                f"'\"2007_trainval\"'): {e}") from None
    return overrides


def config_from_args(args, train: bool = True) -> Config:
    overrides = parse_cfg_overrides(getattr(args, "cfg", []))
    if getattr(args, "loader_workers", None) is not None:
        overrides["tpu__LOADER_WORKERS"] = int(args.loader_workers)
    if getattr(args, "prefetch", None) is not None:
        overrides["tpu__PREFETCH"] = int(args.prefetch)
    if getattr(args, "device_prep", False):
        overrides["tpu__DEVICE_PREP"] = True
    if train:
        if args.lr is not None:
            overrides["TRAIN__LR"] = args.lr
        if args.lr_step is not None:
            overrides["TRAIN__LR_STEP"] = tuple(
                int(e) for e in str(args.lr_step).split(","))
        if getattr(args, "no_flip", False):
            overrides["TRAIN__FLIP"] = False
        if getattr(args, "no_shuffle", False):
            overrides["TRAIN__SHUFFLE"] = False
    cfg = generate_config(args.network, args.dataset, **overrides)
    if args.image_set:
        # train drivers read IMAGE_SET; test-mode drivers (test.py, reeval,
        # demo) read TEST_IMAGE_SET via get_imdb(test=True) — the override
        # must land on the field the driver actually consumes
        field = "IMAGE_SET" if train else "TEST_IMAGE_SET"
        cfg = cfg.replace(dataset=dataclasses.replace(
            cfg.dataset, **{field: args.image_set}))
    if args.dataset_path:
        cfg = cfg.replace(dataset=dataclasses.replace(
            cfg.dataset, DATASET_PATH=args.dataset_path))
    if args.synthetic:
        # from-scratch-friendly: normalize pixel scale (pretrained weights
        # absorb it in the reference contract; random init cannot)
        cfg = cfg.replace(network=dataclasses.replace(
            cfg.network, PIXEL_STDS=(127.0, 127.0, 127.0)))
    if train and getattr(args, "tuned_pipeline", False):
        # boot into the persisted tuned pipeline cell (bench.py --mode
        # pipeline --auto-tune).  Looked up AFTER every other override is
        # applied — the tuned key is a tuned-field-normalized digest of
        # exactly this config.
        from mx_rcnn_tpu.train.pipeline import apply_tuned_to_args

        cfg = apply_tuned_to_args(args, cfg)
    return cfg


def strip_device_prep_for_mesh(cfg: Config, plan) -> Config:
    """Device-side preprocessing is single-mesh only for now (the prep
    output would need the plan's input sharding) — drivers downgrade to
    the host path with a warning instead of fit raising mid-boot."""
    if plan is not None and getattr(cfg.tpu, "DEVICE_PREP", False):
        logger.warning("--device-prep is not supported under a mesh plan "
                       "yet — using the host preprocessing path")
        cfg = cfg.replace(tpu=dataclasses.replace(cfg.tpu,
                                                  DEVICE_PREP=False))
    return cfg


def get_imdb(args, cfg: Config, test: bool = False):
    """Dataset factory (reference: the imdb dispatch in train/test drivers)."""
    if args.synthetic:
        s = cfg.tpu.SCALES[0]
        return SyntheticDataset(num_images=args.synthetic_images,
                                num_classes=cfg.NUM_CLASSES,
                                height=s[0], width=s[1])
    name = cfg.dataset.DATASET
    image_set = cfg.dataset.TEST_IMAGE_SET if test else cfg.dataset.IMAGE_SET
    if name == "PascalVOC":
        return PascalVOC(image_set, args.root_path, cfg.dataset.DATASET_PATH)
    if name == "coco":
        from mx_rcnn_tpu.data.coco_dataset import COCODataset

        return COCODataset(image_set, args.root_path, cfg.dataset.DATASET_PATH)
    raise KeyError(name)


def get_train_roidb(imdb, cfg: Config, roidb=None):
    """gt (or a pre-built ``roidb``, e.g. with proposals attached) → flip →
    filter.  Proposal attachment must happen BEFORE this: flipping mirrors
    the ``proposals`` key."""
    if roidb is None:
        roidb = imdb.gt_roidb()
    if cfg.TRAIN.FLIP:
        roidb = imdb.append_flipped_images(roidb)
    # env-driven fault injection (MXR_FAULT_BAD_RECORD; no-op when unset)
    # AFTER filtering: the corrupted record must survive into the epoch
    # plan for script/fault_smoke.sh to exercise the loader's isolation
    return inject_roidb_faults(imdb.filter_roidb(roidb))


def replay_from_args(args, cfg: Config):
    """``--replay-manifest`` → (replay_roidb, replay_ratio) loader kwargs.

    Returns ``(None, 0.0)`` when replay is off or the manifest mined
    nothing usable (an empty round must not fail the training run)."""
    manifest = getattr(args, "replay_manifest", "")
    if not manifest:
        return None, 0.0
    from mx_rcnn_tpu.data.replay import ReplayDataset

    ds = ReplayDataset(manifest, cfg.NUM_CLASSES,
                       min_score=getattr(args, "replay_thresh", 0.5))
    roidb = ds.gt_roidb()
    if not roidb:
        logger.warning("replay manifest %s yielded no usable records "
                       "(all pseudo-labels below --replay-thresh?) — "
                       "training without replay", manifest)
        return None, 0.0
    logger.info("replay: mixing %d mined record(s) from %s at ratio %.2f",
                len(roidb), manifest, args.replay_ratio)
    return roidb, float(args.replay_ratio)


def init_dist_from_args(args) -> tuple:
    """``--dist-*`` → ``init_distributed``; returns (process_index,
    process_count).  Must run before anything queries devices."""
    from mx_rcnn_tpu.parallel import init_distributed

    return init_distributed(
        coordinator_address=getattr(args, "dist_coordinator", None),
        num_processes=getattr(args, "dist_num_processes", None),
        process_id=getattr(args, "dist_process_id", None),
        auto=getattr(args, "dist_auto", False))


def make_plan(args) -> Optional[MeshPlan]:
    n = args.devices if args.devices > 0 else len(jax.devices())
    if n <= 1:
        return None
    return make_mesh(jax.devices()[:n], data=n)


def setup_parallel(args):
    """Distributed rendezvous (``--dist-*``) THEN mesh plan — in that
    order, since the plan must see the global topology.  Returns
    ``(plan, process_index, process_count)``; every train driver that
    supports multi-host goes through here so the flags can never be
    silently ignored."""
    pidx, pcount = init_dist_from_args(args)
    plan = make_plan(args)
    if pcount > 1 and plan is None:
        raise ValueError(
            "multi-process run resolved to a single-device plan; pass "
            "--devices covering every host's devices (or 0 for all)")
    return plan, pidx, pcount


def start_observability(args, driver: str, rank: int = 0, world: int = 1,
                        run_meta: Optional[dict] = None,
                        configure_telemetry: bool = False):
    """Build the driver's :class:`~mx_rcnn_tpu.telemetry.obs.ObsPlane`
    from the common flags.  Inert (zero binds, zero threads, NULL
    telemetry untouched) unless ``--obs-port`` is set — or
    ``configure_telemetry=True`` and ``--telemetry-dir`` is set, for
    drivers whose sink isn't owned by ``fit`` (test/serve/bench): the
    plane then also owns configure/summary/shutdown.  Call ``close()``
    (ideally in a finally) when the run ends."""
    from mx_rcnn_tpu.telemetry.obs import ObsPlane

    meta = {"driver": driver, **(run_meta or {})}
    return ObsPlane(port=getattr(args, "obs_port", 0) or 0,
                    telemetry_dir=getattr(args, "telemetry_dir", "") or "",
                    rank=rank, world=world, run_meta=meta,
                    configure_telemetry=configure_telemetry)


def check_dist_loader(plan, batch_size: int, pcount: int, pidx: int) -> None:
    """Multi-host loader sanity: the contiguous ``num_parts`` slice must be
    the rows this process's mesh shards hold (no-op single-process)."""
    if pcount > 1:
        from mx_rcnn_tpu.parallel import assert_loader_partition

        assert_loader_partition(plan, batch_size, pcount, pidx)


def init_or_load_params(args, cfg: Config, model, batch_size: int,
                        key=None):
    """Random-init params, then overlay pretrained weights if given
    (reference load_param + Normal-init of new heads in train_net)."""
    if key is None:
        key = jax.random.PRNGKey(0)
    del batch_size  # init shapes don't depend on it
    params = init_params(model, cfg, key, batch_size=1)
    if args.pretrained:
        path = args.pretrained
        if not os.path.exists(path):
            raise FileNotFoundError(path)
        loaded = load_params_npz(path)
        params = _overlay(params, loaded)
        logger.info("loaded pretrained params from %s", path)
    return params


def _overlay(params, loaded):
    """Copy leaves from ``loaded`` into ``params`` where paths+shapes match
    (partial restore: backbone-only checkpoints leave heads at init)."""
    import jax.numpy as jnp

    def walk(dst, src, path=""):
        out = {}
        for k, v in dst.items():
            if isinstance(v, dict):
                out[k] = walk(v, src.get(k, {}), path + k + "/")
            elif k in src and np.shape(src[k]) == np.shape(v):
                out[k] = jnp.asarray(src[k])
            else:
                if k in src:
                    logger.warning("shape mismatch at %s%s: %s vs %s — kept init",
                                   path, k, np.shape(src[k]), np.shape(v))
                out[k] = v
        return out

    return walk(params, loaded)


class CappedLoader:
    """Wraps a loader to at most ``n`` steps per epoch (smoke runs).

    Forwards the resilience fast-forward API (``advance_epochs`` /
    ``skip_next``) so ``--num-steps`` smoke runs still auto-resume: a
    skip of ``m`` consumed batches shrinks THIS wrapper's next epoch to
    ``n - m`` yields, keeping the epoch end at the same global position
    the uninterrupted capped run would have reached."""

    def __init__(self, inner, n: int):
        self._inner = inner
        self._n = n
        self._skip = 0
        self.batch_size = inner.batch_size

    @property
    def steps_per_epoch(self) -> int:
        return min(self._n, self._inner.steps_per_epoch)

    def __len__(self):
        return self.steps_per_epoch

    def advance_epochs(self, n: int) -> None:
        self._inner.advance_epochs(n)

    def skip_next(self, m: int) -> None:
        self._inner.skip_next(m)
        self._skip = m

    # fit() owns the loader put/wrap hooks; proxy them to the wrapped
    # loader so a capped run keeps producer-thread transfer/group
    # assembly (k>1 dispatch groups and device-prep both ride these) —
    # without the proxy fit would fall back to synchronous consumer-side
    # handling for every --num-steps run.
    @property
    def put(self):
        return getattr(self._inner, "put", None)

    @put.setter
    def put(self, v):
        self._inner.put = v

    @property
    def wrap(self):
        return getattr(self._inner, "wrap", None)

    @wrap.setter
    def wrap(self, v):
        self._inner.wrap = v

    def __iter__(self):
        skip, self._skip = self._skip, 0
        budget = max(self.steps_per_epoch - skip, 0)
        it = iter(self._inner)
        used = 0
        for batch in it:
            if used >= budget:
                close = getattr(it, "close", None)
                if close:
                    close()
                break
            # a group-wrap item ("group", n, data) advances the step
            # budget by n — --num-steps counts steps, not dispatches
            used += (batch[1] if isinstance(batch, tuple)
                     and len(batch) == 3 else 1)
            yield batch


def load_eval_params(args, cfg: Config, model):
    """Load a saved checkpoint for inference (de-normalized params — see
    train/checkpoint.py contract)."""
    from mx_rcnn_tpu.train.checkpoint import CheckpointManager

    mgr = CheckpointManager(args.prefix)
    params, _, _ = mgr.load_epoch(args.epoch, cfg, for_training=False)
    return params


def eval_params_from_args(args, cfg: Config, model):
    """Inference params for drivers that also run checkpoint-free
    (serve.py smoke/CI): under ``--synthetic`` random-init params pushed
    through the same de-normalize-at-save fold a real checkpoint carries
    (the bench ``build_infer`` recipe — plumbing and layouts are real,
    detections are noise); otherwise the checkpoint at
    ``--prefix``/``--epoch``."""
    if getattr(args, "synthetic", False):
        from mx_rcnn_tpu.train.checkpoint import denormalize_for_save

        params = init_params(model, cfg, jax.random.PRNGKey(0), batch_size=1)
        return denormalize_for_save(params, cfg)
    return load_eval_params(args, cfg, model)


def _calibration_images(args, cfg: Config, n: int) -> list:
    """Raw uint8 HWC images for the activation-calibration shard: the
    TAIL of the eval image set (held out from nothing the calibration
    could overfit — scales are absmax statistics, not weights), or
    deterministic noise frames under ``--synthetic``."""
    if getattr(args, "synthetic", False):
        rng = np.random.RandomState(0)
        h, w = cfg.tpu.SCALES[0]
        return [rng.randint(0, 256, size=(h, w, 3), dtype=np.uint8)
                for _ in range(n)]
    import cv2

    imdb = get_imdb(args, cfg, test=True)
    roidb = imdb.gt_roidb()
    imgs = []
    for rec in roidb[-n:]:
        im = (rec["image_array"] if "image_array" in rec
              else cv2.imread(rec["image"], cv2.IMREAD_COLOR))
        if im is not None:
            imgs.append(np.ascontiguousarray(im))
    return imgs


def calibrate_from_args(args, cfg: Config, model, params):
    """``--calibrate-shard N`` under ``--infer-dtype int8-activation``:
    run the calibration pass (``eval.tester.calibrate_activation_scales``)
    over the held-out shard, persist the per-tensor scales next to the
    AOT marker manifest (``ProgramRegistry.save_act_scales``), and return
    them for the Predictor.  Returns ``None`` when calibration is not
    requested — the Predictor then auto-loads persisted scales for the
    same config digest, or degrades to weight-only int8 with a warning."""
    n = int(getattr(args, "calibrate_shard", 0) or 0)
    if getattr(args, "infer_dtype", "float32") != "int8-activation":
        if n > 0:
            logger.warning("--calibrate-shard only applies to "
                           "--infer-dtype int8-activation — ignored")
        return None
    if n <= 0:
        return None
    from mx_rcnn_tpu.compile import ProgramRegistry
    from mx_rcnn_tpu.eval.tester import calibrate_activation_scales

    tensors = calibrate_activation_scales(
        model, params, cfg, _calibration_images(args, cfg, n), max_images=n)
    path = ProgramRegistry(cfg, dtype="int8-activation").save_act_scales(
        tensors)
    if path:
        logger.info("persisted %d activation scale(s) to %s",
                    len(tensors), path)
    else:
        logger.warning("no program cache configured (--program-cache / "
                       "MXR_PROGRAM_CACHE) — calibrated scales apply to "
                       "this process only")
    return tensors
