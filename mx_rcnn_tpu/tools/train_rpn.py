"""Stage tool: RPN-only training (reference ``rcnn/tools/train_rpn.py`` —
alternate-training steps 1 and 4).  Same loader as end2end; the graph is
``FasterRCNN.rpn_train`` (backbone + RPN heads + RPN losses only)."""

from __future__ import annotations

import argparse

from mx_rcnn_tpu.data import AnchorLoader
from mx_rcnn_tpu.logger import logger
from mx_rcnn_tpu.models import build_model
from mx_rcnn_tpu.tools.common import (CappedLoader, add_common_args,
                                      check_dist_loader, config_from_args,
                                      get_imdb, get_train_roidb,
                                      init_or_load_params, setup_parallel)
from mx_rcnn_tpu.train import ResilienceOptions, fit


def train_rpn(args, cfg=None, params=None, roidb=None, frozen_shared=False):
    """Callable both as a CLI stage and from train_alternate (which passes
    params of the previous stage and frozen_shared=True for round 2)."""
    plan, pidx, pcount = setup_parallel(args)
    cfg = cfg or config_from_args(args, train=True)
    n_dev = plan.n_data if plan else 1
    batch_size = (getattr(args, "batch_images", None)
                  or n_dev * cfg.TRAIN.BATCH_IMAGES)
    if plan and batch_size % n_dev:
        raise ValueError(f"batch_images {batch_size} not divisible by "
                         f"mesh size {n_dev}")
    if roidb is None:
        imdb = get_imdb(args, cfg)
        roidb = get_train_roidb(imdb, cfg)
    loader = AnchorLoader(roidb, cfg, batch_size, shuffle=cfg.TRAIN.SHUFFLE,
                          num_parts=pcount, part_index=pidx)
    check_dist_loader(plan, batch_size, pcount, pidx)
    if getattr(args, "num_steps", 0):
        loader = CappedLoader(loader, args.num_steps)
    model = build_model(cfg)
    if params is None:
        params = init_or_load_params(args, cfg, model, batch_size)
    fixed = (cfg.network.FIXED_PARAMS_SHARED if frozen_shared
             else cfg.network.FIXED_PARAMS)
    logger.info("train_rpn: %d images, frozen=%s", len(roidb), fixed)
    state = fit(cfg, model, params, loader,
                begin_epoch=args.begin_epoch, end_epoch=args.end_epoch,
                plan=plan, prefix=getattr(args, "prefix", None), graph="rpn",
                seed=getattr(args, "seed", 0),
                frequent=args.frequent, fixed_prefixes=fixed,
                telemetry_dir=getattr(args, "telemetry_dir", "") or None,
                steps_per_dispatch=getattr(args, "steps_per_dispatch", 1),
                resilience=ResilienceOptions.from_args(args))
    return state


def parse_args():
    parser = argparse.ArgumentParser(description="Train RPN")
    add_common_args(parser, train=True)
    return parser.parse_args()


if __name__ == "__main__":
    train_rpn(parse_args())
