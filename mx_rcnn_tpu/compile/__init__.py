"""Unified compiled-program registry + persistent AOT warm-start.

One place that knows every XLA program a driver can dispatch — the
trainer's per-(program, bucket-shape) step cache, the ``Predictor``'s
shape-keyed jit dicts, and the serve engine's predict path all route
their bookkeeping (and their jitted callables) through
:class:`~mx_rcnn_tpu.compile.registry.ProgramRegistry`, which in turn
keys the on-disk persistent compilation cache so a second process over
the same cache dir warms from disk instead of XLA (``compile/aot_hit``
vs ``compile/aot_miss`` in the telemetry stream).
"""

from mx_rcnn_tpu.compile.registry import (ProgramKey, ProgramRegistry,
                                          config_digest, configure_jax_cache,
                                          registry_cache_dir)

__all__ = ["ProgramRegistry", "ProgramKey", "config_digest",
           "configure_jax_cache", "registry_cache_dir"]
