"""Unified compiled-program registry + persistent AOT warm-start.

Before this module, three drivers each kept their own shape-keyed
compile bookkeeping: the trainer's per-(program, bucket-shape)
``seen_programs`` set, the ``Predictor``'s four independent jit dicts
(``_predict``/``_predict_rpn``/``_packed_fns``/``_pyr_fn``), and the
serve engine's ``_seen_shapes``.  None of them talked to the persistent
XLA compilation cache that ``__graft_entry__``/the test suite already
rely on — every server boot recompiled every (bucket, batch) program
from scratch.

:class:`ProgramRegistry` unifies the three:

* **One key.**  :class:`ProgramKey` = ``(model-config digest, program
  kind, input shape, batch, dtype, sharding)``.  The config digest is a
  sha1 over ``config_to_dict(cfg)``, so two processes agree on program
  identity iff they agree on the *entire* frozen config tree.
* **One callable cache.**  ``register(kind, builder)`` +
  ``lookup(kind, static=...)`` replace the Predictor's ad-hoc dicts:
  builders are lazy, built-once, and LRU-evicted past ``max_programs``
  (multi-model serving needs a bound; XLA executables pin device memory).
* **One persistent cache.**  When the registry owns a cache base (the
  ``MXR_PROGRAM_CACHE`` env var or an explicit ``cache_base``), it
  points jax's compilation cache at a machine-fingerprint dir extended
  with the dtype and cache-schema version (``registry_cache_dir``) and
  drops ``jax_persistent_cache_min_compile_time_secs`` to 0 so even
  tiny-model programs persist.  A sidecar *marker manifest*
  (``<dir>/programs/<keyhash>.json``, one JSON file per program) records
  which programs a previous process already compiled: on the first
  in-process dispatch of a key, a present-and-matching marker counts as
  ``compile/aot_hit`` (XLA will load the executable from disk), a
  missing one as ``compile/aot_miss``, and a present-but-mismatching one
  as ``compile/key_collision`` (treated as a miss — the marker is
  overwritten, never trusted).
* **One compile-seconds histogram.**  ``record_compile_seconds`` feeds
  the PR-6 ``Hist`` primitive per program kind plus the aggregate
  ``compile/seconds`` telemetry hist, so the report can show the compile
  tail the AOT path is deleting.

Foreign-machine safety is inherited from ``machine_cache_dir``: AOT CPU
executables compiled on a host with different CPU features are rejected
at load (and documented to risk SIGILL if forced), so the fingerprint
keys them out of reach entirely — the registry only *extends* that key,
it never weakens it.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, Iterable, Optional, Tuple

from mx_rcnn_tpu import telemetry
from mx_rcnn_tpu.logger import logger
from mx_rcnn_tpu.telemetry import Hist

# bump when the marker-manifest layout or the ProgramKey fields change:
# a new schema gets a fresh fingerprint dir, so stale manifests from an
# older code version are ignored rather than misread
CACHE_SCHEMA = "mxr-programs-v1"

ENV_CACHE_BASE = "MXR_PROGRAM_CACHE"

INFER_DTYPES = ("float32", "bfloat16", "int8", "int8-activation")

# schema tag for the activation-scale manifest persisted next to the AOT
# program markers — bump when the calibration doc layout changes
ACT_SCALES_SCHEMA = "mxr-act-scales-v1"


def config_digest(cfg) -> str:
    """sha1 over the full frozen config tree (16 hex chars).

    ``None`` (duck-typed predictors in tests) digests to ``"none"`` —
    such registries still dedupe in-process but share one manifest
    namespace."""
    if cfg is None:
        return "none"
    doc = dataclasses.asdict(cfg)
    blob = json.dumps(doc, sort_keys=True, default=str)
    return hashlib.sha1(blob.encode()).hexdigest()[:16]


def plan_signature(plan) -> str:
    """Stable string identity of a MeshPlan (or ``"none"``): programs
    lowered against different meshes are different executables."""
    if plan is None:
        return "none"
    try:
        return (f"d{plan.n_data}m{plan.n_model}s{plan.n_space}"
                f"x{len(plan.mesh.devices.flat)}")
    except Exception:
        return "plan"


def registry_cache_dir(base: Optional[str] = None,
                       dtype: str = "float32") -> str:
    """Machine-fingerprint cache dir extended with dtype + cache schema.

    Builds on ``__graft_entry__.machine_cache_dir`` (arch, CPU feature
    flags, jax version) and folds in the inference dtype and
    :data:`CACHE_SCHEMA` — a bf16 replica and an f32 replica over the
    same base get disjoint dirs, and a jax upgrade or manifest-layout
    change silently starts cold instead of misusing stale entries."""
    from __graft_entry__ import machine_cache_dir

    base = base or os.environ.get(ENV_CACHE_BASE) \
        or os.environ.get("JAX_TEST_CACHE", "/tmp/jax_test_cache")
    return machine_cache_dir(base, extra=(f"dtype={dtype}", CACHE_SCHEMA))


def configure_jax_cache(cache_dir: str) -> None:
    """Point jax's persistent compilation cache at ``cache_dir`` and
    persist *every* compile (min_compile_time 0): the registry's warm
    boots depend on tiny programs hitting disk too, not just the
    >1 s flagship compiles ``__graft_entry__`` filters for.

    jax initializes its cache object at most once, on the first compile
    — and model/param init compiles typically run before any registry
    exists, pinning the cache to whatever dir the environment set at
    import time.  ``reset_cache()`` drops that instance so the next
    compile re-initializes against ``cache_dir``; without it the config
    update is silently ignored and nothing persists where the marker
    manifest says it does."""
    import jax

    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    from jax.experimental.compilation_cache import compilation_cache
    compilation_cache.reset_cache()


@dataclasses.dataclass(frozen=True)
class ProgramKey:
    """Identity of one XLA program as the registry sees it."""

    digest: str          # config_digest(cfg)
    kind: str            # e.g. "predict", "train_step", "masks_packed"
    # full padded input shape (batch leading), optionally extended with
    # string tokens for non-shape statics baked into the executable (the
    # device-postprocess program appends e.g. "mpi=100"/"th=0.001" — two
    # runs differing only in those flags are different XLA programs)
    shape: Tuple[Any, ...]
    batch: int           # leading dim, kept explicit for the manifest
    dtype: str           # inference/compute dtype variant
    sharding: str        # plan_signature(plan)

    def fields(self) -> dict:
        return {"digest": self.digest, "kind": self.kind,
                "shape": list(self.shape), "batch": self.batch,
                "dtype": self.dtype, "sharding": self.sharding,
                "schema": CACHE_SCHEMA}

    def hash(self) -> str:
        blob = json.dumps(self.fields(), sort_keys=True)
        return hashlib.sha1(blob.encode()).hexdigest()[:20]


class ProgramRegistry:
    """Per-process registry of every program one model can dispatch.

    Parameters
    ----------
    cfg : frozen config (or None for duck-typed predictors)
    dtype : inference dtype variant this registry's programs run in
    plan : MeshPlan or None — folded into every key's sharding field
    cache_base : explicit persistent-cache base dir.  When given (or the
        ``MXR_PROGRAM_CACHE`` env var is set) the registry OWNS the jax
        compilation cache: it points jax at ``registry_cache_dir`` and
        keeps its marker manifest there.  Otherwise it piggybacks marker
        files on whatever cache dir is already configured (the
        ``__graft_entry__``/conftest machine dir), never touching global
        jax config — warm-start accounting still works, test-suite
        caching is untouched.
    max_programs : LRU bound on *built callables* (not markers); None =
        unbounded.
    pinned : exempt this registry's callables from LRU eviction even
        when ``max_programs`` is set.  The multi-model ``ModelPool``
        pins a hot model's registry so its programs survive pressure
        from sibling models; mutable at runtime (``registry.pinned``).
    """

    def __init__(self, cfg=None, dtype: str = "float32", plan=None,
                 cache_base: Optional[str] = None,
                 max_programs: Optional[int] = None,
                 pinned: bool = False):
        if dtype not in INFER_DTYPES:
            raise ValueError(f"dtype must be one of {INFER_DTYPES}, "
                             f"got {dtype!r}")
        self.digest = config_digest(cfg)
        self.dtype = dtype
        self.sharding = plan_signature(plan)
        self.max_programs = max_programs
        self.pinned = bool(pinned)
        self._lock = threading.Lock()
        self._builders: Dict[str, Callable[..., Callable]] = {}
        self._fns: "OrderedDict[Tuple[str, Tuple], Callable]" = OrderedDict()
        self._seen: Dict[ProgramKey, dict] = {}
        self.counters: Dict[str, int] = {
            "programs": 0, "aot_hit": 0, "aot_miss": 0,
            "key_collisions": 0, "evictions": 0,
        }
        self.compile_hist = Hist()

        base = cache_base or os.environ.get(ENV_CACHE_BASE)
        self.owns_cache = bool(base)
        if self.owns_cache:
            self.cache_dir: Optional[str] = registry_cache_dir(base, dtype)
            try:
                configure_jax_cache(self.cache_dir)
            except Exception as e:  # cache is an optimization, not a dep
                logger.warning("program registry: persistent cache "
                               "unavailable (%s)", e)
                self.cache_dir = None
        else:
            self.cache_dir = self._active_jax_cache_dir()

    @staticmethod
    def _active_jax_cache_dir() -> Optional[str]:
        try:
            import jax

            return jax.config.jax_compilation_cache_dir or None
        except Exception:
            return None

    # -- keys + marker manifest -----------------------------------------

    def key_for(self, kind: str, shape: Iterable) -> ProgramKey:
        # int-like tokens normalize to int (numpy scalars hash/serialize
        # differently); anything else (static-arg tags) stays a string
        shape = tuple(s if isinstance(s, str) else int(s) for s in shape)
        ints = [s for s in shape if not isinstance(s, str)]
        batch = int(ints[0]) if ints else 0
        return ProgramKey(self.digest, kind, shape, batch, self.dtype,
                          self.sharding)

    def _marker_path(self, key: ProgramKey) -> Optional[str]:
        if not self.cache_dir:
            return None
        return os.path.join(self.cache_dir, "programs", key.hash() + ".json")

    def _probe_marker(self, key: ProgramKey) -> str:
        """'hit' | 'miss' | 'collision' for this key's on-disk marker."""
        path = self._marker_path(key)
        if not path or not os.path.exists(path):
            return "miss"
        try:
            with open(path) as f:
                stored = json.load(f)
        except (OSError, ValueError):
            return "collision"  # unreadable marker: never trust it
        return "hit" if stored == key.fields() else "collision"

    def _write_marker(self, key: ProgramKey) -> None:
        path = self._marker_path(key)
        if not path:
            return
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            tmp = f"{path}.{os.getpid()}.tmp"
            with open(tmp, "w") as f:
                json.dump(key.fields(), f, sort_keys=True)
            os.replace(tmp, path)  # atomic: concurrent ranks race benignly
        except OSError as e:
            logger.warning("program registry: marker write failed (%s)", e)

    # -- activation-scale manifest (int8-activation calibration) ---------

    def act_scales_path(self) -> Optional[str]:
        """Where this registry persists calibrated activation scales —
        next to the AOT program markers, keyed by config digest, so a
        warm boot of the same config finds the same calibration the AOT
        executables were built against."""
        if not self.cache_dir:
            return None
        return os.path.join(self.cache_dir, "programs",
                            f"act_scales-{self.digest}.json")

    def save_act_scales(self, tensors: Dict[str, dict]) -> Optional[str]:
        """Persist per-tensor calibration scales (``{"tensor": {"absmax",
        "scale"}}``) atomically; returns the path (None when no cache dir
        is configured — calibration then lives only in-process)."""
        path = self.act_scales_path()
        if not path:
            return None
        doc = {"schema": ACT_SCALES_SCHEMA, "digest": self.digest,
               "tensors": tensors}
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            tmp = f"{path}.{os.getpid()}.tmp"
            with open(tmp, "w") as f:
                json.dump(doc, f, sort_keys=True)
            os.replace(tmp, path)
        except OSError as e:
            logger.warning("program registry: act-scales write failed (%s)",
                           e)
            return None
        return path

    def load_act_scales(self) -> Optional[Dict[str, dict]]:
        """Load the persisted calibration manifest for this config digest
        (None when absent/unreadable/schema-mismatched — callers fall
        back to the weight-only int8 behavior)."""
        path = self.act_scales_path()
        if not path or not os.path.exists(path):
            return None
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            return None
        if (doc.get("schema") != ACT_SCALES_SCHEMA
                or doc.get("digest") != self.digest):
            return None
        tensors = doc.get("tensors")
        return tensors if isinstance(tensors, dict) else None

    # -- dispatch accounting --------------------------------------------

    def note_dispatch(self, kind: str, shape: Iterable[int]) -> bool:
        """First-seen accounting for one dispatch.  Returns True exactly
        once per (kind, shape) per process — the caller's "this dispatch
        compiles" signal (steady state must return False forever after).

        On the first sighting, probes the marker manifest: a matching
        marker from a previous process is an ``aot_hit`` (the persistent
        cache will serve the executable), anything else an ``aot_miss``
        (plus ``key_collision`` when a marker exists but disagrees with
        the key — it is overwritten, not trusted)."""
        key = self.key_for(kind, shape)
        with self._lock:
            if key in self._seen:
                return False
            probe = self._probe_marker(key)
            self._seen[key] = {"aot": probe, "t": time.time()}
            self.counters["programs"] += 1
            if probe == "collision":
                self.counters["key_collisions"] += 1
            if probe == "hit":
                self.counters["aot_hit"] += 1
            else:
                self.counters["aot_miss"] += 1
        tel = telemetry.get()
        tel.counter("compile/aot_hit" if probe == "hit"
                    else "compile/aot_miss")
        if probe == "collision":
            tel.counter("compile/key_collision")
        tel.meta("compile/program", kind=kind, shape=list(key.shape),
                 dtype=self.dtype, sharding=self.sharding,
                 digest=self.digest, aot=probe)
        self._write_marker(key)
        return True

    def record_compile_seconds(self, kind: str, shape: Iterable[int],
                               seconds: float) -> None:
        """Observe one program's first-dispatch wall time (compile +
        first run) into the per-kind and aggregate compile histograms."""
        self.compile_hist.observe(seconds)
        tel = telemetry.get()
        tel.observe("compile/seconds", seconds)
        tel.observe(f"compile/seconds/{kind}", seconds)
        key = self.key_for(kind, shape)
        with self._lock:
            info = self._seen.get(key)
            if info is not None:
                info["compile_s"] = seconds

    # -- callable cache --------------------------------------------------

    def register(self, kind: str, builder: Callable[..., Callable]) -> None:
        """Declare how to build the jitted callable for ``kind``.  The
        builder receives the static args later passed to ``lookup`` and
        returns the callable; it runs at most once per distinct statics
        (until LRU-evicted)."""
        with self._lock:
            self._builders[kind] = builder

    def lookup(self, kind: str, static: Tuple = ()) -> Callable:
        """Build-or-fetch the callable for (kind, static), LRU-ordered."""
        ck = (kind, tuple(static))
        with self._lock:
            fn = self._fns.get(ck)
            if fn is not None:
                self._fns.move_to_end(ck)
                return fn
            builder = self._builders.get(kind)
        if builder is None:
            raise KeyError(f"no builder registered for program kind "
                           f"{kind!r} (have {sorted(self._builders)})")
        fn = builder(*ck[1])
        with self._lock:
            # lost-race check: another thread may have built it meanwhile
            if ck not in self._fns:
                self._fns[ck] = fn
                while (not self.pinned
                       and self.max_programs is not None
                       and len(self._fns) > self.max_programs):
                    evicted, _ = self._fns.popitem(last=False)
                    self.counters["evictions"] += 1
                    telemetry.get().counter("compile/eviction")
                    logger.info("program registry: evicted %r "
                                "(max_programs=%d)", evicted,
                                self.max_programs)
            self._fns.move_to_end(ck)
            return self._fns[ck]

    def programs(self) -> int:
        with self._lock:
            return len(self._seen)

    def snapshot(self) -> dict:
        """JSON-able state for ``/metrics`` and the warmup log."""
        with self._lock:
            counters = dict(self.counters)
            seen = [dict(kind=k.kind, shape=list(k.shape),
                         dtype=k.dtype, aot=v["aot"],
                         compile_s=round(v.get("compile_s", 0.0), 3))
                    for k, v in self._seen.items()]
        return {"digest": self.digest, "dtype": self.dtype,
                "sharding": self.sharding, "cache_dir": self.cache_dir,
                "owns_cache": self.owns_cache, "pinned": self.pinned,
                "counters": counters,
                "programs": seen,
                "compile_seconds": self.compile_hist.to_dict()}
