"""Optimizer — reference ``train_end2end.py`` optimizer block as optax.

Reference contract (SURVEY §3.1):
  SGD(learning_rate=lr, momentum=0.9, wd=0.0005, clip_gradient=5,
      lr_scheduler=MultiFactorScheduler(step=lr_steps, factor=0.1),
      rescale_grad=1/batch)
plus ``fixed_param_prefix`` freezing applied by MutableModule
(``rcnn/core/module.py``): params whose name starts with a fixed prefix get
no updates.  Our losses already divide by batch, so ``rescale_grad`` is
folded in.

MXNet SGD applies wd as decoupled-from-loss weight decay inside the update
(grad += wd * weight before momentum); optax ``add_decayed_weights`` before
``sgd`` reproduces it.  Clip is per-element clipping in MXNet
(``clip_gradient`` clamps each gradient value to ±5), NOT global-norm —
mirrored with a custom elementwise clamp.
"""

from __future__ import annotations

from typing import Callable, Sequence

import jax
import jax.numpy as jnp
import optax

from mx_rcnn_tpu.config import Config

# FrozenBN statistics live in the param tree (backbones.FrozenBN) but are
# never optimizer targets, in any config.
_ALWAYS_FROZEN = ("mean", "var")


def fixed_param_mask(params, fixed_prefixes: Sequence[str]):
    """True = trainable, False = frozen.

    Reference semantics (``rcnn/core/module.py`` fixed_param_prefix): MXNet
    matches ``name.startswith(prefix)`` on FLAT param names
    (``conv1_weight``, ``stage1_unit1_conv1_weight`` — so ``conv1`` freezes
    the stem conv but NOT ``stage2_unit1_conv1``).  Our equivalent flat name
    is the tree path below the top-level submodule (backbone/rpn/...)
    joined with ``_``.  Prefixes that are BN leaf names (``gamma``/``beta``)
    freeze those leaves everywhere — frozen-BN affine; running ``mean``/
    ``var`` are never optimizer targets in any config.
    """
    structural = tuple(p for p in fixed_prefixes if p not in ("gamma", "beta"))
    leaf_frozen = set(p for p in fixed_prefixes if p in ("gamma", "beta"))
    leaf_frozen.update(_ALWAYS_FROZEN)

    def frozen(path) -> bool:
        names = [e.key if hasattr(e, "key") else str(e) for e in path]
        flat = "_".join(names[1:]) if len(names) > 1 else names[0]
        if any(flat.startswith(p) for p in structural):
            return True
        return names[-1] in leaf_frozen

    return jax.tree_util.tree_map_with_path(lambda p, _: not frozen(p), params)


def make_lr_schedule(cfg: Config, steps_per_epoch: int,
                     begin_epoch: int = 0) -> Callable:
    """MultiFactorScheduler(step=LR_STEP epochs, factor=LR_FACTOR) with
    optional linear warmup (reference ``config.TRAIN.WARMUP*``)."""
    tr = cfg.TRAIN
    warmup = tr.WARMUP_STEP if (tr.WARMUP and tr.WARMUP_STEP > 0) else 0
    boundaries = {}
    for e in tr.LR_STEP:
        s = (e - begin_epoch) * steps_per_epoch
        if s > 0:
            # join_schedules evaluates the joined schedule at (step - warmup);
            # shift so drops still land on GLOBAL steps like MultiFactor
            boundaries[s - warmup] = tr.LR_FACTOR
    sched = optax.piecewise_constant_schedule(tr.LR, boundaries)
    if warmup:
        warm = optax.linear_schedule(tr.WARMUP_LR, tr.LR, warmup)
        return optax.join_schedules([warm, sched], [warmup])
    return sched


def _clip_elementwise(clip: float) -> optax.GradientTransformation:
    """MXNet ``clip_gradient``: clamp every gradient element to [−clip, clip]."""

    def update(updates, state, params=None):
        del params
        return jax.tree.map(lambda g: jnp.clip(g, -clip, clip), updates), state

    return optax.GradientTransformation(lambda _: optax.EmptyState(), update)


def make_optimizer(cfg: Config, steps_per_epoch: int, params,
                   begin_epoch: int = 0,
                   fixed_prefixes: Sequence[str] | None = None):
    """Build the optax transform + the trainable mask.

    Returns (tx, schedule, mask).  Frozen params receive zero updates via
    ``optax.multi_transform`` — the MutableModule ``fixed_param_prefix``
    contract.  The mask (True = trainable) is also what ``make_train_step``
    uses to ``stop_gradient`` frozen leaves so XLA dead-code-eliminates the
    frozen backward tail (stem kernel grad, maxpool select_and_scatter,
    stage-1 bwd — measured 9.97 → 4.36 ms body fwd+bwd on v5-lite).
    """
    tr = cfg.TRAIN
    if fixed_prefixes is None:
        fixed_prefixes = cfg.network.FIXED_PARAMS
    mask = fixed_param_mask(params, fixed_prefixes)
    schedule = make_lr_schedule(cfg, steps_per_epoch, begin_epoch)
    acc_dtype = (None if tr.OPT_ACC_DTYPE == "float32"
                 else jnp.dtype(tr.OPT_ACC_DTYPE))
    inner = optax.chain(
        _clip_elementwise(tr.CLIP_GRADIENT),
        optax.add_decayed_weights(tr.WD),
        optax.sgd(learning_rate=schedule, momentum=tr.MOMENTUM,
                  accumulator_dtype=acc_dtype),
    )
    labels = jax.tree.map(lambda t: "train" if t else "frozen", mask)
    tx = optax.multi_transform(
        {"train": inner, "frozen": optax.set_to_zero()}, labels)
    return tx, schedule, mask
