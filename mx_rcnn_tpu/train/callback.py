"""Training callbacks (reference ``rcnn/core/callback.py``).

``Speedometer`` logs imgs/sec every N batches — the BASELINE.json
north-star throughput number, emitted per-chip and total.
"""

from __future__ import annotations

import time

from mx_rcnn_tpu import telemetry
from mx_rcnn_tpu.logger import logger


class Speedometer:
    """imgs/sec logger, reset each epoch (reference mx.callback.Speedometer
    as wired by train_end2end.py's ``batch_end_callback``).

    Intervals are measured on ``time.perf_counter`` — the wall clock
    (``time.time``) steps under NTP slew, which corrupts the rate exactly
    when a long run matters most.  Each computed rate is also fed into the
    active telemetry sink (``train/imgs_per_sec`` gauge), so throughput is
    a machine-readable artifact of the run, not a log-only line.
    """

    def __init__(self, batch_size: int, frequent: int = 20, n_chips: int = 1):
        self.batch_size = batch_size  # global images per step
        self.frequent = frequent
        self.n_chips = max(n_chips, 1)
        self._tic = None
        self._count = 0

    def reset(self):
        self._tic = None
        self._count = 0

    def __call__(self, epoch: int, step: int, metric_str: str = ""):
        self._count += 1
        if self._tic is None:
            self._tic = time.perf_counter()
            self._count = 0
            return None
        if self._count % self.frequent == 0:
            dt = time.perf_counter() - self._tic
            speed = self.frequent * self.batch_size / max(dt, 1e-9)
            # sink resolved per emission (once per `frequent` steps), so a
            # run configured after construction is still captured
            telemetry.get().gauge("train/imgs_per_sec", speed)
            logger.info(
                "Epoch[%d] Batch [%d]\tSpeed: %.2f samples/sec (%.2f/chip)\t%s",
                epoch, step, speed, speed / self.n_chips, metric_str)
            self._tic = time.perf_counter()
            return speed
        return None
