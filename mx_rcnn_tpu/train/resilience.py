"""Fault tolerance — step-level checkpoint/auto-resume, NaN sentinel
policies, preemption handling, and retrying checkpoint I/O.

SURVEY §5 marks failure handling as absent in the reference ("crash =
rerun from the last epoch checkpoint via ``--resume``); the ROADMAP
north-star — multi-hour COCO runs on preemptible fleets — needs a run to
survive preemption, a corrupt image, a transient filesystem error, or a
NaN spike without losing the epoch.  This module holds the pieces the
trainer/checkpoint/loader layers wire together:

* :class:`ResilienceOptions` — the knob bundle every train driver exposes
  (``--save-every-n-steps``, ``--auto-resume``, ``--nan-policy``).
* :class:`PreemptionGuard` — SIGTERM/SIGINT → "save at the next step
  boundary and exit cleanly" (the handler only sets a flag; ``fit`` does
  the save where the state is consistent).  A second signal falls back to
  the default handler so a stuck save can still be killed.
* :func:`retry_io` — exponential-backoff retry for transient checkpoint
  I/O errors (``checkpoint/retry`` telemetry counter).
* NaN policies (:data:`NAN_POLICIES`): ``halt`` (diagnostic dump +
  :class:`NonFiniteLossError`), ``skip`` (the step itself discards
  non-finite updates in-graph — params are never poisoned), ``rollback``
  (restore the last good step checkpoint and keep consuming the loader).
* Env-driven fault injection (``MXR_FAULT_BAD_RECORD``,
  ``MXR_FAULT_NAN_STEP``) so ``script/fault_smoke.sh`` can exercise the
  recovery paths through the real CLI drivers; the richer in-process
  harness lives in ``tests/faults.py``.

Every recovery event lands in the telemetry stream
(``train/nan_detected``, ``train/nan_rollback``, ``loader/bad_record``,
``checkpoint/retry``, ``train/preempted``) so PR-1's report can triage
recoveries the same way it triages slow steps.
"""

from __future__ import annotations

import dataclasses
import json
import os
import signal
import threading
import time
from typing import Optional, Tuple

from mx_rcnn_tpu import telemetry
from mx_rcnn_tpu.logger import logger

NAN_POLICIES = ("off", "halt", "skip", "rollback")

# (epoch, consumed) → one orbax int key; an epoch cannot run more batches
# than this (guarded at save).  The decoded pair is the resume position:
# "epoch E, C loader batches already dispatched".
STEP_KEY_STRIDE = 10 ** 7


class NonFiniteLossError(RuntimeError):
    """Raised by the ``halt`` NaN policy (and by ``rollback`` when there is
    no step checkpoint to roll back to)."""


@dataclasses.dataclass(frozen=True)
class ResilienceOptions:
    """Fault-tolerance knobs for ``fit`` (all off by default — a plain
    ``fit`` call compiles the exact same step program as before).

    ``save_every_n_steps``: mid-epoch step checkpoints at this cadence
    (0 = epoch checkpoints only).  ``auto_resume``: pick the latest
    checkpoint — step or epoch — under the prefix and continue from it,
    fast-forwarding the loader (no manual ``--begin_epoch``/``--resume``).
    ``nan_policy``: what to do when the in-step all-finite sentinel trips
    (see :data:`NAN_POLICIES`).  ``max_io_retries``/``io_backoff_s``:
    transient checkpoint-I/O retry budget.
    """

    save_every_n_steps: int = 0
    auto_resume: bool = False
    nan_policy: str = "off"
    max_io_retries: int = 3
    io_backoff_s: float = 0.5

    def __post_init__(self):
        if self.nan_policy not in NAN_POLICIES:
            raise ValueError(f"nan_policy must be one of {NAN_POLICIES}, "
                             f"got {self.nan_policy!r}")
        if self.save_every_n_steps < 0:
            raise ValueError("save_every_n_steps must be >= 0")

    @property
    def enabled(self) -> bool:
        return (self.save_every_n_steps > 0 or self.auto_resume
                or self.nan_policy != "off")

    @property
    def sentinel(self) -> bool:
        """The step must compute the on-device all-finite flag."""
        return self.nan_policy != "off"

    @property
    def skip_nonfinite(self) -> bool:
        """The step must discard non-finite updates in-graph (``skip``
        policy: params are protected before the host ever notices)."""
        return self.nan_policy == "skip"

    @classmethod
    def from_args(cls, args) -> "ResilienceOptions":
        """Build from a train driver's parsed argv (missing attributes —
        e.g. train_alternate's stage calls — default to off)."""
        return cls(
            save_every_n_steps=getattr(args, "save_every_n_steps", 0) or 0,
            auto_resume=getattr(args, "auto_resume", False),
            nan_policy=getattr(args, "nan_policy", "off") or "off",
        )


def add_resilience_args(parser) -> None:
    """The shared ``--save-every-n-steps/--auto-resume/--nan-policy``
    argparse surface (every fit-based train driver gets these through
    ``tools.common.add_common_args``)."""
    parser.add_argument("--save-every-n-steps", type=int, default=0,
                        dest="save_every_n_steps",
                        help="mid-epoch step checkpoints every N steps "
                             "(atomic orbax writes under PREFIX/steps, "
                             "rolling window; 0 = epoch checkpoints only)")
    parser.add_argument("--auto-resume", action="store_true",
                        dest="auto_resume",
                        help="resume from the latest checkpoint (step or "
                             "epoch) under --prefix, fast-forwarding the "
                             "loader to the exact batch; fresh start when "
                             "none exists — safe to pass always")
    parser.add_argument("--nan-policy", default="off", dest="nan_policy",
                        choices=list(NAN_POLICIES),
                        help="non-finite loss/grad handling: halt = "
                             "diagnostic dump + error; skip = drop the bad "
                             "update in-graph and keep going; rollback = "
                             "restore the last good step checkpoint")


# -- step-checkpoint keying ------------------------------------------------

def encode_step_key(epoch: int, consumed: int) -> int:
    """(epoch, consumed loader batches) → the orbax int step key."""
    if not 0 <= consumed < STEP_KEY_STRIDE:
        raise ValueError(f"consumed {consumed} outside [0, {STEP_KEY_STRIDE})")
    return epoch * STEP_KEY_STRIDE + consumed


def decode_step_key(key: int) -> Tuple[int, int]:
    return key // STEP_KEY_STRIDE, key % STEP_KEY_STRIDE


# -- transient-I/O retry ---------------------------------------------------

def retry_io(fn, what: str, retries: int = 3, backoff_s: float = 0.5,
             exceptions=(OSError, TimeoutError)):
    """Run ``fn()`` retrying transient errors with exponential backoff.

    Each retry bumps the ``checkpoint/retry`` telemetry counter and logs
    the error; the last failure re-raises.  ``exceptions`` is deliberately
    narrow (filesystem/timeout) — programming errors must not be retried
    into silence.
    """
    for attempt in range(retries + 1):
        try:
            return fn()
        except exceptions as e:
            if attempt == retries:
                raise
            delay = backoff_s * (2 ** attempt)
            telemetry.get().counter("checkpoint/retry")
            logger.warning("%s failed (%s: %s) — retry %d/%d in %.1fs",
                           what, type(e).__name__, e, attempt + 1, retries,
                           delay)
            time.sleep(delay)


# -- preemption ------------------------------------------------------------

class PreemptionGuard:
    """Context manager turning SIGTERM/SIGINT into a "save at the next
    step boundary" request.

    The handler only sets a flag — all checkpoint work happens on the
    training loop's thread, at a step boundary, where the state is
    consistent and (multi-host) every rank reaches the orbax barriers.
    A SECOND signal restores the previous handlers and re-raises, so a
    hung save never makes the process unkillable.  Installing handlers is
    only legal on the main thread; elsewhere the guard degrades to inert
    (``requested`` stays False) with a warning.
    """

    SIGNALS = (signal.SIGTERM, signal.SIGINT)

    def __init__(self):
        self._requested = False
        self._prev = {}

    @property
    def requested(self) -> bool:
        return self._requested

    def _handler(self, signum, frame):
        if self._requested:
            # second signal: the user means it — restore and re-deliver
            self._restore()
            signal.raise_signal(signum)
            return
        self._requested = True
        name = signal.Signals(signum).name
        # flight-record NOW: if the clean path never reaches its boundary
        # (hung save, wedged loader) this dump is all the post-mortem gets.
        # dump_flight is handler-safe: its lock acquire is bounded, so
        # interrupting a thread inside the sink degrades instead of
        # deadlocking.
        telemetry.get().dump_flight("preempt_signal", signal=name)
        logger.warning("received %s — saving a step checkpoint at the next "
                       "step boundary, then exiting cleanly (send again to "
                       "kill immediately)", name)

    def __enter__(self):
        if threading.current_thread() is not threading.main_thread():
            logger.warning("PreemptionGuard outside the main thread: signal "
                           "handlers not installed, preemption save disabled")
            return self
        for s in self.SIGNALS:
            self._prev[s] = signal.signal(s, self._handler)
        return self

    def _restore(self):
        for s, prev in self._prev.items():
            signal.signal(s, prev)
        self._prev = {}

    def __exit__(self, *exc):
        self._restore()
        return False


def preemption_agreed(local: bool) -> bool:
    """Cross-rank OR of the local preemption flag.

    Multi-host SIGTERMs arrive skewed across ranks, and a rank saving
    alone would deadlock orbax's barriers — so every rank calls this at
    the SAME loop points (metric-fetch boundaries, which advance in
    lockstep) and all exit together once any rank was signalled.
    Single-process: just the local flag, checked every step.
    """
    import jax

    if jax.process_count() <= 1:
        return local
    import numpy as np
    from jax.experimental import multihost_utils

    flags = multihost_utils.process_allgather(np.asarray(bool(local)))
    return bool(np.any(flags))


# -- NaN diagnostics -------------------------------------------------------

def dump_nan_diagnostics(out_dir: Optional[str], epoch: int, consumed: int,
                         step: int, scalars: dict) -> Optional[str]:
    """``halt`` policy's dump: the detection position + the last fetched
    metric scalars, as JSON next to the run's other artifacts."""
    if not out_dir:
        return None
    os.makedirs(out_dir, exist_ok=True)
    path = os.path.join(out_dir, f"nan_dump_e{epoch}_b{consumed}.json")
    doc = {"epoch": int(epoch), "consumed": int(consumed), "step": int(step),
           "time": time.time(),
           "metrics": {k: float(v) for k, v in scalars.items()}}
    with open(path, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
    return path


# -- env-driven fault injection (script/fault_smoke.sh) --------------------

ENV_BAD_RECORD = "MXR_FAULT_BAD_RECORD"
ENV_NAN_STEP = "MXR_FAULT_NAN_STEP"


def inject_roidb_faults(roidb: list) -> list:
    """Corrupt the roidb records named by ``MXR_FAULT_BAD_RECORD`` (comma
    indices) so their load raises — the loader's fault isolation must
    substitute them.  No-op (and zero cost) when the env var is unset;
    called from ``tools.common.get_train_roidb`` so the injection reaches
    every CLI train driver without a dedicated flag."""
    spec = os.environ.get(ENV_BAD_RECORD, "")
    if not spec:
        return roidb
    for tok in spec.split(","):
        i = int(tok) % max(len(roidb), 1)
        rec = dict(roidb[i])
        rec.pop("image_array", None)  # synthetic records ship pixels inline
        rec["image"] = "/nonexistent/mxr_injected_bad_record.jpg"
        roidb[i] = rec
        logger.warning("fault injection: corrupted roidb record %d "
                       "(%s=%s)", i, ENV_BAD_RECORD, spec)
    return roidb


def nan_injection_step() -> Optional[int]:
    """Consumed-batch index at which ``fit`` poisons the images with NaN
    (``MXR_FAULT_NAN_STEP``); None when unset."""
    spec = os.environ.get(ENV_NAN_STEP, "")
    return int(spec) if spec else None
