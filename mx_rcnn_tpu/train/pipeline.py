"""Input-pipeline composer + autotuner: the (k steps/dispatch × N loader
workers × prefetch depth × device-prep) matrix as one driven sweep.

The primitives existed in isolation — ``--steps-per-dispatch`` chain
dispatch (trainer), the PR-4 ``data/workers.py`` shared-memory pool, the
``PREFETCH`` double-buffering queue, and now device-side preprocessing
(``data/device_prep.py``) — but their composition is what actually hides
host work, and the best cell is box- and config-dependent.  This module:

* runs each :class:`PipelineCell` through its own lean measured loop
  (NOT ``fit()``: fit builds fresh step closures per call, so a per-cell
  fit would re-compile every cell and pollute the dispatch numbers; here
  step programs are cached per k and a warmup epoch absorbs compiles),
* reports per-cell imgs/s with the PR-1 breakdown — loader_wait /
  dispatch / fetch_stall measured in-loop, assembly_wait diffed from the
  live telemetry sink,
* persists the winning cell (``--auto-tune``) to a small JSON next to
  the program cache, keyed by a tuned-field-normalized config digest, so
  ``train_end2end.py`` / ``train_alternate.py`` boot straight into the
  tuned (k, workers, prefetch, device_prep) via ``--tuned-pipeline``,
* writes ``sweep.jsonl`` — telemetry-meta-shaped ``pipeline_cell`` rows
  that ``scripts/telemetry_report.py`` folds into its pipeline table.

Entry point for humans: ``bench.py --mode pipeline [--auto-tune]``.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax

from mx_rcnn_tpu import telemetry
from mx_rcnn_tpu.compile.registry import (ENV_CACHE_BASE, ProgramRegistry,
                                          config_digest)
from mx_rcnn_tpu.config import Config
from mx_rcnn_tpu.logger import logger
from mx_rcnn_tpu.train.trainer import LOADER_WAIT_TRIPWIRE_FRAC, \
    _make_group_wrap

TUNED_FILENAME = "pipeline_tuned.json"
TUNED_SCHEMA = "mxr-pipeline-tuned-v1"


@dataclasses.dataclass(frozen=True)
class PipelineCell:
    """One point of the tuning matrix."""

    k: int = 1            # steps per dispatch (lax.scan group size)
    workers: int = 0      # data/workers.py pool size (0 = in-thread)
    prefetch: int = 2     # host→device prefetch queue depth
    device_prep: bool = False  # data/device_prep.py on-device transform

    @property
    def label(self) -> str:
        return (f"k{self.k}_w{self.workers}_p{self.prefetch}"
                + ("_dp" if self.device_prep else ""))


def cell_config(cfg: Config, cell: PipelineCell) -> Config:
    """Fold a cell's loader-side knobs into the config (k is a fit/bench
    argument, not a config field)."""
    return cfg.replace(tpu=dataclasses.replace(
        cfg.tpu, LOADER_WORKERS=int(cell.workers),
        PREFETCH=int(cell.prefetch), DEVICE_PREP=bool(cell.device_prep)))


def pipeline_digest(cfg: Config) -> str:
    """Config digest with the TUNED fields normalized to their defaults —
    the persisted-tuning key must not change when the tuning it selects
    is applied to the config."""
    return config_digest(cfg.replace(tpu=dataclasses.replace(
        cfg.tpu, LOADER_WORKERS=0, PREFETCH=2, DEVICE_PREP=False)))


def tuned_path(base: Optional[str] = None) -> str:
    """The tuned-cell JSON lives next to the program cache (same lifecycle:
    box-local derived state, safe to delete, survives reboots)."""
    base = (base or os.environ.get(ENV_CACHE_BASE)
            or os.path.join("/tmp", "mxr_program_cache"))
    return os.path.join(base, TUNED_FILENAME)


def save_tuned(cfg: Config, cell: PipelineCell, result: dict,
               path: Optional[str] = None) -> str:
    path = path or tuned_path()
    doc = {"schema": TUNED_SCHEMA, "tuned": {}}
    try:
        with open(path) as f:
            prev = json.load(f)
        if prev.get("schema") == TUNED_SCHEMA:
            doc = prev
    except (OSError, ValueError):
        pass
    doc.setdefault("tuned", {})[pipeline_digest(cfg)] = {
        "k": int(cell.k), "workers": int(cell.workers),
        "prefetch": int(cell.prefetch),
        "device_prep": bool(cell.device_prep),
        "imgs_per_sec": float(result.get("imgs_per_sec", 0.0)),
        "loader_wait_frac": float(result.get("loader_wait_frac", 0.0)),
        "recorded_by": "bench.py --mode pipeline --auto-tune",
    }
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
    os.replace(tmp, path)
    return path


def load_tuned(cfg: Config, path: Optional[str] = None) -> Optional[dict]:
    """The persisted cell for this config family, or None."""
    path = path or tuned_path()
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    if doc.get("schema") != TUNED_SCHEMA:
        return None
    return doc.get("tuned", {}).get(pipeline_digest(cfg))


def _span_total(tel, name: str) -> float:
    try:
        sp = tel.summary().get("spans", {}).get(name)
        return float(sp["total_s"]) if sp else 0.0
    except Exception:
        return 0.0


class PipelineSweep:
    """Drives the matrix over one model + synthetic/real roidb.

    ``build_steps``: dependency injection for tests — a callable
    ``() -> (state, steps_factory)`` where ``steps_factory(k) ->
    (step_fn, multi_fn)`` with the fit dispatch contract
    ``fn(state, batch, key) -> (state, metrics)``.  Default builds the
    real model once and caches step programs per k, so cells differing
    only in loader knobs share every compiled program.
    """

    def __init__(self, cfg: Config, roidb: list, batch: int = 1,
                 build_steps: Optional[Callable] = None):
        self.cfg = cfg
        self.roidb = roidb
        self.batch = batch
        self.registry = ProgramRegistry(
            cfg, dtype=(cfg.tpu.COMPUTE_DTYPE if cfg.tpu.COMPUTE_DTYPE in
                        ("float32", "bfloat16") else "float32"))
        if build_steps is None:
            build_steps = self._default_build
        self._state, self._steps_factory = build_steps()
        self._steps: Dict[int, Tuple[Callable, Optional[Callable]]] = {}
        self._prep = None

    # -- model plumbing --------------------------------------------------

    def _default_build(self):
        from mx_rcnn_tpu.data.image import bucket_shape
        from mx_rcnn_tpu.models import build_model, init_params
        from mx_rcnn_tpu.train.train_step import (create_train_state,
                                                  make_multi_train_step,
                                                  make_train_step)

        cfg = self.cfg
        model = build_model(cfg)
        stride = max(cfg.network.IMAGE_STRIDE, cfg.network.RPN_FEAT_STRIDE)
        hw = bucket_shape(cfg.tpu.SCALES[0], stride, landscape=True)
        params = init_params(model, cfg, jax.random.PRNGKey(0), self.batch,
                             hw)
        state, tx, mask = create_train_state(cfg, params,
                                             steps_per_epoch=1000)

        def steps(k: int):
            step = make_train_step(model, tx, trainable_mask=mask)
            multi = (make_multi_train_step(model, tx, k,
                                           trainable_mask=mask)
                     if k > 1 else None)
            return step, multi

        return state, steps

    def _get_steps(self, k: int):
        if k not in self._steps:
            self._steps[k] = self._steps_factory(k)
        return self._steps[k]

    def _get_prep(self):
        if self._prep is None:
            from mx_rcnn_tpu.data.device_prep import DevicePrep

            dp_cfg = self.cfg.replace(tpu=dataclasses.replace(
                self.cfg.tpu, DEVICE_PREP=True))
            self._prep = DevicePrep(dp_cfg, registry=self.registry)
        return self._prep

    # -- measured loop ---------------------------------------------------

    def _dispatch(self, step_fn, multi_fn, state, item, key):
        if isinstance(item, tuple) and len(item) == 3:  # tagged group wrap
            kind, n, data = item
            fn = multi_fn if kind == "group" else step_fn
            state, metrics = fn(state, data, key)
            return state, metrics, n
        state, metrics = step_fn(state, item, key)
        return state, metrics, 1

    def run_cell(self, cell: PipelineCell, epochs: int = 1,
                 warmup_epochs: int = 1) -> dict:
        """One cell: warmup epoch(s) absorb compiles + worker spawn, then
        ``epochs`` measured epochs through the fit-identical hot loop."""
        from mx_rcnn_tpu.data.loader import AnchorLoader

        cfgc = cell_config(self.cfg, cell)
        prep = self._get_prep() if cell.device_prep else None
        step_fn, multi_fn = self._get_steps(cell.k)
        loader = AnchorLoader(self.roidb, cfgc, self.batch, shuffle=True,
                              seed=0)
        if cell.k > 1:
            loader.wrap = _make_group_wrap(cell.k, None, prep=prep)
        else:
            loader.wrap = None
            loader.put = prep.put if prep is not None else jax.device_put
        tel = telemetry.get()
        asm0 = _span_total(tel, "loader/assembly_wait")
        state = self._state
        key = jax.random.PRNGKey(0)
        metrics = None
        try:
            for _ in range(warmup_epochs):
                for item in loader:
                    key, sub = jax.random.split(key)
                    state, metrics, _n = self._dispatch(
                        step_fn, multi_fn, state, item, sub)
            if metrics is not None:
                jax.block_until_ready(metrics)

            waits = disp = 0.0
            steps = 0
            t0 = time.perf_counter()
            for _ in range(epochs):
                it = iter(loader)
                while True:
                    tw = time.perf_counter()
                    try:
                        item = next(it)
                    except StopIteration:
                        break
                    waits += time.perf_counter() - tw
                    td = time.perf_counter()
                    key, sub = jax.random.split(key)
                    state, metrics, n = self._dispatch(
                        step_fn, multi_fn, state, item, sub)
                    disp += time.perf_counter() - td
                    steps += n
            tf = time.perf_counter()
            if metrics is not None:
                jax.device_get(metrics)
            fetch = time.perf_counter() - tf
            wall = time.perf_counter() - t0
        finally:
            loader.close_workers()
        self._state = state
        asm1 = _span_total(tel, "loader/assembly_wait")
        imgs = steps * self.batch
        frac = waits / max(wall, 1e-9)
        res = {
            "cell": cell.label, "k": cell.k, "workers": cell.workers,
            "prefetch": cell.prefetch, "device_prep": cell.device_prep,
            "imgs_per_sec": round(imgs / max(wall, 1e-9), 3),
            "steps": steps, "imgs": imgs,
            "wall_s": round(wall, 4),
            "loader_wait_s": round(waits, 4),
            "dispatch_s": round(disp, 4),
            "fetch_stall_s": round(fetch, 4),
            "assembly_wait_s": round(max(asm1 - asm0, 0.0), 4),
            "loader_wait_frac": round(frac, 4),
            "loader_wait_ok": frac <= LOADER_WAIT_TRIPWIRE_FRAC,
        }
        return res

    def sweep(self, cells: Sequence[PipelineCell], epochs: int = 1,
              warmup_epochs: int = 1, auto_tune: bool = False,
              sweep_jsonl: Optional[str] = None,
              tuned_file: Optional[str] = None) -> dict:
        """Run every cell, report the matrix, optionally persist the best.

        ``sweep_jsonl``: per-cell rows written as telemetry-meta-shaped
        events so ``scripts/telemetry_report.py <file>`` renders the
        pipeline table from the artifact alone."""
        tel = telemetry.get()
        results: List[dict] = []
        writer = open(sweep_jsonl, "w") if sweep_jsonl else None
        try:
            for cell in cells:
                logger.info("pipeline sweep: cell %s ...", cell.label)
                res = self.run_cell(cell, epochs=epochs,
                                    warmup_epochs=warmup_epochs)
                logger.info(
                    "pipeline sweep: %s -> %.1f imgs/s (loader_wait %.2fs,"
                    " dispatch %.2fs, fetch %.2fs, assembly %.2fs)",
                    cell.label, res["imgs_per_sec"], res["loader_wait_s"],
                    res["dispatch_s"], res["fetch_stall_s"],
                    res["assembly_wait_s"])
                tel.meta("pipeline_cell", **res)
                if writer:
                    writer.write(json.dumps(
                        {"kind": "meta", "name": "pipeline_cell", "rank": 0,
                         "fields": res}) + "\n")
                    writer.flush()
                results.append(res)
        finally:
            if writer:
                writer.close()
        best = max(results, key=lambda r: r["imgs_per_sec"])
        out = {"cells": results, "best": best,
               "registry": self.registry.snapshot()}
        if not best["loader_wait_ok"]:
            logger.warning(
                "pipeline sweep: best cell %s still loader-bound "
                "(loader_wait %.0f%% of wall > %.0f%% tripwire)",
                best["cell"], 100 * best["loader_wait_frac"],
                100 * LOADER_WAIT_TRIPWIRE_FRAC)
        if auto_tune:
            cell = PipelineCell(best["k"], best["workers"],
                                best["prefetch"], best["device_prep"])
            path = save_tuned(self.cfg, cell, best, path=tuned_file)
            out["tuned_file"] = path
            out["tuned"] = load_tuned(self.cfg, path=path)
            logger.info("pipeline sweep: tuned cell %s persisted to %s",
                        best["cell"], path)
        return out


def apply_tuned_to_args(args, cfg: Config,
                        path: Optional[str] = None) -> Config:
    """Boot a train driver into the persisted tuned cell.

    Explicit user flags win per field: only fields the user left at their
    parser defaults are overridden.  Returns the (possibly) updated
    config; ``args.steps_per_dispatch`` is mutated in place (k is a fit
    argument, not config state)."""
    tuned = load_tuned(cfg, path=path)
    if tuned is None:
        logger.warning(
            "--tuned-pipeline: no tuned cell for this config under %s — "
            "run `bench.py --mode pipeline --auto-tune` first; continuing "
            "with the configured pipeline", path or tuned_path())
        return cfg
    tpu_over = {}
    if getattr(args, "loader_workers", None) is None:
        tpu_over["LOADER_WORKERS"] = int(tuned["workers"])
    if getattr(args, "prefetch", None) is None:
        tpu_over["PREFETCH"] = int(tuned["prefetch"])
    if not getattr(args, "device_prep", False):
        tpu_over["DEVICE_PREP"] = bool(tuned["device_prep"])
    if getattr(args, "steps_per_dispatch", 1) == 1:
        args.steps_per_dispatch = int(tuned["k"])
    if tpu_over:
        cfg = cfg.replace(tpu=dataclasses.replace(cfg.tpu, **tpu_over))
    logger.info(
        "tuned pipeline: k=%d workers=%d prefetch=%d device_prep=%s "
        "(%.1f imgs/s when tuned)",
        getattr(args, "steps_per_dispatch", 1), cfg.tpu.LOADER_WORKERS,
        cfg.tpu.PREFETCH, cfg.tpu.DEVICE_PREP,
        tuned.get("imgs_per_sec", 0.0))
    return cfg


def parse_cells(k_list: Sequence[int], workers_list: Sequence[int],
                prefetch_list: Sequence[int],
                device_prep: Sequence[bool] = (False,)) -> List[PipelineCell]:
    """Cartesian product in deterministic order (k-major — step-program
    reuse groups neighboring cells)."""
    return [PipelineCell(k, w, p, dp)
            for k in k_list for w in workers_list
            for p in prefetch_list for dp in device_prep]
