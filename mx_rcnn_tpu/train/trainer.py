"""``fit`` — the reference's ``Module.fit`` call in ``train_net``
(train_end2end.py), as an explicit loop over the jitted step.

Responsibilities mirrored: per-epoch data iteration, composite metrics,
Speedometer batch-end callback, do_checkpoint epoch-end callback, resume
(the reference's ``--resume`` loads the begin_epoch checkpoint and
continues).  Batches are transferred (and mesh-scattered — the Module ctx
split) from the loader's prefetch thread via its ``put`` hook, so the
host→device copy overlaps the previous step's compute; loaders without
the hook fall back to a synchronous per-step ``shard_batch``.  Dispatch is
async — metrics are fetched one step late so the host never blocks the
device on the current step's scalars.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np

from mx_rcnn_tpu.config import Config
from mx_rcnn_tpu.logger import logger
from mx_rcnn_tpu.parallel.mesh import MeshPlan, shard_batch, shard_stacked_batch
from mx_rcnn_tpu.train.callback import Speedometer
from mx_rcnn_tpu.train.checkpoint import CheckpointManager
from mx_rcnn_tpu.train.metric import MetricBank
from mx_rcnn_tpu.train.train_step import (TrainState, create_train_state,
                                          make_multi_train_step,
                                          make_train_step)


def _reset_schedule_counts(opt_state):
    """Zero every ``count`` leaf in an optax state tree."""

    def reset(path, leaf):
        names = [getattr(e, "name", getattr(e, "key", "")) for e in path]
        if names and names[-1] == "count":
            return jax.numpy.zeros_like(leaf)
        return leaf

    return jax.tree_util.tree_map_with_path(reset, opt_state)


def fit(cfg: Config, model, params, train_loader,
        begin_epoch: int = 0, end_epoch: int = 10,
        plan: Optional[MeshPlan] = None,
        prefix: Optional[str] = None,
        graph: str = "end2end",
        seed: int = 0,
        frequent: int = 20,
        resume: bool = False,
        profile_dir: Optional[str] = None,
        steps_per_dispatch: int = 1,
        fixed_prefixes=None) -> TrainState:
    """Train ``model`` from ``params`` over ``train_loader`` epochs.

    train_loader: iterable over epochs yielding dict batches (numpy,
    leading axis = global batch), exposing ``steps_per_epoch`` and
    ``batch_size`` (loader.py contract).

    ``resume=True`` (reference ``--resume``) restores params + optimizer
    state + step from ``prefix`` at ``begin_epoch``.

    ``profile_dir``: capture an XProf/perfetto device trace of steps 3–8 of
    the first epoch (the reference has no profiling subsystem — SURVEY §5
    calls this the free win; view with xprof/tensorboard).

    ``steps_per_dispatch`` > 1 groups k consecutive loader batches and
    runs them through ONE dispatched ``lax.scan`` program
    (``make_multi_train_step``): amortizes per-dispatch overhead and lets
    XLA compile the step as a loop body — measured on v5-lite, the FPN
    step drops 21.95 → 17.85 ms inside the loop (better P2-conv layout;
    r4_tpu_session7.log).  Trade-offs at k>1: the loader's prefetch-
    thread ``put`` transfer overlap is disabled — each group is stacked
    on host and shipped synchronously (≈ k×10 MB; ~0.6 ms/step amortized
    on a PCIe-class link at k=8, well under the layout win, but on a
    slow link prefer k=1) — and groups must be shape-homogeneous, so
    every scale/orientation bucket change flushes the partial group
    through the single-step program (mixed-bucket epochs amortize
    less).  Math per step is identical (k=1 parity asserted; k>1 numeric
    parity vs a sequential driver is chaotic — discrete top-k/NMS flips
    amplify ulp differences — so k>1 is covered structurally);
    per-step rng differs from the k=1 stream (keys are fold_in of one
    dispatch key), and metrics arrive as k-step means at dispatch
    granularity.  Epoch remainders smaller than k run through the
    single-step program.
    """
    # thin-shard guard lives in make_train_step (mechanism level); eval's is
    # in Predictor.__init__ since it never builds a train step
    steps_per_epoch = train_loader.steps_per_epoch
    state, tx, mask = create_train_state(cfg, params, steps_per_epoch,
                                   begin_epoch=begin_epoch,
                                   fixed_prefixes=fixed_prefixes)
    ckpt = CheckpointManager(prefix) if prefix else None

    if resume:
        if ckpt is None:
            raise ValueError("resume=True requires a checkpoint prefix")
        abstract = jax.device_get(
            {"params": state.params, "opt_state": state.opt_state, "step": 0})
        r_params, r_opt, r_step = ckpt.load_epoch(
            begin_epoch, cfg, for_training=True, abstract_payload=abstract)
        if r_opt is not None:
            # the LR schedule was rebuilt with boundaries relative to
            # begin_epoch (make_lr_schedule), so its step count must restart
            # at 0 — only momentum buffers carry over.  Keeping the saved
            # global count would fire every LR drop begin_epoch epochs early.
            r_opt = _reset_schedule_counts(r_opt)
        state = TrainState(step=jax.numpy.asarray(r_step, jax.numpy.int32),
                           params=r_params,
                           opt_state=r_opt if r_opt is not None else state.opt_state)
        logger.info("resumed from %s epoch %d (step %d)", prefix, begin_epoch,
                    r_step)

    if plan is not None:
        # multi-host: create the mesh's cross-process communicator NOW,
        # while ranks are aligned — its lazy creation inside the first
        # step would race the ranks' compile-time skew against the Gloo
        # key-exchange deadline (see warm_collectives; no-op otherwise)
        from mx_rcnn_tpu.parallel.distributed import warm_collectives

        warm_collectives(plan)
    step_fn = make_train_step(model, tx, plan=plan, graph=graph,
                              trainable_mask=mask)
    k = int(steps_per_dispatch)
    multi_fn = (make_multi_train_step(model, tx, k, plan=plan, graph=graph,
                                      trainable_mask=mask) if k > 1 else None)
    # device double-buffering: loaders that expose a ``put`` hook transfer
    # each batch from their prefetch thread (overlapping the previous
    # step's compute) instead of synchronously inside step dispatch
    loader_puts = getattr(train_loader, "put", False) is None and k == 1
    if loader_puts:
        train_loader.put = ((lambda b: shard_batch(plan, b))
                            if plan is not None else jax.device_put)
    n_chips = plan.n_data if plan else 1
    # multi-host (parallel/distributed.py): every process runs this same
    # loop over the global mesh in lockstep; only process 0 speaks/saves.
    # The loader carries its num_parts/part_index row slice; metrics are
    # replicated outputs, so the fetch below is a local read everywhere.
    proc0 = jax.process_index() == 0
    speedo = Speedometer(train_loader.batch_size, frequent=frequent,
                         n_chips=n_chips)
    speedo_cb = speedo if proc0 else (lambda *a, **k: None)
    bank = MetricBank()
    key = jax.random.PRNGKey(seed)

    profiling = False
    for epoch in range(begin_epoch, end_epoch):
        bank.reset()
        speedo.reset()
        pending = None
        buf = []
        for i, batch in enumerate(train_loader):
            if profile_dir and epoch == begin_epoch:
                if i == min(3, steps_per_epoch - 1):
                    jax.profiler.start_trace(profile_dir)
                    profiling = True
                elif profiling and i == 8:
                    jax.block_until_ready(pending)
                    jax.profiler.stop_trace()
                    profiling = False
                    logger.info("wrote device trace to %s", profile_dir)
            key, sub = jax.random.split(key)
            if multi_fn is None:
                if plan is not None and not loader_puts:
                    batch = shard_batch(plan, batch)
                state, metrics = step_fn(state, batch, sub)
                pending = metrics
            else:
                # group k loader batches into one scanned dispatch; the
                # epoch remainder (< k) runs through the single-step fn.
                # Bucketed loaders emit one (scale, orientation) shape
                # per batch and shapes DIFFER across batches — a group
                # must be shape-homogeneous, so a bucket change flushes
                # the partial group through the single-step program
                if buf and buf[0]["images"].shape != batch["images"].shape:
                    for b in buf:
                        key, sub = jax.random.split(key)
                        if plan is not None:
                            b = shard_batch(plan, b)
                        state, metrics = step_fn(state, b, sub)
                    pending = metrics
                    buf = []
                buf.append(batch)
                if len(buf) == k:
                    stacked = jax.tree.map(lambda *xs: np.stack(xs), *buf)
                    stacked = (shard_stacked_batch(plan, stacked)
                               if plan is not None
                               else jax.device_put(stacked))
                    state, metrics = multi_fn(state, stacked, sub)
                    pending = metrics
                    buf = []
            # fetch metrics only at Speedometer cadence: a device→host scalar
            # read stalls the dispatch pipeline (and on tunneled devices costs
            # far more than a step), so per-step reads would serialize training
            if (i + 1) % frequent == 0 and pending is not None:
                bank.update(jax.device_get(pending))
                pending = None
            speedo_cb(epoch, i, bank.format())
        if buf:  # epoch remainder (< k) — flushed AFTER the loop so the
            # drain cannot depend on steps_per_epoch matching the
            # iterator's true yield count (wrapper loaders may differ)
            for b in buf:
                key, sub = jax.random.split(key)
                if plan is not None:
                    b = shard_batch(plan, b)
                state, metrics = step_fn(state, b, sub)
            pending = metrics
            buf = []
        if profiling:  # epoch shorter than the stop step: close the trace
            jax.block_until_ready(pending)
            jax.profiler.stop_trace()
            profiling = False
            logger.info("wrote device trace to %s", profile_dir)
        if pending is not None:
            bank.update(jax.device_get(pending))
        if proc0:
            logger.info("Epoch[%d] Train-%s", epoch,
                        bank.format().replace("\t", " Train-"))
        if ckpt is not None:
            # multi-host: EVERY rank calls save — orbax's CheckpointManager
            # runs its own cross-process barriers inside save() and writes
            # from the primary host only (ranks must share one prefix on a
            # shared filesystem).  Gating this on rank 0 deadlocks orbax's
            # sync_global_devices (found by the two-process CLI drive).
            # State leaves are replicated (DP) so device_get is local.
            ckpt.save_epoch(epoch + 1, state.params, cfg,
                            opt_state=state.opt_state,
                            step=int(jax.device_get(state.step)))
    if jax.process_count() > 1:
        # align ranks before returning: after the last collective nothing
        # else synchronizes them, and a rank that exits the process much
        # later than its peers trips the jax.distributed SHUTDOWN barrier
        # deadline under load (observed with Gloo on a contended host)
        from mx_rcnn_tpu.parallel.distributed import sync

        sync("fit_end")
    return state
