"""``fit`` — the reference's ``Module.fit`` call in ``train_net``
(train_end2end.py), as an explicit loop over the jitted step.

Responsibilities mirrored: per-epoch data iteration, composite metrics,
Speedometer batch-end callback, do_checkpoint epoch-end callback, resume
(the reference's ``--resume`` loads the begin_epoch checkpoint and
continues).  Batches are transferred (and mesh-scattered — the Module ctx
split) from the loader's prefetch thread via its ``put`` hook, so the
host→device copy overlaps the previous step's compute; loaders without
the hook fall back to a synchronous per-step ``shard_batch``.  Dispatch is
async — metrics are fetched one step late so the host never blocks the
device on the current step's scalars.
"""

from __future__ import annotations

import contextlib
import time
from typing import Optional

import jax
import numpy as np

from mx_rcnn_tpu import telemetry
from mx_rcnn_tpu.config import Config
from mx_rcnn_tpu.logger import logger
from mx_rcnn_tpu.parallel.mesh import MeshPlan, shard_batch, shard_stacked_batch
from mx_rcnn_tpu.train.callback import Speedometer
from mx_rcnn_tpu.train.checkpoint import CheckpointManager
from mx_rcnn_tpu.train.metric import MetricBank
from mx_rcnn_tpu.train.resilience import (NonFiniteLossError,
                                          PreemptionGuard, ResilienceOptions,
                                          dump_nan_diagnostics,
                                          nan_injection_step,
                                          preemption_agreed)
from mx_rcnn_tpu.train.train_step import (TrainState, create_train_state,
                                          make_multi_train_step,
                                          make_train_step)


def _runtime_owned(tree):
    """Deep-copy restored host (numpy) leaves into runtime-owned device
    buffers before they reach the donated step function.

    Orbax restores into numpy arrays.  On the CPU backend jax converts a
    numpy argument zero-copy — the device buffer aliases memory that numpy
    still owns — and ``donate_argnums`` then lets XLA reuse that aliased
    input buffer for the step's OUTPUT params.  The moment the restored
    tree is dropped (the old ``TrainState`` dies at rebind), numpy frees
    the memory under the live output, which then reads back as heap
    garbage.  An explicit device copy breaks the alias; every restore path
    that feeds ``TrainState`` must go through this."""
    return jax.tree.map(
        lambda a: jax.numpy.array(a) if isinstance(a, np.ndarray) else a,
        tree)


# Tuned steady state must hide the input pipeline: the fraction of epoch
# wall spent blocked on the loader beyond this trips a telemetry counter
# + flight-recorder-visible meta event (train/pipeline.py sweeps use the
# same threshold to mark a cell as loader-bound).
LOADER_WAIT_TRIPWIRE_FRAC = 0.10


def _make_group_wrap(k: int, plan: Optional[MeshPlan], prep=None):
    """Producer-thread group assembly for ``steps_per_dispatch=k``.

    Returns a generator transform (the loader ``wrap`` hook): stacks k
    consecutive shape-homogeneous host batches and ships the group
    (``shard_stacked_batch``) FROM THE PREFETCH THREAD, so k>1 keeps the
    same transfer/compute overlap the k=1 ``put`` hook provides.  A scale/
    orientation bucket change flushes the partial group as single sharded
    batches (groups must be shape-homogeneous — one compiled program per
    bucket), as does the epoch remainder.  Items arrive at the consumer
    tagged ``(kind, n_batches, on_device_data)``.
    """
    if prep is not None:  # device-side preprocessing (plan is None here)
        put1, putk = prep.put, prep.put_stacked
    else:
        put1 = ((lambda b: shard_batch(plan, b)) if plan is not None
                else jax.device_put)
        putk = ((lambda s: shard_stacked_batch(plan, s)) if plan is not None
                else jax.device_put)

    def wrap(gen):
        buf = []

        def flush():
            for b in buf:
                yield ("single", 1, put1(b))
            buf.clear()

        for batch in gen:
            if buf and buf[0]["images"].shape != batch["images"].shape:
                yield from flush()
            buf.append(batch)
            if len(buf) == k:
                stacked = jax.tree.map(lambda *xs: np.stack(xs), *buf)
                buf.clear()
                yield ("group", k, putk(stacked))
        yield from flush()

    return wrap


def _reset_schedule_counts(opt_state, value: int = 0):
    """Set every ``count`` leaf in an optax state tree to ``value`` — the
    number of optimizer updates already taken against the CURRENT schedule
    basis: 0 for an epoch-boundary resume (the schedule is rebuilt relative
    to ``begin_epoch``), ``consumed`` for a mid-epoch step resume (rebuilt
    relative to that epoch, with ``consumed`` steps already inside it)."""

    def reset(path, leaf):
        names = [getattr(e, "name", getattr(e, "key", "")) for e in path]
        if names and names[-1] == "count":
            return jax.numpy.full_like(leaf, value)
        return leaf

    return jax.tree_util.tree_map_with_path(reset, opt_state)


def fit(cfg: Config, model, params, train_loader,
        begin_epoch: int = 0, end_epoch: int = 10,
        plan: Optional[MeshPlan] = None,
        prefix: Optional[str] = None,
        graph: str = "end2end",
        seed: int = 0,
        frequent: int = 20,
        resume: bool = False,
        profile_dir: Optional[str] = None,
        telemetry_dir: Optional[str] = None,
        steps_per_dispatch: int = 1,
        fixed_prefixes=None,
        resilience: Optional[ResilienceOptions] = None) -> TrainState:
    """Train ``model`` from ``params`` over ``train_loader`` epochs.

    train_loader: iterable over epochs yielding dict batches (numpy,
    leading axis = global batch), exposing ``steps_per_epoch`` and
    ``batch_size`` (loader.py contract).

    ``resume=True`` (reference ``--resume``) restores params + optimizer
    state + step from ``prefix`` at ``begin_epoch``.

    ``profile_dir``: capture an XProf/perfetto device trace of steps 3–8 of
    the first epoch (the reference has no profiling subsystem — SURVEY §5
    calls this the free win; view with xprof/tensorboard).

    ``telemetry_dir``: stream structured run telemetry there (JSONL events
    + an end-of-run summary JSON — see ``mx_rcnn_tpu/telemetry``): the
    per-step wall-time breakdown (loader-wait / dispatch / metric-fetch
    stall / checkpoint-save), epoch wall time, and a recompile counter
    keyed on (program, batch bucket shape) so mixed-bucket epochs show
    their true compile cost.  Per-rank event files on multi-host; the
    summary is written by process 0 only (the ``profile_dir`` rank-split
    contract).  When a sink is already active (a driver configured one),
    it is reused and left open.  Disabled, every probe is a no-op sink
    call — one attribute check, zero allocations.

    ``steps_per_dispatch`` > 1 groups k consecutive loader batches and
    runs them through ONE dispatched ``lax.scan`` program
    (``make_multi_train_step``): amortizes per-dispatch overhead and lets
    XLA compile the step as a loop body — measured on v5-lite, the FPN
    step drops 21.95 → 17.85 ms inside the loop (better P2-conv layout;
    r4_tpu_session7.log).  On loaders exposing the ``wrap`` hook
    (AnchorLoader/ROIIter), group stacking AND the host→device transfer
    happen on the loader's prefetch thread (``_make_group_wrap``), so k>1
    keeps the same transfer/compute overlap as k=1; loaders without the
    hook fall back to consumer-side grouping with synchronous transfer.
    Groups must be shape-homogeneous, so every scale/orientation bucket
    change flushes the partial group through the single-step program
    (mixed-bucket epochs amortize less).  Math per step is identical
    (k=1 parity asserted; k>1 numeric parity vs a sequential driver is
    chaotic — discrete top-k/NMS flips amplify ulp differences — so k>1
    is covered structurally); per-step rng differs from the k=1 stream
    (keys are fold_in of one dispatch key), and metrics arrive as k-step
    means at dispatch granularity.  Epoch remainders smaller than k run
    through the single-step program.

    ``resilience`` (``ResilienceOptions``; all knobs off by default — a
    plain call compiles the exact same step program as before):

    * ``save_every_n_steps``: mid-epoch step checkpoints under
      ``{prefix}/steps`` at that batch cadence (always on a dispatch
      boundary; under an active NaN sentinel the due save forces a metric
      fetch first, so a step checkpoint is only ever written from
      verified-finite state).
    * ``auto_resume``: pick the furthest checkpoint — step or epoch —
      under ``prefix`` and continue from it.  Mid-epoch resume is EXACT
      on seed-deterministic loaders: the loader's RNG is advanced past
      the completed epochs (``advance_epochs``) and the resumed epoch's
      plan is generated in full then sliced (``skip_next``), the trainer
      RNG key is restored from the checkpoint, and the LR schedule counts
      restart at ``consumed`` against the epoch-rebased schedule — so the
      tail of the run is batch-for-batch identical to the uninterrupted
      one (k=1; k>1 regrouping at the resume point may differ around
      bucket flushes).
    * ``nan_policy``: the on-device all-finite sentinel is checked at
      every metric fetch.  ``halt`` dumps diagnostics and raises
      ``NonFiniteLossError``; ``skip`` counts (the step discarded the
      non-finite update in-graph, params were never poisoned);
      ``rollback`` restores the latest step checkpoint in-memory and
      keeps consuming the loader (the poisoned stretch contributes
      nothing; schedule counts resume from the checkpoint, so the LR
      step count lags by the rolled-back stretch — accepted).
    * SIGTERM/SIGINT during the epoch loop request a save at the next
      dispatch boundary and a clean return (``train/preempted``); ranks
      agree via allgather at lockstep fetch boundaries so orbax's save
      barriers never deadlock.
    """
    # thin-shard guard lives in make_train_step (mechanism level); eval's is
    # in Predictor.__init__ since it never builds a train step
    steps_per_epoch = train_loader.steps_per_epoch
    tel = telemetry.get()
    owns_tel = False
    if telemetry_dir and not tel.enabled:
        tel = telemetry.configure(
            telemetry_dir, rank=jax.process_index(),
            world=jax.process_count(),
            run_meta={"driver": "fit", "graph": graph,
                      "steps_per_dispatch": int(steps_per_dispatch),
                      "batch_size": train_loader.batch_size,
                      "steps_per_epoch": steps_per_epoch})
        owns_tel = True
    res = resilience if resilience is not None else ResilienceOptions()
    ckpt = (CheckpointManager(prefix, io_retries=res.max_io_retries,
                              io_backoff_s=res.io_backoff_s)
            if prefix else None)

    # auto-resume resolves the true starting position BEFORE the train
    # state exists: the LR schedule's boundaries are built relative to
    # begin_epoch, so the resolved epoch must feed make_optimizer
    begin0 = begin_epoch  # caller's begin (= the interrupted run's begin)
    step_resume = None  # (epoch, consumed) when resuming mid-epoch
    if res.auto_resume:
        if ckpt is None:
            raise ValueError("auto_resume requires a checkpoint prefix")
        point = ckpt.latest_resume_point()
        if point is None:
            logger.info("auto-resume: no checkpoint under %s — fresh start",
                        prefix)
        else:
            kind, r_ep, r_cons = point
            begin_epoch = r_ep
            if kind == "epoch":
                resume = True  # the legacy epoch-resume path below
                logger.info("auto-resume: epoch checkpoint %d under %s",
                            r_ep, prefix)
            else:
                step_resume = (r_ep, r_cons)
                logger.info("auto-resume: step checkpoint (epoch %d, "
                            "batch %d) under %s", r_ep, r_cons, prefix)

    state, tx, mask = create_train_state(cfg, params, steps_per_epoch,
                                   begin_epoch=begin_epoch,
                                   fixed_prefixes=fixed_prefixes)

    restored_key = None
    if resume:
        if ckpt is None:
            raise ValueError("resume=True requires a checkpoint prefix")
        abstract = jax.device_get(
            {"params": state.params, "opt_state": state.opt_state, "step": 0})
        r_params, r_opt, r_step = ckpt.load_epoch(
            begin_epoch, cfg, for_training=True, abstract_payload=abstract)
        if r_opt is not None:
            # the LR schedule was rebuilt with boundaries relative to
            # begin_epoch (make_lr_schedule), so its step count must restart
            # at 0 — only momentum buffers carry over.  Keeping the saved
            # global count would fire every LR drop begin_epoch epochs early.
            r_opt = _reset_schedule_counts(r_opt)
        state = TrainState(step=jax.numpy.asarray(r_step, jax.numpy.int32),
                           params=_runtime_owned(r_params),
                           opt_state=(_runtime_owned(r_opt)
                                      if r_opt is not None
                                      else state.opt_state))
        logger.info("resumed from %s epoch %d (step %d)", prefix, begin_epoch,
                    r_step)
    elif step_resume is not None:
        r_ep, r_cons = step_resume
        abstract = {"params": jax.device_get(state.params),
                    "opt_state": jax.device_get(state.opt_state),
                    "step": 0, "epoch": 0, "consumed": 0,
                    "rng_key": np.zeros((2,), np.uint32)}
        payload = ckpt.load_step_checkpoint(r_ep, r_cons,
                                            abstract_payload=abstract)
        r_opt = payload.get("opt_state")
        if r_opt is not None:
            # schedule rebuilt relative to r_ep; r_cons updates already
            # happened inside that epoch (see _reset_schedule_counts)
            r_opt = _reset_schedule_counts(r_opt, value=r_cons)
        state = TrainState(
            step=jax.numpy.asarray(payload["step"], jax.numpy.int32),
            params=_runtime_owned(payload["params"]),
            opt_state=(_runtime_owned(r_opt) if r_opt is not None
                       else state.opt_state))
        restored_key = payload.get("rng_key")
        logger.info("resumed mid-epoch from %s (epoch %d, batch %d, "
                    "step %d)", prefix, r_ep, r_cons, int(payload["step"]))

    if plan is not None:
        # multi-host: create the mesh's cross-process communicator NOW,
        # while ranks are aligned — its lazy creation inside the first
        # step would race the ranks' compile-time skew against the Gloo
        # key-exchange deadline (see warm_collectives; no-op otherwise)
        from mx_rcnn_tpu.parallel.distributed import warm_collectives

        warm_collectives(plan)
    step_fn = make_train_step(model, tx, plan=plan, graph=graph,
                              trainable_mask=mask, sentinel=res.sentinel,
                              skip_nonfinite=res.skip_nonfinite)
    k = int(steps_per_dispatch)
    multi_fn = (make_multi_train_step(model, tx, k, plan=plan, graph=graph,
                                      trainable_mask=mask,
                                      sentinel=res.sentinel,
                                      skip_nonfinite=res.skip_nonfinite)
                if k > 1 else None)
    # recompile tracking + device-prep program home: jit caches one
    # program per (step fn, bucket shape), so the first dispatch of each
    # pair is the compile.  The program registry mirrors that cache (fit
    # builds fresh step fns, so per-fit is exact), makes mixed-bucket
    # epochs show their true compile cost in the telemetry stream, and —
    # with a persistent program cache configured — accounts each first
    # dispatch as an AOT disk load vs an XLA compile.  Built BEFORE the
    # loader hooks so the device_prep program registers alongside the
    # step programs.
    from mx_rcnn_tpu.compile import ProgramRegistry

    registry = ProgramRegistry(cfg, dtype=cfg.tpu.COMPUTE_DTYPE
                               if cfg.tpu.COMPUTE_DTYPE in
                               ("float32", "bfloat16") else "float32",
                               plan=plan)

    # device-side preprocessing: when the config asks for it, the loader
    # is already emitting raw uint8 batches (+ raw_hw/flip sidecar keys)
    # and a DevicePrep hook must stand where device_put used to — raw
    # bytes reaching the step fn would be garbage.  Mesh plans raise in
    # maybe_device_prep (drivers strip the flag with a warning first).
    from mx_rcnn_tpu.data.device_prep import maybe_device_prep

    prep = maybe_device_prep(cfg, registry=registry, plan=plan)

    # device double-buffering: loaders that expose a ``put`` hook transfer
    # each batch from their prefetch thread (overlapping the previous
    # step's compute) instead of synchronously inside step dispatch; at
    # k>1 the ``wrap`` hook moves the whole group assembly (stacking +
    # stacked transfer) onto that thread instead.  fit OWNS both hooks:
    # they are (re)set every call so a loader reused across fit calls
    # with a different k/plan never runs a stale hook (a leftover group
    # wrap would feed tagged tuples to the k=1 path, and a leftover put
    # would re-transfer the wrap's already-on-device items).
    loader_wraps = False
    if hasattr(train_loader, "wrap"):
        train_loader.wrap = (_make_group_wrap(k, plan, prep=prep)
                             if k > 1 else None)
        loader_wraps = k > 1
    loader_puts = False
    if hasattr(train_loader, "put"):
        if k == 1 and not loader_wraps:
            if prep is not None:
                train_loader.put = prep.put
            else:
                train_loader.put = ((lambda b: shard_batch(plan, b))
                                    if plan is not None else jax.device_put)
            loader_puts = True
        else:  # the wrap transfers its own items — put must stay out
            train_loader.put = None
    if plan is not None and jax.process_count() > 1:
        # diagnose loader-partition misconfigurations at the contract
        # level, before they surface as an opaque jit shape mismatch: a
        # loader left at num_parts=1 on a multi-process mesh would yield a
        # self-consistent but process_count×-sized "global" batch
        # (round-4 advisor finding; the CLI drivers check this too, but
        # direct fit() callers bypassed them)
        from mx_rcnn_tpu.parallel.distributed import assert_loader_partition

        if hasattr(train_loader, "num_parts"):
            assert_loader_partition(plan, train_loader.batch_size,
                                    train_loader.num_parts,
                                    train_loader.part_index)
    n_chips = plan.n_data if plan else 1
    # multi-host (parallel/distributed.py): every process runs this same
    # loop over the global mesh in lockstep; only process 0 speaks/saves.
    # The loader carries its num_parts/part_index row slice; metrics are
    # replicated outputs, so the fetch below is a local read everywhere.
    proc0 = jax.process_index() == 0
    speedo = Speedometer(train_loader.batch_size, frequent=frequent,
                         n_chips=n_chips)
    speedo_cb = speedo if proc0 else (lambda *a, **k: None)
    bank = MetricBank()
    key = jax.random.PRNGKey(seed)
    if restored_key is not None:
        # the trainer key as it was at the interruption's save boundary:
        # the resumed per-step key stream continues bit-exactly
        key = jax.numpy.asarray(restored_key)

    # auto-resume loader fast-forward: burn the completed epochs' RNG
    # draws, then arm the resumed epoch's batch skip (the plan is drawn in
    # full and sliced, so the tail is identical to the uninterrupted run)
    if res.auto_resume and begin_epoch > begin0:
        if hasattr(train_loader, "advance_epochs"):
            train_loader.advance_epochs(begin_epoch - begin0)
        else:
            logger.warning("auto-resume: loader has no advance_epochs(); "
                           "the resumed epochs' schedules will replay the "
                           "loader's first-epoch RNG draws")
    if step_resume is not None:
        if not hasattr(train_loader, "skip_next"):
            raise ValueError(
                "auto_resume hit a mid-epoch step checkpoint but the "
                "loader has no skip_next() — cannot fast-forward "
                f"{type(train_loader).__name__} to batch {step_resume[1]}")
        train_loader.skip_next(step_resume[1])

    nan_at = nan_injection_step()  # env fault injection (fault_smoke.sh)

    profiling = False
    profiled = False
    if profile_dir and jax.process_count() > 1:
        # one trace dir per rank: on a shared filesystem the ranks' trace
        # writers would collide in a single directory (round-4 advisor
        # finding)
        import os

        profile_dir = os.path.join(profile_dir,
                                   f"rank{jax.process_index()}")
    def note_dispatch(fn_kind, shape):
        if registry.note_dispatch(f"train_{fn_kind}", shape):
            tel.counter("train/recompile")
            tel.meta("recompile", program=fn_kind, shape=list(shape))

    guard = PreemptionGuard()
    preempted = False
    last_saved = None  # (epoch, consumed) of the last written step ckpt

    def save_step_ckpt(ep, cur):
        """Step checkpoint of the CURRENT state (idempotent per position —
        a preemption landing on a just-saved boundary must not re-save
        into the same orbax key)."""
        nonlocal last_saved
        if last_saved == (ep, cur):
            return
        ckpt.save_step(ep, cur, state.params, cfg,
                       opt_state=state.opt_state,
                       step=int(jax.device_get(state.step)), rng_key=key)
        last_saved = (ep, cur)

    def handle_nonfinite(ep, cur, fetched):
        """The sentinel tripped at a fetch boundary — apply ``nan_policy``.
        Returns True when state was rolled back (the caller must suppress
        this boundary's step save)."""
        nonlocal state
        tel.counter("train/nan_detected")
        tel.meta("nan_detected", epoch=int(ep), consumed=int(cur),
                 policy=res.nan_policy)
        tel.dump_flight("nan_detected", epoch=int(ep), consumed=int(cur),
                        policy=res.nan_policy)
        logger.warning("non-finite loss/gradients detected (epoch %d, "
                       "batch %d, policy=%s)", ep, cur, res.nan_policy)
        if res.nan_policy == "skip":
            # the in-graph guard already discarded the bad update(s);
            # params were never poisoned — count and continue
            tel.counter("train/nan_skipped")
            return False
        if res.nan_policy == "halt":
            path = dump_nan_diagnostics(
                telemetry_dir or prefix, ep, cur,
                int(jax.device_get(state.step)), fetched)
            raise NonFiniteLossError(
                f"non-finite loss/gradients at epoch {ep}, batch {cur} "
                f"(policy=halt)"
                + (f"; diagnostics dumped to {path}" if path else ""))
        # rollback: restore the latest step checkpoint in-memory and keep
        # consuming the loader — the poisoned stretch contributes nothing
        # (schedule counts resume from the checkpoint, so the LR step
        # count lags by the rolled-back stretch; accepted)
        point = ckpt.latest_step_checkpoint() if ckpt is not None else None
        if point is None:
            raise NonFiniteLossError(
                f"non-finite loss/gradients at epoch {ep}, batch {cur} "
                f"(policy=rollback) with no step checkpoint to roll back "
                f"to — set save_every_n_steps (prefix: {prefix or 'none'})")
        g_ep, g_cons = point
        abstract = {"params": jax.device_get(state.params),
                    "opt_state": jax.device_get(state.opt_state),
                    "step": 0, "epoch": 0, "consumed": 0,
                    "rng_key": np.zeros((2,), np.uint32)}
        payload = ckpt.load_step_checkpoint(g_ep, g_cons,
                                            abstract_payload=abstract)
        r_opt = payload.get("opt_state")
        state = TrainState(
            step=jax.numpy.asarray(payload["step"], jax.numpy.int32),
            params=_runtime_owned(payload["params"]),
            opt_state=(_runtime_owned(r_opt) if r_opt is not None
                       else state.opt_state))
        tel.counter("train/nan_rollback")
        logger.warning("rolled back to step checkpoint (epoch %d, batch "
                       "%d)", g_ep, g_cons)
        return True

    with (guard if res.enabled else contextlib.nullcontext()):
      for epoch in range(begin_epoch, end_epoch):
        bank.reset()
        speedo.reset()
        pending = None
        buf = []
        # loader batches dispatched so far (a group item advances this by
        # k; profiling and metric cadence count batches).  A mid-epoch
        # resume starts the counters at the restored position — the
        # fast-forwarded loader yields exactly the tail.
        start_consumed = (step_resume[1]
                          if step_resume and epoch == begin_epoch else 0)
        consumed = start_consumed
        last_fetch = start_consumed
        last_step_save = start_consumed
        start_at = min(3, steps_per_epoch - 1)
        # epoch wall-time breakdown, telemetry-or-not (the epoch-end log
        # line reports wall/loader-wait either way; two perf_counter reads
        # per item is noise next to a dispatch)
        ep_t0 = time.perf_counter()
        loader_wait_s = 0.0
        it = iter(train_loader)
        while True:
            t_wait = time.perf_counter()
            try:
                item = next(it)
            except StopIteration:
                break
            dt_wait = time.perf_counter() - t_wait
            loader_wait_s += dt_wait
            tel.add("train/loader_wait", dt_wait)
            if (nan_at is not None and consumed == nan_at
                    and isinstance(item, dict)):
                # env fault injection (script/fault_smoke.sh): poison this
                # batch's images so the step's loss/grads go non-finite
                item = dict(item)
                item["images"] = item["images"] * np.float32("nan")
                logger.warning("fault injection: NaN images at batch %d "
                               "(MXR_FAULT_NAN_STEP)", consumed)
            if profile_dir and epoch == begin_epoch and not profiled:
                if not profiling and consumed >= start_at:
                    jax.profiler.start_trace(profile_dir)
                    profiling = True
                elif profiling and consumed >= 8:
                    # fence on state, not pending: a same-step cadence
                    # fetch can have consumed pending (cleared to None),
                    # and block_until_ready(None) returns immediately —
                    # truncating the trace tail.  state is always the
                    # latest dispatched step's output
                    jax.block_until_ready(state)
                    jax.profiler.stop_trace()
                    profiling = False
                    profiled = True
                    logger.info("wrote device trace to %s", profile_dir)
            t_disp = time.perf_counter()
            key, sub = jax.random.split(key)
            n_b = 1
            if loader_wraps:
                # producer-thread group assembly (_make_group_wrap):
                # items arrive tagged, already stacked AND on device —
                # the transfer overlapped the previous step's compute
                kind, n_b, data = item
                note_dispatch(kind, data["images"].shape)
                state, metrics = (multi_fn if kind == "group"
                                  else step_fn)(state, data, sub)
                pending = metrics
            elif multi_fn is None:
                batch = item
                if prep is not None and not loader_puts:
                    # loader without a put hook under device prep: the
                    # batch is still raw uint8 + sidecars — prep it here
                    # (synchronous; only hook-less wrapper loaders hit it)
                    batch = prep.put(batch)
                note_dispatch("single", batch["images"].shape)
                if plan is not None and not loader_puts:
                    batch = shard_batch(plan, batch)
                state, metrics = step_fn(state, batch, sub)
                pending = metrics
            else:
                # consumer-side fallback for loaders without the ``wrap``
                # hook: group k batches into one scanned dispatch (epoch
                # remainder < k runs through the single-step fn; bucket
                # changes flush the partial group — groups must be
                # shape-homogeneous)
                batch = item
                if buf and buf[0]["images"].shape != batch["images"].shape:
                    for b in buf:
                        key, sub = jax.random.split(key)
                        if prep is not None:
                            b = prep.put(b)
                        elif plan is not None:
                            b = shard_batch(plan, b)
                        note_dispatch("single", b["images"].shape)
                        state, metrics = step_fn(state, b, sub)
                    pending = metrics
                    buf = []
                buf.append(batch)
                if len(buf) == k:
                    stacked = jax.tree.map(lambda *xs: np.stack(xs), *buf)
                    if prep is not None:
                        stacked = prep.put_stacked(stacked)
                    elif plan is not None:
                        stacked = shard_stacked_batch(plan, stacked)
                    else:
                        stacked = jax.device_put(stacked)
                    note_dispatch("group", stacked["images"].shape)
                    state, metrics = multi_fn(state, stacked, sub)
                    pending = metrics
                    buf = []
            dt_disp = time.perf_counter() - t_disp
            tel.add("train/dispatch", dt_disp, n=n_b)
            # per-step latency distribution (dispatch wall over the group,
            # amortized per step) — the trainer's feed into the histogram
            # layer, so p99 step time is scrapeable live
            tel.observe("train/step_time", dt_disp / max(n_b, 1))
            cur = consumed + n_b
            # fetch metrics only at Speedometer cadence: a device→host scalar
            # read stalls the dispatch pipeline (and on tunneled devices costs
            # far more than a step), so per-step reads would serialize
            # training.  A due step save under an active sentinel forces the
            # fetch first, so checkpoints only capture verified-finite state;
            # saves happen only with ``buf`` empty (pulled-not-dispatched
            # batches would desync the saved position from the state).
            save_due = (res.save_every_n_steps > 0 and ckpt is not None
                        and not buf
                        and cur - last_step_save >= res.save_every_n_steps)
            fetch_due = (cur - last_fetch >= frequent
                         or (save_due and res.sentinel))
            if fetch_due and pending is not None:
                with tel.span("train/fetch_stall"):
                    fetched = jax.device_get(pending)
                pending = None
                last_fetch = cur
                finite = fetched.pop("all_finite", None)
                bank.update(fetched)
                if finite is not None and finite < 1.0:
                    if handle_nonfinite(epoch, cur, fetched):
                        save_due = False  # just restored FROM a checkpoint
                        last_step_save = cur
            if save_due:
                save_step_ckpt(epoch, cur)
                last_step_save = cur
            # preemption: single-process reads the flag at every boundary;
            # multi-process must agree at deterministic lockstep points —
            # the fetch boundaries — or a rank saving alone would deadlock
            # orbax's cross-process barriers
            if jax.process_count() > 1:
                want_stop = (preemption_agreed(guard.requested)
                             if fetch_due else False)
            else:
                want_stop = guard.requested
            if want_stop and not buf:
                if ckpt is not None:
                    save_step_ckpt(epoch, cur)
                tel.counter("train/preempted")
                # flight-record the shutdown at the safe boundary (the
                # signal handler's own dump has no step context)
                tel.dump_flight("preempted", epoch=epoch,
                                consumed=int(cur))
                preempted = True
            for j in range(n_b):
                speedo_cb(epoch, consumed + j, bank.format())
            consumed += n_b
            if preempted:
                break
        if buf:  # epoch remainder (< k) — flushed AFTER the loop so the
            # drain cannot depend on steps_per_epoch matching the
            # iterator's true yield count (wrapper loaders may differ)
            t_disp = time.perf_counter()
            for b in buf:
                key, sub = jax.random.split(key)
                if prep is not None:
                    b = prep.put(b)
                elif plan is not None:
                    b = shard_batch(plan, b)
                note_dispatch("single", b["images"].shape)
                state, metrics = step_fn(state, b, sub)
            pending = metrics
            dt_disp = time.perf_counter() - t_disp
            tel.add("train/dispatch", dt_disp, n=len(buf))
            tel.observe("train/step_time", dt_disp / max(len(buf), 1))
            buf = []
        if profiling:  # epoch shorter than the stop step: close the trace
            jax.block_until_ready(state)  # pending may be fetched-and-None
            jax.profiler.stop_trace()
            profiling = False
            logger.info("wrote device trace to %s", profile_dir)
        if pending is not None:
            with tel.span("train/fetch_stall"):
                fetched = jax.device_get(pending)
            finite = fetched.pop("all_finite", None)
            bank.update(fetched)
            if finite is not None and finite < 1.0:
                handle_nonfinite(epoch, consumed, fetched)
        ep_wall = time.perf_counter() - ep_t0
        tel.add("train/epoch", ep_wall)
        tel.counter("train/steps", consumed - start_consumed)
        # tuned-pipeline tripwire: a saturated input pipeline keeps the
        # consumer's loader wait ≈ 0; spending more than the threshold
        # fraction of epoch wall blocked on the loader means the tuned
        # (k, workers, prefetch) cell no longer hides host work on this
        # box — surfaced as a counter + meta event so perf triage and the
        # pipeline sweep read the same signal.  Needs a few steps of
        # signal: a 1–2 step epoch is all warmup, not steady state.
        ep_steps = consumed - start_consumed
        wait_frac = loader_wait_s / max(ep_wall, 1e-9)
        if ep_steps >= 8 and wait_frac > LOADER_WAIT_TRIPWIRE_FRAC:
            tel.counter("train/loader_wait_tripwire")
            tel.meta("loader_wait_tripwire", epoch=epoch,
                     frac=round(wait_frac, 4),
                     loader_wait_s=round(loader_wait_s, 3),
                     wall_s=round(ep_wall, 3))
            if proc0:
                logger.warning(
                    "input pipeline not saturated: loader_wait %.1fs is "
                    "%.0f%% of epoch wall (threshold %.0f%%) — retune with "
                    "bench.py --mode pipeline --auto-tune",
                    loader_wait_s, 100 * wait_frac,
                    100 * LOADER_WAIT_TRIPWIRE_FRAC)
        if proc0:
            # wall + loader-wait on the one-line epoch summary: single-log
            # triage of "slow epoch — device or input pipeline?" without
            # opening the JSONL
            logger.info("Epoch[%d] Train-%s\tWall=%.1fs LoaderWait=%.1fs",
                        epoch, bank.format().replace("\t", " Train-"),
                        ep_wall, loader_wait_s)
        if preempted:
            if proc0:
                logger.info("preemption requested — exiting cleanly after "
                            "step checkpoint (epoch %d, batch %d); rerun "
                            "with auto_resume to continue", epoch, consumed)
            break
        if ckpt is not None:
            # multi-host: EVERY rank calls save — orbax's CheckpointManager
            # runs its own cross-process barriers inside save() and writes
            # from the primary host only (ranks must share one prefix on a
            # shared filesystem).  Gating this on rank 0 deadlocks orbax's
            # sync_global_devices (found by the two-process CLI drive).
            # State leaves are replicated (DP) so device_get is local.
            with tel.span("train/checkpoint_save"):
                ckpt.save_epoch(epoch + 1, state.params, cfg,
                                opt_state=state.opt_state,
                                step=int(jax.device_get(state.step)))
    if jax.process_count() > 1:
        # align ranks before returning: after the last collective nothing
        # else synchronizes them, and a rank that exits the process much
        # later than its peers trips the jax.distributed SHUTDOWN barrier
        # deadline under load (observed with Gloo on a contended host)
        from mx_rcnn_tpu.parallel.distributed import sync

        sync("fit_end")
    if owns_tel:
        # every rank streams its own event file; only process 0 writes the
        # aggregated summary (the profile_dir rank-split contract) — the
        # cross-rank fold is scripts/telemetry_report.py's job
        if proc0:
            path = tel.write_summary()
            logger.info("wrote telemetry summary to %s", path)
        telemetry.shutdown()
    return state
