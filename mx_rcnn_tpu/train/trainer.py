"""``fit`` — the reference's ``Module.fit`` call in ``train_net``
(train_end2end.py), as an explicit loop over the jitted step.

Responsibilities mirrored: per-epoch data iteration, composite metrics,
Speedometer batch-end callback, do_checkpoint epoch-end callback, resume
(the reference's ``--resume`` loads the begin_epoch checkpoint and
continues).  Batches are transferred (and mesh-scattered — the Module ctx
split) from the loader's prefetch thread via its ``put`` hook, so the
host→device copy overlaps the previous step's compute; loaders without
the hook fall back to a synchronous per-step ``shard_batch``.  Dispatch is
async — metrics are fetched one step late so the host never blocks the
device on the current step's scalars.
"""

from __future__ import annotations

import time
from typing import Optional

import jax
import numpy as np

from mx_rcnn_tpu import telemetry
from mx_rcnn_tpu.config import Config
from mx_rcnn_tpu.logger import logger
from mx_rcnn_tpu.parallel.mesh import MeshPlan, shard_batch, shard_stacked_batch
from mx_rcnn_tpu.train.callback import Speedometer
from mx_rcnn_tpu.train.checkpoint import CheckpointManager
from mx_rcnn_tpu.train.metric import MetricBank
from mx_rcnn_tpu.train.train_step import (TrainState, create_train_state,
                                          make_multi_train_step,
                                          make_train_step)


def _make_group_wrap(k: int, plan: Optional[MeshPlan]):
    """Producer-thread group assembly for ``steps_per_dispatch=k``.

    Returns a generator transform (the loader ``wrap`` hook): stacks k
    consecutive shape-homogeneous host batches and ships the group
    (``shard_stacked_batch``) FROM THE PREFETCH THREAD, so k>1 keeps the
    same transfer/compute overlap the k=1 ``put`` hook provides.  A scale/
    orientation bucket change flushes the partial group as single sharded
    batches (groups must be shape-homogeneous — one compiled program per
    bucket), as does the epoch remainder.  Items arrive at the consumer
    tagged ``(kind, n_batches, on_device_data)``.
    """
    put1 = ((lambda b: shard_batch(plan, b)) if plan is not None
            else jax.device_put)
    putk = ((lambda s: shard_stacked_batch(plan, s)) if plan is not None
            else jax.device_put)

    def wrap(gen):
        buf = []

        def flush():
            for b in buf:
                yield ("single", 1, put1(b))
            buf.clear()

        for batch in gen:
            if buf and buf[0]["images"].shape != batch["images"].shape:
                yield from flush()
            buf.append(batch)
            if len(buf) == k:
                stacked = jax.tree.map(lambda *xs: np.stack(xs), *buf)
                buf.clear()
                yield ("group", k, putk(stacked))
        yield from flush()

    return wrap


def _reset_schedule_counts(opt_state):
    """Zero every ``count`` leaf in an optax state tree."""

    def reset(path, leaf):
        names = [getattr(e, "name", getattr(e, "key", "")) for e in path]
        if names and names[-1] == "count":
            return jax.numpy.zeros_like(leaf)
        return leaf

    return jax.tree_util.tree_map_with_path(reset, opt_state)


def fit(cfg: Config, model, params, train_loader,
        begin_epoch: int = 0, end_epoch: int = 10,
        plan: Optional[MeshPlan] = None,
        prefix: Optional[str] = None,
        graph: str = "end2end",
        seed: int = 0,
        frequent: int = 20,
        resume: bool = False,
        profile_dir: Optional[str] = None,
        telemetry_dir: Optional[str] = None,
        steps_per_dispatch: int = 1,
        fixed_prefixes=None) -> TrainState:
    """Train ``model`` from ``params`` over ``train_loader`` epochs.

    train_loader: iterable over epochs yielding dict batches (numpy,
    leading axis = global batch), exposing ``steps_per_epoch`` and
    ``batch_size`` (loader.py contract).

    ``resume=True`` (reference ``--resume``) restores params + optimizer
    state + step from ``prefix`` at ``begin_epoch``.

    ``profile_dir``: capture an XProf/perfetto device trace of steps 3–8 of
    the first epoch (the reference has no profiling subsystem — SURVEY §5
    calls this the free win; view with xprof/tensorboard).

    ``telemetry_dir``: stream structured run telemetry there (JSONL events
    + an end-of-run summary JSON — see ``mx_rcnn_tpu/telemetry``): the
    per-step wall-time breakdown (loader-wait / dispatch / metric-fetch
    stall / checkpoint-save), epoch wall time, and a recompile counter
    keyed on (program, batch bucket shape) so mixed-bucket epochs show
    their true compile cost.  Per-rank event files on multi-host; the
    summary is written by process 0 only (the ``profile_dir`` rank-split
    contract).  When a sink is already active (a driver configured one),
    it is reused and left open.  Disabled, every probe is a no-op sink
    call — one attribute check, zero allocations.

    ``steps_per_dispatch`` > 1 groups k consecutive loader batches and
    runs them through ONE dispatched ``lax.scan`` program
    (``make_multi_train_step``): amortizes per-dispatch overhead and lets
    XLA compile the step as a loop body — measured on v5-lite, the FPN
    step drops 21.95 → 17.85 ms inside the loop (better P2-conv layout;
    r4_tpu_session7.log).  On loaders exposing the ``wrap`` hook
    (AnchorLoader/ROIIter), group stacking AND the host→device transfer
    happen on the loader's prefetch thread (``_make_group_wrap``), so k>1
    keeps the same transfer/compute overlap as k=1; loaders without the
    hook fall back to consumer-side grouping with synchronous transfer.
    Groups must be shape-homogeneous, so every scale/orientation bucket
    change flushes the partial group through the single-step program
    (mixed-bucket epochs amortize less).  Math per step is identical
    (k=1 parity asserted; k>1 numeric parity vs a sequential driver is
    chaotic — discrete top-k/NMS flips amplify ulp differences — so k>1
    is covered structurally); per-step rng differs from the k=1 stream
    (keys are fold_in of one dispatch key), and metrics arrive as k-step
    means at dispatch granularity.  Epoch remainders smaller than k run
    through the single-step program.
    """
    # thin-shard guard lives in make_train_step (mechanism level); eval's is
    # in Predictor.__init__ since it never builds a train step
    steps_per_epoch = train_loader.steps_per_epoch
    tel = telemetry.get()
    owns_tel = False
    if telemetry_dir and not tel.enabled:
        tel = telemetry.configure(
            telemetry_dir, rank=jax.process_index(),
            world=jax.process_count(),
            run_meta={"driver": "fit", "graph": graph,
                      "steps_per_dispatch": int(steps_per_dispatch),
                      "batch_size": train_loader.batch_size,
                      "steps_per_epoch": steps_per_epoch})
        owns_tel = True
    state, tx, mask = create_train_state(cfg, params, steps_per_epoch,
                                   begin_epoch=begin_epoch,
                                   fixed_prefixes=fixed_prefixes)
    ckpt = CheckpointManager(prefix) if prefix else None

    if resume:
        if ckpt is None:
            raise ValueError("resume=True requires a checkpoint prefix")
        abstract = jax.device_get(
            {"params": state.params, "opt_state": state.opt_state, "step": 0})
        r_params, r_opt, r_step = ckpt.load_epoch(
            begin_epoch, cfg, for_training=True, abstract_payload=abstract)
        if r_opt is not None:
            # the LR schedule was rebuilt with boundaries relative to
            # begin_epoch (make_lr_schedule), so its step count must restart
            # at 0 — only momentum buffers carry over.  Keeping the saved
            # global count would fire every LR drop begin_epoch epochs early.
            r_opt = _reset_schedule_counts(r_opt)
        state = TrainState(step=jax.numpy.asarray(r_step, jax.numpy.int32),
                           params=r_params,
                           opt_state=r_opt if r_opt is not None else state.opt_state)
        logger.info("resumed from %s epoch %d (step %d)", prefix, begin_epoch,
                    r_step)

    if plan is not None:
        # multi-host: create the mesh's cross-process communicator NOW,
        # while ranks are aligned — its lazy creation inside the first
        # step would race the ranks' compile-time skew against the Gloo
        # key-exchange deadline (see warm_collectives; no-op otherwise)
        from mx_rcnn_tpu.parallel.distributed import warm_collectives

        warm_collectives(plan)
    step_fn = make_train_step(model, tx, plan=plan, graph=graph,
                              trainable_mask=mask)
    k = int(steps_per_dispatch)
    multi_fn = (make_multi_train_step(model, tx, k, plan=plan, graph=graph,
                                      trainable_mask=mask) if k > 1 else None)
    # device double-buffering: loaders that expose a ``put`` hook transfer
    # each batch from their prefetch thread (overlapping the previous
    # step's compute) instead of synchronously inside step dispatch; at
    # k>1 the ``wrap`` hook moves the whole group assembly (stacking +
    # stacked transfer) onto that thread instead.  fit OWNS both hooks:
    # they are (re)set every call so a loader reused across fit calls
    # with a different k/plan never runs a stale hook (a leftover group
    # wrap would feed tagged tuples to the k=1 path, and a leftover put
    # would re-transfer the wrap's already-on-device items).
    loader_wraps = False
    if hasattr(train_loader, "wrap"):
        train_loader.wrap = _make_group_wrap(k, plan) if k > 1 else None
        loader_wraps = k > 1
    loader_puts = False
    if hasattr(train_loader, "put"):
        if k == 1 and not loader_wraps:
            train_loader.put = ((lambda b: shard_batch(plan, b))
                                if plan is not None else jax.device_put)
            loader_puts = True
        else:  # the wrap transfers its own items — put must stay out
            train_loader.put = None
    if plan is not None and jax.process_count() > 1:
        # diagnose loader-partition misconfigurations at the contract
        # level, before they surface as an opaque jit shape mismatch: a
        # loader left at num_parts=1 on a multi-process mesh would yield a
        # self-consistent but process_count×-sized "global" batch
        # (round-4 advisor finding; the CLI drivers check this too, but
        # direct fit() callers bypassed them)
        from mx_rcnn_tpu.parallel.distributed import assert_loader_partition

        if hasattr(train_loader, "num_parts"):
            assert_loader_partition(plan, train_loader.batch_size,
                                    train_loader.num_parts,
                                    train_loader.part_index)
    n_chips = plan.n_data if plan else 1
    # multi-host (parallel/distributed.py): every process runs this same
    # loop over the global mesh in lockstep; only process 0 speaks/saves.
    # The loader carries its num_parts/part_index row slice; metrics are
    # replicated outputs, so the fetch below is a local read everywhere.
    proc0 = jax.process_index() == 0
    speedo = Speedometer(train_loader.batch_size, frequent=frequent,
                         n_chips=n_chips)
    speedo_cb = speedo if proc0 else (lambda *a, **k: None)
    bank = MetricBank()
    key = jax.random.PRNGKey(seed)

    profiling = False
    profiled = False
    if profile_dir and jax.process_count() > 1:
        # one trace dir per rank: on a shared filesystem the ranks' trace
        # writers would collide in a single directory (round-4 advisor
        # finding)
        import os

        profile_dir = os.path.join(profile_dir,
                                   f"rank{jax.process_index()}")
    # recompile tracking: jit caches one program per (step fn, bucket
    # shape), so the first dispatch of each pair is the compile.  The set
    # mirrors that cache (fit builds fresh step fns, so per-fit is exact)
    # and makes mixed-bucket epochs show their true compile cost in the
    # telemetry stream instead of as unexplained slow steps.
    seen_programs = set()

    def note_dispatch(fn_kind, shape):
        pkey = (fn_kind, tuple(shape))
        if pkey not in seen_programs:
            seen_programs.add(pkey)
            tel.counter("train/recompile")
            tel.meta("recompile", program=fn_kind, shape=list(shape))

    for epoch in range(begin_epoch, end_epoch):
        bank.reset()
        speedo.reset()
        pending = None
        buf = []
        consumed = 0  # loader batches dispatched so far (a group item
        # advances this by k; profiling and metric cadence count batches)
        last_fetch = 0
        start_at = min(3, steps_per_epoch - 1)
        # epoch wall-time breakdown, telemetry-or-not (the epoch-end log
        # line reports wall/loader-wait either way; two perf_counter reads
        # per item is noise next to a dispatch)
        ep_t0 = time.perf_counter()
        loader_wait_s = 0.0
        it = iter(train_loader)
        while True:
            t_wait = time.perf_counter()
            try:
                item = next(it)
            except StopIteration:
                break
            dt_wait = time.perf_counter() - t_wait
            loader_wait_s += dt_wait
            tel.add("train/loader_wait", dt_wait)
            if profile_dir and epoch == begin_epoch and not profiled:
                if not profiling and consumed >= start_at:
                    jax.profiler.start_trace(profile_dir)
                    profiling = True
                elif profiling and consumed >= 8:
                    jax.block_until_ready(pending)
                    jax.profiler.stop_trace()
                    profiling = False
                    profiled = True
                    logger.info("wrote device trace to %s", profile_dir)
            t_disp = time.perf_counter()
            key, sub = jax.random.split(key)
            n_b = 1
            if loader_wraps:
                # producer-thread group assembly (_make_group_wrap):
                # items arrive tagged, already stacked AND on device —
                # the transfer overlapped the previous step's compute
                kind, n_b, data = item
                note_dispatch(kind, data["images"].shape)
                state, metrics = (multi_fn if kind == "group"
                                  else step_fn)(state, data, sub)
                pending = metrics
            elif multi_fn is None:
                batch = item
                note_dispatch("single", batch["images"].shape)
                if plan is not None and not loader_puts:
                    batch = shard_batch(plan, batch)
                state, metrics = step_fn(state, batch, sub)
                pending = metrics
            else:
                # consumer-side fallback for loaders without the ``wrap``
                # hook: group k batches into one scanned dispatch (epoch
                # remainder < k runs through the single-step fn; bucket
                # changes flush the partial group — groups must be
                # shape-homogeneous)
                batch = item
                if buf and buf[0]["images"].shape != batch["images"].shape:
                    for b in buf:
                        key, sub = jax.random.split(key)
                        note_dispatch("single", b["images"].shape)
                        if plan is not None:
                            b = shard_batch(plan, b)
                        state, metrics = step_fn(state, b, sub)
                    pending = metrics
                    buf = []
                buf.append(batch)
                if len(buf) == k:
                    stacked = jax.tree.map(lambda *xs: np.stack(xs), *buf)
                    note_dispatch("group", stacked["images"].shape)
                    stacked = (shard_stacked_batch(plan, stacked)
                               if plan is not None
                               else jax.device_put(stacked))
                    state, metrics = multi_fn(state, stacked, sub)
                    pending = metrics
                    buf = []
            tel.add("train/dispatch", time.perf_counter() - t_disp, n=n_b)
            # fetch metrics only at Speedometer cadence: a device→host scalar
            # read stalls the dispatch pipeline (and on tunneled devices costs
            # far more than a step), so per-step reads would serialize training
            if consumed + n_b - last_fetch >= frequent and pending is not None:
                with tel.span("train/fetch_stall"):
                    bank.update(jax.device_get(pending))
                pending = None
                last_fetch = consumed + n_b
            for j in range(n_b):
                speedo_cb(epoch, consumed + j, bank.format())
            consumed += n_b
        if buf:  # epoch remainder (< k) — flushed AFTER the loop so the
            # drain cannot depend on steps_per_epoch matching the
            # iterator's true yield count (wrapper loaders may differ)
            t_disp = time.perf_counter()
            for b in buf:
                key, sub = jax.random.split(key)
                note_dispatch("single", b["images"].shape)
                if plan is not None:
                    b = shard_batch(plan, b)
                state, metrics = step_fn(state, b, sub)
            pending = metrics
            tel.add("train/dispatch", time.perf_counter() - t_disp,
                    n=len(buf))
            buf = []
        if profiling:  # epoch shorter than the stop step: close the trace
            jax.block_until_ready(pending)
            jax.profiler.stop_trace()
            profiling = False
            logger.info("wrote device trace to %s", profile_dir)
        if pending is not None:
            with tel.span("train/fetch_stall"):
                bank.update(jax.device_get(pending))
        ep_wall = time.perf_counter() - ep_t0
        tel.add("train/epoch", ep_wall)
        tel.counter("train/steps", consumed)
        if proc0:
            # wall + loader-wait on the one-line epoch summary: single-log
            # triage of "slow epoch — device or input pipeline?" without
            # opening the JSONL
            logger.info("Epoch[%d] Train-%s\tWall=%.1fs LoaderWait=%.1fs",
                        epoch, bank.format().replace("\t", " Train-"),
                        ep_wall, loader_wait_s)
        if ckpt is not None:
            # multi-host: EVERY rank calls save — orbax's CheckpointManager
            # runs its own cross-process barriers inside save() and writes
            # from the primary host only (ranks must share one prefix on a
            # shared filesystem).  Gating this on rank 0 deadlocks orbax's
            # sync_global_devices (found by the two-process CLI drive).
            # State leaves are replicated (DP) so device_get is local.
            with tel.span("train/checkpoint_save"):
                ckpt.save_epoch(epoch + 1, state.params, cfg,
                                opt_state=state.opt_state,
                                step=int(jax.device_get(state.step)))
    if jax.process_count() > 1:
        # align ranks before returning: after the last collective nothing
        # else synchronizes them, and a rank that exits the process much
        # later than its peers trips the jax.distributed SHUTDOWN barrier
        # deadline under load (observed with Gloo on a contended host)
        from mx_rcnn_tpu.parallel.distributed import sync

        sync("fit_end")
    if owns_tel:
        # every rank streams its own event file; only process 0 writes the
        # aggregated summary (the profile_dir rank-split contract) — the
        # cross-rank fold is scripts/telemetry_report.py's job
        if proc0:
            path = tel.write_summary()
            logger.info("wrote telemetry summary to %s", path)
        telemetry.shutdown()
    return state
