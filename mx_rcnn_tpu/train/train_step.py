"""The jitted SPMD train step.

One function replaces the reference's per-step machinery (SURVEY §3.1):
Module forward/backward per GPU, ProposalTarget's device→host→device sync
(eliminated — sampling is in-graph), KVStore gradient push/pull (XLA
all-reduce over the mesh data axis), SGD update, metric readback (six
scalars, one transfer).

The step is ``jax.jit``-ed with explicit shardings: batch over the data
axis, state replicated.  XLA inserts the gradient ``psum`` where the
KVStore reduce used to be; donation reuses the state buffers in place.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import optax

from mx_rcnn_tpu.config import Config
from mx_rcnn_tpu.parallel.mesh import (MeshPlan, check_spatial,
                                       stack_sharding)
from mx_rcnn_tpu.train.metric import metric_scalars
from mx_rcnn_tpu.train.optim import make_optimizer


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class TrainState:
    """Replicated training state (params + momentum + step counter)."""

    step: jnp.ndarray
    params: Any
    opt_state: Any


def create_train_state(cfg: Config, params, steps_per_epoch: int,
                       begin_epoch: int = 0,
                       fixed_prefixes=None):
    """-> (TrainState, tx, trainable_mask).  Pass the mask to
    ``make_train_step`` so frozen subtrees are stop_gradient-ed (XLA then
    dead-code-eliminates their whole backward chain instead of computing
    gradients the optimizer would zero anyway)."""
    # copy params into the state: the jitted step donates its state, and
    # aliasing the caller's buffers would delete them after the first step
    # (the alternate-training driver reuses one init tree across stages)
    params = jax.tree.map(lambda x: jnp.array(x, copy=True), params)
    tx, _, mask = make_optimizer(cfg, steps_per_epoch, params,
                                 begin_epoch=begin_epoch,
                                 fixed_prefixes=fixed_prefixes)
    return TrainState(step=jnp.zeros((), jnp.int32), params=params,
                      opt_state=tx.init(params)), tx, mask


def _loss_fn(params, model, batch, key, graph: str):
    """Dispatch to the model's training graph: 'end2end' | 'rpn' | 'rcnn'."""
    if graph == "end2end":
        kwargs = {}
        if "gt_masks" in batch:
            kwargs["gt_masks"] = batch["gt_masks"]
        total, aux = model.apply(
            {"params": params}, batch["images"], batch["im_info"],
            batch["gt_boxes"], batch["gt_classes"], batch["gt_valid"], key,
            rngs={"dropout": jax.random.fold_in(key, 1)}, **kwargs)
    elif graph == "rpn":
        total, aux = model.apply(
            {"params": params}, batch["images"], batch["im_info"],
            batch["gt_boxes"], batch["gt_valid"], key,
            method=type(model).rpn_train)
    elif graph == "rcnn":
        total, aux = model.apply(
            {"params": params}, batch["images"], batch["im_info"],
            batch["rois"], batch["roi_valid"], batch["gt_boxes"],
            batch["gt_classes"], batch["gt_valid"], key,
            method=type(model).rcnn_train,
            rngs={"dropout": jax.random.fold_in(key, 1)})
    else:
        raise ValueError(f"unknown graph '{graph}'")
    return total, aux


def _all_finite(total, grads):
    """On-device scalar: loss AND every gradient leaf finite (the NaN
    sentinel — one cheap fused reduction per leaf, no host sync)."""
    flags = [jnp.isfinite(total)]
    flags += [jnp.all(jnp.isfinite(g)) for g in jax.tree_util.tree_leaves(grads)]
    return jnp.all(jnp.stack(flags))


def _build_step(model, tx: optax.GradientTransformation, graph: str,
                trainable_mask, sentinel: bool = False,
                skip_nonfinite: bool = False) -> Callable:
    """The raw (un-jitted) train step shared by ``make_train_step`` and
    ``make_multi_train_step``: loss+grad, frozen-subtree stop_gradient,
    optimizer update, metric scalars, step counter.

    ``sentinel`` adds an on-device all-finite flag over (loss, grads) to
    the metrics (``all_finite``) — fetched by the trainer at Speedometer
    cadence, it drives the NaN policies without a per-step host sync.
    ``skip_nonfinite`` (the ``skip`` policy) additionally guards the
    update in-graph: a non-finite step keeps the previous params AND
    optimizer state (only the step counter advances), so params can never
    be poisoned in the window before the host notices.
    """

    def step(state: TrainState, batch, key):
        def loss_fn(params):
            if trainable_mask is not None:
                params = jax.tree.map(
                    lambda v, t: v if t else jax.lax.stop_gradient(v),
                    params, trainable_mask)
            return _loss_fn(params, model=model, batch=batch, key=key,
                            graph=graph)

        (total, aux), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(state.params)
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        metrics = metric_scalars(aux)
        metrics["total_loss"] = total
        if sentinel:
            finite = _all_finite(total, grads)
            metrics["all_finite"] = finite.astype(jnp.float32)
            if skip_nonfinite:
                keep = lambda new, old: jnp.where(finite, new, old)
                params = jax.tree.map(keep, params, state.params)
                opt_state = jax.tree.map(keep, opt_state, state.opt_state)
        new_state = TrainState(step=state.step + 1, params=params,
                               opt_state=opt_state)
        return new_state, metrics

    return step


def make_train_step(model, tx: optax.GradientTransformation,
                    plan: Optional[MeshPlan] = None,
                    graph: str = "end2end",
                    donate: bool = True,
                    trainable_mask=None,
                    sentinel: bool = False,
                    skip_nonfinite: bool = False) -> Callable:
    """Build ``train_step(state, batch, key) -> (state, metrics)``.

    With a ``MeshPlan``, inputs/outputs carry NamedShardings (batch split on
    the data axis, state replicated) — the whole of data parallelism; no
    pmap, no hand-written collectives.  Without one, plain single-device jit
    (the reference's 1-GPU path).

    ``trainable_mask`` (the tree from ``create_train_state``; True =
    trainable): frozen leaves are ``stop_gradient``-ed inside the loss, so
    their gradients are structural zeros and XLA dead-code-eliminates the
    frozen backward tail entirely (the reference freezes conv1+stage1 —
    ``fixed_param_prefix`` — but still computed those gradients; we don't).

    ``sentinel``/``skip_nonfinite``: the NaN sentinel / in-graph
    non-finite-update guard (see ``_build_step``; driven by
    ``resilience.ResilienceOptions.nan_policy``).
    """
    if plan is not None:
        # thin-shard guard at the mechanism level: every spatially-sharded
        # step (fit, dryrun, direct callers) compiles through here
        check_spatial(plan, model.cfg)

    step = _build_step(model, tx, graph, trainable_mask,
                       sentinel=sentinel, skip_nonfinite=skip_nonfinite)
    if plan is None:
        return jax.jit(step, donate_argnums=(0,) if donate else ())
    return _jit_planned(step, plan, donate)


def _jit_planned(fn, plan: MeshPlan, donate: bool, wrap=lambda sh: sh):
    """jit ``fn(state, batch, key)`` with the plan's shardings — the one
    wiring shared by the single-step and multi-step makers (``wrap``
    lifts each batch sharding; the multi-step maker passes
    ``stack_sharding`` to prepend the unsharded stack axis).

    For tensor parallelism (MeshPlan.param_shardings on the head FCs)
    and/or spatial parallelism (image height over the space axis), the
    state sharding tree is structural and the batch sharding tree
    depends on the batch's keys, so both are built lazily from the first
    call and the jitted fn cached — keyed on the batch's key set: the
    spatial in_shardings are a per-key dict, so a batch gaining/losing
    an optional key (gt_masks) must get its own jitted entry, not a
    pytree structure mismatch at dispatch."""
    repl = plan.replicated()
    batch_sh = wrap(plan.batch())
    if plan.n_model > 1 or plan.n_space > 1:
        cache = {}

        def stepper(state, batch, key):
            ck = frozenset(batch) if plan.n_space > 1 else "fn"
            jitted = cache.get(ck)
            if jitted is None:
                st_sh = plan.state_shardings(state)
                b_sh = ({k: wrap(plan.images()) if k == "images" else batch_sh
                         for k in batch}
                        if plan.n_space > 1 else batch_sh)
                jitted = jax.jit(
                    fn,
                    in_shardings=(st_sh, b_sh, repl),
                    out_shardings=(st_sh, repl),
                    donate_argnums=(0,) if donate else (),
                )
                cache[ck] = jitted
            return jitted(state, batch, key)

        return stepper
    return jax.jit(
        fn,
        in_shardings=(repl, batch_sh, repl),
        out_shardings=(repl, repl),
        donate_argnums=(0,) if donate else (),
    )

def make_multi_train_step(model, tx: optax.GradientTransformation, k: int,
                          plan: Optional[MeshPlan] = None,
                          graph: str = "end2end",
                          donate: bool = True,
                          trainable_mask=None,
                          unroll: Optional[bool] = None,
                          sentinel: bool = False,
                          skip_nonfinite: bool = False) -> Callable:
    """``k`` train steps in ONE dispatched program: ``lax.scan`` over
    batches stacked on a leading axis (every leaf shaped (k, ...)).

    Why this exists (round 4, measured): dispatching one program per step
    pays a per-dispatch cost — host RPC on remote devices, and, less
    obviously, a per-program compilation horizon: profiled on v5-lite,
    XLA compiles the FPN step to 21.95 ms standalone but 17.85 ms as a
    loop body (it picks a better layout for the P2-resolution neck convs
    when the program is a loop — r4_tpu_session7.log, validated with
    per-iteration-varying data and asserted step counts).  Scanning the
    step is also the idiomatic JAX recipe for small steps.  ``fit(...,
    steps_per_dispatch=k)`` feeds this from the real loader by stacking
    k consecutive batches.

    Semantics vs k sequential ``make_train_step`` calls: identical math
    per step (same ``_build_step``); the per-step rng keys are
    ``fold_in(key, i)`` for i in [0, k); the returned metrics are the
    MEAN over the k steps (the per-step values feed the same MetricBank
    averaging that single-step fit samples at Speedometer cadence).
    Parity is tested in tests/test_train.py.

    ``unroll``: pass ``unroll=k`` to ``lax.scan`` (straight-line body
    repetition instead of a compiled loop).  Default: unrolled on the CPU
    backend, rolled loop elsewhere.  Values are identical either way
    (same scan semantics); the split exists because XLA:CPU's compile
    time for a scan-of-train-step under SPMD is pathological — measured
    round 5: >17 min at 8 partitions and >25 min in one 2-partition
    config on a host that compiles the same step standalone in 29 s —
    while on TPU the rolled loop is both fine to compile and the point
    of the feature (the loop-body layout win, above)."""
    if k < 1:
        raise ValueError(f"steps_per_dispatch must be >= 1, got {k}")
    if unroll is None:
        unroll = jax.default_backend() == "cpu"
    if plan is not None:
        check_spatial(plan, model.cfg)
    step = _build_step(model, tx, graph, trainable_mask,
                       sentinel=sentinel, skip_nonfinite=skip_nonfinite)

    def multi(state: TrainState, batches, key):
        if k == 1:
            # no scan at k=1: same values (fold_in(key, 0); mean over one
            # step is identity), and the scan construct itself is what
            # XLA:CPU compiles pathologically under SPMD (unroll=k cannot
            # help a length-1 loop)
            return step(state, jax.tree.map(lambda x: x[0], batches),
                        jax.random.fold_in(key, 0))

        def body(st, xs):
            i, b = xs
            return step(st, b, jax.random.fold_in(key, i))

        state, ms = jax.lax.scan(body, state, (jnp.arange(k), batches),
                                 unroll=k if unroll else 1)
        return state, jax.tree.map(lambda x: jnp.mean(x, axis=0), ms)

    if plan is None:
        return jax.jit(multi, donate_argnums=(0,) if donate else ())
    return _jit_planned(multi, plan, donate, wrap=stack_sharding)
