"""Training core — the analogue of the reference's ``rcnn/core``
(``module.py``/``metric.py``/``callback.py``) plus the driver logic of
``train_end2end.py: train_net``, rebuilt as one jitted SPMD train step
over a data mesh.
"""

from mx_rcnn_tpu.train.optim import make_optimizer, make_lr_schedule, fixed_param_mask
from mx_rcnn_tpu.train.metric import MetricBank
from mx_rcnn_tpu.train.callback import Speedometer
from mx_rcnn_tpu.train.train_step import (TrainState, create_train_state,
                                          make_multi_train_step,
                                          make_train_step)
from mx_rcnn_tpu.train.resilience import (NonFiniteLossError,
                                          PreemptionGuard, ResilienceOptions,
                                          add_resilience_args, retry_io)
from mx_rcnn_tpu.train.trainer import fit
