"""The six reference training metrics (``rcnn/core/metric.py``).

| Reference class    | Here (key)      | Definition                                      |
|--------------------|-----------------|-------------------------------------------------|
| RPNAccMetric       | RPNAcc          | argmax accuracy over anchors with label != −1   |
| RPNLogLossMetric   | RPNLogLoss      | the RPN softmax CE (valid-normalized)           |
| RPNL1LossMetric    | RPNL1Loss       | the RPN smooth-L1 loss                          |
| RCNNAccMetric      | RCNNAcc         | argmax accuracy over sampled (weighted) RoIs    |
| RCNNLogLossMetric  | RCNNLogLoss     | the RCNN softmax CE (batch-normalized)          |
| RCNNL1LossMetric   | RCNNL1Loss      | the RCNN smooth-L1 loss                         |

The reference computes these on host from executor outputs each batch and
keeps running means inside ``mx.metric.CompositeEvalMetric``; here the
per-step scalars are produced inside the jitted step (metric_scalars, one
transfer of six floats) and ``MetricBank`` keeps the running means.
"""

from __future__ import annotations

import jax.numpy as jnp


def metric_scalars(aux: dict) -> dict:
    """Fold a train-step ``aux`` dict into the six named scalars (device)."""
    out = {}
    if "rpn_label" in aux:
        valid = aux["rpn_label"] != -1
        correct = (aux["rpn_pred"] == aux["rpn_label"]) & valid
        out["RPNAcc"] = (jnp.sum(correct.astype(jnp.float32))
                         / jnp.maximum(jnp.sum(valid.astype(jnp.float32)), 1.0))
        out["RPNLogLoss"] = aux["rpn_cls_loss"]
        out["RPNL1Loss"] = aux["rpn_bbox_loss"]
    if "rcnn_label" in aux:
        w = aux["rcnn_label_weight"]
        correct = (aux["rcnn_pred"] == aux["rcnn_label"]).astype(jnp.float32) * w
        out["RCNNAcc"] = jnp.sum(correct) / jnp.maximum(jnp.sum(w), 1.0)
        out["RCNNLogLoss"] = aux["rcnn_cls_loss"]
        out["RCNNL1Loss"] = aux["rcnn_bbox_loss"]
    if "mask_loss" in aux:
        out["MaskLoss"] = aux["mask_loss"]
    return out


class MetricBank:
    """Running means over an epoch — the CompositeEvalMetric analogue."""

    def __init__(self):
        self._sum: dict = {}
        self._n = 0

    def update(self, scalars: dict):
        for k, v in scalars.items():
            self._sum[k] = self._sum.get(k, 0.0) + float(v)
        self._n += 1

    def reset(self):
        self._sum.clear()
        self._n = 0

    def get(self) -> dict:
        if self._n == 0:
            return {}
        return {k: v / self._n for k, v in self._sum.items()}

    def format(self) -> str:
        return "\t".join(f"{k}={v:.5f}" for k, v in self.get().items())
