"""Checkpointing — reference ``rcnn/core/callback.py: do_checkpoint`` +
``rcnn/utils/{load,save}_model.py``, on orbax.

Contracts kept:

* **De-normalize at save**: training regresses bbox targets normalized by
  (BBOX_MEANS, BBOX_STDS); ``do_checkpoint`` folds them into the
  ``bbox_pred`` weights/bias before writing, so the saved checkpoint
  predicts raw deltas and inference needs no de-normalization.  On resume,
  the inverse fold is applied (reference train_end2end resume path).
* Epoch-indexed checkpoints under ``prefix`` (``prefix-%04d.params`` →
  ``{prefix}/epoch_{n:04d}`` orbax directories), plus step-level resume —
  an upgrade the survey calls for (SURVEY §5 failure-detection row).
"""

from __future__ import annotations

import os
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import orbax.checkpoint as ocp

from mx_rcnn_tpu.logger import logger


def _bbox_fold(params, means, stds, num_classes: int, invert: bool):
    """Fold (or unfold) target normalization into the bbox_pred layer.

    kernel: (D, 4K); bias: (4K,).  saved = trained * stds + means(bias only);
    invert recovers the trained parametrization.
    """
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    has_bbox = any(
        any((getattr(e, "key", None) == "bbox_pred") for e in path)
        for path, _ in flat)
    if not has_bbox:
        return params

    stds_t = jnp.asarray(np.tile(np.asarray(stds, np.float32), num_classes))
    means_t = jnp.asarray(np.tile(np.asarray(means, np.float32), num_classes))

    def fold(path, leaf):
        names = [getattr(e, "key", str(e)) for e in path]
        if "bbox_pred" not in names:
            return leaf
        if names[-1] == "kernel":
            return leaf / stds_t[None, :] if invert else leaf * stds_t[None, :]
        if names[-1] == "bias":
            return (leaf - means_t) / stds_t if invert else leaf * stds_t + means_t
        return leaf

    return jax.tree_util.tree_map_with_path(fold, params)


def denormalize_for_save(params, cfg):
    return _bbox_fold(params, cfg.TRAIN.BBOX_MEANS, cfg.TRAIN.BBOX_STDS,
                      cfg.NUM_CLASSES, invert=False)


def normalize_for_train(params, cfg):
    return _bbox_fold(params, cfg.TRAIN.BBOX_MEANS, cfg.TRAIN.BBOX_STDS,
                      cfg.NUM_CLASSES, invert=True)


class CheckpointManager:
    """Thin orbax wrapper with the reference's epoch naming."""

    def __init__(self, prefix: str, max_to_keep: Optional[int] = None):
        self.prefix = os.path.abspath(prefix)
        os.makedirs(self.prefix, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self.prefix,
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep,
                                                 create=True),
        )

    def save_epoch(self, epoch: int, params, cfg, opt_state=None,
                   step: int = 0):
        """``do_checkpoint`` analogue: de-normalized params + raw training
        state for exact resume."""
        payload = {
            "params": jax.device_get(denormalize_for_save(params, cfg)),
            "step": step,
        }
        if opt_state is not None:
            payload["opt_state"] = jax.device_get(opt_state)
        self._mgr.save(epoch, args=ocp.args.StandardSave(payload))
        self._mgr.wait_until_finished()
        if jax.process_index() == 0:
            logger.info("Saved checkpoint epoch %d -> %s", epoch, self.prefix)

    def load_epoch(self, epoch: int, cfg, for_training: bool = True,
                   abstract_payload=None):
        """Returns (params, opt_state_or_None, step).

        For exact training resume pass ``abstract_payload`` — a pytree
        skeleton matching what was saved, e.g.
        ``{"params": params_like, "opt_state": tx.init(params_like),
        "step": 0}`` — so orbax restores the true optax state classes
        (target-less restore returns raw dicts optax cannot consume).
        """
        if abstract_payload is not None:
            restored = self._mgr.restore(
                epoch, args=ocp.args.StandardRestore(abstract_payload))
        else:
            restored = self._mgr.restore(epoch)
        params = restored["params"]
        if for_training:
            params = normalize_for_train(params, cfg)
        return params, restored.get("opt_state"), int(restored.get("step", 0))

    def latest_epoch(self) -> Optional[int]:
        return self._mgr.latest_step()


def save_params_npz(path: str, params) -> None:
    """Flat .npz export (the deployment artifact; also the pretrained-backbone
    interchange format — utils/load_model.py reads it back)."""
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    out = {}
    for p, leaf in flat:
        key = "/".join(getattr(e, "key", str(e)) for e in p)
        out[key] = np.asarray(jax.device_get(leaf))
    np.savez(path, **out)


def load_params_npz(path: str):
    """Inverse of save_params_npz -> nested dict pytree."""
    data = np.load(path)
    tree: dict = {}
    for key in data.files:
        parts = key.split("/")
        node = tree
        for part in parts[:-1]:
            node = node.setdefault(part, {})
        node[parts[-1]] = jnp.asarray(data[key])
    return tree
