"""Checkpointing — reference ``rcnn/core/callback.py: do_checkpoint`` +
``rcnn/utils/{load,save}_model.py``, on orbax.

Contracts kept:

* **De-normalize at save**: training regresses bbox targets normalized by
  (BBOX_MEANS, BBOX_STDS); ``do_checkpoint`` folds them into the
  ``bbox_pred`` weights/bias before writing, so the saved checkpoint
  predicts raw deltas and inference needs no de-normalization.  On resume,
  the inverse fold is applied (reference train_end2end resume path).
* Epoch-indexed checkpoints under ``prefix`` (``prefix-%04d.params`` →
  ``{prefix}/epoch_{n:04d}`` orbax directories), plus step-level resume —
  the SURVEY §5 failure-detection upgrade, now implemented: mid-epoch
  step checkpoints live under ``{prefix}/steps/{epoch·STRIDE+consumed}``
  (atomic orbax writes, rolling window) and carry the RAW training
  parametrization + optimizer state + the trainer's RNG key, so
  ``fit(auto_resume)`` restores the exact step the run died at.  Epoch
  checkpoints keep the de-normalized inference contract; step
  checkpoints are resume-only artifacts and skip the fold entirely.
* Saves retry transient I/O errors with exponential backoff
  (``resilience.retry_io`` — ``checkpoint/retry`` telemetry counter).
"""

from __future__ import annotations

import functools
import os
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import orbax.checkpoint as ocp

from mx_rcnn_tpu import telemetry
from mx_rcnn_tpu.logger import logger
from mx_rcnn_tpu.train.resilience import (decode_step_key, encode_step_key,
                                          retry_io)


def _bbox_fold(params, means, stds, num_classes: int, invert: bool):
    """Fold (or unfold) target normalization into the bbox_pred layer.

    kernel: (D, 4K); bias: (4K,).  saved = trained * stds + means(bias only);
    invert recovers the trained parametrization.
    """
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    has_bbox = any(
        any((getattr(e, "key", None) == "bbox_pred") for e in path)
        for path, _ in flat)
    if not has_bbox:
        return params

    stds_t = jnp.asarray(np.tile(np.asarray(stds, np.float32), num_classes))
    means_t = jnp.asarray(np.tile(np.asarray(means, np.float32), num_classes))

    def fold(path, leaf):
        names = [getattr(e, "key", str(e)) for e in path]
        if "bbox_pred" not in names:
            return leaf
        if names[-1] == "kernel":
            return leaf / stds_t[None, :] if invert else leaf * stds_t[None, :]
        if names[-1] == "bias":
            return (leaf - means_t) / stds_t if invert else leaf * stds_t + means_t
        return leaf

    return jax.tree_util.tree_map_with_path(fold, params)


def denormalize_for_save(params, cfg):
    return _bbox_fold(params, cfg.TRAIN.BBOX_MEANS, cfg.TRAIN.BBOX_STDS,
                      cfg.NUM_CLASSES, invert=False)


def normalize_for_train(params, cfg):
    return _bbox_fold(params, cfg.TRAIN.BBOX_MEANS, cfg.TRAIN.BBOX_STDS,
                      cfg.NUM_CLASSES, invert=True)


class CheckpointManager:
    """Thin orbax wrapper with the reference's epoch naming, plus the
    step-checkpoint tier (``{prefix}/steps``) for mid-epoch resume."""

    STEP_SUBDIR = "steps"

    def __init__(self, prefix: str, max_to_keep: Optional[int] = None,
                 step_keep: int = 2, io_retries: int = 3,
                 io_backoff_s: float = 0.5):
        self.prefix = os.path.abspath(prefix)
        os.makedirs(self.prefix, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self.prefix,
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep,
                                                 create=True),
        )
        self._step_keep = step_keep
        self._retry = functools.partial(retry_io, retries=io_retries,
                                        backoff_s=io_backoff_s)
        self._steps_mgr = None  # lazy: most runs never write step ckpts

    def _steps(self) -> ocp.CheckpointManager:
        if self._steps_mgr is None:
            self._steps_mgr = ocp.CheckpointManager(
                os.path.join(self.prefix, self.STEP_SUBDIR),
                options=ocp.CheckpointManagerOptions(
                    max_to_keep=self._step_keep, create=True),
            )
        return self._steps_mgr

    def save_epoch(self, epoch: int, params, cfg, opt_state=None,
                   step: int = 0):
        """``do_checkpoint`` analogue: de-normalized params + raw training
        state for exact resume."""
        payload = {
            "params": jax.device_get(denormalize_for_save(params, cfg)),
            "step": step,
        }
        if opt_state is not None:
            payload["opt_state"] = jax.device_get(opt_state)

        def do_save():
            self._mgr.save(epoch, args=ocp.args.StandardSave(payload))
            self._mgr.wait_until_finished()

        self._retry(do_save, what=f"epoch checkpoint {epoch}")
        if jax.process_index() == 0:
            logger.info("Saved checkpoint epoch %d -> %s", epoch, self.prefix)

    def available_epochs(self) -> list:
        return sorted(self._mgr.all_steps())

    def load_epoch(self, epoch: int, cfg, for_training: bool = True,
                   abstract_payload=None):
        """Returns (params, opt_state_or_None, step).

        For exact training resume pass ``abstract_payload`` — a pytree
        skeleton matching what was saved, e.g.
        ``{"params": params_like, "opt_state": tx.init(params_like),
        "step": 0}`` — so orbax restores the true optax state classes
        (target-less restore returns raw dicts optax cannot consume).
        """
        have = self.available_epochs()
        if epoch not in have:
            raise FileNotFoundError(
                f"no checkpoint for epoch {epoch} under {self.prefix}; "
                f"epochs present: {have or 'none'} — pass one of those (or "
                f"retrain; the latest is selected by fit(auto_resume))")
        if abstract_payload is not None:
            restored = self._mgr.restore(
                epoch, args=ocp.args.StandardRestore(abstract_payload))
        else:
            # target-less StandardRestore, never a bare restore(): a
            # manager that didn't write the save (fresh process — eval,
            # serving hot-reload) has no handler registered for the
            # item and bare restore() raises KeyError
            restored = self._mgr.restore(epoch,
                                         args=ocp.args.StandardRestore())
        params = restored["params"]
        if for_training:
            params = normalize_for_train(params, cfg)
        return params, restored.get("opt_state"), int(restored.get("step", 0))

    def latest_epoch(self) -> Optional[int]:
        return self._mgr.latest_step()

    # -- step checkpoints (mid-epoch resume; resilience.py contract) -----

    def save_step(self, epoch: int, consumed: int, params, cfg,
                  opt_state=None, step: int = 0, rng_key=None):
        """Step checkpoint at ``consumed`` loader batches into ``epoch``.

        RAW training parametrization (no bbox de-normalize — this is a
        resume-only artifact, never an inference input), plus the
        trainer's RNG key so the resumed per-step key stream continues
        bit-exactly.  ``cfg`` is accepted for signature symmetry with
        ``save_epoch`` but unused.  All ranks must call (orbax barriers).
        """
        del cfg
        payload = {
            "params": jax.device_get(params),
            "step": int(step),
            "epoch": int(epoch),
            "consumed": int(consumed),
        }
        if opt_state is not None:
            payload["opt_state"] = jax.device_get(opt_state)
        if rng_key is not None:
            payload["rng_key"] = np.asarray(jax.device_get(rng_key))
        key = encode_step_key(epoch, consumed)
        mgr = self._steps()

        def do_save():
            mgr.save(key, args=ocp.args.StandardSave(payload))
            mgr.wait_until_finished()

        with telemetry.get().span("checkpoint/step_save"):
            self._retry(do_save,
                        what=f"step checkpoint (epoch {epoch}, "
                             f"batch {consumed})")
        if jax.process_index() == 0:
            logger.info("Saved step checkpoint epoch %d batch %d -> %s/%s",
                        epoch, consumed, self.prefix, self.STEP_SUBDIR)

    def latest_step_checkpoint(self) -> Optional[Tuple[int, int]]:
        """Latest step checkpoint as (epoch, consumed), or None."""
        if not os.path.isdir(os.path.join(self.prefix, self.STEP_SUBDIR)):
            return None
        key = self._steps().latest_step()
        return None if key is None else decode_step_key(key)

    def load_step_checkpoint(self, epoch: int, consumed: int,
                             abstract_payload=None) -> dict:
        """Restore a step checkpoint's full payload (params stay in the
        RAW training parametrization — do NOT ``normalize_for_train``)."""
        key = encode_step_key(epoch, consumed)
        mgr = self._steps()
        if key not in mgr.all_steps():
            have = [decode_step_key(k) for k in sorted(mgr.all_steps())]
            raise FileNotFoundError(
                f"no step checkpoint (epoch {epoch}, batch {consumed}) under "
                f"{self.prefix}/{self.STEP_SUBDIR}; present: {have or 'none'}")
        if abstract_payload is not None:
            return mgr.restore(
                key, args=ocp.args.StandardRestore(abstract_payload))
        # see load_epoch: target-less StandardRestore for fresh-process
        # readers (bare restore() requires the writer's handler registry)
        return mgr.restore(key, args=ocp.args.StandardRestore())

    def latest_resume_point(self) -> Optional[Tuple[str, int, int]]:
        """The furthest position any checkpoint reaches, for auto-resume:
        ``("epoch", E, 0)`` (epoch checkpoint E = start of epoch E) or
        ``("step", E, C)`` (C batches into epoch E); None when the prefix
        holds no checkpoints.  A stale step checkpoint from before the
        latest epoch checkpoint loses the comparison, so a finished epoch
        always wins over its own mid-epoch saves."""
        cands = []
        e = self.latest_epoch()
        if e is not None:
            cands.append((e, 0, "epoch"))
        s = self.latest_step_checkpoint()
        if s is not None:
            cands.append((s[0], s[1], "step"))
        if not cands:
            return None
        ep, cons, kind = max(cands)
        return kind, ep, cons


def save_params_npz(path: str, params) -> None:
    """Flat .npz export (the deployment artifact; also the pretrained-backbone
    interchange format — utils/load_model.py reads it back)."""
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    out = {}
    for p, leaf in flat:
        key = "/".join(getattr(e, "key", str(e)) for e in p)
        out[key] = np.asarray(jax.device_get(leaf))
    np.savez(path, **out)


def load_params_npz(path: str):
    """Inverse of save_params_npz -> nested dict pytree."""
    data = np.load(path)
    tree: dict = {}
    for key in data.files:
        parts = key.split("/")
        node = tree
        for part in parts[:-1]:
            node = node.setdefault(part, {})
        node[parts[-1]] = jnp.asarray(data[key])
    return tree
