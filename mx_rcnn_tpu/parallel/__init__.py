"""Device-mesh parallelism layer.

TPU-native replacement for the reference's multi-device machinery
(``mx.mod.Module`` ctx-group batch split + ``KVStore('device')`` gradient
aggregation, selected in ``train_end2end.py`` via ``--gpus``/``--kvstore``):
a ``jax.sharding.Mesh`` with a data axis riding ICI (and a DCN axis for
multi-slice), batch sharded over data, params replicated, gradient
all-reduce performed by XLA-inserted collectives.
"""

from mx_rcnn_tpu.parallel.distributed import (assert_loader_partition,
                                               init_distributed,
                                               local_row_range, sync)
from mx_rcnn_tpu.parallel.mesh import (MeshPlan, check_spatial, make_mesh,
                                        make_multislice_mesh, shard_batch,
                                        shard_stacked_batch)
