"""Multi-process (multi-host) execution — the reference's
``KVStore('dist_sync')`` tier (SURVEY §2.2: ps-lite parameter server over
ZMQ/TCP, available in MXNet but left unscripted by the reference repo).

Here the same capability is the standard JAX multi-controller model: every
host runs the SAME program over a GLOBAL ``jax.sharding.Mesh`` spanning all
processes' devices; gradient all-reduce is an XLA collective riding ICI
within a host/slice and DCN across them — no parameter server, no push/pull.
Three pieces make the training loop multi-host:

1. :func:`init_distributed` — ``jax.distributed.initialize`` wrapper
   (coordinator rendezvous; on TPU pods the no-arg form auto-detects).
2. Loader sharding — each process loads only its rows of every global
   batch (``AnchorLoader(num_parts=, part_index=)``, the MXNet DataIter
   partition kwargs).  The epoch SCHEDULE (shuffle, buckets, scales,
   wrap-padding) is computed from the replicated roidb with a shared seed,
   so every process sees the identical batch-shape sequence — mandatory,
   since all processes must dispatch the same compiled program in lockstep.
3. :func:`global_from_local` — assembles the per-process rows into global
   ``jax.Array``s laid out exactly as the plan's shardings demand
   (``shard_batch`` routes here automatically when the plan's mesh spans
   processes, so ``fit`` is unchanged).

Validated by a REAL two-process run in ``tests/test_multiprocess.py``
(2 × 4 virtual CPU devices, Gloo collectives): final state bit-identical
across the two processes and equal to the single-process 8-device control.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import numpy as np

from mx_rcnn_tpu.logger import logger


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None,
                     auto: bool = False,
                     warmup: bool = True) -> tuple:
    """Join (or create) the multi-process runtime; returns
    ``(process_index, process_count)``.

    Call BEFORE any other jax API touches the backend.  ``auto=True`` is
    the TPU-pod form — ``jax.distributed.initialize()`` reads the slice
    topology from the TPU runtime; on CPU/GPU (and in tests) pass the
    coordinator triple explicitly.  With neither, a plain local run:
    does nothing.

    ``warmup`` runs one trivial cross-process barrier immediately after
    the rendezvous.  This is load-bearing on the CPU/Gloo backend: the
    collective clique's context is created lazily at the FIRST collective
    and its key-exchange has a hard ~30 s deadline, so if ranks reach
    their first real collective >30 s apart (asymmetric compile times of
    a big train step), the job dies with "Gloo context initialization
    failed: GetKeyValue() timed out".  A barrier compiled in ~1 s aligns
    the ranks and establishes the clique while the window is easy.
    """
    triple = (coordinator_address, num_processes, process_id)
    if auto and any(v is not None for v in triple):
        raise ValueError(
            "auto=True (pod auto-detection) cannot be combined with an "
            "explicit coordinator triple — pick one form")
    if not auto and any(v is not None for v in triple) \
            and not all(v is not None for v in triple):
        # a partial triple must not fall through to a standalone run (other
        # ranks block at the rendezvous) or to jax's cluster auto-detect
        # (whose error never names the missing flag)
        raise ValueError(
            "partial --dist configuration: pass ALL of coordinator_address, "
            "num_processes and process_id (or auto=True on a TPU pod); got "
            f"coordinator_address={coordinator_address!r}, "
            f"num_processes={num_processes!r}, process_id={process_id!r}")
    if auto:
        jax.distributed.initialize()
        logger.info("joined distributed runtime (auto): process %d/%d",
                    jax.process_index(), jax.process_count())
    elif coordinator_address is not None or num_processes is not None:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id)
        logger.info("joined distributed runtime at %s: process %d/%d",
                    coordinator_address, jax.process_index(),
                    jax.process_count())
    elif jax.process_count() == 1:
        # single process, nothing requested: plain local run
        return 0, 1
    if jax.process_count() > 1:
        # rank{N}-prefix every log record from here on: multi-host logs
        # interleave on shared consoles/files, and an unattributed line is
        # useless in a deadlock post-mortem (idempotent — logger.py owns
        # exactly one handler)
        from mx_rcnn_tpu.logger import setup_logging

        setup_logging(rank=jax.process_index())
    if warmup and jax.process_count() > 1:
        sync("init_distributed_warmup")
    return jax.process_index(), jax.process_count()


@functools.lru_cache(maxsize=64)
def _is_multiprocess_mesh(mesh, _pcount: int) -> bool:
    pid = jax.process_index()
    return any(d.process_index != pid for d in mesh.devices.flat)


def is_multiprocess_mesh(mesh) -> bool:
    """True when ``mesh`` contains devices this process cannot address.
    Cached per (mesh, process_count): this sits on the per-batch dispatch
    path, and the process_count key makes a pre-``init_distributed`` call
    harmless — the count changes at init, so the stale single-process
    answer is never reused afterwards (round-4 advisor finding)."""
    return _is_multiprocess_mesh(mesh, jax.process_count())


@functools.lru_cache(maxsize=64)
def _owned_row_blocks_impl(plan, _pcount: int) -> tuple:
    """(sorted row-shard ids owned by this process, total row shards).

    A "row shard" is one block of the batch axis: the flattened coordinate
    over the plan's batch axes (dcn, data).  Ownership comes from each
    device's ``process_index`` in the mesh array, so any device order the
    runtime produces is read back faithfully rather than assumed.  Cached
    per (plan, process_count) — the plan is a frozen dataclass over the
    immutable Mesh, and the count key protects library callers who touch a
    mesh before ``init_distributed`` (same rationale as
    :func:`is_multiprocess_mesh`); the coordinate sweep is pure Python and
    would otherwise run every batch.
    """
    mesh = plan.mesh
    axes = plan.batch_axes
    pid = jax.process_index()
    owned = set()
    devs = mesh.devices
    names = mesh.axis_names
    for coord in np.ndindex(*devs.shape):
        if devs[coord].process_index != pid:
            continue
        rb = 0
        for name, c in zip(names, coord):
            if name in axes:
                rb = rb * mesh.shape[name] + c
        owned.add(rb)
    return tuple(sorted(owned)), plan.n_data


def _owned_row_blocks(plan) -> tuple:
    return _owned_row_blocks_impl(plan, jax.process_count())


def local_row_range(plan, global_batch: int) -> tuple:
    """Global-batch rows ``[lo, hi)`` this process must supply.

    Errors when the mesh interleaves this process's row shards with another
    process's (cannot happen with the process-major device order
    ``jax.devices()`` returns, but a hand-built mesh could): the loader
    partition contract is a contiguous row slice per process.
    """
    owned, n_blocks = _owned_row_blocks(plan)
    if global_batch % n_blocks:
        raise ValueError(f"global batch {global_batch} does not divide over "
                         f"{n_blocks} data shards")
    rpb = global_batch // n_blocks
    if not owned:
        raise ValueError("mesh owns no devices on this process")
    if owned != tuple(range(owned[0], owned[0] + len(owned))):
        raise ValueError(
            f"process {jax.process_index()} owns non-contiguous row shards "
            f"{owned}; build the mesh from jax.devices() order so each "
            "process's batch rows are one contiguous slice")
    return owned[0] * rpb, (owned[-1] + 1) * rpb


def assert_loader_partition(plan, global_batch: int, num_parts: int,
                            part_index: int) -> None:
    """Check that ``AnchorLoader(num_parts, part_index)``'s contiguous
    equal split produces exactly the rows :func:`local_row_range` says this
    process's devices hold."""
    lo, hi = local_row_range(plan, global_batch)
    bl = global_batch // num_parts
    want = (part_index * bl, (part_index + 1) * bl)
    if (lo, hi) != want:
        raise ValueError(
            f"loader part {part_index}/{num_parts} supplies rows {want} but "
            f"this process's mesh shards cover rows {(lo, hi)}; use "
            "part_index=jax.process_index() with num_parts="
            "jax.process_count() on a jax.devices()-ordered mesh")


@functools.lru_cache(maxsize=256)
def _indices_map(sharding, gshape):
    """Cached ``(device, index-tuple)`` pairs for a (sharding, shape):
    constant for the life of the mesh, queried every batch."""
    return tuple(sharding.addressable_devices_indices_map(gshape).items())


def _make_global(x, sharding, gshape, batch_dim: int, lo: int):
    """One leaf: local rows ``x`` (covering global rows [lo, hi) of
    ``batch_dim``) → a global ``jax.Array`` with ``sharding``."""
    imap = _indices_map(sharding, gshape)
    shards = []
    devices = []
    for d, idx in imap:
        sel = list(idx)
        while len(sel) < len(gshape):
            sel.append(slice(None))
        b = sel[batch_dim]
        sel[batch_dim] = slice((b.start or 0) - lo,
                               (b.stop if b.stop is not None else
                                gshape[batch_dim]) - lo)
        shards.append(x[tuple(sel)])
        devices.append(d)
    arrs = [jax.device_put(s, d) for s, d in zip(shards, devices)]
    return jax.make_array_from_single_device_arrays(gshape, sharding, arrs)


def global_from_local(plan, batch: dict, stacked: bool = False):
    """Per-process batch rows → global on-mesh arrays (multi-process
    ``shard_batch``).

    ``batch``: dict of host numpy leaves.  Normal batches carry the batch
    on axis 0; ``stacked=True`` is the ``shard_stacked_batch`` form — a
    leading unsharded (k,) stack axis with the batch on axis 1
    (``steps_per_dispatch`` groups).  The global batch size is derived
    from the local row count and the mesh's row-shard ownership, so the
    caller passes exactly what the loader yielded.
    """
    from mx_rcnn_tpu.parallel.mesh import stack_sharding

    if not isinstance(batch, dict):
        raise TypeError("multi-process batches must be dicts (loader "
                        f"output); got {type(batch).__name__}")
    owned, n_blocks = _owned_row_blocks(plan)
    if not owned:
        raise ValueError("mesh owns no devices on this process")
    bdim = 1 if stacked else 0
    any_leaf = next(iter(batch.values()))
    local_rows = any_leaf.shape[bdim]
    if local_rows % len(owned):
        raise ValueError(f"local batch {local_rows} does not divide over "
                         f"this process's {len(owned)} row shards")
    global_batch = (local_rows // len(owned)) * n_blocks
    # local_row_range re-validates contiguity and yields lo with the
    # actionable error messages (do not re-derive the row math here)
    lo, hi = local_row_range(plan, global_batch)
    if hi - lo != local_rows:
        raise ValueError(f"local batch rows {local_rows} != rows "
                         f"[{lo}, {hi}) this process's shards cover")
    b_sh = plan.batch()
    im_sh = plan.images()
    if stacked:
        b_sh, im_sh = stack_sharding(b_sh), stack_sharding(im_sh)
    out = {}
    for k, x in batch.items():
        sh = im_sh if k == "images" else b_sh
        gshape = (x.shape[:bdim] + (global_batch,) + x.shape[bdim + 1:])
        out[k] = _make_global(np.asarray(x), sh, gshape, bdim, lo)
    return out


@functools.lru_cache(maxsize=32)
def _warm_collectives_impl(plan, _pcount: int) -> None:
    """Eagerly create the cross-process communicator for ``plan``'s FULL
    device clique (no-op on single-process meshes; cached per
    (plan, process_count) like the helpers above).

    Backends create a communicator lazily at the first collective that
    needs it, i.e. inside the first execution of the big train step — and
    Gloo's communicator key-exchange has a hard ~30 s deadline, while the
    ranks reach that first execution skewed by their big-program COMPILE
    times (tens of seconds apart on a loaded host; the init-time barrier
    cannot help because it synchronizes a different, per-process clique).
    Running one trivial sharded reduction here — compiled in ~1 s while
    the ranks are still aligned — creates the full-clique communicator
    up front; the train step then reuses it with no deadline in play.
    """
    if not is_multiprocess_mesh(plan.mesh):
        return
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    # align ranks RIGHT before the clique-creating collective: whatever
    # each rank compiled on the way here (param init etc.) skewed them,
    # and the RPC barrier below — unlike a device collective — has a
    # generous configurable deadline
    sync("warm_collectives")
    n = plan.n_data
    lo, hi = local_row_range(plan, n)
    garr = _make_global(np.zeros((hi - lo,), np.float32), plan.batch(),
                        (n,), 0, lo)
    out = jax.jit(jnp.sum,
                  out_shardings=NamedSharding(plan.mesh, P()))(garr)
    jax.block_until_ready(out)
    logger.info("process %d/%d: warmed the %d-device cross-process "
                "collective clique", jax.process_index(),
                jax.process_count(), plan.mesh.devices.size)


def warm_collectives(plan) -> None:
    _warm_collectives_impl(plan, jax.process_count())


_sync_counter = [0]
_warned_sync_fallback = False


def sync(name: str = "barrier", timeout_ms: int = 600_000) -> None:
    """Cross-process barrier (no-op single-process).

    Uses the coordination-service RPC barrier, NOT a device collective:
    device collectives lazily create backend communicators whose
    key-exchange deadline (~30 s under Gloo) is far tighter than the skew
    real jobs accumulate while compiling, which is exactly when a barrier
    is needed.  The RPC barrier takes an explicit (long) deadline.  Falls
    back to ``sync_global_devices`` if the private client API moves.
    Barrier ids are name+counter; the counter advances identically on all
    ranks because every call site runs in lockstep.
    """
    if jax.process_count() <= 1:
        return
    _sync_counter[0] += 1
    bid = f"mxr_{name}_{_sync_counter[0]}"
    try:
        from jax._src import distributed as _dist

        client = _dist.global_state.client
    except Exception:
        client = None
    if client is not None:
        client.wait_at_barrier(bid, timeout_in_ms=timeout_ms)
        return
    # The fallback is a DEVICE collective: it lazily creates a backend
    # communicator whose key-exchange deadline (~30 s under Gloo) is the
    # exact failure mode this function exists to dodge, so losing the RPC
    # path silently would lose the barrier's load-bearing property
    # (round-4 advisor finding).  Warn once, loudly: a jax upgrade that
    # moved jax._src.distributed should be met by re-pinning the private
    # import, not by shipping the weaker barrier.
    global _warned_sync_fallback
    if not _warned_sync_fallback:
        _warned_sync_fallback = True
        logger.warning(
            "jax._src.distributed.global_state.client is unavailable "
            "(jax %s; the private API was verified present on 0.9.0, the "
            "pinned build) — sync(%r) falling back to sync_global_devices, a "
            "device collective subject to the ~30 s Gloo key-exchange "
            "deadline this barrier exists to avoid; expect spurious "
            "barrier timeouts under compile-time skew", jax.__version__,
            name)
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(bid)
