"""Mesh construction + sharding plans.

The reference's single parallelism strategy is data parallelism
(SURVEY §2.3): ``Module`` splits each host batch across ``ctx = [mx.gpu(i)]``
and ``KVStore('device')`` all-reduces gradients over PCIe/NVLink.  Here the
same strategy is a named mesh axis:

* ``data`` — batch axis.  Gradients are all-reduced over it by XLA (the
  collective rides ICI within a slice, DCN across slices when the axis spans
  slices).
* ``model`` — reserved model axis (size 1 in the reference configs; the
  mesh abstraction keeps it open for sharding large backbones / FPN heads —
  an intentional extension point, not a reference capability).
* ``space`` — spatial-parallel axis (``make_mesh(space=N)``): the image
  HEIGHT dimension shards over it, so the conv body runs on H-slices with
  XLA/GSPMD inserting the halo exchanges every 3×3/stride conv needs —
  the detection analogue of sequence/context parallelism for inputs too
  large for one chip's HBM (aerial/medical tiles).  Where the graph stops
  being spatially shardable (the per-image proposal sort/NMS and the RoI
  head), GSPMD's propagation inserts the gather; compute up to c4 — 90%
  of the FLOPs (SURVEY §3.5) — stays sharded.  Like ``model``, an
  extension beyond the reference's DP-only strategy.

Everything here is plain `jax.sharding`; no pmap.  A jitted step whose
inputs carry these shardings gets its collectives inserted by XLA — the
TPU equivalent of the KVStore push/pull in the reference call stack
(SURVEY §3.1 "KVStore push/pull gradient reduce").
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class MeshPlan:
    """A mesh plus the shardings the train/eval steps use.

    Axis convention: an optional leading ``dcn`` axis (slice-crossing, for
    multi-slice jobs), then ``data`` (ICI within a slice), then ``model``.
    The batch shards over every batch axis present, so a multi-slice
    gradient all-reduce decomposes into an ICI reduce within each slice
    plus a DCN reduce across slices — XLA picks the hierarchical schedule
    from the mesh's device order (the "How to Scale Your Model" recipe:
    name the axes, annotate, let XLA place collectives).
    """

    mesh: Mesh

    @property
    def batch_axes(self) -> tuple:
        return tuple(n for n in self.mesh.axis_names
                     if n not in ("model", "space"))

    @property
    def n_data(self) -> int:
        n = 1
        for a in self.batch_axes:
            n *= self.mesh.shape[a]
        return n

    def batch(self) -> NamedSharding:
        """Leading-axis (batch) sharding over all batch axes (dcn, data)."""
        return NamedSharding(self.mesh, P(self.batch_axes))

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    @property
    def n_model(self) -> int:
        return self.mesh.shape.get("model", 1)

    @property
    def n_space(self) -> int:
        return self.mesh.shape.get("space", 1)

    def images(self) -> NamedSharding:
        """Sharding for image tensors (B, H, W, C) — batch over the batch
        axes AND height over ``space`` (rows split across chips; GSPMD
        halo-exchanges the conv borders).  Identical to ``batch()`` when
        the mesh has no space axis."""
        if self.n_space <= 1:
            return self.batch()
        return NamedSharding(self.mesh, P(self.batch_axes, "space"))

    # -- tensor parallelism over the head FCs (model axis > 1) --------------
    # The classic Megatron pairing on the RoI-head MLP, which is where the
    # shardable parameters are (VGG fc6 alone is 25088×4096 ≈ 100M params;
    # the FPN box head uses the same fc6/fc7 names): fc6 column-parallel
    # (output features sharded — its bias shards with them; the relu/dropout
    # between the FCs are elementwise on the sharded features), fc7
    # row-parallel (contracts the sharded axis; XLA inserts the psum and
    # the replicated fc7 bias adds after it).  Everything else replicates —
    # conv backbones are data-parallel territory (SURVEY §2.3: DP is the
    # reference's only strategy; the model axis is our extension point).
    _TP_RULES = (
        (("fc6", "kernel"), P(None, "model")),
        (("fc6", "bias"), P("model")),
        (("fc7", "kernel"), P("model", None)),
        (("fc7", "bias"), P()),
    )

    def _tp_rule(self, path):
        names = tuple(getattr(e, "key", getattr(e, "name", str(e)))
                      for e in path)
        for suffix, spec in self._TP_RULES:
            if names[-len(suffix):] == tuple(suffix):
                return NamedSharding(self.mesh, spec)
        return self.replicated()

    def param_shardings(self, params):
        """Sharding tree for a param tree: replicated except the TP rules
        above (no-op mesh without a >1 ``model`` axis → all replicated)."""
        if self.n_model <= 1:
            return jax.tree.map(lambda _: self.replicated(), params)
        return jax.tree_util.tree_map_with_path(
            lambda p, _: self._tp_rule(p), params)

    def state_shardings(self, state):
        """Sharding tree for a TrainState (same pytree structure, shardings
        as leaves — jit's in_shardings/out_shardings form).  Optimizer-state
        leaves match by PATH SUFFIX: optax's momentum trees keep the param
        tree's key path as a suffix (…/trace/head_body/fc6/kernel), so the
        same TP rules apply; scalar counts fall through to replicated."""
        return dataclasses.replace(
            state, step=self.replicated(),
            params=self.param_shardings(state.params),
            opt_state=self.param_shardings(state.opt_state))


def make_mesh(devices: Optional[Sequence[jax.Device]] = None,
              data: Optional[int] = None, model: int = 1,
              space: int = 1,
              axis_names=None) -> MeshPlan:
    """Build a (data, model[, space]) mesh from the visible devices.

    ``data`` defaults to ``len(devices) // (model * space)``.  On a real
    pod slice, device order from `jax.devices()` keeps ICI neighbours
    adjacent, so the inner axes ride ICI — ``space`` is innermost because
    halo exchanges are the most latency-sensitive collective.  For
    multi-slice jobs use ``make_multislice_mesh`` (a leading DCN axis —
    the reference's `dist_sync` kvstore analogue, which upstream left
    unscripted; here it is scripted and tested on the virtual mesh).
    """
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    if axis_names is None:
        axis_names = (("data", "model", "space") if space > 1
                      else ("data", "model"))
    elif space > 1 and (len(axis_names) != 3 or axis_names[2] != "space"):
        # the device grid below is shaped (data, model, space); caller-
        # supplied names must agree or images silently stop height-sharding
        raise ValueError(
            f"space={space} needs axis_names (data, model, 'space'); "
            f"got {axis_names}")
    if data is None:
        data = len(devices) // (model * space)
    n = data * model * space
    if n > len(devices):
        raise ValueError(f"mesh {data}x{model}x{space} needs {n} devices, "
                         f"have {len(devices)}")
    if n < len(devices):
        # same contract as make_multislice_mesh: an explicit smaller mesh
        # must not silently idle chips — slice the device list yourself
        raise ValueError(
            f"mesh {data}x{model}x{space} uses only {n} of {len(devices)} "
            "devices; pass devices[:n] explicitly if that is intended")
    shape = (data, model, space) if space > 1 else (data, model)
    arr = np.asarray(devices).reshape(shape)
    return MeshPlan(mesh=Mesh(arr, axis_names))


def make_multislice_mesh(devices: Optional[Sequence[jax.Device]] = None,
                         slices: Optional[int] = None,
                         data_per_slice: Optional[int] = None,
                         model: int = 1) -> MeshPlan:
    """Hierarchical data-parallel mesh for multi-slice jobs:
    axes ``(dcn, data, model)`` with ``dcn`` crossing slice boundaries.

    On real multi-slice hardware the slice of each device is read from
    ``device.slice_index`` (devices grouped so DCN is the outer axis and
    ICI neighbours stay adjacent on the inner axes — the layout
    `jax.experimental.mesh_utils.create_hybrid_device_mesh` produces).
    When the runtime exposes no slice topology (single slice, CPU test
    mesh), ``slices`` partitions the device list positionally — that is
    how the multi-slice step compiles and runs on the 8-device virtual
    mesh in tests.

    The train step needs no changes: ``MeshPlan.batch()`` shards the batch
    over (dcn, data) jointly and XLA lowers the gradient all-reduce into
    the within-slice ICI part and the cross-slice DCN part.
    """
    if devices is None:
        devices = jax.devices()
    devices = list(devices)

    slice_ids = [getattr(d, "slice_index", 0) for d in devices]
    n_real = len(set(slice_ids))
    if n_real > 1:  # real multi-slice topology: group by slice
        by_slice: dict = {}
        for d, s in zip(devices, slice_ids):
            by_slice.setdefault(s, []).append(d)
        groups = [by_slice[s] for s in sorted(by_slice)]
        if slices is None:
            slices = len(groups)
        if slices != len(groups):
            raise ValueError(f"requested {slices} slices, topology has {len(groups)}")
        sizes = {len(g) for g in groups}
        if len(sizes) > 1:  # never silently drop a slice's extra chips
            raise ValueError(f"slices are uneven: sizes {sorted(sizes)}; "
                             "pass an explicit device subset")
        per = len(groups[0])
    else:  # positional emulation (single slice / virtual CPU mesh)
        if slices is None:
            raise ValueError("slices required when the runtime exposes no "
                             "slice topology")
        if slices < 1 or len(devices) % slices:
            raise ValueError(f"{len(devices)} devices do not divide into "
                             f"{slices} slices")
        per = len(devices) // slices
        groups = [devices[i * per:(i + 1) * per] for i in range(slices)]
    if data_per_slice is None:
        data_per_slice = per // model
    n = data_per_slice * model
    if n > per:
        raise ValueError(f"slice mesh {data_per_slice}x{model} needs {n} "
                         f"devices per slice, have {per}")
    if n < per:
        # mirrors the uneven-slice error above: an explicit data_per_slice
        # smaller than the slice must not silently idle chips
        raise ValueError(
            f"slice mesh {data_per_slice}x{model} uses only {n} of {per} "
            "devices per slice; pass an explicit device subset if that is "
            "intended")
    arr = np.asarray(groups).reshape(slices, data_per_slice, model)
    return MeshPlan(mesh=Mesh(arr, ("dcn", "data", "model")))


def check_spatial(plan: MeshPlan, cfg) -> None:
    """Reject spatial plans whose height shards would be thinner than a
    stride-2 conv's halo.

    Round-4 finding (virtual CPU mesh, jax 0.9/XLA): when a height-sharded
    stride-2 3×3 conv's input has only ONE row per ``space`` shard, the
    SPMD-partitioned program returns garbage (isolated: a lone conv is
    fine; inside the ResNet bottleneck composite the output is off by O(1)
    — an XLA partitioner bug with halos spanning multiple shards, not a
    rounding effect).  With ≥ 2 rows per shard at every stride-2 input the
    sharded program matches the flat one to f32 rounding (measured 1e-5
    on the full FPN pyramid).  The invariant is ≥ 2 rows/shard at every
    stride-2 input **with a spatial window > 1** (i.e. a halo): the
    deepest such input is C4 (stride 16) for FPN's stage 5, C3 (stride 8)
    for the classic body (whose stage 5 runs on pooled RoIs, not the
    sharded map) — hence ``min SCALES height >= 2 * stride * n_space``.
    FPN's P6 subsample does consume the stride-32 P5 map at 1 row/shard
    inside this envelope, but it is a 1×1-window stride-2 max_pool
    (``models/fpn.py``): each output row reads exactly one input row, no
    halo exchange exists to miscompile, and the H=64 space=2 eval parity
    test (``tests/test_eval_mesh.py``) runs exactly that 1-row/shard P6
    shape and matches the flat program."""
    if plan.n_space <= 1:
        return
    stride = 16 if cfg.network.HAS_FPN else 8
    min_h = min(int(h) for h, _ in cfg.tpu.SCALES)
    need = 2 * stride * plan.n_space
    if min_h < need:
        raise ValueError(
            f"space={plan.n_space} needs image height >= {need} "
            f"(2 rows/shard at the deepest stride-2 conv input, stride "
            f"{stride}); SCALES has height {min_h}.  Thinner shards hit an "
            f"XLA SPMD halo miscompile — see parallel/mesh.py:check_spatial")


def shard_batch(plan: MeshPlan, batch):
    """Place a host batch (pytree of np arrays, leading axis = batch) onto
    the mesh, split over the data axis — the analogue of Module's
    ``work_load_list`` ctx split, minus the host copy per device: a single
    `device_put` with a sharding does the scatter.  On a spatial mesh the
    ``images`` entry additionally splits its height rows over ``space``
    (``MeshPlan.images``).

    On a mesh spanning several processes (multi-host — see
    ``parallel/distributed.py``) each process passes only ITS rows of the
    global batch (the loader's ``num_parts``/``part_index`` slice) and the
    global arrays are assembled per-shard; the single-process fast path is
    one ``device_put`` scatter."""
    from mx_rcnn_tpu.parallel.distributed import (global_from_local,
                                                  is_multiprocess_mesh)

    if is_multiprocess_mesh(plan.mesh):
        return global_from_local(plan, batch)
    sh = plan.batch()
    if isinstance(batch, dict):
        im_sh = plan.images()
        return jax.device_put(
            batch, {k: im_sh if k == "images" else sh for k in batch})
    if plan.n_space > 1:
        raise TypeError(
            "spatial meshes require dict batches (the 'images' key selects "
            f"the height-sharded placement); got {type(batch).__name__}")
    return jax.tree.map(lambda x: jax.device_put(x, sh), batch)

def stack_sharding(sh):
    """The same placement with an unsharded leading (stack) axis
    prepended — the one rule for multi-step (k, batch, ...) trees; both
    ``shard_stacked_batch`` and ``make_multi_train_step``'s in_shardings
    derive from here so the two can never diverge."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(sh.mesh, P(None, *sh.spec))


def shard_stacked_batch(plan: MeshPlan, batches):
    """Place a STACK of k host batches (every leaf (k, batch, ...)) onto
    the mesh for ``make_multi_train_step``: the leading stack axis stays
    unsharded, the batch axis splits over the data axes, and ``images``
    additionally splits height over ``space`` when present.  Multi-process
    meshes assemble global arrays from each process's rows, like
    ``shard_batch``."""
    from mx_rcnn_tpu.parallel.distributed import (global_from_local,
                                                  is_multiprocess_mesh)

    if is_multiprocess_mesh(plan.mesh):
        return global_from_local(plan, batches, stacked=True)
    sh = stack_sharding(plan.batch())
    if isinstance(batches, dict):
        im_sh = stack_sharding(plan.images())
        return jax.device_put(
            batches, {k: im_sh if k == "images" else sh for k in batches})
    if plan.n_space > 1:
        raise TypeError(
            "spatial meshes require dict batches (the 'images' key selects "
            f"the height-sharded placement); got {type(batches).__name__}")
    return jax.tree.map(lambda x: jax.device_put(x, sh), batches)
