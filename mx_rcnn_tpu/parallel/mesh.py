"""Mesh construction + sharding plans.

The reference's single parallelism strategy is data parallelism
(SURVEY §2.3): ``Module`` splits each host batch across ``ctx = [mx.gpu(i)]``
and ``KVStore('device')`` all-reduces gradients over PCIe/NVLink.  Here the
same strategy is a named mesh axis:

* ``data`` — batch axis.  Gradients are all-reduced over it by XLA (the
  collective rides ICI within a slice, DCN across slices when the axis spans
  slices).
* ``model`` — reserved model axis (size 1 in the reference configs; the
  mesh abstraction keeps it open for sharding large backbones / FPN heads —
  an intentional extension point, not a reference capability).

Everything here is plain `jax.sharding`; no pmap.  A jitted step whose
inputs carry these shardings gets its collectives inserted by XLA — the
TPU equivalent of the KVStore push/pull in the reference call stack
(SURVEY §3.1 "KVStore push/pull gradient reduce").
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class MeshPlan:
    """A mesh plus the shardings the train/eval steps use.

    Axis convention: an optional leading ``dcn`` axis (slice-crossing, for
    multi-slice jobs), then ``data`` (ICI within a slice), then ``model``.
    The batch shards over every batch axis present, so a multi-slice
    gradient all-reduce decomposes into an ICI reduce within each slice
    plus a DCN reduce across slices — XLA picks the hierarchical schedule
    from the mesh's device order (the "How to Scale Your Model" recipe:
    name the axes, annotate, let XLA place collectives).
    """

    mesh: Mesh

    @property
    def batch_axes(self) -> tuple:
        return tuple(n for n in self.mesh.axis_names if n != "model")

    @property
    def n_data(self) -> int:
        n = 1
        for a in self.batch_axes:
            n *= self.mesh.shape[a]
        return n

    def batch(self) -> NamedSharding:
        """Leading-axis (batch) sharding over all batch axes (dcn, data)."""
        return NamedSharding(self.mesh, P(self.batch_axes))

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    @property
    def n_model(self) -> int:
        return self.mesh.shape.get("model", 1)

    # -- tensor parallelism over the head FCs (model axis > 1) --------------
    # The classic Megatron pairing on the RoI-head MLP, which is where the
    # shardable parameters are (VGG fc6 alone is 25088×4096 ≈ 100M params;
    # the FPN box head uses the same fc6/fc7 names): fc6 column-parallel
    # (output features sharded — its bias shards with them; the relu/dropout
    # between the FCs are elementwise on the sharded features), fc7
    # row-parallel (contracts the sharded axis; XLA inserts the psum and
    # the replicated fc7 bias adds after it).  Everything else replicates —
    # conv backbones are data-parallel territory (SURVEY §2.3: DP is the
    # reference's only strategy; the model axis is our extension point).
    _TP_RULES = (
        (("fc6", "kernel"), P(None, "model")),
        (("fc6", "bias"), P("model")),
        (("fc7", "kernel"), P("model", None)),
        (("fc7", "bias"), P()),
    )

    def _tp_rule(self, path):
        names = tuple(getattr(e, "key", getattr(e, "name", str(e)))
                      for e in path)
        for suffix, spec in self._TP_RULES:
            if names[-len(suffix):] == tuple(suffix):
                return NamedSharding(self.mesh, spec)
        return self.replicated()

    def param_shardings(self, params):
        """Sharding tree for a param tree: replicated except the TP rules
        above (no-op mesh without a >1 ``model`` axis → all replicated)."""
        if self.n_model <= 1:
            return jax.tree.map(lambda _: self.replicated(), params)
        return jax.tree_util.tree_map_with_path(
            lambda p, _: self._tp_rule(p), params)

    def state_shardings(self, state):
        """Sharding tree for a TrainState (same pytree structure, shardings
        as leaves — jit's in_shardings/out_shardings form).  Optimizer-state
        leaves match by PATH SUFFIX: optax's momentum trees keep the param
        tree's key path as a suffix (…/trace/head_body/fc6/kernel), so the
        same TP rules apply; scalar counts fall through to replicated."""
        return dataclasses.replace(
            state, step=self.replicated(),
            params=self.param_shardings(state.params),
            opt_state=self.param_shardings(state.opt_state))


def make_mesh(devices: Optional[Sequence[jax.Device]] = None,
              data: Optional[int] = None, model: int = 1,
              axis_names=("data", "model")) -> MeshPlan:
    """Build a (data, model) mesh from the visible devices.

    ``data`` defaults to ``len(devices) // model``.  On a real pod slice,
    device order from `jax.devices()` keeps ICI neighbours adjacent, so the
    data axis rides ICI.  For multi-slice jobs use ``make_multislice_mesh``
    (a leading DCN axis — the reference's `dist_sync` kvstore analogue,
    which upstream left unscripted; here it is scripted and tested on the
    virtual mesh).
    """
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    if data is None:
        data = len(devices) // model
    n = data * model
    if n > len(devices):
        raise ValueError(f"mesh {data}x{model} needs {n} devices, have {len(devices)}")
    if n < len(devices):
        # same contract as make_multislice_mesh: an explicit smaller mesh
        # must not silently idle chips — slice the device list yourself
        raise ValueError(
            f"mesh {data}x{model} uses only {n} of {len(devices)} devices; "
            "pass devices[:n] explicitly if that is intended")
    arr = np.asarray(devices).reshape(data, model)
    return MeshPlan(mesh=Mesh(arr, axis_names))


def make_multislice_mesh(devices: Optional[Sequence[jax.Device]] = None,
                         slices: Optional[int] = None,
                         data_per_slice: Optional[int] = None,
                         model: int = 1) -> MeshPlan:
    """Hierarchical data-parallel mesh for multi-slice jobs:
    axes ``(dcn, data, model)`` with ``dcn`` crossing slice boundaries.

    On real multi-slice hardware the slice of each device is read from
    ``device.slice_index`` (devices grouped so DCN is the outer axis and
    ICI neighbours stay adjacent on the inner axes — the layout
    `jax.experimental.mesh_utils.create_hybrid_device_mesh` produces).
    When the runtime exposes no slice topology (single slice, CPU test
    mesh), ``slices`` partitions the device list positionally — that is
    how the multi-slice step compiles and runs on the 8-device virtual
    mesh in tests.

    The train step needs no changes: ``MeshPlan.batch()`` shards the batch
    over (dcn, data) jointly and XLA lowers the gradient all-reduce into
    the within-slice ICI part and the cross-slice DCN part.
    """
    if devices is None:
        devices = jax.devices()
    devices = list(devices)

    slice_ids = [getattr(d, "slice_index", 0) for d in devices]
    n_real = len(set(slice_ids))
    if n_real > 1:  # real multi-slice topology: group by slice
        by_slice: dict = {}
        for d, s in zip(devices, slice_ids):
            by_slice.setdefault(s, []).append(d)
        groups = [by_slice[s] for s in sorted(by_slice)]
        if slices is None:
            slices = len(groups)
        if slices != len(groups):
            raise ValueError(f"requested {slices} slices, topology has {len(groups)}")
        sizes = {len(g) for g in groups}
        if len(sizes) > 1:  # never silently drop a slice's extra chips
            raise ValueError(f"slices are uneven: sizes {sorted(sizes)}; "
                             "pass an explicit device subset")
        per = len(groups[0])
    else:  # positional emulation (single slice / virtual CPU mesh)
        if slices is None:
            raise ValueError("slices required when the runtime exposes no "
                             "slice topology")
        if slices < 1 or len(devices) % slices:
            raise ValueError(f"{len(devices)} devices do not divide into "
                             f"{slices} slices")
        per = len(devices) // slices
        groups = [devices[i * per:(i + 1) * per] for i in range(slices)]
    if data_per_slice is None:
        data_per_slice = per // model
    n = data_per_slice * model
    if n > per:
        raise ValueError(f"slice mesh {data_per_slice}x{model} needs {n} "
                         f"devices per slice, have {per}")
    if n < per:
        # mirrors the uneven-slice error above: an explicit data_per_slice
        # smaller than the slice must not silently idle chips
        raise ValueError(
            f"slice mesh {data_per_slice}x{model} uses only {n} of {per} "
            "devices per slice; pass an explicit device subset if that is "
            "intended")
    arr = np.asarray(groups).reshape(slices, data_per_slice, model)
    return MeshPlan(mesh=Mesh(arr, ("dcn", "data", "model")))


def shard_batch(plan: MeshPlan, batch):
    """Place a host batch (pytree of np arrays, leading axis = batch) onto
    the mesh, split over the data axis — the analogue of Module's
    ``work_load_list`` ctx split, minus the host copy per device: a single
    `device_put` with a sharding does the scatter."""
    sh = plan.batch()
    return jax.tree.map(lambda x: jax.device_put(x, sh), batch)
