"""Mesh construction + sharding plans.

The reference's single parallelism strategy is data parallelism
(SURVEY §2.3): ``Module`` splits each host batch across ``ctx = [mx.gpu(i)]``
and ``KVStore('device')`` all-reduces gradients over PCIe/NVLink.  Here the
same strategy is a named mesh axis:

* ``data`` — batch axis.  Gradients are all-reduced over it by XLA (the
  collective rides ICI within a slice, DCN across slices when the axis spans
  slices).
* ``model`` — reserved model axis (size 1 in the reference configs; the
  mesh abstraction keeps it open for sharding large backbones / FPN heads —
  an intentional extension point, not a reference capability).

Everything here is plain `jax.sharding`; no pmap.  A jitted step whose
inputs carry these shardings gets its collectives inserted by XLA — the
TPU equivalent of the KVStore push/pull in the reference call stack
(SURVEY §3.1 "KVStore push/pull gradient reduce").
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class MeshPlan:
    """A mesh plus the shardings the train/eval steps use."""

    mesh: Mesh

    @property
    def data_axis(self) -> str:
        return self.mesh.axis_names[0]

    @property
    def n_data(self) -> int:
        return self.mesh.shape[self.data_axis]

    def batch(self) -> NamedSharding:
        """Leading-axis (batch) sharding over the data axis."""
        return NamedSharding(self.mesh, P(self.data_axis))

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())


def make_mesh(devices: Optional[Sequence[jax.Device]] = None,
              data: Optional[int] = None, model: int = 1,
              axis_names=("data", "model")) -> MeshPlan:
    """Build a (data, model) mesh from the visible devices.

    ``data`` defaults to ``len(devices) // model``.  On a real pod slice,
    device order from `jax.devices()` keeps ICI neighbours adjacent, so the
    data axis rides ICI; a multi-slice job would add a leading DCN axis via
    `jax.experimental.mesh_utils` — kept out of scope until multi-slice is
    scripted (the reference's `dist_sync` kvstore analogue, also unscripted
    there).
    """
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    if data is None:
        data = len(devices) // model
    n = data * model
    if n > len(devices):
        raise ValueError(f"mesh {data}x{model} needs {n} devices, have {len(devices)}")
    arr = np.asarray(devices[:n]).reshape(data, model)
    return MeshPlan(mesh=Mesh(arr, axis_names))


def shard_batch(plan: MeshPlan, batch):
    """Place a host batch (pytree of np arrays, leading axis = batch) onto
    the mesh, split over the data axis — the analogue of Module's
    ``work_load_list`` ctx split, minus the host copy per device: a single
    `device_put` with a sharding does the scatter."""
    sh = plan.batch()
    return jax.tree.map(lambda x: jax.device_put(x, sh), batch)
