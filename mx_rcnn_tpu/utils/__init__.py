"""Param utilities (reference ``rcnn/utils/``: load_model / save_model /
combine_model).  Load/save live in ``train/checkpoint.py`` (orbax + npz);
``combine_model`` merges alternate-training stage params."""

from mx_rcnn_tpu.utils.combine_model import combine_model
from mx_rcnn_tpu.utils.load_data import load_proposals, merge_roidb
