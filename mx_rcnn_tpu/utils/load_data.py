"""Proposal roidb assembly (reference ``rcnn/utils/load_data.py``:
``load_proposal_roidb`` / ``merge_roidb``): attach cached RPN proposals
(the .pkl written by ``tools/test_rpn``) to a gt roidb for ROIIter
training, and concatenate roidbs across image sets.
"""

from __future__ import annotations

import pickle
from typing import List

import numpy as np

from mx_rcnn_tpu.data.imdb import IMDB
from mx_rcnn_tpu.logger import logger


def load_proposals(roidb: list, pkl_path: str) -> list:
    """Attach per-image proposals from a test_rpn cache (aligned by index)."""
    with open(pkl_path, "rb") as f:
        proposals = pickle.load(f)
    if len(proposals) != len(roidb):
        raise ValueError(f"proposal cache has {len(proposals)} entries for "
                         f"{len(roidb)} roidb records")
    n = 0
    for rec, props in zip(roidb, proposals):
        rec["proposals"] = IMDB.sanitize_proposals(
            props if props is not None else np.zeros((0, 4), np.float32),
            rec["width"], rec["height"])
        n += len(rec["proposals"])
    logger.info("attached %d proposals from %s", n, pkl_path)
    return roidb


def merge_roidb(roidbs: List[list]) -> list:
    """Concatenate roidbs (reference ``merge_roidb`` — multi-image-set
    training, e.g. VOC07+12; PascalVOC already handles '+' sets natively,
    this covers arbitrary combinations)."""
    out: list = []
    for r in roidbs:
        out.extend(r)
    return out
