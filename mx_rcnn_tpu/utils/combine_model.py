"""``combine_model`` (reference ``rcnn/utils/combine_model.py``): merge the
RPN-trained and RCNN-trained parameter trees from 4-step alternate training
into one deployment tree — backbone + RPN head from the RPN stage,
RCNN head (head_body + rcnn_out) from the RCNN stage.
"""

from __future__ import annotations

RPN_KEYS = ("backbone", "neck", "rpn")  # neck: FPN models share it with RPN
RCNN_KEYS = ("head_body", "rcnn_out", "mask_head")


def combine_model(rpn_params: dict, rcnn_params: dict) -> dict:
    """Merge stage params into a single tree for the unified test graph."""
    out = {}
    for k in rpn_params:
        if k in RPN_KEYS:
            out[k] = rpn_params[k]
    for k in rcnn_params:
        if k in RCNN_KEYS:
            out[k] = rcnn_params[k]
    missing = [k for k in ("backbone", "rpn", "head_body", "rcnn_out")
               if k not in out]
    if missing:
        raise KeyError(f"combine_model: missing submodules {missing}")
    return out
