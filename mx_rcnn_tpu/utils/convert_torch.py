"""Convert torchvision ImageNet checkpoints → this framework's .npz layout.

The reference consumes MXNet ImageNet params (``--pretrained``); with no
MXNet here, the practical interchange is a torchvision ``state_dict``
(``resnet{50,101,152}``, ``vgg16``) saved as .pth — convert offline with this
module, then pass the .npz to ``--pretrained`` (tools/common.py overlays it
onto the init tree by path+shape match).

Name maps (torchvision → flax tree under ``backbone``/``head_body``):

ResNet:  conv1→backbone/conv1, bn1→backbone/bn1,
         layer{1..3}.{u}.*→backbone/stage{1..3}/unit{u+1}/*,
         layer4.{u}.*→head_body/stage4/unit{u+1}/*,
         convN/downsample.0→convN/sc_conv (OIHW→HWIO),
         bnN/downsample.1→{gamma,beta,mean,var}.
VGG16:   features.{idx}→backbone/conv{b}_{i} (the 13 convs in order),
         classifier.{0,3}→head_body/{fc6,fc7} (fc weights transposed).
"""

from __future__ import annotations

from typing import Dict

import numpy as np

RESNET_UNITS = {"resnet50": (3, 4, 6, 3), "resnet101": (3, 4, 23, 3),
                "resnet152": (3, 8, 36, 3)}

# torchvision vgg16 features indices of the 13 convs, in block order
_VGG_CONV_IDX = [0, 2, 5, 7, 10, 12, 14, 17, 19, 21, 24, 26, 28]
_VGG_NAMES = ["conv1_1", "conv1_2", "conv2_1", "conv2_2", "conv3_1",
              "conv3_2", "conv3_3", "conv4_1", "conv4_2", "conv4_3",
              "conv5_1", "conv5_2", "conv5_3"]


def _conv(w: np.ndarray) -> np.ndarray:
    """OIHW → HWIO."""
    return np.transpose(np.asarray(w), (2, 3, 1, 0))


def _bn(prefix: str, sd: Dict) -> Dict[str, np.ndarray]:
    return {
        "gamma": np.asarray(sd[prefix + ".weight"]),
        "beta": np.asarray(sd[prefix + ".bias"]),
        "mean": np.asarray(sd[prefix + ".running_mean"]),
        "var": np.asarray(sd[prefix + ".running_var"]),
    }


def convert_resnet(sd: Dict, depth: str = "resnet50") -> Dict[str, np.ndarray]:
    """torchvision resnet state_dict → flat {path: array} for
    save_params_npz's layout (backbone stages 1-3 + head_body stage4)."""
    out: Dict[str, np.ndarray] = {}

    def put(path: str, arr: np.ndarray):
        out[path] = np.asarray(arr)

    put("backbone/conv1/kernel", _conv(sd["conv1.weight"]))
    for k, v in _bn("bn1", sd).items():
        put(f"backbone/bn1/{k}", v)

    units = RESNET_UNITS[depth]
    for li, n in enumerate(units, start=1):
        scope = f"backbone/stage{li}" if li <= 3 else "head_body/stage4"
        for u in range(n):
            src = f"layer{li}.{u}"
            dst = f"{scope}/unit{u + 1}"
            for c in (1, 2, 3):
                put(f"{dst}/conv{c}/kernel", _conv(sd[f"{src}.conv{c}.weight"]))
                for k, v in _bn(f"{src}.bn{c}", sd).items():
                    put(f"{dst}/bn{c}/{k}", v)
            if f"{src}.downsample.0.weight" in sd:
                put(f"{dst}/sc_conv/kernel",
                    _conv(sd[f"{src}.downsample.0.weight"]))
                for k, v in _bn(f"{src}.downsample.1", sd).items():
                    put(f"{dst}/sc_bn/{k}", v)
    return out


def convert_vgg16(sd: Dict) -> Dict[str, np.ndarray]:
    out: Dict[str, np.ndarray] = {}
    for idx, name in zip(_VGG_CONV_IDX, _VGG_NAMES):
        out[f"backbone/{name}/kernel"] = _conv(sd[f"features.{idx}.weight"])
        out[f"backbone/{name}/bias"] = np.asarray(sd[f"features.{idx}.bias"])
    # classifier.0 = fc6 (25088→4096).  torch flattens pooled features in
    # CHW order, our VGGFC flattens HWC — permute the input axis to match.
    w6 = np.asarray(sd["classifier.0.weight"])          # (4096, 512*7*7)
    w6 = w6.reshape(4096, 512, 7, 7).transpose(2, 3, 1, 0).reshape(-1, 4096)
    out["head_body/fc6/kernel"] = w6
    out["head_body/fc6/bias"] = np.asarray(sd["classifier.0.bias"])
    out["head_body/fc7/kernel"] = np.asarray(sd["classifier.3.weight"]).T
    out["head_body/fc7/bias"] = np.asarray(sd["classifier.3.bias"])
    return out


def convert(state_dict: Dict, network: str) -> Dict[str, np.ndarray]:
    if network in RESNET_UNITS:
        return convert_resnet(state_dict, network)
    if network == "vgg16":
        return convert_vgg16(state_dict)
    raise KeyError(network)


def convert_file(pth_path: str, network: str, npz_path: str) -> None:
    """CLI entry: torch .pth (state_dict) → .npz."""
    import torch

    sd = torch.load(pth_path, map_location="cpu", weights_only=True)
    if hasattr(sd, "state_dict"):
        sd = sd.state_dict()
    flat = convert({k: v.numpy() for k, v in sd.items()}, network)
    np.savez(npz_path, **flat)


if __name__ == "__main__":
    import argparse

    ap = argparse.ArgumentParser(description="torch .pth -> framework .npz")
    ap.add_argument("pth")
    ap.add_argument("network", choices=sorted(RESNET_UNITS) + ["vgg16"])
    ap.add_argument("npz")
    a = ap.parse_args()
    convert_file(a.pth, a.network, a.npz)
