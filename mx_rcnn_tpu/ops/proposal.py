"""Proposal generation (RPN output → RoIs) — the reference's ``Proposal`` op.

Behavioral contract (rcnn/symbol/proposal.py CustomOp, and MXNet's C++/CUDA
``mx.contrib.sym.Proposal`` selected by config.CXX_PROPOSAL):

1. decode per-anchor deltas into boxes (bbox_pred), clip to the image;
2. drop boxes smaller than min_size · im_scale on either side;
3. keep the top pre_nms_top_n by fg score (12000 train / 6000 test);
4. greedy NMS at 0.7;
5. keep the top post_nms_top_n (2000 train / 300 test), padding the output
   to that static size — the reference pads by duplicating kept boxes
   (npr.choice over keep); we return an explicit validity mask instead and
   duplicate-pad, which downstream masked ops consume directly.

Non-differentiable by contract (reference backward is zeros): callers wrap
the output in ``stop_gradient``.

This is a jitted device-side op; the NMS inside is ``ops.nms.nms_padded``
(pure JAX) or the Pallas bitmask kernel (kernels/nms_pallas.py) chosen by
``use_pallas``.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from mx_rcnn_tpu.ops.boxes import bbox_pred, clip_boxes
from mx_rcnn_tpu.ops.nms import nms_padded


@partial(jax.jit, static_argnames=("pre_nms_top_n", "post_nms_top_n", "nms_thresh",
                                   "min_size", "use_pallas"))
def propose(
    scores: jnp.ndarray,
    bbox_deltas: jnp.ndarray,
    anchors: jnp.ndarray,
    im_h: jnp.ndarray,
    im_w: jnp.ndarray,
    im_scale: jnp.ndarray,
    *,
    pre_nms_top_n: int = 6000,
    post_nms_top_n: int = 300,
    nms_thresh: float = 0.7,
    min_size: int = 16,
    use_pallas: bool = False,
):
    """Generate proposals for one image.

    Args:
      scores: (N,) per-anchor foreground probability (already sliced from the
        2-way softmax, matching the reference's ``scores[:, A:, :, :]``).
      bbox_deltas: (N, 4) per-anchor regression output.
      anchors: (N, 4) anchor boxes for this feature shape.
      im_h, im_w, im_scale: effective image size and resize scale (traced).

    Returns:
      rois: (post_nms_top_n, 4) float32, duplicate-padded.
      roi_scores: (post_nms_top_n,) float32.
      roi_valid: (post_nms_top_n,) bool.
    """
    n = scores.shape[0]
    boxes = bbox_pred(anchors, bbox_deltas)
    boxes = clip_boxes(boxes, im_h, im_w)

    ws = boxes[:, 2] - boxes[:, 0] + 1.0
    hs = boxes[:, 3] - boxes[:, 1] + 1.0
    ms = min_size * im_scale
    size_ok = (ws >= ms) & (hs >= ms)
    scores = jnp.where(size_ok, scores, -1.0)

    k = min(pre_nms_top_n, n)
    top_scores, top_idx = jax.lax.top_k(scores, k)
    top_boxes = boxes[top_idx]
    top_valid = top_scores > -0.5

    if use_pallas:
        from mx_rcnn_tpu.kernels.nms_pallas import nms_pallas
        keep_idx, keep_mask = nms_pallas(
            top_boxes, top_scores, max_out=post_nms_top_n,
            iou_thresh=nms_thresh, valid=top_valid)
    else:
        keep_idx, keep_mask = nms_padded(
            top_boxes, top_scores, max_out=post_nms_top_n,
            iou_thresh=nms_thresh, valid=top_valid)

    rois = top_boxes[keep_idx]
    roi_scores = jnp.where(keep_mask, top_scores[keep_idx], 0.0)
    # duplicate-pad: invalid slots point at keep_idx 0 (the top box) already,
    # because nms_padded emits index 0 for empty slots; mask tells the truth.
    return rois, roi_scores, keep_mask


def _level_topk(scores: jnp.ndarray, k: int) -> jnp.ndarray:
    """Exact top-k indices of a flat score vector, shaped to dodge the v5e
    windowed-TopK emitter bug (see the crash ledger at the call site).

    Two-stage: reshape to (G, n/G) rows, take top-k per row (every global
    top-k element is in its row's top-k, so the union is a superset), then
    top-k over the G·k survivors.  Both stages see row lengths far below
    the crashing (1, 116736) shape.  Order within ties differs from
    argsort — irrelevant at the call site (candidates are re-sorted
    jointly).  Falls back to argsort when the vector is too small to
    split.
    """
    n = scores.shape[0]
    # largest split with whole rows no shorter than k (P2 @ 116736/k=2400
    # → g=16; P3 @ 29184 → g=8; smaller levels fall back to argsort)
    g = next((g for g in (16, 8, 4, 2) if n % g == 0 and n // g >= k), 1)
    if g == 1:
        return jnp.argsort(-scores)[:k]
    rows = scores.reshape(g, n // g)
    v1, i1 = jax.lax.top_k(rows, k)                      # (G, k) per-row
    base = (jnp.arange(g, dtype=jnp.int32) * (n // g))[:, None]
    flat_idx = (i1 + base).reshape(-1)                   # (G·k,)
    _, i2 = jax.lax.top_k(v1.reshape(-1), k)             # exact global k
    return flat_idx[i2]


def propose_fpn(
    level_scores,
    level_deltas,
    level_anchors,
    im_h,
    im_w,
    im_scale,
    *,
    pre_nms_top_n: int = 12000,
    post_nms_top_n: int = 2000,
    nms_thresh: float = 0.7,
    min_size: int = 16,
    use_pallas: bool = False,
):
    """Multi-level proposal generation (FPN): per-level decode + top-k
    (pre_nms_top_n split evenly across levels, the Detectron per-level cap),
    concat, then ONE joint NMS to post_nms_top_n.

    Args are parallel lists over pyramid levels; same per-image contract and
    return shape as ``propose``.
    """
    nl = len(level_scores)
    k_level = max(pre_nms_top_n // nl, 1)
    cand_boxes, cand_scores = [], []
    for scores, deltas, anchors in zip(level_scores, level_deltas,
                                       level_anchors):
        boxes = bbox_pred(anchors, deltas)
        boxes = clip_boxes(boxes, im_h, im_w)
        ws = boxes[:, 2] - boxes[:, 0] + 1.0
        hs = boxes[:, 3] - boxes[:, 1] + 1.0
        ms = min_size * im_scale
        scores = jnp.where((ws >= ms) & (hs >= ms), scores, -1.0)
        k = min(k_level, scores.shape[0])
        # argsort instead of lax.top_k — v5e compiler-bug fence, widened in
        # round 3.  Crash ledger (all in the full FPN train graph; each
        # works standalone):
        #   * lax.top_k (round 2, jax 0.9.0): `F fusion_util.cc:3726 Check
        #     failed: chunk_counts[new_window_dim] == 1 ... TransformWindow
        #     ... f32[1,116736,1]` → SIGABRT.
        #   * approx_max_k(recall_target=1.0) (round 3):
        #     `TopkEmitter::EmitBatchForWindowedR2: Check failed:
        #     operand.span_size.RawSize() > 0` → SIGABRT.
        #   * lax.top_k behind jax.lax.optimization_barrier (round 3): same
        #     span_size check in `TopkEmitter::EmitWindowedR2` — the bug is
        #     in the windowed TopK emitter itself at this (1, 116736)/
        #     k=2400 shape, not the fusion pass, so isolation cannot fix
        #     it.  (assign_anchor's top_k survives because its k=256 takes
        #     a different emitter path.)
        # The argsort costs ~1.3 ms at P2; retry the ledger on libtpu/jax
        # upgrades.
        top_idx = _level_topk(scores, k)
        cand_boxes.append(boxes[top_idx])
        cand_scores.append(scores[top_idx])
    boxes = jnp.concatenate(cand_boxes, axis=0)
    scores = jnp.concatenate(cand_scores, axis=0)
    # global score sort: each level's top-k is sorted internally but not
    # across levels, and the NMS backends' greedy order (and the Pallas
    # sweep's index order) must be score-descending
    order = jnp.argsort(-scores)
    boxes = boxes[order]
    scores = scores[order]
    valid = scores > -0.5

    if use_pallas:
        from mx_rcnn_tpu.kernels.nms_pallas import nms_pallas
        keep_idx, keep_mask = nms_pallas(boxes, scores, max_out=post_nms_top_n,
                                         iou_thresh=nms_thresh, valid=valid)
    else:
        keep_idx, keep_mask = nms_padded(boxes, scores, max_out=post_nms_top_n,
                                         iou_thresh=nms_thresh, valid=valid)
    rois = boxes[keep_idx]
    roi_scores = jnp.where(keep_mask, scores[keep_idx], 0.0)
    return rois, roi_scores, keep_mask
