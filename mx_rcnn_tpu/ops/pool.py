"""Non-overlapping 2x2/2 max pooling via reshape+max — MEASURED NEUTRAL,
kept as a reproduction harness, NOT used by the model path.

Hypothesis (round 4): XLA lowers ``nn.max_pool``'s backward to
``select-and-scatter`` (~1.4 ms across the two live VGG16 pools), the
classically slow TPU pool transpose; for non-overlapping 2x2/2 windows a
reshape+max formulation gets an equality-select backward instead.

Measured on TPU v5-lite (r4_tpu_session2/3.log, scripts/bench_pool.py):
the swap is device-NEUTRAL — VGG16 step 17.336 ms (reshape) vs
17.333 ms (reduce_window); isolated bwd 5.80/6.83 ms (reshape, two pool
shapes) vs 6.53/6.26 ms (reduce_window).  The scatter's cost here equals
the equality-select's, so ``VGGConv`` keeps ``nn.max_pool`` — its
select-and-scatter backward routes tie gradients to the first window
maximum like the reference's cudnn max-pool bwd routes to the recorded
argmax, while this form would split ties evenly (relu-zero ties, the
common bf16 case, are killed upstream by relu's zero gradient either
way).  Retry on a libtpu upgrade only if select-and-scatter regresses.

Reference: MXNet Pooling (pool_type='max', 2x2/2) in ``get_vgg_conv``
(symbol_vgg.py) — blocks 1-4 of the VGG16 body.
"""

import jax.numpy as jnp


def max_pool_2x2(x: jnp.ndarray) -> jnp.ndarray:
    """Max-pool NHWC ``x`` with 2x2 windows, stride 2, VALID padding.

    Forward bit-equal to ``nn.max_pool(x, (2, 2), strides=(2, 2))``; odd
    H/W trailing rows/cols are dropped (floor), matching reduce_window's
    VALID-window semantics without any padding value entering a max.
    """
    n, h, w, c = x.shape
    he, we = h - (h % 2), w - (w % 2)
    if (he, we) != (h, w):
        x = x[:, :he, :we, :]
    x = x.reshape(n, he // 2, 2, we // 2, 2, c)
    return jnp.max(x, axis=(2, 4))
