"""RPN anchor target assignment — in-graph, fixed-size, masked.

Behavioral contract of the reference's ``assign_anchor`` (rcnn/io/rpn.py):

1. slide A base anchors over the feature grid;
2. only anchors fully inside the image (± allowed_border) participate;
3. labels: 1 (fg) if IoU ≥ RPN_POSITIVE_OVERLAP with some gt **or** the
   anchor attains the per-gt max IoU (ties included); 0 (bg) if max IoU <
   RPN_NEGATIVE_OVERLAP; −1 (ignore) otherwise and for outside anchors;
4. subsample: at most RPN_FG_FRACTION·RPN_BATCH_SIZE fg and
   (RPN_BATCH_SIZE − num_fg) bg survive; excess are flipped to −1 at random;
5. bbox targets = encode(anchor → its argmax gt), weights 1 on fg anchors.

TPU-first divergence (documented): the reference computes this per batch on
the host in numpy (host hot-loop #1 in SURVEY §3.1); here it is a jittable
pure function on padded gt boxes, running inside the train step on device,
with ``jax.random`` subsampling instead of host ``npr.choice``.  Seeds
differ from the reference by construction, so parity is statistical (mAP),
not bitwise — same caveat as SURVEY §7 hard-part 3.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from mx_rcnn_tpu.ops.boxes import bbox_overlaps, bbox_transform


def _keep_topk_random(mask: jnp.ndarray, k, key, k_cap: int) -> jnp.ndarray:
    """Keep at most k True entries of ``mask``, chosen uniformly.

    Deterministic given the key: draws a uniform priority per entry and
    keeps the top-k priorities among True entries.  ``k`` may be a traced
    scalar; ``k_cap`` is its static upper bound (the subsample quota).
    Implemented as ``lax.top_k(k_cap)`` + a k-limited scatter of the winner
    indices — a full argsort-rank costs 4 (1, N) sorts per assign at FPN's
    155k concatenated anchors (~6.8 ms/step profiled on v5-lite) where the
    static-k top_k is ~0.2 ms, and top_k's break-ties-by-index keeps the
    ≤ k contract exact (a float-tie at the threshold would not).
    """
    k_cap = min(k_cap, mask.shape[-1])  # quotas can exceed the anchor count
    r = jax.random.uniform(key, mask.shape)
    r = jnp.where(mask, r, -1.0)
    _, idx = jax.lax.top_k(r, k_cap)
    sel = jnp.arange(k_cap) < k
    keep = jnp.zeros(mask.shape, bool).at[idx].set(sel)
    return keep & mask


@partial(jax.jit, static_argnames=("batch_size", "fg_fraction",
                                   "pos_overlap", "neg_overlap", "allowed_border",
                                   "clobber_positives", "iou_bf16", "fused",
                                   "_fused_interpret"))
def assign_anchor(
    anchors: jnp.ndarray,
    gt_boxes: jnp.ndarray,
    gt_valid: jnp.ndarray,
    im_h: jnp.ndarray,
    im_w: jnp.ndarray,
    key: jax.Array,
    *,
    batch_size: int = 256,
    fg_fraction: float = 0.5,
    pos_overlap: float = 0.7,
    neg_overlap: float = 0.3,
    allowed_border: int = 0,
    clobber_positives: bool = False,
    iou_bf16: bool = False,
    fused: bool = True,
    _fused_interpret: bool = False,
):
    """Compute RPN labels/targets for one image.

    Args:
      anchors: (N, 4) all anchors for this feature shape (static constant).
      gt_boxes: (G, 4) padded gt boxes.
      gt_valid: (G,) bool validity of each padded row.
      im_h, im_w: effective (pre-padding) image size, traced scalars.
      key: jax PRNG key for fg/bg subsampling.

    Returns dict with:
      label: (N,) int32 ∈ {−1, 0, 1}
      bbox_target: (N, 4) float32
      bbox_weight: (N, 4) float32 (1 on fg rows)
    """
    n = anchors.shape[0]
    num_fg_cap = int(batch_size * fg_fraction)

    inside = (
        (anchors[:, 0] >= -allowed_border)
        & (anchors[:, 1] >= -allowed_border)
        & (anchors[:, 2] < im_w + allowed_border)
        & (anchors[:, 3] < im_h + allowed_border)
    )

    any_gt = jnp.any(gt_valid)
    use_kernel = (fused and not iou_bf16 and gt_boxes.shape[0] <= 128
                  and (_fused_interpret or jax.default_backend() == "tpu"))
    if use_kernel:
        # cfg.tpu.ASSIGN_FUSED (default): fused Pallas reductions — IoU
        # recomputed on the fly per tile, the (N, G) matrix never touches
        # HBM (~100× less traffic at FPN's 155 520 anchors); semantics
        # bit-identical to the dense path below (kernels/assign_pallas.py)
        from mx_rcnn_tpu.kernels.assign_pallas import assign_reduce_pallas

        max_overlap, argmax_gt, gt_max, is_gt_argmax = assign_reduce_pallas(
            anchors, gt_boxes, gt_valid, inside,
            interpret=_fused_interpret)
    else:
        # IoU against padded gt; invalid columns masked to -1 so they
        # never win
        overlaps = bbox_overlaps(anchors, gt_boxes)  # (N, G)
        if iou_bf16:
            # cfg.TRAIN.RPN_ASSIGN_IOU_BF16: the (N, G) matrix is read
            # three times by the reductions below (max/argmax axis 1, max
            # axis 0) — at FPN's 155 520 anchors that traffic dominates
            # assign cost.  Storing it bf16 halves the bytes; IoU is still
            # computed in f32 (the cast fuses into the producer pass), so
            # only the stored values and the threshold comparisons round
            # (see config.py).
            overlaps = overlaps.astype(jnp.bfloat16)
        overlaps = jnp.where(gt_valid[None, :], overlaps,
                             jnp.asarray(-1.0, overlaps.dtype))

        max_overlap = jnp.max(overlaps, axis=1)  # (N,)
        argmax_gt = jnp.argmax(overlaps, axis=1)  # (N,)

        # per-gt max over *inside* anchors; an anchor tying the per-gt max
        # is fg
        ov_inside = jnp.where(inside[:, None], overlaps, -1.0)
        gt_max = jnp.max(ov_inside, axis=0)  # (G,)
        is_gt_argmax = jnp.any(
            (ov_inside == gt_max[None, :]) & gt_valid[None, :]
            & (gt_max[None, :] > 0), axis=1
        )

    fg = (max_overlap >= pos_overlap) | is_gt_argmax
    bg = max_overlap < neg_overlap
    if clobber_positives:
        fg = fg & ~bg
    else:
        bg = bg & ~fg
    # no gt in image → everything eligible is bg (reference: labels[:] = 0)
    fg = fg & any_gt & inside
    bg = jnp.where(any_gt, bg, True) & inside

    # subsample
    k_fg, k_bg = jax.random.split(key)
    fg_kept = _keep_topk_random(fg, num_fg_cap, k_fg, num_fg_cap)
    num_fg = jnp.sum(fg_kept)
    bg_kept = _keep_topk_random(bg, batch_size - num_fg, k_bg, batch_size)

    label = jnp.full((n,), -1, dtype=jnp.int32)
    label = jnp.where(bg_kept, 0, label)
    label = jnp.where(fg_kept, 1, label)

    # one-hot contraction instead of gt_boxes[argmax_gt]: a (N,) gather
    # from (G, 4) serializes on TPU (profiled 0.38 ms/step at FPN's 155 520
    # anchors); the (N, G) @ (G, 4) one-hot matmul rides the MXU.  The dot
    # must run at Precision.HIGHEST: the default TPU matmul truncates f32
    # operands to bf16 before the MXU, which rounds gt coordinates at real
    # image scales (~1000 px → ulp ≈ 2 px) and corrupts the regression
    # targets the exact gather used to produce.  The op is (N, G≤100) @
    # (G, 4) — tiny — so HIGHEST costs nothing measurable.
    onehot_gt = jax.nn.one_hot(argmax_gt, gt_boxes.shape[0],
                               dtype=jnp.float32)
    matched_gt = jnp.matmul(onehot_gt, gt_boxes.astype(jnp.float32),
                            precision=jax.lax.Precision.HIGHEST)  # (N, 4)
    bbox_target = bbox_transform(anchors, matched_gt).astype(jnp.float32)
    bbox_target = jnp.where(any_gt, bbox_target, jnp.zeros_like(bbox_target))
    bbox_weight = jnp.where(fg_kept[:, None], 1.0, 0.0).astype(jnp.float32)
    # zero targets on non-fg rows for cleanliness (reference leaves garbage,
    # masked by weights; zeros keep grads identical and debugging saner)
    bbox_target = bbox_target * bbox_weight

    return {"label": label, "bbox_target": bbox_target, "bbox_weight": bbox_weight}
