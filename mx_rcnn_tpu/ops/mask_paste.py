"""On-device Mask R-CNN mask pasting (reference: the host-side cv2 paste in
``rcnn/core/tester.py``'s mask loop + vendored ``maskApi.c`` RLE encode).

The reference pastes each 28×28 mask probability map into the full image
frame on host (one cv2.resize + threshold per detection — ~150 ms/img at
the 100-detection cap) and RLE-encodes in C.  Here the paste is a pair of
tiny matmuls per detection on the MXU — bilinear resize is separable, so
``mask = Wy @ prob @ Wx`` with per-box weight matrices built in-graph —
followed by an in-graph threshold + bit-pack, so a whole batch's masks come
back in ONE ~packed-bitplane readback and the host only runs the C++ RLE
encoder (``native.rle_encode_packed``).

Semantics match ``eval.tester.paste_mask`` (the oracle): integer paste
window [floor(x1), ceil(x2)] × [floor(y1), ceil(y2)], cv2-style half-pixel
source mapping ``src = (j + 0.5) * M/extent - 0.5`` with border-replicate
clamping, threshold ``>= 0.5``.

Output layout is TRANSPOSED and bit-packed for the encoder's column-major
scan: (B, R, Wp, Hp//8) uint8, bit ``y & 7`` of byte ``[x, y >> 3]`` is
pixel (y, x), LSB-first — so an RLE column read is a sequential byte
stream.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _axis_weights(lo, hi, npix: int, m: int):
    """Bilinear paste weights for one axis: (..., npix, m).

    ``lo``/``hi``: box edges (inclusive pixel coordinates, any float) with
    arbitrary leading batch dims.  Row ``p`` holds the source-bin weights
    of global pixel ``p``; rows outside the integer paste window are zero.
    """
    lo_i = jnp.floor(lo)[..., None]                       # (..., 1)
    extent = jnp.maximum(jnp.ceil(hi)[..., None] - lo_i + 1.0, 1.0)
    pix = jnp.arange(npix, dtype=jnp.float32)             # (npix,)
    j = pix - lo_i                                        # (..., npix)
    inside = (j >= 0.0) & (j <= extent - 1.0)
    src = (j + 0.5) * (float(m) / extent) - 0.5
    i0 = jnp.floor(src)
    f = src - i0
    w0 = jax.nn.one_hot(jnp.clip(i0, 0, m - 1).astype(jnp.int32), m,
                        dtype=jnp.float32) * (1.0 - f)[..., None]
    w1 = jax.nn.one_hot(jnp.clip(i0 + 1.0, 0, m - 1).astype(jnp.int32), m,
                        dtype=jnp.float32) * f[..., None]
    return jnp.where(inside[..., None], w0 + w1, 0.0)     # (..., npix, m)


def paste_masks(probs, boxes, hp: int, wp: int, chunk: int = 8):
    """(B, R, M, M) probabilities + (B, R, 4) original-frame boxes →
    (B, R, wp, hp//8) packed binary masks in the padded (hp, wp) frame.

    ``hp``/``wp`` are static padded frame dims: hp a multiple of 64 (the
    encoder streams 64-bit words down columns), wp ≥ image width.  Pixels
    beyond the true (h, w) are junk the encoder never reads.  ``chunk``
    bounds peak memory: the (chunk, hp, wp) f32 pasted slab lives only
    inside one ``lax.map`` step.
    """
    assert hp % 64 == 0, hp
    b, r, m, _ = probs.shape
    nch = -(-r // chunk)
    rp = nch * chunk
    probs = jnp.asarray(probs, jnp.float32)
    boxes = jnp.asarray(boxes, jnp.float32)
    if rp != r:
        probs = jnp.pad(probs, ((0, 0), (0, rp - r), (0, 0), (0, 0)))
        boxes = jnp.pad(boxes, ((0, 0), (0, rp - r), (0, 0)))
    probs = probs.reshape(b, nch, chunk, m, m).transpose(1, 0, 2, 3, 4)
    boxes = boxes.reshape(b, nch, chunk, 4).transpose(1, 0, 2, 3)
    bitw = (jnp.uint8(1) << jnp.arange(8, dtype=jnp.uint8))  # LSB-first

    def body(args):
        p, bx = args                                       # (B,C,M,M), (B,C,4)
        wy = _axis_weights(bx[..., 1], bx[..., 3], hp, m)  # (B,C,hp,M)
        wx = _axis_weights(bx[..., 0], bx[..., 2], wp, m)  # (B,C,wp,M)
        # transposed paste: out[w, h] so the pack axis (h) is minor —
        # HIGHEST precision: f32 accumulate, matching the host oracle
        pasted = jnp.einsum("bcwn,bcmn,bchm->bcwh", wx, p, wy,
                            precision=jax.lax.Precision.HIGHEST)
        bits = (pasted >= 0.5).astype(jnp.uint8)
        bits = bits.reshape(b, chunk, wp, hp // 8, 8)
        return jnp.sum(bits * bitw, axis=-1, dtype=jnp.uint8)

    packed = jax.lax.map(body, (probs, boxes))             # (nch,B,C,wp,hb)
    packed = packed.transpose(1, 0, 2, 3, 4).reshape(b, rp, wp, hp // 8)
    return packed[:, :r]
