"""Box codecs and IoU.

Behavioral contracts of the reference's ``rcnn/processing/bbox_transform.py``
(``bbox_transform`` = encode, ``bbox_pred`` = decode, ``clip_boxes``) and
``rcnn/cython/bbox.pyx`` (``bbox_overlaps_cython``), rebuilt as jittable
jax.numpy functions.  The legacy "+1" width convention (w = x2 - x1 + 1) is
preserved throughout for numeric parity.

All functions are shape-polymorphic over leading dims and safe under jit.
"""

from __future__ import annotations

import jax.numpy as jnp

# matches the reference's epsilon guard in nonlinear_transform
_EPS = 1e-14


def bbox_transform(ex_rois: jnp.ndarray, gt_rois: jnp.ndarray) -> jnp.ndarray:
    """Encode gt boxes w.r.t. example (anchor/RoI) boxes → (…, 4) deltas.

    delta = (dx, dy, dw, dh) with dx,dy normalized by ex width/height and
    dw,dh log-ratios (reference: nonlinear_transform).
    """
    ex_w = ex_rois[..., 2] - ex_rois[..., 0] + 1.0
    ex_h = ex_rois[..., 3] - ex_rois[..., 1] + 1.0
    ex_cx = ex_rois[..., 0] + 0.5 * (ex_w - 1.0)
    ex_cy = ex_rois[..., 1] + 0.5 * (ex_h - 1.0)

    gt_w = gt_rois[..., 2] - gt_rois[..., 0] + 1.0
    gt_h = gt_rois[..., 3] - gt_rois[..., 1] + 1.0
    gt_cx = gt_rois[..., 0] + 0.5 * (gt_w - 1.0)
    gt_cy = gt_rois[..., 1] + 0.5 * (gt_h - 1.0)

    dx = (gt_cx - ex_cx) / (ex_w + _EPS)
    dy = (gt_cy - ex_cy) / (ex_h + _EPS)
    dw = jnp.log(gt_w / (ex_w + _EPS))
    dh = jnp.log(gt_h / (ex_h + _EPS))
    return jnp.stack([dx, dy, dw, dh], axis=-1)


def bbox_pred(boxes: jnp.ndarray, deltas: jnp.ndarray) -> jnp.ndarray:
    """Decode deltas w.r.t. boxes (reference: nonlinear_pred / bbox_pred).

    boxes: (..., N, 4); deltas: (..., N, 4*K) class-specific layout → output
    (..., N, 4*K).  Works for K=1 (RPN) and K=num_classes (RCNN head).
    """
    w = boxes[..., 2:3] - boxes[..., 0:1] + 1.0
    h = boxes[..., 3:4] - boxes[..., 1:2] + 1.0
    cx = boxes[..., 0:1] + 0.5 * (w - 1.0)
    cy = boxes[..., 1:2] + 0.5 * (h - 1.0)

    dx = deltas[..., 0::4]
    dy = deltas[..., 1::4]
    dw = deltas[..., 2::4]
    dh = deltas[..., 3::4]

    pred_cx = dx * w + cx
    pred_cy = dy * h + cy
    pred_w = jnp.exp(dw) * w
    pred_h = jnp.exp(dh) * h

    x1 = pred_cx - 0.5 * (pred_w - 1.0)
    y1 = pred_cy - 0.5 * (pred_h - 1.0)
    x2 = pred_cx + 0.5 * (pred_w - 1.0)
    y2 = pred_cy + 0.5 * (pred_h - 1.0)

    # interleave back to (..., N, 4K): stack on a new trailing axis then fold
    out = jnp.stack([x1, y1, x2, y2], axis=-1)  # (..., N, K, 4)
    return out.reshape(*deltas.shape[:-1], deltas.shape[-1])


def clip_boxes(boxes: jnp.ndarray, im_h, im_w) -> jnp.ndarray:
    """Clip (..., 4K) boxes to [0, W-1] × [0, H-1] (reference: clip_boxes).

    im_h/im_w may be traced scalars (per-image effective size before padding).
    """
    x1 = jnp.clip(boxes[..., 0::4], 0.0, im_w - 1.0)
    y1 = jnp.clip(boxes[..., 1::4], 0.0, im_h - 1.0)
    x2 = jnp.clip(boxes[..., 2::4], 0.0, im_w - 1.0)
    y2 = jnp.clip(boxes[..., 3::4], 0.0, im_h - 1.0)
    out = jnp.stack([x1, y1, x2, y2], axis=-1)
    return out.reshape(boxes.shape)


def bbox_overlaps(boxes: jnp.ndarray, query_boxes: jnp.ndarray) -> jnp.ndarray:
    """(N, K) IoU matrix (reference: bbox_overlaps_cython).

    On TPU this lowers to broadcast elementwise ops — bandwidth-bound, fused
    by XLA; no custom kernel needed at the sizes the pipeline uses.
    """
    b = boxes[:, None, :]  # (N, 1, 4)
    q = query_boxes[None, :, :]  # (1, K, 4)

    iw = jnp.minimum(b[..., 2], q[..., 2]) - jnp.maximum(b[..., 0], q[..., 0]) + 1.0
    ih = jnp.minimum(b[..., 3], q[..., 3]) - jnp.maximum(b[..., 1], q[..., 1]) + 1.0
    iw = jnp.maximum(iw, 0.0)
    ih = jnp.maximum(ih, 0.0)
    inter = iw * ih

    area_b = (b[..., 2] - b[..., 0] + 1.0) * (b[..., 3] - b[..., 1] + 1.0)
    area_q = (q[..., 2] - q[..., 0] + 1.0) * (q[..., 3] - q[..., 1] + 1.0)
    union = area_b + area_q - inter
    return inter / jnp.maximum(union, _EPS)
