"""Mask-head training targets, in-graph.

Contract (Mask R-CNN paper §3, and the standard crop-resize
implementation): for each sampled fg RoI, the target is its matched gt
instance mask cropped to the RoI and resampled to MASK_SIZE², values {0,1}.

The data layer rasterizes each gt polygon ONCE into a fixed-resolution crop
aligned to the gt box (``gt_masks``: (G, S, S), gt-box frame).  In-graph we
map each RoI's 28×28 grid into that gt-box frame and bilinearly sample —
fully static shapes, no polygon math on device.

Round 4: the sampler is SEPARABLE (the RoIAlign lesson, ops/roi_align.py)
— the bilinear resample of RoI r is ``Wy[r] @ mask[r] @ Wx[r]^T`` with
(out, S) one-axis interpolation matrices, two einsums on the MXU instead
of 4 gathers per output pixel.  The round-4 mask-config profile
attributed 4.1 ms/step to this op's gather form (``fusion f32[100352]``,
r4_tpu_session4.log) — TPU gathers serialize (the round-3 loss lesson);
the einsum form is ~112 MFLOP ≈ noise.  The gather path stays as the
vmapped oracle (`_sample_gather`), parity-tested in
tests/test_fpn_mask.py.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


@partial(jax.jit, static_argnames=("out_size",))
def mask_targets_for_rois(gt_masks: jnp.ndarray, gt_boxes: jnp.ndarray,
                          rois: jnp.ndarray, gt_index: jnp.ndarray,
                          *, out_size: int = 28) -> jnp.ndarray:
    """(G, S, S) gt-box-frame masks → (R, out, out) per-RoI targets.

    Args:
      gt_masks: (G, S, S) float or bool, mask of gt g in its own box frame.
      gt_boxes: (G, 4) the frames those masks live in (scaled image coords).
      rois: (R, 4) sampled rois (scaled image coords).
      gt_index: (R,) index of the matched gt per roi.
    """
    g, s, _ = gt_masks.shape
    r = rois.shape[0]

    box = gt_boxes[gt_index]                      # (R, 4)
    bw = jnp.maximum(box[:, 2] - box[:, 0], 1e-3)
    bh = jnp.maximum(box[:, 3] - box[:, 1], 1e-3)

    # RoI pixel-center grid in image coords
    ys = (jnp.arange(out_size, dtype=jnp.float32) + 0.5) / out_size
    xs = (jnp.arange(out_size, dtype=jnp.float32) + 0.5) / out_size
    gy = rois[:, 1:2] + ys[None, :] * (rois[:, 3:4] - rois[:, 1:2])  # (R, out)
    gx = rois[:, 0:1] + xs[None, :] * (rois[:, 2:3] - rois[:, 0:1])

    # map into the gt-box frame [0, S)
    my = (gy - box[:, 1:2]) / bh[:, None] * s - 0.5   # (R, out)
    mx = (gx - box[:, 0:1]) / bw[:, None] * s - 0.5

    masks = gt_masks[gt_index].astype(jnp.float32)    # (R, S, S)

    # separable form: target[r] = Wy[r] @ mask[r] @ Wx[r]^T on the MXU
    wy = _lerp_weights(my, s)                         # (R, out, S)
    wx = _lerp_weights(mx, s)
    u = jnp.einsum("rpy,ryx->rpx", wy, masks)
    out = jnp.einsum("rqx,rpx->rpq", wx, u)           # (R, out, out)
    return (out >= 0.5).astype(jnp.float32)


def _lerp_weights(t: jnp.ndarray, s: int) -> jnp.ndarray:
    """One-axis linear-interpolation matrix (..., out, S) for coords ``t``.

    Row p carries `_sample_gather`'s edge semantics exactly: weight
    (1-frac) on clip(floor(t)) and frac on clip(floor(t)+1) — at the top
    edge both clip to S-1 and the weights sum to 1 — and rows for
    outside points (t ≤ -1 or t ≥ S) are all-zero.
    """
    cells = jnp.arange(s, dtype=jnp.float32)
    inside = (t > -1.0) & (t < s)
    t0 = jnp.clip(jnp.floor(t), 0, s - 1)
    t1 = jnp.clip(t0 + 1, 0, s - 1)
    frac = jnp.clip(t - t0, 0.0, 1.0)
    w = ((1.0 - frac)[..., None] * (cells == t0[..., None]) +
         frac[..., None] * (cells == t1[..., None]))
    return jnp.where(inside[..., None], w, 0.0)


def _sample_gather(masks, my, mx, out_size: int, s: int):
    """The original per-pixel 4-gather sampler — kept as the separable
    path's oracle (TPU gathers serialize; 4.1 ms/step at (128, 28, 28) in
    the round-4 profile vs ~noise for the einsum form)."""
    def sample_one(m, yy, xx):
        yy2 = jnp.broadcast_to(yy[:, None], (out_size, out_size))
        xx2 = jnp.broadcast_to(xx[None, :], (out_size, out_size))
        inside = (yy2 > -1.0) & (yy2 < s) & (xx2 > -1.0) & (xx2 < s)
        y0 = jnp.clip(jnp.floor(yy2), 0, s - 1)
        x0 = jnp.clip(jnp.floor(xx2), 0, s - 1)
        y1 = jnp.clip(y0 + 1, 0, s - 1)
        x1 = jnp.clip(x0 + 1, 0, s - 1)
        ly = jnp.clip(yy2 - y0, 0.0, 1.0)
        lx = jnp.clip(xx2 - x0, 0.0, 1.0)
        y0i, x0i, y1i, x1i = (a.astype(jnp.int32) for a in (y0, x0, y1, x1))
        v = ((1 - ly) * (1 - lx) * m[y0i, x0i] + (1 - ly) * lx * m[y0i, x1i]
             + ly * (1 - lx) * m[y1i, x0i] + ly * lx * m[y1i, x1i])
        return jnp.where(inside, v, 0.0)

    return jax.vmap(sample_one)(masks, my, mx)
