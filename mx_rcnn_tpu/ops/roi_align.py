"""ROIAlign / ROIPool — pure-JAX reference implementations.

The reference's RoI feature extractor is MXNet's CUDA ``ROIPooling``
(roi_pooling.cu; 7×7 max pool, spatial_scale 1/16, coordinate rounding).
The Mask R-CNN capability target uses ROIAlign (bilinear, no rounding).

TPU-first design: both are expressed as dense bilinear gathers with a
*static* sample grid — (R, P, P, S, S) sample points per RoI — which XLA
lowers to vectorized gathers; no dynamic shapes, no per-RoI loops.  ROIPool
is realized as max over the same static sample grid (documented divergence:
the reference's exact integer-binned max-pool has data-dependent bin
extents which are hostile to static shapes; a dense 4-sample-per-bin max is
the standard TPU substitute and is accuracy-neutral-or-better, like
ROIAlign itself).  A fused Pallas kernel behind the same signature is
planned (kernels/ tier); this module is the reference path and test oracle.

Coordinate semantics follow ROIAlign (Mask R-CNN paper): continuous
coordinates, half-pixel centers, sampling_ratio points per bin axis,
average (align) or max (pool) reduction.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


def _bilinear(feat: jnp.ndarray, y: jnp.ndarray, x: jnp.ndarray) -> jnp.ndarray:
    """Bilinear sample feat (H, W, C) at (…,) y/x grids → (…, C).

    Out-of-range points contribute 0 (matches ROIAlign's behavior of
    skipping samples outside the feature map).
    """
    h, w, _ = feat.shape
    in_range = (y > -1.0) & (y < h) & (x > -1.0) & (x < w)
    y = jnp.clip(y, 0.0, h - 1.0)
    x = jnp.clip(x, 0.0, w - 1.0)

    y0 = jnp.floor(y)
    x0 = jnp.floor(x)
    y1 = jnp.minimum(y0 + 1, h - 1.0)
    x1 = jnp.minimum(x0 + 1, w - 1.0)
    ly = (y - y0)[..., None]
    lx = (x - x0)[..., None]
    hy = 1.0 - ly
    hx = 1.0 - lx

    y0i, x0i, y1i, x1i = y0.astype(jnp.int32), x0.astype(jnp.int32), y1.astype(jnp.int32), x1.astype(jnp.int32)
    v00 = feat[y0i, x0i]
    v01 = feat[y0i, x1i]
    v10 = feat[y1i, x0i]
    v11 = feat[y1i, x1i]
    # blend in the FEATURE dtype: the corner weights are combined in f32 and
    # cast once just before the multiply, else bf16 features promote to f32
    # and the big (R, P, P, S, S, C) intermediate materializes at twice the
    # bytes (profiled ~2 ms/call of extra DMA at (100, 14, 14, 1024)).
    # Non-float features (if ever passed) keep the old promote-to-f32 path —
    # fractional weights would truncate to zero in an integer dtype.
    dt = feat.dtype if jnp.issubdtype(feat.dtype, jnp.floating) else jnp.float32
    out = ((hy * hx).astype(dt) * v00 + (hy * lx).astype(dt) * v01 +
           (ly * hx).astype(dt) * v10 + (ly * lx).astype(dt) * v11)
    return jnp.where(in_range[..., None], out, jnp.zeros((), dt))


def _roi_sample_grid(roi: jnp.ndarray, spatial_scale: float, pooled: int, sampling: int):
    """Sample point grid for one RoI → (pooled, pooled, sampling, sampling) y/x."""
    x1 = roi[0] * spatial_scale
    y1 = roi[1] * spatial_scale
    x2 = roi[2] * spatial_scale
    y2 = roi[3] * spatial_scale
    roi_w = jnp.maximum(x2 - x1, 1.0)
    roi_h = jnp.maximum(y2 - y1, 1.0)
    bin_w = roi_w / pooled
    bin_h = roi_h / pooled

    py = jnp.arange(pooled, dtype=jnp.float32)
    px = jnp.arange(pooled, dtype=jnp.float32)
    sy = (jnp.arange(sampling, dtype=jnp.float32) + 0.5) / sampling
    sx = (jnp.arange(sampling, dtype=jnp.float32) + 0.5) / sampling

    ys = y1 + (py[:, None, None, None] + sy[None, None, :, None]) * bin_h
    xs = x1 + (px[None, :, None, None] + sx[None, None, None, :]) * bin_w
    ys = jnp.broadcast_to(ys, (pooled, pooled, sampling, sampling))
    xs = jnp.broadcast_to(xs, (pooled, pooled, sampling, sampling))
    return ys, xs


@partial(jax.jit, static_argnames=("pooled_size", "sampling_ratio", "spatial_scale", "mode"))
def roi_align(
    features: jnp.ndarray,
    rois: jnp.ndarray,
    *,
    spatial_scale: float = 1.0 / 16,
    pooled_size: int = 7,
    sampling_ratio: int = 2,
    mode: str = "avg",
) -> jnp.ndarray:
    """ROIAlign over one feature map.

    Args:
      features: (H, W, C) — NHWC without batch; callers vmap over batch.
      rois: (R, 4) boxes in *image* coordinates.

    Returns: (R, pooled, pooled, C).
    """
    def one(roi):
        ys, xs = _roi_sample_grid(roi, spatial_scale, pooled_size, sampling_ratio)
        if sampling_ratio == 1:
            # one sample per bin: no sample axes to reduce, so avg == max
            # == the single sample and the (P, P, 1, 1, C) intermediate
            # never exists (simpler graph; device time is unchanged — XLA
            # already folded the squeeze)
            return _bilinear(features, ys[:, :, 0, 0], xs[:, :, 0, 0])
        vals = _bilinear(features, ys, xs)  # (P, P, S, S, C)
        if mode == "avg":
            return vals.mean(axis=(2, 3))
        return vals.max(axis=(2, 3))

    return jax.vmap(one)(rois)


def roi_pool(features, rois, *, spatial_scale=1.0 / 16, pooled_size: int = 7,
             sampling_ratio: int = 2):
    """ROIPool compatibility wrapper: max reduction over the static grid."""
    return roi_align(features, rois, spatial_scale=spatial_scale,
                     pooled_size=pooled_size, sampling_ratio=sampling_ratio,
                     mode="max")
