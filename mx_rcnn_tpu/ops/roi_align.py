"""ROIAlign / ROIPool — separable-matmul formulation (MXU-native).

The reference's RoI feature extractor is MXNet's CUDA ``ROIPooling``
(roi_pooling.cu; 7×7 max pool, spatial_scale 1/16, coordinate rounding).
The Mask R-CNN capability target uses ROIAlign (bilinear, no rounding).

TPU-first design (round 2): bilinear interpolation is *separable*, so for
the avg mode the whole pooled crop of RoI r is two small matmuls

    crop[r] = Ry[r] @ feat @ Rx[r]^T          (per channel)

where ``Ry[r]`` is (P, H) and ``Rx[r]`` is (P, W), each row holding the
averaged 1-D interpolation weights of that bin's sample points (≤ 2·S
nonzeros).  Expressed as two einsums this runs entirely on the MXU —
~12 GFLOPs at the flagship shape (128 RoIs, 14×14, 1024 ch) ≈ 0.1 ms —
and, crucially, its *backward* is again einsums: the transposed matmuls.
The round-1 gather formulation spent ~1.2 ms/step gathering forward and
~2.5 ms/step in four serialized scatter-adds backward (profiled on
v5-lite); the separable form removes every gather/scatter from the RoI
path.  Max mode with sampling_ratio > 1 is not separable and keeps the
dense-gather path (it is off the flagship hot path).

Coordinate semantics follow ROIAlign (Mask R-CNN paper): continuous
coordinates, half-pixel sample centers within each bin, samples outside
the feature map contribute 0, coordinates clamped like the CUDA kernel
(y0 = floor(clip(y)), y1 = min(y0+1, H-1), duplicate-index weights sum).
The gather path (`_roi_align_gather`) is kept as the test oracle for the
einsum path and as the max-mode implementation.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


def _bilinear(feat: jnp.ndarray, y: jnp.ndarray, x: jnp.ndarray) -> jnp.ndarray:
    """Bilinear sample feat (H, W, C) at (…,) y/x grids → (…, C).

    Out-of-range points contribute 0 (matches ROIAlign's behavior of
    skipping samples outside the feature map).
    """
    h, w, _ = feat.shape
    in_range = (y > -1.0) & (y < h) & (x > -1.0) & (x < w)
    y = jnp.clip(y, 0.0, h - 1.0)
    x = jnp.clip(x, 0.0, w - 1.0)

    y0 = jnp.floor(y)
    x0 = jnp.floor(x)
    y1 = jnp.minimum(y0 + 1, h - 1.0)
    x1 = jnp.minimum(x0 + 1, w - 1.0)
    ly = (y - y0)[..., None]
    lx = (x - x0)[..., None]
    hy = 1.0 - ly
    hx = 1.0 - lx

    y0i, x0i, y1i, x1i = y0.astype(jnp.int32), x0.astype(jnp.int32), y1.astype(jnp.int32), x1.astype(jnp.int32)
    v00 = feat[y0i, x0i]
    v01 = feat[y0i, x1i]
    v10 = feat[y1i, x0i]
    v11 = feat[y1i, x1i]
    # blend in the FEATURE dtype: the corner weights are combined in f32 and
    # cast once just before the multiply, else bf16 features promote to f32
    # and the big (R, P, P, S, S, C) intermediate materializes at twice the
    # bytes (profiled ~2 ms/call of extra DMA at (100, 14, 14, 1024)).
    # Non-float features (if ever passed) keep the old promote-to-f32 path —
    # fractional weights would truncate to zero in an integer dtype.
    dt = feat.dtype if jnp.issubdtype(feat.dtype, jnp.floating) else jnp.float32
    out = ((hy * hx).astype(dt) * v00 + (hy * lx).astype(dt) * v01 +
           (ly * hx).astype(dt) * v10 + (ly * lx).astype(dt) * v11)
    return jnp.where(in_range[..., None], out, jnp.zeros((), dt))


def _roi_bins(roi: jnp.ndarray, spatial_scale: float, pooled: int):
    """Shared RoI → bin geometry: (y1, x1, bin_h, bin_w) in feature coords,
    with the reference's min-1px degenerate-box clamp.  Both the gather and
    the separable paths derive their sample points from this one function so
    they stay bit-identical (the gather path is the separable path's test
    oracle)."""
    x1 = roi[0] * spatial_scale
    y1 = roi[1] * spatial_scale
    roi_w = jnp.maximum(roi[2] * spatial_scale - x1, 1.0)
    roi_h = jnp.maximum(roi[3] * spatial_scale - y1, 1.0)
    return y1, x1, roi_h / pooled, roi_w / pooled


def _roi_sample_grid(roi: jnp.ndarray, spatial_scale: float, pooled: int, sampling: int):
    """Sample point grid for one RoI → (pooled, pooled, sampling, sampling) y/x."""
    y1, x1, bin_h, bin_w = _roi_bins(roi, spatial_scale, pooled)

    py = jnp.arange(pooled, dtype=jnp.float32)
    px = jnp.arange(pooled, dtype=jnp.float32)
    sy = (jnp.arange(sampling, dtype=jnp.float32) + 0.5) / sampling
    sx = (jnp.arange(sampling, dtype=jnp.float32) + 0.5) / sampling

    ys = y1 + (py[:, None, None, None] + sy[None, None, :, None]) * bin_h
    xs = x1 + (px[None, :, None, None] + sx[None, None, None, :]) * bin_w
    ys = jnp.broadcast_to(ys, (pooled, pooled, sampling, sampling))
    xs = jnp.broadcast_to(xs, (pooled, pooled, sampling, sampling))
    return ys, xs


def _axis_weights(lo, bin_sz, n: int, pooled: int, sampling: int):
    """1-D interpolation matrix (pooled, n) for one axis of one RoI.

    Row i averages the ``sampling`` sample points of bin i; each sample
    contributes linear-interpolation weights to its two neighbor cells with
    exactly `_bilinear`'s edge semantics (out-of-range → 0, clamp, y1 =
    min(y0+1, n-1) so duplicate indices at the high edge sum to 1).
    """
    p = jnp.arange(pooled, dtype=jnp.float32)[:, None]
    s = (jnp.arange(sampling, dtype=jnp.float32)[None, :] + 0.5) / sampling
    t = lo + (p + s) * bin_sz                      # (P, S) sample coords
    ok = (t > -1.0) & (t < n)
    tc = jnp.clip(t, 0.0, n - 1.0)
    t0 = jnp.floor(tc)
    t1 = jnp.minimum(t0 + 1.0, n - 1.0)
    frac = tc - t0
    cells = jnp.arange(n, dtype=jnp.float32)       # (n,)
    w = ((1.0 - frac)[..., None] * (cells == t0[..., None]) +
         frac[..., None] * (cells == t1[..., None]))   # (P, S, n)
    w = jnp.where(ok[..., None], w, 0.0)
    return w.mean(axis=1)                          # (P, n)


def _roi_align_separable(features, rois, spatial_scale, pooled, sampling):
    """Avg-mode ROIAlign as two einsums (see module docstring)."""
    h, w, _ = features.shape

    def weights(roi):
        y1, x1, bin_h, bin_w = _roi_bins(roi, spatial_scale, pooled)
        return (_axis_weights(y1, bin_h, h, pooled, sampling),
                _axis_weights(x1, bin_w, w, pooled, sampling))

    ry, rx = jax.vmap(weights)(rois)               # (R, P, H), (R, P, W)
    dt = (features.dtype if jnp.issubdtype(features.dtype, jnp.floating)
          else jnp.float32)
    features = features.astype(dt)
    ry = ry.astype(dt)
    rx = rx.astype(dt)
    # contract the LARGER spatial axis first: the (R, P, min(h,w), C)
    # intermediate is HBM-resident at flagship shapes (~139 MB bf16 vs
    # ~235 MB the other way on a 38×64 map), and the op is bandwidth-bound
    if w > h:
        u = jnp.einsum("rqw,hwc->rqhc", rx, features)
        return jnp.einsum("rph,rqhc->rpqc", ry, u)
    u = jnp.einsum("rph,hwc->rpwc", ry, features)
    return jnp.einsum("rqw,rpwc->rpqc", rx, u)


def _roi_align_gather(features, rois, spatial_scale, pooled, sampling, mode):
    """Dense static-grid gather path (round-1 formulation): needed for max
    mode at sampling > 1, and serves as the einsum path's test oracle."""
    def one(roi):
        ys, xs = _roi_sample_grid(roi, spatial_scale, pooled, sampling)
        if sampling == 1:
            # one sample per bin: no sample axes to reduce, so avg == max
            # == the single sample
            return _bilinear(features, ys[:, :, 0, 0], xs[:, :, 0, 0])
        vals = _bilinear(features, ys, xs)  # (P, P, S, S, C)
        if mode == "avg":
            return vals.mean(axis=(2, 3))
        return vals.max(axis=(2, 3))

    return jax.vmap(one)(rois)


def _exact_axis_mask(start, size, n: int, pooled: int):
    """Integer bin membership for one axis of one RoI → bool (pooled, n).

    Reference bin arithmetic (MXNet ``roi_pooling.cu``): bin p covers
    feature cells [floor(p·size/P), ceil((p+1)·size/P)) offset by
    ``start``, clipped to [0, n); an empty range yields an all-False row.
    """
    # EXACT integer bin arithmetic: floor(p·size/P) = p·size // P and
    # ceil((p+1)·size/P) = -((-(p+1)·size) // P) — no float division.
    # Fidelity note: the CUDA kernel computes these with f32
    # `(float)size / P` then `floor/ceil(p * bin_size)`.  For every
    # non-integer p·size/P the f32 result provably equals the exact one
    # (the quotient sits ≥ 1/P away from an integer, f32 error ~1e-5 at
    # these magnitudes); at exact-integer boundaries f32 rounding can
    # leak ONE extra already-clipped cell into the last bin
    # (ceil(P·RN(size/P)) = size+1 for some sizes).  That quirk is
    # hardware-arithmetic noise, not design intent, and is NOT
    # reproduced: XLA's accelerator divide is reciprocal-based (≠ IEEE
    # RTN), so matching it bit-for-bit in-graph is not portably
    # possible.  Everything else — rounding, inclusive widths,
    # overlapping bins, empty-bin zeros — is exact.
    p = jnp.arange(pooled, dtype=jnp.int32)
    lo = (p * size) // pooled + start
    hi = -((-(p + 1) * size) // pooled) + start
    lo = jnp.clip(lo, 0, n)
    hi = jnp.clip(hi, 0, n)
    cells = jnp.arange(n, dtype=jnp.int32)
    return (cells[None, :] >= lo[:, None]) & (cells[None, :] < hi[:, None])


def _roi_pool_exact(features, rois, spatial_scale, pooled):
    """The reference's integer-binned max ROIPooling, semantics-exact.

    Semantics of MXNet's CUDA ``ROIPoolForwardKernel`` (roi_pooling.cu),
    the op the classic configs actually trained with:
      * RoI corners ROUNDED to integer feature cells
        (round(coord × spatial_scale)), inclusive, min size 1 cell;
      * bin p spans integer cells [floor(p·sz/P), ceil((p+1)·sz/P)) —
        bins OVERLAP when the RoI is small and skip cells when large,
        unlike ROIAlign's uniform continuous bins;
      * plain max over the bin's cells, no interpolation;
      * empty bins (fully clipped) output 0.

    TPU formulation: the bin membership is separable (rows × cols), so
    the pool is two masked max-reductions — cols then rows — with static
    shapes; XLA fuses the where-mask into each reduction so the
    (R, P, H, W, C) predicate product never materializes (the
    intermediate is (R, P, H, C)).  Backward: JAX's reduce-max VJP
    splits tie gradients evenly where the CUDA kernel's atomic add goes
    to the recorded argmax — irrelevant for the intended use (inference
    on byte-exact MXNet weight transplants; see divergence ledger).
    """
    h, w, _ = features.shape

    def rnd(v):
        # C roundf (what the CUDA kernel calls): half away from zero —
        # jnp.round would be banker's (half to even)
        return (jnp.sign(v) * jnp.floor(jnp.abs(v) + 0.5)).astype(jnp.int32)

    def masks(roi):
        x1 = rnd(roi[0] * spatial_scale)
        y1 = rnd(roi[1] * spatial_scale)
        x2 = rnd(roi[2] * spatial_scale)
        y2 = rnd(roi[3] * spatial_scale)
        rw = jnp.maximum(x2 - x1 + 1, 1)
        rh = jnp.maximum(y2 - y1 + 1, 1)
        return (_exact_axis_mask(y1, rh, h, pooled),
                _exact_axis_mask(x1, rw, w, pooled))

    my, mx = jax.vmap(masks)(rois)                 # (R, P, H), (R, P, W)
    dt = (features.dtype if jnp.issubdtype(features.dtype, jnp.floating)
          else jnp.float32)
    neg = jnp.asarray(jnp.finfo(dt).min, dt)
    f = features.astype(dt)
    # cols: t[r, q, h, c] = max over the w-cells of bin column q
    t = jnp.max(jnp.where(mx[:, :, None, :, None],
                          f[None, None, :, :, :], neg), axis=3)
    # rows: out[r, p, q, c] = max over the h-cells of bin row p
    out = jnp.max(jnp.where(my[:, :, None, :, None],
                            t[:, None, :, :, :], neg), axis=3)
    valid = (my.any(axis=2)[:, :, None] & mx.any(axis=2)[:, None, :])
    return jnp.where(valid[..., None], out, jnp.zeros((), dt))


@partial(jax.jit, static_argnames=("pooled_size", "sampling_ratio", "spatial_scale", "mode"))
def roi_align(
    features: jnp.ndarray,
    rois: jnp.ndarray,
    *,
    spatial_scale: float = 1.0 / 16,
    pooled_size: int = 7,
    sampling_ratio: int = 2,
    mode: str = "avg",
) -> jnp.ndarray:
    """ROIAlign over one feature map.

    Args:
      features: (H, W, C) — NHWC without batch; callers vmap over batch.
      rois: (R, 4) boxes in *image* coordinates.

    Returns: (R, pooled, pooled, C).
    """
    if mode not in ("avg", "max", "exact"):
        raise ValueError(
            f"roi_align mode must be 'avg', 'max' or 'exact', got {mode!r}")
    if mode == "exact":
        # the reference's integer-binned ROIPooling semantics
        # (sampling_ratio is meaningless there and ignored)
        return _roi_pool_exact(features, rois, spatial_scale, pooled_size)
    if mode == "avg" or sampling_ratio == 1:
        # max == avg at one sample per bin, so the separable path covers it
        return _roi_align_separable(features, rois, spatial_scale,
                                    pooled_size, sampling_ratio)
    return _roi_align_gather(features, rois, spatial_scale, pooled_size,
                             sampling_ratio, mode)


def roi_pool(features, rois, *, spatial_scale=1.0 / 16, pooled_size: int = 7,
             sampling_ratio: int = 2):
    """ROIPool compatibility wrapper: max reduction over the static grid."""
    return roi_align(features, rois, spatial_scale=spatial_scale,
                     pooled_size=pooled_size, sampling_ratio=sampling_ratio,
                     mode="max")
