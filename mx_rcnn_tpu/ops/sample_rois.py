"""RoI sampling + target assignment for the R-CNN head — in-graph, fixed-size.

Behavioral contract of the reference's ``sample_rois`` (rcnn/io/rcnn.py) as
invoked by the ``ProposalTarget`` CustomOp (rcnn/symbol/proposal_target.py):

1. gt boxes are appended to the incoming proposals (done by the caller,
   see ops/proposal.py: the detector graph concatenates them);
2. each RoI is matched to its argmax-IoU gt; its label is that gt's class;
3. fg candidates: IoU ≥ FG_THRESH; at most BATCH_ROIS·FG_FRACTION sampled;
4. bg candidates: IoU ∈ [BG_THRESH_LO, BG_THRESH_HI); fill the remaining
   slots, sampling **with replacement** when there are too few (the
   reference uses npr.choice(replace=True) — we cycle the ranked candidate
   list, same multiset semantics);
5. output exactly BATCH_ROIS rows: rois, label (0 = background), and
   class-specific bbox targets/weights in the 4·K layout
   (``expand_bbox_regression_targets``), optionally normalized by
   BBOX_MEANS/STDS.

The reference runs this on host numpy inside the training graph **every
step** (the device→host→device crossing called out in SURVEY §3.1).  Here it
is a jitted function on device; RNG via ``jax.random``.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from mx_rcnn_tpu.ops.boxes import bbox_overlaps, bbox_transform


@partial(jax.jit, static_argnames=("num_classes", "batch_rois", "fg_fraction",
                                   "fg_thresh", "bg_thresh_hi", "bg_thresh_lo"))
def sample_rois(
    rois: jnp.ndarray,
    roi_valid: jnp.ndarray,
    gt_boxes: jnp.ndarray,
    gt_classes: jnp.ndarray,
    gt_valid: jnp.ndarray,
    key: jax.Array,
    *,
    num_classes: int,
    batch_rois: int = 128,
    fg_fraction: float = 0.25,
    fg_thresh: float = 0.5,
    bg_thresh_hi: float = 0.5,
    bg_thresh_lo: float = 0.0,
    bbox_means=(0.0, 0.0, 0.0, 0.0),
    bbox_stds=(0.1, 0.1, 0.2, 0.2),
):
    """Sample BATCH_ROIS rois for one image.

    Args:
      rois: (R, 4) padded proposals (gt already appended by caller).
      roi_valid: (R,) bool.
      gt_boxes: (G, 4) padded. gt_classes: (G,) int. gt_valid: (G,) bool.
      key: PRNG key.

    Returns dict with:
      rois: (batch_rois, 4)
      label: (batch_rois,) int32 (0 = bg; padded/unfillable slots → 0 with
             zero loss weight via ``label_weight``)
      label_weight: (batch_rois,) float32 — 0 only when the image had no
             usable candidates at all (degenerate), else 1.
      bbox_target: (batch_rois, 4·num_classes) float32 (normalized)
      bbox_weight: (batch_rois, 4·num_classes) float32
    """
    fg_rois_cap = int(round(batch_rois * fg_fraction))

    overlaps = bbox_overlaps(rois, gt_boxes)  # (R, G)
    overlaps = jnp.where(gt_valid[None, :] & roi_valid[:, None], overlaps, -1.0)
    max_ov = jnp.max(overlaps, axis=1)
    argmax_gt = jnp.argmax(overlaps, axis=1)

    fg_mask = (max_ov >= fg_thresh) & roi_valid
    bg_mask = (max_ov < bg_thresh_hi) & (max_ov >= bg_thresh_lo) & roi_valid & ~fg_mask
    # reference fallback: images with no in-range bg fall back to any non-fg
    # valid roi, so the batch always fills
    no_bg = ~jnp.any(bg_mask)
    bg_mask = jnp.where(no_bg, roi_valid & ~fg_mask, bg_mask)

    kf, kb = jax.random.split(key)

    def ranked(mask, k):
        r = jax.random.uniform(k, mask.shape)
        r = jnp.where(mask, r, -1.0)
        return jnp.argsort(-r)  # candidates first, in random order

    fg_order = ranked(fg_mask, kf)  # (R,)
    bg_order = ranked(bg_mask, kb)
    fg_count = jnp.sum(fg_mask)
    bg_count = jnp.sum(bg_mask)

    num_fg = jnp.minimum(fg_count, fg_rois_cap)
    slots = jnp.arange(batch_rois)

    # slot i < num_fg → i-th ranked fg; else cycle the ranked bg list
    # (with-replacement fill, matching npr.choice(replace=True)); if the
    # image has no bg at all, cycle fg instead so every slot is real.
    bg_slot = (slots - num_fg) % jnp.maximum(bg_count, 1)
    fg_cycle = slots % jnp.maximum(fg_count, 1)
    take_fg = slots < num_fg
    any_bg = bg_count > 0
    idx = jnp.where(take_fg, fg_order[jnp.minimum(slots, fg_order.shape[0] - 1)],
                    jnp.where(any_bg, bg_order[bg_slot], fg_order[fg_cycle]))
    is_fg = take_fg | (~any_bg & (fg_count > 0))

    sampled_rois = rois[idx]
    sampled_gt_idx = argmax_gt[idx]
    sampled_label = jnp.where(is_fg, gt_classes[sampled_gt_idx], 0).astype(jnp.int32)

    degenerate = (fg_count + bg_count) == 0
    label_weight = jnp.where(degenerate, 0.0, 1.0) * jnp.ones((batch_rois,), jnp.float32)

    # class-specific 4K bbox targets (expand_bbox_regression_targets layout)
    raw_target = bbox_transform(sampled_rois, gt_boxes[sampled_gt_idx])
    means = jnp.asarray(bbox_means, jnp.float32)
    stds = jnp.asarray(bbox_stds, jnp.float32)
    raw_target = (raw_target - means) / stds

    k4 = 4 * num_classes
    col = sampled_label[:, None] * 4 + jnp.arange(4)[None, :]  # (B, 4)
    onehot_cols = jax.nn.one_hot(col, k4, dtype=jnp.float32)  # (B, 4, 4K)
    # HIGHEST precision: the default TPU matmul would truncate the f32
    # normalized deltas (O(1) after /stds) to bf16 before the MXU — the
    # same rounding assign_anchor's one-hot contraction guards against.
    bbox_target = jnp.einsum("bf,bfk->bk", raw_target.astype(jnp.float32),
                             onehot_cols,
                             precision=jax.lax.Precision.HIGHEST)
    fg_w = (is_fg & (sampled_label > 0)).astype(jnp.float32)[:, None, None]
    bbox_weight = jnp.sum(onehot_cols * fg_w, axis=1)
    bbox_target = bbox_target * bbox_weight

    return {
        "rois": sampled_rois,
        "label": sampled_label,
        "label_weight": label_weight,
        "bbox_target": bbox_target,
        "bbox_weight": bbox_weight,
        "gt_index": sampled_gt_idx,   # for the mask head's target crop
        "is_fg": is_fg,
    }
