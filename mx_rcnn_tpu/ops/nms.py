"""Non-maximum suppression.

The reference ships three NMS backends (``rcnn/processing/nms.py`` wrapping
``rcnn/cython/cpu_nms.pyx`` and the CUDA bitmask kernel in
``rcnn/cython/nms_kernel.cu``), all implementing the same greedy
suppress-by-IoU contract.  Here:

* ``nms_padded`` — exact greedy NMS as a jittable, fixed-output-size op.
  Formulated as a scan over *output slots* (post-NMS count, 300–2000)
  rather than over input boxes (6000–12000): each step argmaxes the live
  scores, emits that index, and suppresses its IoU neighborhood with one
  vectorized pass.  O(max_out · N) work, O(N) memory, no N×N matrix.
  This is the pure-JAX reference path; ``kernels/nms_pallas.py`` provides
  the blocked-bitmask Pallas kernel (the CUDA kernel's algorithm, re-tiled
  for 8×128 vregs) behind the same signature.
* ``nms`` — host-side numpy greedy NMS matching the reference's
  ``py_nms_wrapper`` contract, for the (off-hot-path) eval loop.

Greedy NMS tie/threshold semantics follow the reference: a box is
suppressed when IoU > thresh (strict) w.r.t. a kept box; legacy "+1" areas.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from mx_rcnn_tpu.ops.boxes import bbox_overlaps

_NEG = -1e10


def _iou_one_many(box: jnp.ndarray, boxes: jnp.ndarray) -> jnp.ndarray:
    """IoU of one (4,) box against (N,4) boxes — single source of truth is
    boxes.bbox_overlaps (legacy +1 convention lives there only)."""
    return bbox_overlaps(box[None, :], boxes)[0]


def nms_padded(
    boxes: jnp.ndarray,
    scores: jnp.ndarray,
    max_out: int,
    iou_thresh: float,
    valid: jnp.ndarray | None = None,
):
    """Exact greedy NMS with static output size.

    Args:
      boxes: (N, 4) float.
      scores: (N,) float.
      max_out: static number of output slots.
      iou_thresh: suppression threshold (suppress when IoU > thresh).
      valid: optional (N,) bool; False rows can never be selected.

    Returns:
      keep_idx: (max_out,) int32 indices into boxes; padded slots hold 0.
      keep_mask: (max_out,) bool; True where the slot holds a real kept box.

    Selection order (and therefore the padded prefix) is score-descending,
    matching the reference's argsort-then-suppress loop.
    """
    n = boxes.shape[0]
    live = scores.astype(jnp.float32)
    if valid is not None:
        live = jnp.where(valid, live, _NEG)

    def body(live_scores, _):
        i = jnp.argmax(live_scores)
        ok = live_scores[i] > _NEG / 2
        iou = _iou_one_many(boxes[i], boxes)
        # suppress the neighborhood of the selected box (includes itself,
        # IoU=1) — only if the selection was real, else leave state untouched
        suppress = iou > iou_thresh
        new_scores = jnp.where(suppress & ok, _NEG, live_scores)
        # also retire the selected box even if iou_thresh >= 1
        new_scores = jnp.where(ok, new_scores.at[i].set(_NEG), new_scores)
        return new_scores, (jnp.where(ok, i, 0).astype(jnp.int32), ok)

    _, (keep_idx, keep_mask) = jax.lax.scan(body, live, None, length=max_out)
    return keep_idx, keep_mask


def nms_ranked(
    boxes: jnp.ndarray,
    scores: jnp.ndarray,
    max_out: int,
    iou_thresh: float,
    valid: jnp.ndarray | None = None,
    use_pallas: bool = False,
):
    """Greedy NMS over UNSORTED candidates → score-ranked padded detections.

    The building block of the fused device post-process (one per-class NMS
    per image inside the ``predict_post`` program): sorts descending by
    score (invalid rows sink below every real candidate, satisfying the
    Pallas kernel's score-sorted contract), runs the padded greedy kernel,
    and gathers the kept rows.

    Returns:
      dets: (max_out, 5) float32 [x1,y1,x2,y2,score], score-descending —
        the same row order the host loop's argsort-then-suppress produces;
        padded slots are zeroed.
      keep_mask: (max_out,) bool.

    ``use_pallas`` (static) routes through ``kernels.nms_pallas`` — the
    blocked-bitmask TPU kernel, which itself falls back to ``nms_padded``
    on non-TPU backends, so CPU tests exercise this exact code path.
    """
    s = scores.astype(jnp.float32)
    if valid is not None:
        s = jnp.where(valid, s, _NEG)
    order = jnp.argsort(-s)
    bs = boxes[order].astype(jnp.float32)
    ss = s[order]
    sv = ss > _NEG / 2
    if use_pallas:
        from mx_rcnn_tpu.kernels.nms_pallas import nms_pallas

        keep_idx, keep_mask = nms_pallas(bs, ss, max_out=max_out,
                                         iou_thresh=iou_thresh, valid=sv)
    else:
        keep_idx, keep_mask = nms_padded(bs, ss, max_out=max_out,
                                         iou_thresh=iou_thresh, valid=sv)
    dets = jnp.concatenate([bs[keep_idx], ss[keep_idx][:, None]], axis=1)
    return jnp.where(keep_mask[:, None], dets, 0.0), keep_mask


def nms(dets: np.ndarray, thresh: float) -> list:
    """Host numpy greedy NMS over (N, 5) [x1,y1,x2,y2,score] rows.

    Same contract as the reference's py_nms/cpu_nms wrappers; used by the
    eval loop (``eval/tester.py``) which runs off-device.
    """
    if dets.size == 0:
        return []
    x1, y1, x2, y2, scores = dets[:, 0], dets[:, 1], dets[:, 2], dets[:, 3], dets[:, 4]
    areas = (x2 - x1 + 1) * (y2 - y1 + 1)
    order = scores.argsort()[::-1]
    keep = []
    while order.size > 0:
        i = order[0]
        keep.append(int(i))
        xx1 = np.maximum(x1[i], x1[order[1:]])
        yy1 = np.maximum(y1[i], y1[order[1:]])
        xx2 = np.minimum(x2[i], x2[order[1:]])
        yy2 = np.minimum(y2[i], y2[order[1:]])
        w = np.maximum(0.0, xx2 - xx1 + 1)
        h = np.maximum(0.0, yy2 - yy1 + 1)
        inter = w * h
        ovr = inter / (areas[i] + areas[order[1:]] - inter)
        inds = np.where(ovr <= thresh)[0]
        order = order[inds + 1]
    return keep
