"""Anchor generation.

Behavioral contract of the reference's ``rcnn/processing/generate_anchor.py``:
``generate_anchors(base_size=16, ratios=[0.5,1,2], scales=[8,16,32])`` returns
an (A, 4) array of base anchors produced by enumerating aspect ratios of a
base_size×base_size box centered at ((base_size-1)/2), then scaling each.
Box widths/heights use the legacy "+1" convention (w = x2 - x1 + 1), which we
preserve everywhere for numeric parity with the reference.

Anchors are static given the config → computed in numpy at trace time and
closed over as constants in the jitted graph (no runtime cost).
"""

from __future__ import annotations

import numpy as np


def _whctrs(anchor: np.ndarray):
    """width, height, center x, center y of an (x1,y1,x2,y2) anchor."""
    w = anchor[2] - anchor[0] + 1.0
    h = anchor[3] - anchor[1] + 1.0
    x_ctr = anchor[0] + 0.5 * (w - 1.0)
    y_ctr = anchor[1] + 0.5 * (h - 1.0)
    return w, h, x_ctr, y_ctr


def _mkanchors(ws, hs, x_ctr, y_ctr):
    ws = ws[:, None]
    hs = hs[:, None]
    return np.hstack(
        (
            x_ctr - 0.5 * (ws - 1.0),
            y_ctr - 0.5 * (hs - 1.0),
            x_ctr + 0.5 * (ws - 1.0),
            y_ctr + 0.5 * (hs - 1.0),
        )
    )


def _ratio_enum(anchor, ratios):
    w, h, x_ctr, y_ctr = _whctrs(anchor)
    size = w * h
    size_ratios = size / ratios
    ws = np.round(np.sqrt(size_ratios))
    hs = np.round(ws * ratios)
    return _mkanchors(ws, hs, x_ctr, y_ctr)


def _scale_enum(anchor, scales):
    w, h, x_ctr, y_ctr = _whctrs(anchor)
    ws = w * scales
    hs = h * scales
    return _mkanchors(ws, hs, x_ctr, y_ctr)


def generate_anchors(base_size: int = 16, ratios=(0.5, 1.0, 2.0), scales=(8, 16, 32)) -> np.ndarray:
    """(A, 4) float32 base anchors; A = len(ratios) * len(scales)."""
    ratios = np.asarray(ratios, dtype=np.float64)
    scales = np.asarray(scales, dtype=np.float64)
    base_anchor = np.array([0, 0, base_size - 1, base_size - 1], dtype=np.float64)
    ratio_anchors = _ratio_enum(base_anchor, ratios)
    anchors = np.vstack(
        [_scale_enum(ratio_anchors[i], scales) for i in range(ratio_anchors.shape[0])]
    )
    return anchors.astype(np.float32)


def all_anchors(
    feat_h: int,
    feat_w: int,
    stride: int,
    base_anchors: np.ndarray | None = None,
    **kw,
) -> np.ndarray:
    """Slide base anchors over an H×W feature grid (reference: the shift
    enumeration at the top of ``assign_anchor`` in rcnn/io/rpn.py and of the
    Proposal op).

    Returns (feat_h * feat_w * A, 4) float32, ordered row-major over the grid
    with the A anchors contiguous per cell — i.e. index = (y * W + x) * A + a.
    """
    if base_anchors is None:
        base_anchors = generate_anchors(base_size=stride, **kw)
    A = base_anchors.shape[0]
    shift_x = np.arange(feat_w, dtype=np.float32) * stride
    shift_y = np.arange(feat_h, dtype=np.float32) * stride
    sx, sy = np.meshgrid(shift_x, shift_y)
    shifts = np.stack([sx.ravel(), sy.ravel(), sx.ravel(), sy.ravel()], axis=1)
    # (K, 1, 4) + (1, A, 4) → (K, A, 4)
    anchors = shifts[:, None, :] + base_anchors[None, :, :]
    return anchors.reshape(-1, 4).astype(np.float32)
