"""Numeric core ops.

Pure-function, jittable, fixed-size/masked implementations of every numeric
contract in the reference's ``rcnn/processing`` + ``rcnn/io`` +
``rcnn/symbol/{proposal,proposal_target}.py`` layers, rebuilt TPU-first:
static shapes, vectorized masks instead of boolean indexing, ``jax.random``
instead of host numpy RNG.
"""

from mx_rcnn_tpu.ops.anchors import generate_anchors, all_anchors
from mx_rcnn_tpu.ops.boxes import (
    bbox_transform,
    bbox_pred,
    clip_boxes,
    bbox_overlaps,
)
from mx_rcnn_tpu.ops.nms import nms_padded, nms
from mx_rcnn_tpu.ops.assign_anchor import assign_anchor
from mx_rcnn_tpu.ops.sample_rois import sample_rois
from mx_rcnn_tpu.ops.proposal import propose
from mx_rcnn_tpu.ops.roi_align import roi_align, roi_pool
from mx_rcnn_tpu.ops.postprocess import (
    decode_image_boxes,
    per_class_nms,
    detections_to_records,
)
