"""Shared host post-process: decode → clip → per-class NMS → max_per_image.

This is the block every inference consumer runs after the device forward —
``pred_eval``'s dataset loop, the online serve engine, and any future
batch-prediction tool.  It used to live inline in ``eval/tester.py``; the
serve subsystem needs the exact same math (a drifted copy would make served
detections disagree with the eval metric for the same weights), so the
single source of truth lives here and a parity test pins it to the
reference block's semantics (``tests/test_serve.py``).

All host numpy, off the hot path — identical accounting to the reference's
``pred_eval`` (per-class score threshold → NMS → global per-image cap).
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from mx_rcnn_tpu.ops.boxes import bbox_pred as decode_boxes, clip_boxes


def decode_image_boxes(rois: np.ndarray, deltas: np.ndarray,
                       im_info_row) -> np.ndarray:
    """One image's raw RPN rois + head deltas → (R, 4K) boxes in ORIGINAL
    image coordinates (reference ``im_detect``: bbox_pred + clip_boxes,
    then divide by im_scale).  ``im_info_row`` is the (eh, ew, scale)
    triple the loader ships."""
    eh, ew, s = im_info_row
    boxes = decode_boxes(rois, deltas)
    boxes = clip_boxes(boxes, eh, ew)
    return np.asarray(boxes) / s


def per_class_nms(scores: np.ndarray, boxes: np.ndarray, valid,
                  num_classes: int, thresh: float, nms_thresh: float,
                  max_per_image: int, nms_fn=None) -> List[Optional[np.ndarray]]:
    """One image's (R, K) scores + (R, 4K) original-frame boxes →
    per-class (N, 5) [x1,y1,x2,y2,score] detections (reference
    ``pred_eval`` inner block: per-class score threshold → NMS → global
    per-image score cap).

    Returns a list indexed by class; index 0 (background) is ``None``.
    ``nms_fn`` defaults to the native C++ NMS (numpy fallback inside) —
    injectable for oracle tests."""
    if nms_fn is None:
        from mx_rcnn_tpu.native import nms as nms_fn
    v = np.asarray(valid, bool)
    dets: List[Optional[np.ndarray]] = [None] * num_classes
    for k in range(1, num_classes):
        sel = (scores[:, k] > thresh) & v
        cls_scores = scores[sel, k]
        cls_boxes = boxes[sel, 4 * k:4 * (k + 1)]
        cls_dets = np.hstack([cls_boxes, cls_scores[:, None]]).astype(
            np.float32)
        keep = nms_fn(cls_dets, nms_thresh)
        dets[k] = cls_dets[keep]
    # cap total detections per image (reference max_per_image block)
    if max_per_image > 0:
        scores_all = np.concatenate(
            [dets[k][:, 4] for k in range(1, num_classes)])
        if len(scores_all) > max_per_image:
            th = np.sort(scores_all)[-max_per_image]
            for k in range(1, num_classes):
                dets[k] = dets[k][dets[k][:, 4] >= th]
    return dets


def detections_to_records(dets_per_class) -> List[dict]:
    """Per-class (N, 5) arrays → flat JSON-serializable records sorted by
    descending score — the serve response payload shape."""
    out = []
    for k, d in enumerate(dets_per_class):
        if not k or d is None:
            continue
        for row in d:
            out.append({"cls": int(k), "score": float(row[4]),
                        "bbox": [float(c) for c in row[:4]]})
    out.sort(key=lambda r: -r["score"])
    return out
