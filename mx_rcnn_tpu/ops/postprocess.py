"""Shared host post-process: decode → clip → per-class NMS → max_per_image.

This is the block every inference consumer runs after the device forward —
``pred_eval``'s dataset loop, the online serve engine, and any future
batch-prediction tool.  It used to live inline in ``eval/tester.py``; the
serve subsystem needs the exact same math (a drifted copy would make served
detections disagree with the eval metric for the same weights), so the
single source of truth lives here and a parity test pins it to the
reference block's semantics (``tests/test_serve.py``).

All host numpy, off the hot path — identical accounting to the reference's
``pred_eval`` (per-class score threshold → NMS → global per-image cap).
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from mx_rcnn_tpu.ops.boxes import bbox_pred as decode_boxes, clip_boxes


def decode_image_boxes(rois: np.ndarray, deltas: np.ndarray,
                       im_info_row) -> np.ndarray:
    """One image's raw RPN rois + head deltas → (R, 4K) boxes in ORIGINAL
    image coordinates (reference ``im_detect``: bbox_pred + clip_boxes,
    then divide by im_scale).  ``im_info_row`` is the (eh, ew, scale)
    triple the loader ships."""
    eh, ew, s = im_info_row
    boxes = decode_boxes(rois, deltas)
    boxes = clip_boxes(boxes, eh, ew)
    return np.asarray(boxes) / s


def per_class_nms(scores: np.ndarray, boxes: np.ndarray, valid,
                  num_classes: int, thresh: float, nms_thresh: float,
                  max_per_image: int, nms_fn=None) -> List[Optional[np.ndarray]]:
    """One image's (R, K) scores + (R, 4K) original-frame boxes →
    per-class (N, 5) [x1,y1,x2,y2,score] detections (reference
    ``pred_eval`` inner block: per-class score threshold → NMS → global
    per-image score cap).

    Returns a list indexed by class; index 0 (background) is ``None``.
    ``nms_fn`` defaults to the native C++ NMS (numpy fallback inside) —
    injectable for oracle tests."""
    if nms_fn is None:
        from mx_rcnn_tpu.native import nms as nms_fn
    v = np.asarray(valid, bool)
    dets: List[Optional[np.ndarray]] = [None] * num_classes
    for k in range(1, num_classes):
        sel = (scores[:, k] > thresh) & v
        cls_scores = scores[sel, k]
        cls_boxes = boxes[sel, 4 * k:4 * (k + 1)]
        cls_dets = np.hstack([cls_boxes, cls_scores[:, None]]).astype(
            np.float32)
        keep = nms_fn(cls_dets, nms_thresh)
        dets[k] = cls_dets[keep]
    # cap total detections per image (reference max_per_image block)
    if max_per_image > 0:
        scores_all = np.concatenate(
            [dets[k][:, 4] for k in range(1, num_classes)])
        if len(scores_all) > max_per_image:
            th = np.sort(scores_all)[-max_per_image]
            for k in range(1, num_classes):
                dets[k] = dets[k][dets[k][:, 4] >= th]
    return dets


def device_postprocess(rois, roi_valid, cls_prob, bbox_deltas, im_info, *,
                       num_classes: int, thresh: float, nms_thresh: float,
                       max_per_image: int, per_class_max: Optional[int] = None,
                       use_pallas: bool = False):
    """The jit-traceable fusion of :func:`decode_image_boxes` +
    :func:`per_class_nms` — the ``--device-postprocess`` readback shrink.

    Runs inside the ``predict_post`` program right after the forward, so
    the host reads back ``(B, cap, 6)`` final detections instead of the
    full ``(R, K)`` scores + ``(R, 4K)`` deltas.  Per image: decode + clip
    to the scaled frame, map to ORIGINAL coordinates, per-class score
    threshold → greedy NMS (``ops.nms.nms_ranked``; ``use_pallas`` routes
    the TPU bitmask kernel), then the global top-``max_per_image`` cap
    over all classes.

    Semantics match the host path with one documented exception: the host
    cap keeps every detection tied AT the cut-off score (``>= th`` can
    exceed ``max_per_image``), while ``lax.top_k`` keeps exactly
    ``max_per_image`` rows — exact score ties at the cap boundary may
    differ.  The parity test pins everything else.

    Returns:
      dets: (B, cap, 6) float32 [x1,y1,x2,y2,score,cls], score-descending;
        padded rows zeroed.
      valid: (B, cap) bool.
    """
    import jax
    import jax.numpy as jnp

    NEG = -1e10
    R = rois.shape[1]
    K = num_classes
    pcm = per_class_max or (max_per_image if max_per_image > 0 else R)
    cap = max_per_image if max_per_image > 0 else (K - 1) * pcm
    cap = min(cap, (K - 1) * pcm)

    def one_image(rois_i, valid_i, scores_i, deltas_i, info_i):
        from mx_rcnn_tpu.ops.nms import nms_ranked

        boxes = decode_boxes(rois_i, deltas_i)
        boxes = clip_boxes(boxes, info_i[0], info_i[1]) / info_i[2]
        boxes_k = boxes.reshape(R, K, 4).transpose(1, 0, 2)[1:]  # (K-1, R, 4)
        scores_k = scores_i.T[1:]                                # (K-1, R)
        sel_k = (scores_k > thresh) & valid_i[None, :].astype(bool)
        dets_k, mask_k = jax.vmap(
            lambda b, s, v: nms_ranked(b, s, pcm, nms_thresh, valid=v,
                                       use_pallas=use_pallas))(
            boxes_k, scores_k, sel_k)            # (K-1, pcm, 5) / (K-1, pcm)
        flat = dets_k.reshape(-1, 5)
        fscore = jnp.where(mask_k.reshape(-1), flat[:, 4], NEG)
        top_s, top_i = jax.lax.top_k(fscore, cap)
        cls = (top_i // pcm + 1).astype(jnp.float32)
        out = jnp.concatenate([flat[top_i], cls[:, None]], axis=1)
        dvalid = top_s > NEG / 2
        return jnp.where(dvalid[:, None], out, 0.0), dvalid

    return jax.vmap(one_image)(rois, roi_valid, cls_prob, bbox_deltas,
                               im_info)


def device_dets_to_per_class(dets: np.ndarray, valid,
                             num_classes: int) -> List[Optional[np.ndarray]]:
    """One image's :func:`device_postprocess` readback → the per-class
    ``[None, (N1,5), ...]`` shape :func:`per_class_nms` returns, so
    ``all_boxes`` filling (and everything downstream — mask pass, vis,
    det_cache, scoring) is path-agnostic.  Rows arrive score-descending
    from the device top-k, which is exactly the host NMS keep order
    within a class."""
    dets = np.asarray(dets, np.float32)
    v = np.asarray(valid, bool)
    rows = dets[v]
    out: List[Optional[np.ndarray]] = [None] * num_classes
    for k in range(1, num_classes):
        out[k] = np.ascontiguousarray(rows[rows[:, 5] == k][:, :5],
                                      np.float32)
    return out


def detections_to_records(dets_per_class) -> List[dict]:
    """Per-class (N, 5) arrays → flat JSON-serializable records sorted by
    descending score — the serve response payload shape."""
    out = []
    for k, d in enumerate(dets_per_class):
        if not k or d is None:
            continue
        for row in d:
            out.append({"cls": int(k), "score": float(row[4]),
                        "bbox": [float(c) for c in row[:4]]})
    out.sort(key=lambda r: -r["score"])
    return out
