"""Data flywheel: capture served requests, mine hard examples, replay them.

Three stages close the serve -> train -> serve loop:

- :mod:`capture` — a sampled, bounded request-log ring attached to the serve
  engine, spilled as atomic JSONL+npz shards under ``--capture-dir``.
- :mod:`miner` — ranks captured images by hardness (score entropy, threshold
  disagreement, low max score) and writes a ``mined-<digest>.json`` manifest.
- :mod:`loop` — orchestrates capture -> mine -> replay-train rounds; the
  replay side lives in :class:`mx_rcnn_tpu.data.replay.ReplayDataset`.
"""

from .capture import CaptureOptions, NullCapture, NULL_CAPTURE, RequestCapture
from .miner import mine_shards, write_manifest, load_manifest
from .loop import FlywheelLoop

__all__ = [
    "CaptureOptions", "NullCapture", "NULL_CAPTURE", "RequestCapture",
    "mine_shards", "write_manifest", "load_manifest", "FlywheelLoop",
]
