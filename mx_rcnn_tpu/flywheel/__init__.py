"""Data flywheel: capture served requests, mine hard examples, replay them.

Three stages close the serve -> train -> serve loop:

- :mod:`capture` — a sampled, bounded request-log ring attached to the serve
  engine, spilled as atomic JSONL+npz shards under ``--capture-dir``.
- :mod:`miner` — ranks captured images by hardness (score entropy, threshold
  disagreement, low max score) and writes a ``mined-<digest>.json`` manifest.
- :mod:`loop` — orchestrates capture -> mine -> replay-train rounds; the
  replay side lives in :class:`mx_rcnn_tpu.data.replay.ReplayDataset`.
- :mod:`fleet` — the fabric-scale loop (ISSUE 17): per-member capture
  manifests merged fault-tolerantly, a distributed mine folded into one
  global top-K, and promotion gated on a measured eval-shard quality delta
  with drift detection triggering the next round.
"""

from .capture import (CaptureOptions, NullCapture, NULL_CAPTURE,
                      RequestCapture, list_member_manifests, member_id,
                      merge_manifests)
from .miner import (fold_rankings, load_manifest, mine_member, mine_shards,
                    write_manifest)
from .loop import FlywheelLoop, run_train_cmd
from .fleet import (DriftDetector, FleetFlywheel, build_eval_shard,
                    detection_agreement, eval_shard_quality,
                    load_eval_shard)

__all__ = [
    "CaptureOptions", "NullCapture", "NULL_CAPTURE", "RequestCapture",
    "list_member_manifests", "member_id", "merge_manifests",
    "mine_shards", "mine_member", "fold_rankings", "write_manifest",
    "load_manifest", "FlywheelLoop", "run_train_cmd",
    "FleetFlywheel", "DriftDetector", "build_eval_shard",
    "detection_agreement", "eval_shard_quality", "load_eval_shard",
]
