"""Fleet flywheel coordinator: chaos-certified continuous learning at
fabric scale (ISSUE 17 tentpole).

The single-host loop (:mod:`loop`) mines one capture dir and trusts the
checkpoint watcher to roll the result out.  At fleet scale every stage
gets a distributed twin, and every twin is built to converge under the
faults the fabric already survives:

* **merge** — fold the per-member capture manifests
  (:func:`~mx_rcnn_tpu.flywheel.capture.merge_manifests`): absent/late
  members are merged next round, duplicate deliveries dedup.
* **mine** — a per-member ranking pass (:func:`~mx_rcnn_tpu.flywheel.
  miner.mine_member`) folded into one global top-K
  (:func:`~mx_rcnn_tpu.flywheel.miner.fold_rankings`).  A member
  partitioned away mid-mine costs its contribution, never the round.
* **train** — the replay-train subprocess; a trainer killed mid-epoch
  fails the round and the next round retries off the same captures.
* **promote** — the retrained generation rolls out over the PR-12
  cross-host hot-reload path ONLY after the member-side eval-shard
  quality gate (:func:`eval_shard_quality`, wired into
  ``reload_engine_params``) scores the candidate no worse than the
  incumbent — the PR-8 canary extended from "finite outputs" to a
  measured quality delta on held-out mined records.  A rejected
  generation leaves every member on the incumbent (the pool's
  abort+rollback).
* **drift** — windowed score-distribution drift vs the promoted
  generation's training snapshot (:class:`DriftDetector`) triggers the
  next mine instead of waiting out a fixed cadence.

Promotion, rejection, and drift are first-class telemetry events
(``flywheel/promoted`` / ``flywheel/rejected`` /
``flywheel/drift_detected`` + flight dumps) carrying the PR-16 trace ids
of the mined records — a promoted generation links back to the serving
traces that taught it.

Fleet fault injection (env-owned here, composed by
``tests/faults.py:fleet_fault_env``):

* ``MXR_FAULT_FLYWHEEL_PARTITION_MINE="m1"`` — the named member(s)
  (comma-separated) become unreachable mid-mine: their ranking pass
  raises, the fold proceeds without them.
* ``MXR_FAULT_FLYWHEEL_KILL_TRAIN="0:0.5"`` — the round-0 trainer is
  SIGKILLed 0.5s into its epoch (``ROUND:SECONDS``).
* duplicate manifest delivery and corrupt capture shards live with the
  capture code (``MXR_FAULT_FLYWHEEL_{DUP_MANIFEST,CORRUPT_SHARD}``).
"""

from __future__ import annotations

import collections
import json
import os
from typing import Callable, Optional, Sequence

import numpy as np

from mx_rcnn_tpu import telemetry
from mx_rcnn_tpu.logger import logger

from .capture import SCORE_BANDS, list_shards, merge_manifests, score_stats
from .loop import run_train_cmd
from .miner import fold_rankings, mine_member, write_manifest

# Fleet fault-injection env vars (package code owns names + parsing; the
# tests/faults.py composer only builds env dicts from these).
ENV_PARTITION_MINE = "MXR_FAULT_FLYWHEEL_PARTITION_MINE"
ENV_KILL_TRAIN = "MXR_FAULT_FLYWHEEL_KILL_TRAIN"

EVAL_SHARD_SCHEMA = "mxr_eval_shard"

# lineage breadth: how many mined trace ids ride the promotion events
MAX_LINEAGE_TRACES = 8


# -- eval shard: the promotion gate's held-out set --------------------------

def build_eval_shard(capture_dir, entries, base_path):
    """Materialize held-out entries into one self-contained shard pair
    (``<base>.npz`` pixels + ``<base>.json`` rows) so the member-side
    promotion gate scores against a frozen set instead of reaching back
    into capture shards that rotation (or chaos) may have eaten.

    Records whose pixels cannot be read back — the corrupt-capture-shard
    injection lands exactly here — are skipped and counted: a damaged
    member costs eval coverage, never the round.  npz before json, both
    atomic (the capture spill discipline).  Returns
    ``(json_path_or_None, kept, skipped)``.
    """
    tel = telemetry.get()
    pixels, rows, skipped = {}, [], 0
    for e in entries:
        try:
            with np.load(os.path.join(capture_dir, e["npz"])) as npz:
                px = np.asarray(npz[e["key"]], dtype=np.uint8)
        except Exception:  # noqa: BLE001 — torn/corrupt/missing pixels
            skipped += 1
            tel.counter("flywheel/eval_skipped")
            continue
        pixels[e["key"]] = px
        rows.append({"key": e["key"], "rid": e["rid"],
                     "raw_hw": e["raw_hw"], "orig_hw": e["orig_hw"],
                     "labels": e["detections"],
                     "trace_id": e.get("trace_id")})
    if not rows:
        return None, 0, skipped
    npz_tmp = base_path + ".npz.tmp"
    with open(npz_tmp, "wb") as fh:
        np.savez(fh, **pixels)
    os.replace(npz_tmp, base_path + ".npz")
    doc = {"schema": EVAL_SHARD_SCHEMA, "version": 1,
           "npz": os.path.basename(base_path + ".npz"),
           "records": rows}
    json_tmp = base_path + ".json.tmp"
    with open(json_tmp, "w") as fh:
        fh.write(json.dumps(doc, sort_keys=True, indent=1))
    os.replace(json_tmp, base_path + ".json")
    tel.counter("flywheel/eval_records", len(rows))
    return base_path + ".json", len(rows), skipped


def load_eval_shard(path):
    """Load an eval shard into ``{"records": [...], "pixels": {key:
    uint8 HWC}}``.  Raises on anything unreadable — the promotion gate
    fails CLOSED on a torn eval shard rather than waiving the check."""
    with open(path) as fh:
        doc = json.load(fh)
    if doc.get("schema") != EVAL_SHARD_SCHEMA:
        raise ValueError(f"{path}: not a {EVAL_SHARD_SCHEMA} document")
    npz_path = os.path.join(os.path.dirname(path), doc["npz"])
    pixels = {}
    with np.load(npz_path) as npz:
        for rec in doc["records"]:
            pixels[rec["key"]] = np.asarray(npz[rec["key"]], np.uint8)
    return {"records": doc["records"], "pixels": pixels}


def _iou(a, b):
    ix = max(0.0, min(a[2], b[2]) - max(a[0], b[0]))
    iy = max(0.0, min(a[3], b[3]) - max(a[1], b[1]))
    inter = ix * iy
    if inter <= 0:
        return 0.0
    area = ((a[2] - a[0]) * (a[3] - a[1])
            + (b[2] - b[0]) * (b[3] - b[1]) - inter)
    return inter / max(area, 1e-9)


def detection_agreement(preds, labels, iou_thresh=0.5, score_floor=0.1,
                        label_floor=0.3):
    """F1-style agreement in ``[0, 1]`` between served detections and a
    record's pseudo-labels: greedy same-class IoU matching, each label
    matched at most once.  Both empty → 1.0 (nothing to disagree
    about); one empty → 0.0.  ``label_floor`` mirrors the miner's
    ``min_label_score`` so weak captured detections don't count as
    ground truth."""
    preds = [p for p in preds if float(p["score"]) >= score_floor]
    labels = [g for g in labels if float(g["score"]) >= label_floor]
    if not preds and not labels:
        return 1.0
    if not preds or not labels:
        return 0.0
    used, matched = set(), 0
    for p in sorted(preds, key=lambda r: -float(r["score"])):
        best, best_iou = None, iou_thresh
        for i, g in enumerate(labels):
            if i in used or int(g["cls"]) != int(p["cls"]):
                continue
            ov = _iou(p["bbox"], g["bbox"])
            if ov >= best_iou:
                best, best_iou = i, ov
        if best is not None:
            used.add(best)
            matched += 1
    return 2.0 * matched / (len(preds) + len(labels))


def eval_shard_quality(engine, shard, timeout_s=30.0):
    """Mean detection agreement of the CURRENT weights over an eval
    shard — the measured stand-in for mAP the promotion gate compares
    between incumbent and candidate.  Pixels are replayed at their
    captured raw extent; pseudo-labels (stored in ORIGINAL coords, like
    every served detection) are scaled to that extent first, the
    ReplayDataset coordinate convention."""
    futs = []
    for rec in shard["records"]:
        px = shard["pixels"][rec["key"]]
        rh, rw = rec["raw_hw"]
        futs.append((rec, engine.submit(
            np.ascontiguousarray(px[:rh, :rw]))))
    vals = []
    for rec, fut in futs:
        dets = fut.result(timeout=timeout_s) or []
        rh, rw = rec["raw_hw"]
        oh, ow = rec["orig_hw"]
        sy, sx = rh / max(oh, 1), rw / max(ow, 1)
        labels = [dict(g, bbox=[g["bbox"][0] * sx, g["bbox"][1] * sy,
                                g["bbox"][2] * sx, g["bbox"][3] * sy])
                  for g in rec["labels"]]
        vals.append(detection_agreement(dets, labels))
    return float(np.mean(vals)) if vals else 0.0


# -- drift: when the traffic leaves the training snapshot behind -----------

def score_distribution(stats_list):
    """Summary of a set of per-record score stats: mean of mean_score
    and entropy, plus the fraction of records with at least one survivor
    in each score band."""
    n = max(len(stats_list), 1)
    out = {"mean_score": 0.0, "entropy": 0.0}
    bands = {f"{t:.1f}": 0.0 for t in SCORE_BANDS}
    for s in stats_list:
        out["mean_score"] += float(s.get("mean_score", 0.0)) / n
        out["entropy"] += float(s.get("entropy", 0.0)) / n
        sb = s.get("bands", {})
        for k in bands:
            bands[k] += (1.0 / n) if sb.get(k, 0) > 0 else 0.0
    out["bands"] = bands
    return out


def drift_metric(ref, cur):
    """Max absolute difference across the distribution summaries —
    one number an operator can threshold."""
    diffs = [abs(ref["mean_score"] - cur["mean_score"]),
             abs(ref["entropy"] - cur["entropy"])]
    for k in ref.get("bands", {}):
        diffs.append(abs(ref["bands"].get(k, 0.0)
                         - cur.get("bands", {}).get(k, 0.0)))
    return max(diffs) if diffs else 0.0


class DriftDetector:
    """Windowed score-distribution drift vs the training snapshot.

    ``snapshot()`` freezes the distribution the promoted generation was
    trained on (the fold's entries); ``observe()`` feeds per-record
    stats captured since.  ``check()`` compares the recent window
    against the snapshot — a metric above ``threshold`` means the
    traffic has moved and the next mine should fire now, not at the
    next fixed cadence."""

    def __init__(self, threshold=0.25, window=64, min_observed=8):
        self.threshold = float(threshold)
        self.min_observed = int(min_observed)
        self._window = collections.deque(maxlen=int(window))
        self._ref = None

    def snapshot(self, stats_list):
        self._ref = score_distribution(list(stats_list))
        self._window.clear()
        return self._ref

    def observe(self, stats):
        self._window.append(stats)

    def check(self):
        """(drifted, metric) — False until a snapshot exists and the
        window has enough mass to mean anything."""
        if self._ref is None or len(self._window) < self.min_observed:
            return False, 0.0
        metric = drift_metric(self._ref,
                              score_distribution(list(self._window)))
        return metric > self.threshold, metric


# -- the coordinator -------------------------------------------------------

class FleetFlywheel:
    """One continuous-learning loop over a fleet: merge → per-member
    mine → fold → train → gated promotion → drift watch.

    ``rollout_fn(target) -> bool`` rolls the candidate fleet-wide
    (default: POST ``/admin/reload`` to ``promote_to``, i.e. the fabric
    router — the pool's rolling reload with abort+rollback);
    ``candidate_fn() -> target|None`` discovers the retrained
    checkpoint (default: ``scan_checkpoints(ckpt_prefix)``).  Both are
    injectable, the fabric's fake-clock test discipline."""

    def __init__(self, capture_dir: str, top_k: int = 64,
                 min_label_score: float = 0.3,
                 out_dir: Optional[str] = None,
                 train_cmd: Optional[Sequence[str]] = None,
                 ckpt_prefix: Optional[str] = None,
                 promote_to: Optional[str] = None,
                 rollout_fn: Optional[Callable[[dict], bool]] = None,
                 candidate_fn: Optional[Callable[[], Optional[dict]]] = None,
                 eval_every: int = 4, quality_slack: float = 0.0,
                 drift_threshold: float = 0.25, drift_window: int = 64,
                 env: Optional[dict] = None):
        self.capture_dir = capture_dir
        self.top_k = top_k
        self.min_label_score = min_label_score
        self.out_dir = out_dir
        self.train_cmd = list(train_cmd) if train_cmd else None
        self.ckpt_prefix = ckpt_prefix
        self.promote_to = promote_to
        self.rollout_fn = rollout_fn or self._default_rollout
        self.candidate_fn = candidate_fn or self._default_candidate
        self.eval_every = int(eval_every)
        self.quality_slack = float(quality_slack)
        self.drift = DriftDetector(drift_threshold, drift_window)
        self.promoted_rounds = 0
        self._last_candidate_key = None
        env = os.environ if env is None else env
        self._partitioned = {m.strip() for m in
                             env.get(ENV_PARTITION_MINE, "").split(",")
                             if m.strip()}
        self._kill_round, self._kill_after_s = self._parse_kill(
            env.get(ENV_KILL_TRAIN, ""))

    @staticmethod
    def _parse_kill(raw):
        if not raw:
            return None, None
        rnd, _, secs = raw.partition(":")
        try:
            return int(rnd), float(secs or 0.0)
        except ValueError:
            logger.warning("bad %s value %r (want ROUND:SECONDS)",
                           ENV_KILL_TRAIN, raw)
            return None, None

    # -- default candidate discovery / rollout wiring ---------------------

    def _default_candidate(self):
        from mx_rcnn_tpu.serve.replica import scan_checkpoints, target_key
        if not self.ckpt_prefix:
            return None
        tgt = scan_checkpoints(self.ckpt_prefix)
        if tgt is None:
            return None
        key = target_key(tgt)
        if self._last_candidate_key is not None \
                and key <= self._last_candidate_key:
            return None  # nothing newer than what already rolled out
        return tgt

    def _default_rollout(self, target):
        from mx_rcnn_tpu.serve.frontend import address_request
        if not self.promote_to:
            logger.warning("fleet flywheel: no rollout path configured "
                           "(promote_to/rollout_fn)")
            return False
        status, doc = address_request(self.promote_to, "POST",
                                      "/admin/reload", doc=target,
                                      timeout=600.0)
        return status == 200 and bool(
            isinstance(doc, dict) and doc.get("ok", True))

    # -- one round --------------------------------------------------------

    def mine_round(self, round_idx: int = 0) -> dict:
        """merge → per-member mine (partition-tolerant) → fold → commit
        manifest + eval shard.  Returns the mine summary."""
        tel = telemetry.get()
        merged = merge_manifests(self.capture_dir)
        rankings, failed = [], []
        for key in sorted(merged["members"]):
            mdoc = merged["members"][key]
            member = mdoc.get("member", "unknown")
            try:
                if member in self._partitioned:
                    raise OSError(f"injected partition: member "
                                  f"{member} unreachable mid-mine")
                rankings.append(mine_member(
                    self.capture_dir, mdoc, top_k=self.top_k,
                    min_label_score=self.min_label_score))
            except (OSError, ValueError) as e:
                failed.append(member)
                tel.counter("flywheel/mine_member_failed")
                tel.dump_flight("mine_member_failed", member=member,
                                round=round_idx, cause=str(e))
                logger.warning("fleet mine round %d: member %s failed "
                               "(%s) — folding without it", round_idx,
                               member, e)
        train, evals, scanned, skipped = fold_rankings(
            rankings, top_k=self.top_k, eval_every=self.eval_every)
        summary = {"round": round_idx, "mined": len(train),
                   "eval": len(evals), "scanned": scanned,
                   "skipped": skipped,
                   "members": sorted(r["member"] for r in rankings),
                   "mine_failed": sorted(failed),
                   "duplicates_dropped": merged["duplicates_dropped"],
                   "manifest": None, "eval_shard": None}
        if not train:
            logger.info("fleet mine round %d: nothing mined (%d members,"
                        " %d scanned)", round_idx, len(rankings), scanned)
            return summary
        manifest = write_manifest(
            self.capture_dir, train, scanned, self.top_k,
            out_dir=self.out_dir, min_label_score=self.min_label_score,
            extra={"members": summary["members"],
                   "eval_entries": evals})
        summary["manifest"] = manifest
        if evals:
            shard_path, kept, dropped = build_eval_shard(
                self.capture_dir, evals,
                manifest[:-len(".json")] + "-eval")
            summary["eval_shard"] = shard_path
            summary["eval"] = kept
            if dropped:
                logger.warning("fleet mine round %d: %d eval record(s) "
                               "unreadable (corrupt capture shard?) — "
                               "gating on the %d readable", round_idx,
                               dropped, kept)
        tel.gauge("flywheel/round", round_idx)
        logger.info("fleet mine round %d: %d member(s) -> %d train + %d "
                    "eval of %d scanned -> %s", round_idx, len(rankings),
                    len(train), summary["eval"], scanned,
                    os.path.basename(manifest))
        return summary

    def _lineage(self, manifest_path):
        """The first few trace ids riding the mined entries — promotion
        events link the new generation back to the requests that taught
        it (PR-16 provenance)."""
        try:
            with open(manifest_path) as fh:
                doc = json.load(fh)
        except (OSError, ValueError):
            return []
        tids = [e["trace_id"] for e in doc.get("entries", [])
                if e.get("trace_id")]
        return tids[:MAX_LINEAGE_TRACES]

    def run_round(self, round_idx: int = 0) -> dict:
        """One full fleet round: mine, train, gated promotion.  A failed
        train (chaos kill, OOM) or rejected promotion leaves
        ``promoted=False``; the captures are still on disk, so the next
        round retries the whole stage chain."""
        tel = telemetry.get()
        summary = self.mine_round(round_idx)
        summary.update({"train_rc": None, "promoted": False})
        if not summary["manifest"]:
            return summary
        if self.train_cmd:
            kill_s = (self._kill_after_s
                      if round_idx == self._kill_round else None)
            rc = run_train_cmd(self.train_cmd, summary["manifest"],
                               kill_after_s=kill_s)
            summary["train_rc"] = rc
            if rc != 0:
                tel.counter("flywheel/train_failed")
                tel.dump_flight("fleet_train_failed", round=round_idx,
                                rc=rc)
                logger.error("fleet round %d: train rc=%d — generation "
                             "not promoted, retrying next round",
                             round_idx, rc)
                return summary
        candidate = self.candidate_fn()
        if candidate is None:
            summary["error"] = "no candidate checkpoint"
            logger.warning("fleet round %d: no candidate checkpoint to "
                           "promote", round_idx)
            return summary
        target = dict(candidate)
        if summary["eval_shard"]:
            target["eval_shard"] = summary["eval_shard"]
            target["quality_slack"] = self.quality_slack
        lineage = self._lineage(summary["manifest"])
        if lineage:
            target["trace_ids"] = lineage
        ok = bool(self.rollout_fn(target))
        summary["promoted"] = ok
        if ok:
            self.promoted_rounds += 1
            self._last_candidate_key = (candidate["epoch"],
                                        candidate["consumed"],
                                        candidate["kind"])
            tel.counter("flywheel/promoted")
            tel.dump_flight("generation_promoted", round=round_idx,
                            target=[candidate["epoch"],
                                    candidate["consumed"],
                                    candidate["kind"]],
                            manifest=os.path.basename(summary["manifest"]),
                            members=summary["members"],
                            trace_ids=lineage)
            self._snapshot_from_manifest(summary["manifest"])
            logger.info("fleet round %d: generation PROMOTED fleet-wide "
                        "(%d member(s) mined, lineage %d trace(s))",
                        round_idx, len(summary["members"]), len(lineage))
        else:
            tel.counter("flywheel/rejected")
            tel.dump_flight("generation_rejected", round=round_idx,
                            target=[candidate.get("epoch"),
                                    candidate.get("consumed"),
                                    candidate.get("kind")],
                            trace_ids=lineage)
            logger.error("fleet round %d: promotion REJECTED — every "
                         "member stays on the incumbent", round_idx)
        return summary

    def _snapshot_from_manifest(self, manifest_path):
        """Freeze the promoted generation's training score distribution
        as the drift reference (stats recomputed from the entries'
        captured detections)."""
        try:
            with open(manifest_path) as fh:
                doc = json.load(fh)
        except (OSError, ValueError):
            return
        stats = [score_stats(e.get("detections", []))
                 for e in doc.get("entries", [])]
        if stats:
            self.drift.snapshot(stats)

    def check_drift(self, window: int = 64) -> tuple:
        """Feed the newest captured rows into the drift window and
        compare against the training snapshot.  Drift is a first-class
        event: counted, flight-dumped, and the run loop treats it as
        the trigger for the next mine."""
        rows = []
        for shard in list_shards(self.capture_dir)[-8:]:
            try:
                with open(shard["jsonl"]) as fh:
                    for line in fh:
                        line = line.strip()
                        if line:
                            try:
                                rows.append(json.loads(line))
                            except ValueError:
                                continue
            except OSError:
                continue
        for row in rows[-window:]:
            self.drift.observe(row.get("stats", {}))
        drifted, metric = self.drift.check()
        if drifted:
            telemetry.get().counter("flywheel/drift_detected")
            telemetry.get().dump_flight(
                "flywheel_drift", metric=round(metric, 4),
                threshold=self.drift.threshold)
            logger.warning("fleet flywheel: score distribution DRIFTED "
                           "%.3f past the training snapshot (threshold "
                           "%.3f) — next mine triggered", metric,
                           self.drift.threshold)
        return drifted, metric

    def run(self, max_rounds: int = 3) -> list:
        """Round until a generation promotes (convergence under chaos:
        a killed trainer or partitioned miner costs rounds, not the
        loop), then keep going only while drift says the world moved."""
        results = []
        for i in range(max_rounds):
            summary = self.run_round(i)
            results.append(summary)
            if summary["promoted"]:
                drifted, metric = self.check_drift()
                summary["drift"] = {"drifted": drifted,
                                    "metric": round(metric, 4)}
                if not drifted:
                    break
        return results
