"""Hard-example mining over captured request shards.

Reads the ``shard-*.jsonl`` rows a :class:`~mx_rcnn_tpu.flywheel.capture.
RequestCapture` spilled, scores each record's hardness, and writes the
top-K as an atomic ``mined-<digest>.json`` manifest with full provenance
(source shard, request id, model generation that served it, and — when the
serving path ran with distributed tracing on — the trace id, so a hard
example links back to the exact request trace that produced it).

Hardness combines the three signals the capture stage recorded:

- **entropy** — normalized detection-score entropy; flat score mass means
  the model could not separate its hypotheses.
- **disagreement** — NMS-survivor falloff across adjacent score
  thresholds; many loose survivors that die at the strict threshold mark
  borderline detections.
- **low max score** — ``1 - max_score``; the model's best guess is weak.

The manifest rename is the commit point: a SIGTERM mid-mine leaves only a
``.tmp`` file behind, never a partial manifest (pinned in tests via
:data:`ENV_MINE_PAUSE_S`, which sleeps between write and rename).

Distributed mine (ISSUE 17): at fleet scale the mine splits into a
per-member ranking pass (:func:`mine_member` — each member's shards are
read off its own capture manifest, so a member still spilling can't tear
the scan) and a fold (:func:`fold_rankings`) that merges the rankings
into one global top-K with cross-member dedup.  The fold's total order is
deterministic in ANY member order — hardness desc, then rid asc (the
tie-break), then (npz, key) as a final anchor — so re-folding after a
partition heals lands on the byte-identical manifest, committed through
the same ``mined-<digest>.json`` rename point.
"""

import hashlib
import json
import os
import time

from mx_rcnn_tpu import telemetry

from .capture import list_shards
# The scoring math lives in flywheel/hardness.py, shared with the serve
# cascade gate so mining and serving rank the same frames hard; the
# re-exports keep this module's historical import surface intact.
from .hardness import W_DISAGREE, W_ENTROPY, W_LOW_MAX, hardness

__all__ = ["W_ENTROPY", "W_DISAGREE", "W_LOW_MAX", "hardness",
           "mine_shards", "mine_member", "fold_rankings",
           "write_manifest", "load_manifest",
           "MEMBER_RANKING_SCHEMA", "MANIFEST_SCHEMA", "ENV_MINE_PAUSE_S"]

MEMBER_RANKING_SCHEMA = "mxr_member_ranking"

# Test hook: sleep this many seconds between writing the tmp manifest and
# the atomic rename, widening the window a SIGTERM-atomicity test needs.
ENV_MINE_PAUSE_S = "MXR_FLYWHEEL_MINE_PAUSE_S"

MANIFEST_SCHEMA = "mxr_mined_manifest"


def mine_shards(capture_dir, top_k=64, min_label_score=0.3, shards=None,
                member=None):
    """Scan shard rows, rank by hardness, return (entries, scanned, skipped).

    Records with no detection at or above ``min_label_score`` carry no
    usable pseudo-label and are skipped (counted, not errored).  Rows that
    fail to parse are skipped the same way — a torn jsonl must not kill
    the mine.

    ``shards`` restricts the scan to an explicit shard list (the fleet
    path mines exactly what a member's manifest names); ``member`` tags
    each entry with its source member — the single-host path passes
    neither, so its entries (and therefore its manifest bytes) are
    untouched by fleet mode.
    """
    tel = telemetry.get()
    scanned = skipped = 0
    scored = []
    for shard in (list_shards(capture_dir) if shards is None else shards):
        with open(shard["jsonl"]) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                scanned += 1
                try:
                    row = json.loads(line)
                except ValueError:
                    skipped += 1
                    tel.counter("flywheel/skipped_bad_row")
                    continue
                dets = row.get("detections", [])
                if not any(d["score"] >= min_label_score for d in dets):
                    skipped += 1
                    tel.counter("flywheel/skipped_unlabeled")
                    continue
                score, signals = hardness(row.get("stats", {}))
                entry = {
                    "shard": os.path.basename(shard["jsonl"]),
                    "npz": row["npz"],
                    "key": row["key"],
                    "rid": row["rid"],
                    "hardness": score,
                    "signals": signals,
                    "generation": row.get("generation", 0),
                    "trace_id": row.get("trace_id"),
                    "bucket": row["bucket"],
                    "raw_hw": row["raw_hw"],
                    "orig_hw": row["orig_hw"],
                    "detections": dets,
                }
                if member is not None:
                    entry["member"] = member
                scored.append((score, entry))
    # stable, deterministic order: hardness desc, then rid asc
    scored.sort(key=lambda se: (-se[0], se[1]["rid"]))
    entries = [e for _, e in scored[:top_k]]
    if member is None:
        # a member-tagged scan is an intermediate ranking; the FOLD
        # counts what was actually mined, so the counter isn't inflated
        # by per-member passes over overlapping hard sets
        tel.counter("flywheel/mined", len(entries))
    return entries, scanned, skipped


def mine_member(capture_dir, manifest_doc, top_k=64, min_label_score=0.3):
    """One member's ranking pass: scan exactly the shards its capture
    manifest names (not a dir glob other members are mutating), rank,
    and return a ranking doc for :func:`fold_rankings`.  Shards the
    manifest names but the dir no longer holds (byte-budget rotation, a
    corrupted-and-removed pair) are skipped and counted — a member's
    stale claim costs coverage, never the mine."""
    tel = telemetry.get()
    member = manifest_doc.get("member", "unknown")
    shards, missing = [], 0
    for name in manifest_doc.get("shards", []):
        base = os.path.join(capture_dir, name)
        try:
            st = os.stat(base + ".jsonl")
            nbytes = os.path.getsize(base + ".npz") + st.st_size
        except OSError:
            missing += 1
            tel.counter("flywheel/shard_missing")
            continue
        shards.append({"base": base, "npz": base + ".npz",
                       "jsonl": base + ".jsonl", "bytes": nbytes,
                       "mtime": st.st_mtime})
    shards.sort(key=lambda p: (p["mtime"], p["base"]))
    entries, scanned, skipped = mine_shards(
        capture_dir, top_k=top_k, min_label_score=min_label_score,
        shards=shards, member=member)
    return {"schema": MEMBER_RANKING_SCHEMA, "member": member,
            "pid": manifest_doc.get("pid"), "entries": entries,
            "scanned": scanned, "skipped": skipped,
            "missing_shards": missing}


def fold_rankings(rankings, top_k=64, eval_every=0):
    """Fold per-member rankings into one global top-K.

    Cross-member dedup on ``(npz, key)``: the same captured record
    arriving through two rankings (duplicate manifest delivery) ranks
    once.  The total order is deterministic regardless of fold order —
    hardness desc, rid asc (the cross-member tie-break), then
    ``(npz, key)`` as a final anchor so equal-rid records from different
    members cannot flip between runs.

    With ``eval_every > 0`` every ``eval_every``-th record of the ranked
    stream is RESERVED as a held-out eval entry for the promotion gate —
    never trained on, so the gate scores generalization, not
    memorization.

    Returns ``(train_entries, eval_entries, scanned, skipped)``.
    """
    pool = {}
    scanned = skipped = 0
    for r in rankings:
        if not r:
            continue
        scanned += int(r.get("scanned", 0))
        skipped += int(r.get("skipped", 0))
        for e in r.get("entries", []):
            ident = (e["npz"], e["key"])
            prev = pool.get(ident)
            # duplicate across rankings (re-delivered manifest): keep
            # the canonically-smallest member tag, NOT first-seen —
            # first-seen would leak fold order into the manifest bytes
            if prev is None or (e.get("member") or "") \
                    < (prev.get("member") or ""):
                pool[ident] = e
    pool = sorted(pool.values(),
                  key=lambda e: (-e["hardness"], e["rid"],
                                 e["npz"], e["key"]))
    train, evals, taken = [], [], 0
    for e in pool:
        if len(train) >= top_k:
            break
        taken += 1
        if eval_every and taken % eval_every == 0:
            evals.append(e)
        else:
            train.append(e)
    telemetry.get().counter("flywheel/mined", len(train))
    return train, evals, scanned, skipped


def write_manifest(capture_dir, entries, scanned, top_k,
                   out_dir=None, min_label_score=None, extra=None):
    """Atomically write ``mined-<digest>.json``; returns its path.

    The digest covers the entry provenance, so re-mining identical
    captures lands on the same filename (idempotent rounds) — fleet
    re-folds after a healed partition commit through this same rename
    point.  ``extra`` adds fleet-mode keys (``members``,
    ``eval_entries``) strictly ADDITIVELY: it may not shadow a legacy
    key, and the single-host path passes none, keeping its manifest
    byte-for-byte unchanged.
    """
    doc = {
        "schema": MANIFEST_SCHEMA,
        "version": 1,
        "capture_dir": os.path.abspath(capture_dir),
        "top_k": int(top_k),
        "total_scanned": int(scanned),
        "min_label_score": min_label_score,
        "entries": entries,
    }
    for key, value in (extra or {}).items():
        if key in doc:
            raise ValueError(f"extra manifest key {key!r} shadows a "
                             f"legacy field")
        doc[key] = value
    payload = json.dumps(doc, sort_keys=True, indent=1)
    digest = hashlib.sha256(json.dumps(
        [(e["npz"], e["key"]) for e in entries]).encode()).hexdigest()[:12]
    out_dir = capture_dir if out_dir is None else out_dir
    os.makedirs(out_dir, exist_ok=True)
    path = os.path.join(out_dir, f"mined-{digest}.json")
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        fh.write(payload)
    pause = float(os.environ.get(ENV_MINE_PAUSE_S, "0") or 0)
    if pause > 0:
        time.sleep(pause)
    os.replace(tmp, path)
    return path


def load_manifest(path):
    with open(path) as fh:
        doc = json.load(fh)
    if doc.get("schema") != MANIFEST_SCHEMA:
        raise ValueError(f"{path}: not a {MANIFEST_SCHEMA} document")
    return doc
