"""Hard-example mining over captured request shards.

Reads the ``shard-*.jsonl`` rows a :class:`~mx_rcnn_tpu.flywheel.capture.
RequestCapture` spilled, scores each record's hardness, and writes the
top-K as an atomic ``mined-<digest>.json`` manifest with full provenance
(source shard, request id, model generation that served it, and — when the
serving path ran with distributed tracing on — the trace id, so a hard
example links back to the exact request trace that produced it).

Hardness combines the three signals the capture stage recorded:

- **entropy** — normalized detection-score entropy; flat score mass means
  the model could not separate its hypotheses.
- **disagreement** — NMS-survivor falloff across adjacent score
  thresholds; many loose survivors that die at the strict threshold mark
  borderline detections.
- **low max score** — ``1 - max_score``; the model's best guess is weak.

The manifest rename is the commit point: a SIGTERM mid-mine leaves only a
``.tmp`` file behind, never a partial manifest (pinned in tests via
:data:`ENV_MINE_PAUSE_S`, which sleeps between write and rename).
"""

import hashlib
import json
import os
import time

from mx_rcnn_tpu import telemetry

from .capture import SCORE_BANDS, list_shards

# Test hook: sleep this many seconds between writing the tmp manifest and
# the atomic rename, widening the window a SIGTERM-atomicity test needs.
ENV_MINE_PAUSE_S = "MXR_FLYWHEEL_MINE_PAUSE_S"

# Signal weights; entropy and disagreement dominate, low-max breaks ties.
W_ENTROPY = 1.0
W_DISAGREE = 1.0
W_LOW_MAX = 0.5

MANIFEST_SCHEMA = "mxr_mined_manifest"


def hardness(stats):
    """Scalar hardness of one captured record from its score stats."""
    bands = stats.get("bands", {})
    loose = bands.get(f"{SCORE_BANDS[0]:.1f}", 0)
    strict = bands.get(f"{SCORE_BANDS[-1]:.1f}", 0)
    disagree = (loose - strict) / max(1, loose)
    entropy = float(stats.get("entropy", 0.0))
    low_max = 1.0 - float(stats.get("max_score", 0.0))
    score = W_ENTROPY * entropy + W_DISAGREE * disagree + W_LOW_MAX * low_max
    return score, {"entropy": entropy, "disagreement": disagree,
                   "low_max": low_max}


def mine_shards(capture_dir, top_k=64, min_label_score=0.3):
    """Scan shard rows, rank by hardness, return (entries, scanned, skipped).

    Records with no detection at or above ``min_label_score`` carry no
    usable pseudo-label and are skipped (counted, not errored).  Rows that
    fail to parse are skipped the same way — a torn jsonl must not kill
    the mine.
    """
    tel = telemetry.get()
    scanned = skipped = 0
    scored = []
    for shard in list_shards(capture_dir):
        with open(shard["jsonl"]) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                scanned += 1
                try:
                    row = json.loads(line)
                except ValueError:
                    skipped += 1
                    tel.counter("flywheel/skipped_bad_row")
                    continue
                dets = row.get("detections", [])
                if not any(d["score"] >= min_label_score for d in dets):
                    skipped += 1
                    tel.counter("flywheel/skipped_unlabeled")
                    continue
                score, signals = hardness(row.get("stats", {}))
                scored.append((score, {
                    "shard": os.path.basename(shard["jsonl"]),
                    "npz": row["npz"],
                    "key": row["key"],
                    "rid": row["rid"],
                    "hardness": score,
                    "signals": signals,
                    "generation": row.get("generation", 0),
                    "trace_id": row.get("trace_id"),
                    "bucket": row["bucket"],
                    "raw_hw": row["raw_hw"],
                    "orig_hw": row["orig_hw"],
                    "detections": dets,
                }))
    # stable, deterministic order: hardness desc, then rid asc
    scored.sort(key=lambda se: (-se[0], se[1]["rid"]))
    entries = [e for _, e in scored[:top_k]]
    tel.counter("flywheel/mined", len(entries))
    return entries, scanned, skipped


def write_manifest(capture_dir, entries, scanned, top_k,
                   out_dir=None, min_label_score=None):
    """Atomically write ``mined-<digest>.json``; returns its path.

    The digest covers the entry provenance, so re-mining identical
    captures lands on the same filename (idempotent rounds).
    """
    doc = {
        "schema": MANIFEST_SCHEMA,
        "version": 1,
        "capture_dir": os.path.abspath(capture_dir),
        "top_k": int(top_k),
        "total_scanned": int(scanned),
        "min_label_score": min_label_score,
        "entries": entries,
    }
    payload = json.dumps(doc, sort_keys=True, indent=1)
    digest = hashlib.sha256(json.dumps(
        [(e["npz"], e["key"]) for e in entries]).encode()).hexdigest()[:12]
    out_dir = capture_dir if out_dir is None else out_dir
    os.makedirs(out_dir, exist_ok=True)
    path = os.path.join(out_dir, f"mined-{digest}.json")
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        fh.write(payload)
    pause = float(os.environ.get(ENV_MINE_PAUSE_S, "0") or 0)
    if pause > 0:
        time.sleep(pause)
    os.replace(tmp, path)
    return path


def load_manifest(path):
    with open(path) as fh:
        doc = json.load(fh)
    if doc.get("schema") != MANIFEST_SCHEMA:
        raise ValueError(f"{path}: not a {MANIFEST_SCHEMA} document")
    return doc
