"""The ONE hardness definition shared by mining and serving.

The flywheel miner ranks captured requests by a scalar hardness folded
from three signals (normalized score entropy, NMS-survivor falloff
between the loose and strict :data:`~mx_rcnn_tpu.flywheel.capture.
SCORE_BANDS`, and a weak best guess).  The cascade serving gate routes
live requests on the *same* scalar: frames the small model finds easy
are answered cheaply, frames above the threshold escalate to the big
model — and those are exactly the frames the miner would rank hardest.

Both consumers import from here so the definitions can never drift:

- :func:`hardness` — the host/stats-dict form the miner scores shard
  rows with (moved verbatim from ``flywheel/miner.py``).
- :func:`build_device_hardness` — a jit-compiled device program that
  folds a ``(B, cap, 6)`` detection tensor + validity mask (the
  ``predict_serve_e2e`` output, still on device) into per-image
  hardness, reproducing ``hardness(score_stats(records))`` without a
  host readback of the detections.  The equivalence is pinned by
  ``tests/test_cascade.py``.
"""

from .capture import SCORE_BANDS, score_stats

# Signal weights; entropy and disagreement dominate, low-max breaks ties.
W_ENTROPY = 1.0
W_DISAGREE = 1.0
W_LOW_MAX = 0.5

# Upper bound of the hardness scalar (every signal saturated).  The
# cascade threshold is expressed in [0, 1] of this scale, so
# ``--cascade-thresh 0`` escalates everything and ``1`` nothing —
# entropy = 1 requires a uniform positive score mass, which forces
# max_score > 0, so the bound itself is unreachable.
HARDNESS_MAX = W_ENTROPY + W_DISAGREE + W_LOW_MAX


def hardness(stats):
    """Scalar hardness of one captured record from its score stats."""
    bands = stats.get("bands", {})
    loose = bands.get(f"{SCORE_BANDS[0]:.1f}", 0)
    strict = bands.get(f"{SCORE_BANDS[-1]:.1f}", 0)
    disagree = (loose - strict) / max(1, loose)
    entropy = float(stats.get("entropy", 0.0))
    low_max = 1.0 - float(stats.get("max_score", 0.0))
    score = W_ENTROPY * entropy + W_DISAGREE * disagree + W_LOW_MAX * low_max
    return score, {"entropy": entropy, "disagreement": disagree,
                   "low_max": low_max}


def hardness_from_records(records):
    """Host reference path: detection records → hardness scalar.

    Exactly what the capture→mine pipeline computes for a served image
    (``hardness(score_stats(records))``); the device gate must agree
    with this on identical detections.
    """
    score, _ = hardness(score_stats(records))
    return score


def build_device_hardness():
    """Build the jitted cascade-gate program: ``(dets, valid) → (B,)``.

    ``dets`` is the ``(B, cap, 6)`` ``[x1,y1,x2,y2,score,cls]`` tensor
    ``predict_serve_e2e`` leaves on device (padded rows zeroed) and
    ``valid`` its ``(B, cap)`` row mask.  Per image this mirrors
    :func:`~mx_rcnn_tpu.flywheel.capture.score_stats` +
    :func:`hardness` term by term:

    - entropy: score-mass entropy over valid rows, normalized by
      ``log(n)`` (total valid count), zero when ``n <= 1`` or the mass
      is empty;
    - disagreement: ``(loose - strict) / max(1, loose)`` survivor
      falloff between the loose and strict bands;
    - low max: ``1 - max_score``.

    Imports jax lazily (module import stays CPU/numpy-safe) and runs in
    float32 — the host reference is float64, so agreement is to float32
    tolerance, pinned by test.
    """
    import jax
    import jax.numpy as jnp

    loose_t = float(SCORE_BANDS[0])
    strict_t = float(SCORE_BANDS[-1])

    def fn(dets, valid):
        v = valid.astype(jnp.float32)                    # (B, cap)
        s = dets[..., 4].astype(jnp.float32) * v         # zeros off-mask
        n = v.sum(axis=-1)                               # (B,)
        total = s.sum(axis=-1)
        max_score = s.max(axis=-1)
        p = s / jnp.where(total > 0, total, 1.0)[..., None]
        plogp = jnp.where(p > 0, p * jnp.log(jnp.where(p > 0, p, 1.0)), 0.0)
        raw_ent = -plogp.sum(axis=-1) / jnp.log(jnp.maximum(n, 2.0))
        entropy = jnp.where((n > 1) & (total > 0), raw_ent, 0.0)
        loose = ((s >= loose_t) & (v > 0)).sum(axis=-1).astype(jnp.float32)
        strict = ((s >= strict_t) & (v > 0)).sum(axis=-1).astype(jnp.float32)
        disagree = (loose - strict) / jnp.maximum(1.0, loose)
        low_max = 1.0 - max_score
        return (W_ENTROPY * entropy + W_DISAGREE * disagree
                + W_LOW_MAX * low_max).astype(jnp.float32)

    return jax.jit(fn)
