"""Request capture: a sampled, bounded log of served images at the engine.

The capture sink hangs off :class:`mx_rcnn_tpu.serve.engine.ServeEngine` the
same way telemetry does: the engine holds :data:`NULL_CAPTURE` unless a
capture dir was configured, and the hot path pays exactly one attribute check
(``if self.capture.enabled:``) when capture is off.  The NULL sink *raises*
if recorded into, so tests can pin the zero-overhead contract directly.

What gets captured is PII-free by construction: the staged uint8 pixel
buffer the model actually saw, its sidecar extents, the detection records
the server returned, and per-image score statistics.  No client identity,
no headers, no raw request bytes.

Captured records accumulate in a bounded in-memory ring and spill to disk
as shard pairs under the capture dir::

    shard-<member>-<pid>-000000.npz    # uint8 pixel arrays, one key each
    shard-<member>-<pid>-000000.jsonl  # one JSON row per record

Both files are written via tmp + ``os.replace`` and the npz lands first, so
a visible ``.jsonl`` implies its pixels exist.  A byte budget rotates the
oldest shard pairs out.

Fleet capture (ISSUE 17): the shard name folds in a MEMBER id (``--capture-
member`` or the sanitized hostname) ahead of the pid — two fleet members
sharing one capture dir over a network filesystem can collide on pid alone
(separate pid namespaces), never on member+pid.  Each writer additionally
maintains an atomic per-member manifest::

    manifest-<member>-<pid>.json   # schema mxr_capture_manifest

listing every shard it has spilled plus its counters, so the distributed
miner reads exactly what each member claims to have delivered instead of
globbing a dir that other members are still mutating.
:func:`merge_manifests` folds them into one fleet view, tolerating absent
or late members (whoever has published is merged), torn manifest files
(skipped), and duplicate deliveries (same member+pid twice — highest
sequence wins, duplicates counted).

Fault injection (chaos tests): the env vars below name a shard index whose
spill is corrupted/truncated after the atomic rename, simulating torn disks
so the replay loader's bad-record substitution path can be pinned.
``MXR_FAULT_FLYWHEEL_DUP_MANIFEST`` names a member id (or ``*``) whose
manifest is delivered TWICE under different names — the at-least-once
delivery shape the merge step must dedup.
"""

import json
import os
import re
import socket
import threading
from dataclasses import dataclass
from typing import Optional

import numpy as np

from mx_rcnn_tpu import telemetry

# Fault-injection env vars (package code owns the names + parsing; the
# tests/faults.py composers only build env dicts from these).  The value is
# the 0-based index of the shard to damage after it has been spilled.
ENV_CORRUPT_SHARD = "MXR_FAULT_FLYWHEEL_CORRUPT_SHARD"
ENV_TRUNCATE_SPILL = "MXR_FAULT_FLYWHEEL_TRUNCATE_SPILL"
# value = member id (or "*" for any) whose per-member manifest is written
# twice under distinct names — duplicate delivery, not corruption
ENV_DUP_MANIFEST = "MXR_FAULT_FLYWHEEL_DUP_MANIFEST"

CAPTURE_MANIFEST_SCHEMA = "mxr_capture_manifest"

# Score thresholds used for the NMS-survivor disagreement signal: how many
# detections survive at adjacent operating points.  A big falloff between
# loose and strict thresholds marks a confused image.
SCORE_BANDS = (0.3, 0.5, 0.7)

# Detections stored per captured record (rows are score-sorted upstream).
MAX_DETS_PER_RECORD = 100


class NullCapture:
    """Capture disabled: one attribute check on the hot path, nothing else.

    ``record_batch`` raises so tests can pin that a disabled engine never
    reaches the sink (the telemetry NULL-sink contract, enforced harder).
    """

    enabled = False

    def record_batch(self, entries, generation):
        raise RuntimeError("capture is disabled; engine must not record")

    def metrics(self):
        return {}

    def flush(self):
        pass

    def close(self):
        pass


NULL_CAPTURE = NullCapture()


@dataclass(frozen=True)
class CaptureOptions:
    capture_dir: str
    sample_every: int = 1          # capture every Nth submitted request
    ring_size: int = 256           # max records pending spill in memory
    shard_records: int = 32        # records per spilled shard pair
    byte_budget: int = 256 << 20   # rotate oldest shards beyond this
    member: Optional[str] = None   # fleet member id (default: hostname)


def member_id(member: Optional[str] = None) -> str:
    """Filesystem-safe member id: the given member name or the local
    hostname, with anything outside ``[A-Za-z0-9_.]`` folded to ``_`` —
    shard and manifest names embed it, so it must never introduce a
    path separator or break the ``shard-*`` name grammar."""
    raw = member or socket.gethostname() or "host"
    return re.sub(r"[^A-Za-z0-9_.]", "_", raw) or "host"


def score_stats(records):
    """Per-image hardness signals from the served detection records.

    Returns a JSON-safe dict: detection count, max/mean score, normalized
    score entropy, and survivor counts at each band in :data:`SCORE_BANDS`.
    """
    scores = np.asarray([float(r["score"]) for r in records], np.float64)
    n = scores.size
    stats = {"count": int(n), "max_score": 0.0, "mean_score": 0.0,
             "entropy": 0.0,
             "bands": {f"{t:.1f}": 0 for t in SCORE_BANDS}}
    if n == 0:
        return stats
    stats["max_score"] = float(scores.max())
    stats["mean_score"] = float(scores.mean())
    if n > 1 and scores.sum() > 0:
        p = scores / scores.sum()
        p = p[p > 0]
        stats["entropy"] = float(-(p * np.log(p)).sum() / np.log(n))
    for t in SCORE_BANDS:
        stats["bands"][f"{t:.1f}"] = int((scores >= t).sum())
    return stats


class RequestCapture:
    """Bounded, sampled request log that spills atomic shard pairs.

    Thread safety: ``record_batch`` runs on the engine's batch worker
    thread; ``flush``/``metrics`` may be called from any thread.  A single
    lock guards the ring and counters; spills happen synchronously on the
    batch thread (capture-on is allowed to cost — only capture-OFF is
    pinned to zero work).
    """

    enabled = True

    def __init__(self, opts: CaptureOptions, env: Optional[dict] = None):
        if opts.sample_every < 1:
            raise ValueError("sample_every must be >= 1")
        self.opts = opts
        os.makedirs(opts.capture_dir, exist_ok=True)
        env = os.environ if env is None else env
        self._corrupt_shard = _env_index(env, ENV_CORRUPT_SHARD)
        self._truncate_spill = _env_index(env, ENV_TRUNCATE_SPILL)
        self.member = member_id(opts.member)
        self._dup_manifest = env.get(ENV_DUP_MANIFEST, "")
        self._manifest_path = os.path.join(
            opts.capture_dir,
            "manifest-%s-%d.json" % (self.member, os.getpid()))
        self._manifest_shards = []    # basenames of spilled shard pairs
        self._lock = threading.Lock()
        self._pending = []            # [(meta dict, uint8 pixels)]
        self._seen = 0                # submitted requests considered
        self._rid = 0                 # monotonic record id
        self._shard_idx = 0
        self.counters = {"captured": 0, "sampled_out": 0, "dropped": 0,
                         "spilled_bytes": 0, "shards": 0, "spill_errors": 0}

    # ------------------------------------------------------------- record
    def record_batch(self, entries, generation: int):
        """Record a served batch.

        ``entries``: iterable of ``(pixels, raw_hw, orig_hw, records)``
        or ``(pixels, raw_hw, orig_hw, records, trace_id)`` where
        ``pixels`` is the staged uint8 HWC buffer the model saw,
        ``raw_hw`` its valid extent, ``orig_hw`` the pre-staging image
        dims (detection boxes are in those original coordinates),
        ``records`` the detection records returned to the client, and
        ``trace_id`` (optional 5th element) the distributed-trace id the
        request served under — provenance that lets a mined hard example
        link back to the serving trace that produced it.

        An optional 6th element is a dict of EXTRA meta keys merged into
        the row strictly additively (a key shadowing a legacy field is an
        error) — the cascade router tags escalated frames with
        ``{"tags": ["cascade_escalated"]}`` this way, so the miner can
        see which captures the small model already flagged hard.  Rows
        without the element are byte-identical to pre-cascade captures.
        """
        spill = None
        with self._lock:
            for entry in entries:
                pixels, raw_hw, orig_hw, records = entry[:4]
                trace_id = entry[4] if len(entry) > 4 else None
                extra = entry[5] if len(entry) > 5 else None
                self._seen += 1
                if (self._seen - 1) % self.opts.sample_every != 0:
                    self.counters["sampled_out"] += 1
                    continue
                if len(self._pending) >= self.opts.ring_size:
                    self.counters["dropped"] += 1
                    continue
                # a failed request (deadline, forward error) has no
                # detections — capture it with an empty record list
                records = records if records is not None else []
                rid = self._rid
                self._rid += 1
                meta = {
                    "rid": rid,
                    "key": "r%08d" % rid,
                    "bucket": [int(pixels.shape[0]), int(pixels.shape[1])],
                    "raw_hw": [int(raw_hw[0]), int(raw_hw[1])],
                    "orig_hw": [int(orig_hw[0]), int(orig_hw[1])],
                    "generation": int(generation),
                    "stats": score_stats(records),
                    "detections": [
                        {"cls": int(r["cls"]), "score": float(r["score"]),
                         "bbox": [float(v) for v in r["bbox"]]}
                        for r in records[:MAX_DETS_PER_RECORD]],
                }
                if trace_id is not None:
                    meta["trace_id"] = str(trace_id)
                for k in (extra or {}):
                    if k in meta:
                        raise ValueError(f"extra capture meta key {k!r} "
                                         f"shadows a legacy field")
                    meta[k] = extra[k]
                self._pending.append((meta, np.ascontiguousarray(
                    pixels, dtype=np.uint8)))
                self.counters["captured"] += 1
            if len(self._pending) >= self.opts.shard_records:
                spill = self._take_pending()
        if spill:
            self._spill(spill)

    def _take_pending(self):
        batch, self._pending = self._pending, []
        return batch

    # -------------------------------------------------------------- spill
    def _spill(self, batch):
        """Write one shard pair atomically; npz before jsonl."""
        with self._lock:
            idx = self._shard_idx
            self._shard_idx += 1
        # member + pid in the name: replica children sharing one capture
        # dir must never clobber each other's shards, and two FLEET
        # members sharing the dir over a network filesystem can collide
        # on pid alone (separate pid namespaces) — never on member+pid
        base = os.path.join(
            self.opts.capture_dir,
            "shard-%s-%d-%06d" % (self.member, os.getpid(), idx))
        tel = telemetry.get()
        try:
            npz_tmp = base + ".npz.tmp"
            with open(npz_tmp, "wb") as fh:
                np.savez(fh, **{m["key"]: px for m, px in batch})
            os.replace(npz_tmp, base + ".npz")
            rows = []
            for meta, _ in batch:
                row = dict(meta)
                row["npz"] = os.path.basename(base + ".npz")
                rows.append(json.dumps(row, sort_keys=True))
            jsonl_tmp = base + ".jsonl.tmp"
            with open(jsonl_tmp, "w") as fh:
                fh.write("\n".join(rows) + "\n")
            os.replace(jsonl_tmp, base + ".jsonl")
        except OSError:
            with self._lock:
                self.counters["spill_errors"] += 1
            tel.counter("flywheel/spill_error")
            return
        self._inject_fault(idx, base)
        nbytes = os.path.getsize(base + ".npz") + os.path.getsize(
            base + ".jsonl")
        with self._lock:
            self.counters["spilled_bytes"] += nbytes
            self.counters["shards"] += 1
            self._manifest_shards.append(os.path.basename(base))
        tel.counter("flywheel/captured", len(batch))
        tel.counter("flywheel/spilled_bytes", nbytes)
        tel.counter("flywheel/shards")
        self._write_member_manifest()
        self._rotate(keep=base)

    def _write_member_manifest(self):
        """Atomically publish this writer's manifest after every spill —
        the fleet miner's view of what this member has delivered.  The
        ``seq`` field lets :func:`merge_manifests` pick the newest of a
        duplicated delivery; a write failure is counted, never raised
        (capture must outlive a flaky manifest disk)."""
        with self._lock:
            doc = {
                "schema": CAPTURE_MANIFEST_SCHEMA,
                "version": 1,
                "member": self.member,
                "pid": os.getpid(),
                "seq": len(self._manifest_shards),
                "shards": list(self._manifest_shards),
                "counters": dict(self.counters),
                "rid_hi": self._rid,
            }
        payload = json.dumps(doc, sort_keys=True, indent=1)
        try:
            tmp = self._manifest_path + ".tmp"
            with open(tmp, "w") as fh:
                fh.write(payload)
            os.replace(tmp, self._manifest_path)
            if self._dup_manifest in (self.member, "*") \
                    and self._dup_manifest:
                # injected at-least-once delivery: the same manifest
                # content lands AGAIN under a second name; the merge
                # step must fold it to one member, not double-count
                dup = self._manifest_path[:-len(".json")] + ".dup.json"
                dup_tmp = dup + ".tmp"
                with open(dup_tmp, "w") as fh:
                    fh.write(payload)
                os.replace(dup_tmp, dup)
        except OSError:
            with self._lock:
                self.counters["spill_errors"] += 1
            telemetry.get().counter("flywheel/spill_error")

    def _inject_fault(self, idx, base):
        if self._corrupt_shard == idx:
            with open(base + ".npz", "wb") as fh:
                fh.write(b"not an npz: injected corruption\n")
        if self._truncate_spill == idx:
            size = os.path.getsize(base + ".npz")
            with open(base + ".npz", "rb+") as fh:
                fh.truncate(max(1, size // 2))

    def _rotate(self, keep):
        """Delete oldest shard pairs while the dir exceeds the budget."""
        pairs = list_shards(self.opts.capture_dir)
        total = sum(p["bytes"] for p in pairs)
        for p in pairs:
            if total <= self.opts.byte_budget:
                break
            if p["base"] == keep:
                continue
            for path in (p["base"] + ".jsonl", p["base"] + ".npz"):
                try:
                    os.unlink(path)
                except OSError:
                    pass
            total -= p["bytes"]

    # ------------------------------------------------------------- public
    def flush(self):
        """Spill whatever is pending (partial shard included)."""
        with self._lock:
            batch = self._take_pending()
        if batch:
            self._spill(batch)

    def close(self):
        self.flush()

    def metrics(self):
        with self._lock:
            out = dict(self.counters)
        out["sample_every"] = self.opts.sample_every
        return out


def _env_index(env, name):
    raw = env.get(name, "")
    if not raw:
        return None
    try:
        return int(raw)
    except ValueError:
        raise ValueError(f"{name} must be a shard index, got {raw!r}")


def list_shards(capture_dir):
    """Complete shard pairs, oldest first: [{base, npz, jsonl, bytes}].

    Ordered by jsonl mtime (then name): shard names carry the writer's
    pid, so name order alone is not spill order across replicas.
    """
    out = []
    try:
        names = os.listdir(capture_dir)
    except OSError:
        return out
    for name in names:
        if not (name.startswith("shard-") and name.endswith(".jsonl")):
            continue
        base = os.path.join(capture_dir, name[:-len(".jsonl")])
        if not os.path.exists(base + ".npz"):
            continue
        try:
            st = os.stat(base + ".jsonl")
            nbytes = os.path.getsize(base + ".npz") + st.st_size
        except OSError:
            continue
        out.append({"base": base, "npz": base + ".npz",
                    "jsonl": base + ".jsonl", "bytes": nbytes,
                    "mtime": st.st_mtime})
    out.sort(key=lambda p: (p["mtime"], p["base"]))
    return out


def list_member_manifests(capture_dir):
    """Every parseable ``manifest-*.json`` under ``capture_dir`` —
    duplicate deliveries included (dedup is :func:`merge_manifests`'
    job).  Torn or unreadable files are skipped: a member whose manifest
    write was interrupted simply has not published yet."""
    docs = []
    try:
        names = sorted(os.listdir(capture_dir))
    except OSError:
        return docs
    for name in names:
        if not (name.startswith("manifest-") and name.endswith(".json")):
            continue
        try:
            with open(os.path.join(capture_dir, name)) as fh:
                doc = json.load(fh)
        except (OSError, ValueError):
            continue
        if not isinstance(doc, dict) \
                or doc.get("schema") != CAPTURE_MANIFEST_SCHEMA:
            continue
        docs.append(doc)
    return docs


def merge_manifests(capture_dir):
    """Fold per-member capture manifests into one fleet view.

    Tolerant by design: absent or late members are simply not in the
    merge yet (the next mine picks them up), torn manifests are skipped,
    and duplicate deliveries of one member's manifest (at-least-once
    delivery, or the injected ``MXR_FAULT_FLYWHEEL_DUP_MANIFEST``) fold
    to a single entry — highest ``seq`` wins, duplicates counted.

    Returns ``{"members": {"<member>-<pid>": doc, ...},
    "duplicates_dropped": n}``.
    """
    merged, dropped = {}, 0
    for doc in list_member_manifests(capture_dir):
        key = "%s-%d" % (doc.get("member", "unknown"),
                         int(doc.get("pid", 0) or 0))
        prev = merged.get(key)
        if prev is not None:
            dropped += 1
            if int(doc.get("seq", 0)) <= int(prev.get("seq", 0)):
                continue
        merged[key] = doc
    if dropped:
        telemetry.get().counter("flywheel/manifest_dup_dropped", dropped)
    return {"members": merged, "duplicates_dropped": dropped}
