"""Flywheel round orchestration: capture dir -> mined manifest -> train.

A round mines whatever the serving fleet has spilled so far, then (when a
train command is configured) launches the replay-mixed training as a
subprocess with ``--replay-manifest`` appended.  The loop does NOT manage
serving: replicas already follow checkpoints via ``--watch-checkpoints``
(PR-8 canary/rollback), so a training run that saves a checkpoint closes
the loop on its own.  Round/generation progress is published as
``flywheel/*`` telemetry.
"""

import subprocess
from typing import Optional, Sequence

from mx_rcnn_tpu import telemetry
from mx_rcnn_tpu.logger import logger

from .miner import mine_shards, write_manifest


def run_train_cmd(train_cmd, manifest, kill_after_s=None):
    """Launch the replay-train subprocess and return its rc.

    ``kill_after_s`` is the kill-trainer-mid-epoch chaos injection: the
    child is SIGKILLed after that many seconds unless it finished first
    — the loop code owns the kill so the fault lands deterministically
    in the chosen round (a negative rc, exactly what a preempted or
    OOM-killed trainer reports)."""
    cmd = list(train_cmd) + ["--replay-manifest", manifest]
    proc = subprocess.Popen(cmd)
    if kill_after_s is not None:
        try:
            return proc.wait(timeout=kill_after_s)
        except subprocess.TimeoutExpired:
            logger.warning("FAULT flywheel: SIGKILL trainer pid %d after "
                           "%.2fs mid-epoch", proc.pid, kill_after_s)
            proc.kill()
            return proc.wait()
    return proc.wait()


class FlywheelLoop:
    def __init__(self, capture_dir: str, top_k: int = 64,
                 min_label_score: float = 0.3,
                 out_dir: Optional[str] = None,
                 train_cmd: Optional[Sequence[str]] = None):
        self.capture_dir = capture_dir
        self.top_k = top_k
        self.min_label_score = min_label_score
        self.out_dir = out_dir
        self.train_cmd = list(train_cmd) if train_cmd else None

    def run_round(self, round_idx: int = 0) -> dict:
        """Mine once, optionally train once; returns the round summary."""
        tel = telemetry.get()
        entries, scanned, skipped = mine_shards(
            self.capture_dir, top_k=self.top_k,
            min_label_score=self.min_label_score)
        result = {"round": round_idx, "mined": len(entries),
                  "scanned": scanned, "skipped": skipped,
                  "manifest": None, "train_rc": None}
        if not entries:
            logger.info("flywheel round %d: nothing mined (%d scanned)",
                        round_idx, scanned)
            return result
        manifest = write_manifest(
            self.capture_dir, entries, scanned, self.top_k,
            out_dir=self.out_dir, min_label_score=self.min_label_score)
        result["manifest"] = manifest
        tel.gauge("flywheel/round", round_idx)
        logger.info("flywheel round %d: mined %d/%d -> %s",
                    round_idx, len(entries), scanned, manifest)
        if self.train_cmd:
            rc = run_train_cmd(self.train_cmd, manifest)
            result["train_rc"] = rc
            if rc != 0:
                tel.counter("flywheel/train_failed")
                logger.error("flywheel round %d: train rc=%d",
                             round_idx, rc)
        return result

    def run(self, rounds: int = 1) -> list:
        return [self.run_round(i) for i in range(rounds)]
