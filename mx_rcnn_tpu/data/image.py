"""Image IO + geometric transform (reference ``rcnn/io/image.py``).

Contracts kept from the reference:

* ``get_image``: load BGR→RGB, resize so the short side hits SCALE[0] with
  the long side capped at SCALE[1] (``resize`` keeps aspect; the scale
  factor is min(target/short, max/long)).
* pixel-mean subtraction (+ std division; reference PIXEL_STDS=1).
* stride padding — generalized to *bucket padding*: every image lands in a
  static (H, W) bucket shape so XLA compiles one program per bucket
  (replaces ``tensor_vstack`` ragged pad + MutableModule rebinding).

Divergence (documented): the reference feeds CHW float32; we feed NHWC
(TPU-native conv layout).  Flipping is done on the roidb records
(imdb.append_flipped_images) exactly like the reference — the image flip
itself happens here at load time via the ``flipped`` flag.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import cv2
import numpy as np


def bucket_shape(scale: Tuple[int, int], stride: int,
                 landscape: bool = True) -> Tuple[int, int]:
    """Static padded (H, W) for a (short, long) scale pair.

    After the reference resize rule the short side is ≤ scale[0] and the
    long side ≤ scale[1]; rounding both up to the feature stride gives one
    static shape per orientation.  Orientation split = the reference's
    aspect-ratio grouping (``rcnn/core/loader.py`` groups horizontal /
    vertical images per batch), which here also picks the compiled program.
    """
    stride = max(int(stride), 1)
    short, long_ = scale
    s = int(np.ceil(short / stride) * stride)
    l = int(np.ceil(long_ / stride) * stride)
    return (s, l) if landscape else (l, s)


def compute_scale(h: int, w: int, scale: Tuple[int, int]) -> float:
    """Reference resize rule: short side → scale[0], long side ≤ scale[1]."""
    short, long_ = min(h, w), max(h, w)
    s = float(scale[0]) / short
    if s * long_ > scale[1]:
        s = float(scale[1]) / long_
    return s


def get_image(path: str, flipped: bool = False) -> np.ndarray:
    """Load an image file → RGB uint8 HWC (reference loads BGR via cv2 and
    keeps BGR; we standardize on RGB and set PIXEL_MEANS accordingly)."""
    im = cv2.imread(path, cv2.IMREAD_COLOR)
    if im is None:
        raise FileNotFoundError(path)
    im = cv2.cvtColor(im, cv2.COLOR_BGR2RGB)
    if flipped:
        im = im[:, ::-1, :]
    return im


def transform_image(im: np.ndarray, pixel_means: Sequence[float],
                    pixel_stds: Sequence[float] = (1.0, 1.0, 1.0)) -> np.ndarray:
    """float32 + normalize; stays HWC (reference ``transform`` also moves to
    CHW — not here, TPU convs are NHWC)."""
    out = im.astype(np.float32)
    out -= np.asarray(pixel_means, np.float32)
    out /= np.asarray(pixel_stds, np.float32)
    return out


def resize_to_bucket(im: np.ndarray, scale: Tuple[int, int], stride: int):
    """Resize by the reference rule and zero-pad into the orientation's
    bucket shape.

    Returns (padded_image (Hb, Wb, 3), im_scale, (eff_h, eff_w)) where
    eff_h/eff_w are the valid (non-pad) extents — the im_info contract
    (reference im_info = [round(h·s), round(w·s), s])."""
    h, w = im.shape[:2]
    s = compute_scale(h, w, scale)
    im_r = cv2.resize(im, None, None, fx=s, fy=s, interpolation=cv2.INTER_LINEAR)
    eh, ew = im_r.shape[:2]
    hb, wb = bucket_shape(scale, stride, landscape=(w >= h))
    if eh > hb or ew > wb:  # guard: rounding never exceeds the bucket
        im_r = im_r[:hb, :wb]
        eh, ew = im_r.shape[:2]
    out = np.zeros((hb, wb) + im.shape[2:], np.float32)
    out[:eh, :ew] = im_r
    return out, s, (eh, ew)


def stage_raw_to_bucket(im: np.ndarray, scale: Tuple[int, int], stride: int):
    """Stage RAW uint8 pixels into the orientation's bucket for device-side
    preprocessing (``data/device_prep.py``).

    The device program resamples from the raw extent (h, w) to the effective
    extent (eh, ew) with the same center-aligned bilinear rule cv2 uses, so
    the host only has to park the untouched bytes in a static buffer — no
    float conversion, no resize, no flip (the device mirrors the source
    coordinate instead).

    Returns ``(staged (Hb, Wb, 3) uint8, raw_hw (2,) int32, ratio ()
    float32, im_info (3,) float32)`` where ``raw_hw`` is the valid raw
    extent inside the staging buffer, ``ratio`` is the dst→src coordinate
    factor the device must use on BOTH axes, and ``im_info = [eh, ew, s]``
    matches the host-path contract bit-for-bit (same ``compute_scale``,
    same rounding).

    ``ratio`` is ``1/s`` — NOT ``raw/effective`` per axis: cv2's
    ``resize(fx=s)`` maps ``src = (dst + 0.5)/s - 0.5`` with the exact
    given factor even though the output dims round to integers, so a
    per-axis ``h/eh`` ratio diverges whenever ``h*s`` is fractional
    (measured up to ~1.3 normalized units on a 120×200 raw).

    When the raw image is LARGER than the bucket (strong downscale), the
    raw bytes cannot be staged whole; we pre-shrink on host with the same
    cv2 call the host path uses so the device resample degenerates to an
    identity gather (ratio = 1).  That uint8-domain shrink is the one
    documented fidelity divergence vs the host float path — oversized
    raws only, bounded by uint8 rounding.
    """
    h, w = im.shape[:2]
    s = compute_scale(h, w, scale)
    hb, wb = bucket_shape(scale, stride, landscape=(w >= h))
    if h > hb or w > wb:
        im = cv2.resize(im, None, None, fx=s, fy=s,
                        interpolation=cv2.INTER_LINEAR)[:hb, :wb]
        h, w = im.shape[:2]
        eh, ew, ratio = h, w, 1.0
    else:
        # cv2.resize(fx=s) computes dsize = cvRound(dim * s) (round-half-
        # even, same as python round) — mirror it so im_info matches the
        # host path bit-for-bit.
        eh, ew = min(int(round(h * s)), hb), min(int(round(w * s)), wb)
        ratio = 1.0 / s
    out = np.zeros((hb, wb) + im.shape[2:], np.uint8)
    out[:h, :w] = im
    return (out, np.asarray([h, w], np.int32), np.float32(ratio),
            np.asarray([eh, ew, s], np.float32))


def space_to_depth2(im: np.ndarray) -> np.ndarray:
    """2×2 space-to-depth: (H, W, C) → (H/2, W/2, 4C), channel order
    (di, dj, c) — exactly the regroup ``models.backbones.StemConvS2D``
    performs on device for 3-channel input, hoisted to the host where the
    prefetch thread hides it (the device-side transpose of the raw image
    is lane-hostile and costs ~1 ms/step)."""
    h, w, c = im.shape
    assert h % 2 == 0 and w % 2 == 0, (h, w)
    return (im.reshape(h // 2, 2, w // 2, 2, c)
            .transpose(0, 2, 1, 3, 4)
            .reshape(h // 2, w // 2, 4 * c))
