"""COCO dataset (reference ``rcnn/dataset/coco.py``), without pycocotools.

The reference loads annotations through the vendored
``rcnn/pycocotools/coco.py``; with no pycocotools in this environment
(SURVEY §7 preamble) the json is indexed directly — same roidb out the
other end.  Evaluation goes through the in-repo ``eval/coco_eval.py``
(COCOeval math re-derived; RLE mask ops in ``eval/mask_rle.py`` with a C++
fast path).
"""

from __future__ import annotations

import json
import os
from typing import Dict, List

import numpy as np

from mx_rcnn_tpu.data.imdb import IMDB
from mx_rcnn_tpu.logger import logger


class COCODataset(IMDB):
    """``image_set``: train2017 / val2017 / minival2014-style names; images
    under ``{dataset_path}/{image_set}``, annotations under
    ``{dataset_path}/annotations/instances_{image_set}.json``."""

    def __init__(self, image_set: str, root_path: str, dataset_path: str):
        super().__init__("coco", image_set, root_path, dataset_path)
        self.ann_file = os.path.join(dataset_path, "annotations",
                                     f"instances_{image_set}.json")
        with open(self.ann_file) as f:
            ann = json.load(f)

        # categories: COCO ids are sparse; map to contiguous [1..K]
        cats = sorted(ann["categories"], key=lambda c: c["id"])
        self.classes = ["__background__"] + [c["name"] for c in cats]
        self._cat_to_cls = {c["id"]: i + 1 for i, c in enumerate(cats)}
        self._cls_to_cat = {i + 1: c["id"] for i, c in enumerate(cats)}

        self._images: List[Dict] = sorted(ann["images"], key=lambda r: r["id"])
        self._img_index = {im["id"]: i for i, im in enumerate(self._images)}
        self.num_images = len(self._images)

        self._anns_by_image: Dict[int, list] = {im["id"]: [] for im in self._images}
        for a in ann["annotations"]:
            if a["image_id"] in self._anns_by_image:
                self._anns_by_image[a["image_id"]].append(a)
        logger.info("%s: %d images, %d classes", self.name, self.num_images,
                    self.num_classes)

    def image_path(self, i: int) -> str:
        return os.path.join(self.data_path, self.image_set,
                            self._images[i]["file_name"])

    @property
    def image_ids(self) -> List[int]:
        return [im["id"] for im in self._images]

    def gt_roidb(self) -> list:
        return self.load_cached("gt_roidb", self._build_gt_roidb)

    def _build_gt_roidb(self) -> list:
        roidb = []
        for i, im in enumerate(self._images):
            h, w = im["height"], im["width"]
            objs = []
            for a in self._anns_by_image[im["id"]]:
                if a.get("iscrowd", 0):
                    continue  # reference skips crowd boxes for training
                x, y, bw, bh = a["bbox"]
                # xywh → x1y1x2y2, clipped (reference coco.py sanitization)
                x1 = max(0.0, x)
                y1 = max(0.0, y)
                x2 = min(w - 1.0, x1 + max(0.0, bw - 1.0))
                y2 = min(h - 1.0, y1 + max(0.0, bh - 1.0))
                if a.get("area", 0) > 0 and x2 >= x1 and y2 >= y1:
                    objs.append((x1, y1, x2, y2, self._cat_to_cls[a["category_id"]],
                                 a.get("segmentation")))
            g = len(objs)
            boxes = np.zeros((g, 4), np.float32)
            gt_classes = np.zeros((g,), np.int32)
            overlaps = np.zeros((g, self.num_classes), np.float32)
            segs = []
            for j, (x1, y1, x2, y2, cls, seg) in enumerate(objs):
                boxes[j] = (x1, y1, x2, y2)
                gt_classes[j] = cls
                overlaps[j, cls] = 1.0
                segs.append(seg)
            roidb.append({
                "image": self.image_path(i), "height": h, "width": w,
                "boxes": boxes, "gt_classes": gt_classes,
                "gt_overlaps": overlaps,
                "max_classes": overlaps.argmax(axis=1),
                "max_overlaps": overlaps.max(axis=1) if g else np.zeros((0,)),
                "segmentation": segs,
                "flipped": False,
            })
        return roidb

    # -- evaluation ----------------------------------------------------------
    def detections_to_coco(self, detections) -> list:
        """all_boxes layout → COCO results-json records (reference
        ``coco.py``'s results writeout), scores kept raw."""
        results = []
        for k in range(1, self.num_classes):
            cat_id = self._cls_to_cat[k]
            per_img = detections[k]
            for i, dets in enumerate(per_img):
                if dets is None or len(dets) == 0:
                    continue
                img_id = self._images[i]["id"]
                for x1, y1, x2, y2, sc in np.asarray(dets, np.float64):
                    results.append({
                        "image_id": int(img_id), "category_id": int(cat_id),
                        "bbox": [x1, y1, x2 - x1 + 1, y2 - y1 + 1],
                        "score": float(sc),
                    })
        return results

    def evaluate_detections(self, detections, iou_type: str = "bbox") -> dict:
        from mx_rcnn_tpu.eval.coco_eval import COCOEval

        results = self.detections_to_coco(detections)
        ev = COCOEval(self.ann_file, results, iou_type=iou_type)
        stats = ev.evaluate()
        logger.info("COCO %s AP: %.4f (AP50 %.4f AP75 %.4f)", iou_type,
                    stats["AP"], stats["AP50"], stats["AP75"])
        return stats

    def segmentations_to_coco(self, detections, masks) -> list:
        """(all_boxes, all_masks) → COCO segm results records; masks are
        full-image RLE dicts aligned row-for-row with all_boxes."""
        from mx_rcnn_tpu.eval.mask_rle import area

        results = []
        for k in range(1, self.num_classes):
            cat_id = self._cls_to_cat[k]
            for i, dets in enumerate(detections[k]):
                if dets is None or len(dets) == 0:
                    continue
                img_id = self._images[i]["id"]
                row_masks = masks[k][i] or []
                for di, d in enumerate(np.asarray(dets, np.float64)):
                    if di >= len(row_masks) or row_masks[di] is None:
                        continue
                    rle = row_masks[di]
                    results.append({
                        "image_id": int(img_id), "category_id": int(cat_id),
                        "segmentation": rle, "area": float(area(rle)),
                        "score": float(d[4]),
                    })
        return results

    def evaluate_sds(self, detections, masks) -> dict:
        """Joint box + mask scoring (Mask R-CNN eval; name from the
        SDS/'simultaneous detection and segmentation' lineage).  Returns
        {'bbox': {...}, 'segm': {...}}."""
        from mx_rcnn_tpu.eval.coco_eval import COCOEval

        out = {"bbox": self.evaluate_detections(detections)}
        segm_results = self.segmentations_to_coco(detections, masks)
        ev = COCOEval(self.ann_file, segm_results, iou_type="segm")
        stats = ev.evaluate()
        logger.info("COCO segm AP: %.4f (AP50 %.4f AP75 %.4f)",
                    stats["AP"], stats["AP50"], stats["AP75"])
        out["segm"] = stats
        return out
