"""PASCAL VOC dataset (reference ``rcnn/dataset/pascal_voc.py`` +
``pascal_voc_eval.py``).

Contracts kept: VOCdevkit directory layout, XML annotation parsing with
difficult-object filtering, pickle-cached gt_roidb, detection writeout in
the official per-class file format, and ``voc_eval`` scoring (both the
VOC07 11-point AP and the later area-under-PR metric).
"""

from __future__ import annotations

import os
import xml.etree.ElementTree as ET
from typing import Dict, List, Optional

import numpy as np

from mx_rcnn_tpu.data.imdb import IMDB
from mx_rcnn_tpu.logger import logger

VOC_CLASSES = (
    "__background__",
    "aeroplane", "bicycle", "bird", "boat", "bottle", "bus", "car", "cat",
    "chair", "cow", "diningtable", "dog", "horse", "motorbike", "person",
    "pottedplant", "sheep", "sofa", "train", "tvmonitor",
)


def parse_voc_rec(filename: str) -> List[Dict]:
    """Parse one VOC XML annotation into object dicts (reference
    ``pascal_voc_eval.parse_rec``)."""
    tree = ET.parse(filename)
    objects = []
    for obj in tree.findall("object"):
        bbox = obj.find("bndbox")
        objects.append({
            "name": obj.find("name").text,
            "difficult": int(obj.find("difficult").text)
            if obj.find("difficult") is not None else 0,
            # VOC pixels are 1-indexed → 0-indexed here, like the reference
            "bbox": [int(float(bbox.find("xmin").text)) - 1,
                     int(float(bbox.find("ymin").text)) - 1,
                     int(float(bbox.find("xmax").text)) - 1,
                     int(float(bbox.find("ymax").text)) - 1],
        })
    return objects


class PascalVOC(IMDB):
    """``image_set`` is ``<year>_<set>`` or a ``+``-join of several
    (``2007_trainval+2012_trainval``, reference train_end2end ``--dataset``)."""

    def __init__(self, image_set: str, root_path: str, dataset_path: str):
        super().__init__("voc", image_set, root_path, dataset_path)
        self.classes = list(VOC_CLASSES)
        self._sets = image_set.split("+")
        self._index: List[tuple] = []  # (year, image_id)
        for s in self._sets:
            year, split = s.split("_")
            for idx in self._load_image_set_index(year, split):
                self._index.append((year, idx))
        self.num_images = len(self._index)
        logger.info("%s: %d images", self.name, self.num_images)

    # -- paths ---------------------------------------------------------------
    def _devkit(self, year: str) -> str:
        return os.path.join(self.data_path, f"VOC{year}")

    def _load_image_set_index(self, year: str, split: str) -> List[str]:
        path = os.path.join(self._devkit(year), "ImageSets", "Main", split + ".txt")
        with open(path) as f:
            return [line.strip().split()[0] for line in f if line.strip()]

    def image_path(self, i: int) -> str:
        year, idx = self._index[i]
        return os.path.join(self._devkit(year), "JPEGImages", idx + ".jpg")

    def annotation_path(self, i: int) -> str:
        year, idx = self._index[i]
        return os.path.join(self._devkit(year), "Annotations", idx + ".xml")

    # -- roidb ---------------------------------------------------------------
    def gt_roidb(self) -> list:
        return self.load_cached("gt_roidb", self._build_gt_roidb)

    def _build_gt_roidb(self) -> list:
        name_to_cls = {n: i for i, n in enumerate(self.classes)}
        roidb = []
        for i in range(self.num_images):
            objs = parse_voc_rec(self.annotation_path(i))
            # reference keeps non-difficult objects for training
            objs = [o for o in objs if not o["difficult"]]
            g = len(objs)
            boxes = np.zeros((g, 4), np.float32)
            gt_classes = np.zeros((g,), np.int32)
            overlaps = np.zeros((g, self.num_classes), np.float32)
            for j, o in enumerate(objs):
                boxes[j] = o["bbox"]
                cls = name_to_cls[o["name"]]
                gt_classes[j] = cls
                overlaps[j, cls] = 1.0
            size = _image_size(self.image_path(i))
            roidb.append({
                "image": self.image_path(i),
                "height": size[0], "width": size[1],
                "boxes": boxes, "gt_classes": gt_classes,
                "gt_overlaps": overlaps,
                "max_classes": overlaps.argmax(axis=1),
                "max_overlaps": overlaps.max(axis=1) if g else np.zeros((0,)),
                "flipped": False,
            })
        return roidb

    # -- selective search (legacy Fast-RCNN proposal source) -----------------
    def selective_search_roidb(self, roidb: Optional[list] = None) -> list:
        """Attach precomputed selective-search proposals (reference
        ``selective_search_roidb``): loads the rbg-released
        ``selective_search_data/voc_<year>_<set>.mat`` files (one per
        ``+``-joined set, looked up under ``root_path``), whose per-image
        cells are (K, 4) boxes in MATLAB (y1, x1, y2, x2) 1-based order —
        reordered to 0-based (x1, y1, x2, y2) exactly like the reference's
        ``boxes[:, (1, 0, 3, 2)] - 1``.

        Divergence from the reference's offline pipeline, by design: the
        reference bakes SS boxes into a merged roidb with precomputed
        overlaps for host-side sampling; here they ride the ``proposals``
        key that ``ROIIter``/``rcnn_train`` consume, with IoU + sampling
        in-graph (the same path RPN-cached proposals use).  Attach BEFORE
        ``append_flipped_images`` — flipping mirrors proposals too.
        """
        roidb = roidb if roidb is not None else self.gt_roidb()
        box_list = self.load_cached("selective_search", self._load_ss_boxes)
        if len(box_list) != len(roidb):
            raise ValueError(
                f"{len(box_list)} selective-search entries for "
                f"{len(roidb)} images")
        n = 0  # (truncation vs TRAIN.RPN_POST_NMS_TOP_N is ROIIter's to
        # diagnose — it knows the actual cap and warns on construction)
        for rec, boxes in zip(roidb, box_list):
            rec["proposals"] = self.sanitize_proposals(
                boxes, rec["width"], rec["height"])
            n += len(boxes)
        logger.info("%s: attached %d selective-search proposals", self.name, n)
        return roidb

    def _load_ss_boxes(self) -> list:
        import scipy.io as sio

        box_list: list = []
        for s in self._sets:
            year, split = s.split("_")
            path = os.path.join(self.root_path, "selective_search_data",
                                f"voc_{year}_{split}.mat")
            raw = sio.loadmat(path)["boxes"].ravel()
            for i in range(raw.shape[0]):
                if raw[i].size == 0:  # empty MATLAB cell → no proposals
                    box_list.append(np.zeros((0, 4), np.float32))
                    continue
                boxes = raw[i][:, (1, 0, 3, 2)] - 1  # y1x1y2x2 1-based → x1y1x2y2
                box_list.append(boxes.astype(np.float32))
        if len(box_list) != self.num_images:
            # validate BEFORE load_cached pickles the result: a stale bad
            # cache would otherwise survive fixed .mat files
            raise ValueError(
                f"{len(box_list)} selective-search entries for "
                f"{self.num_images} images — wrong/partial "
                "selective_search_data set?")
        return box_list

    # -- evaluation ----------------------------------------------------------
    def write_results(self, detections, out_dir: str) -> None:
        """Official per-class result files (reference ``write_pascal_results``:
        ``comp4_det_<set>_<cls>.txt`` rows ``id score x1 y1 x2 y2``,
        1-indexed pixels)."""
        os.makedirs(out_dir, exist_ok=True)
        for k, cls in enumerate(self.classes):
            if cls == "__background__":
                continue
            path = os.path.join(out_dir,
                                f"comp4_det_{self.image_set}_{cls}.txt")
            with open(path, "w") as f:
                for i, dets in enumerate(detections[k]):
                    if dets is None or len(dets) == 0:
                        continue
                    _, idx = self._index[i]
                    for d in dets:
                        f.write(f"{idx} {d[4]:.3f} {d[0] + 1:.1f} "
                                f"{d[1] + 1:.1f} {d[2] + 1:.1f} {d[3] + 1:.1f}\n")
        logger.info("wrote VOC result files to %s", out_dir)

    def evaluate_detections(self, detections, use_07_metric: bool = True,
                            out_dir: Optional[str] = None) -> dict:
        """detections: list over classes (bg included, index 0 unused) of
        per-image (N, 5) [x1,y1,x2,y2,score] arrays — the reference
        ``all_boxes`` layout from pred_eval.  Returns {class: AP, 'mAP': m}."""
        from mx_rcnn_tpu.eval.voc_eval import voc_eval

        if out_dir:
            self.write_results(detections, out_dir)

        # gt in voc_eval's expected form, one recs dict per image index
        recs = {}
        for i in range(self.num_images):
            recs[i] = parse_voc_rec(self.annotation_path(i))

        aps = {}
        for k, cls in enumerate(self.classes):
            if cls == "__background__":
                continue
            ap = voc_eval(detections[k], recs, cls, ovthresh=0.5,
                          use_07_metric=use_07_metric)
            aps[cls] = ap
            logger.info("AP for %s = %.4f", cls, ap)
        aps["mAP"] = float(np.mean([v for v in aps.values()]))
        logger.info("Mean AP = %.4f", aps["mAP"])
        return aps


def _image_size(path: str):
    """(height, width) without decoding the full image."""
    from PIL import Image

    with Image.open(path) as im:
        w, h = im.size
    return h, w
