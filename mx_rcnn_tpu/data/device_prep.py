"""Device-side image preprocessing: the per-sample resize/flip/normalize/
pad hot path as ONE jitted program per (batch, bucket, dtype).

Host contract (``data/image.py::stage_raw_to_bucket`` via ``data/loader.py``
with ``cfg.tpu.DEVICE_PREP``): the loader ships raw uint8 pixels parked in
the output bucket shape plus three sidecar keys —

* ``images``     (B, Hb, Wb, 3) uint8 — raw bytes, valid extent = raw_hw
* ``raw_hw``     (B, 2) int32   — raw (h, w) inside the staging buffer
* ``prep_ratio`` (B,) float32   — exact dst→src factor (1/s; 1 if staged
  pre-shrunk)
* ``flip``       (B,) bool      — mirror the SOURCE coordinate on device
* ``im_info``    (B, 3) float32 — [eh, ew, s], identical to the host path

The program reproduces cv2's ``resize(fx=s)`` INTER_LINEAR semantics
exactly: per output pixel the source coordinate is
``(dst + 0.5) * ratio - 0.5`` with edge clamp, bilinear in float32.  Because mean/std normalization is affine and
bilinear weights sum to 1, normalize-after-resize here equals the host
path's resize-after-normalize up to float32 rounding — parity is pinned by
``tests/test_device_prep.py``.  Flip mirrors the source x coordinate
(``sx -> (w-1) - sx``) which equals flipping the raw image before the
resize; gt boxes are already flipped on the roidb records, so the host
ships untouched bytes either way.

Programs are registered through the PR-7 ``compile/registry.py`` under
kind ``"device_prep"`` — one program per (batch, bucket, s2d, dtype),
first-dispatch accounted via ``note_dispatch`` so the AOT marker manifest
and warm-start counters cover preprocessing like every other program.
"""

from __future__ import annotations

import logging
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

logger = logging.getLogger(__name__)

KIND = "device_prep"


def _prep_one(raw, raw_hw, ratio, im_info, flip, mean, std, s2d: bool,
              out_dtype):
    """One image: (Hb, Wb, 3) uint8 -> (Hb, Wb, 3) or s2d (Hb/2, Wb/2, 12)."""
    hb, wb = raw.shape[0], raw.shape[1]
    hi, wi = raw_hw[0], raw_hw[1]
    h = hi.astype(jnp.float32)
    w = wi.astype(jnp.float32)

    # cv2 INTER_LINEAR center-aligned sampling with border-replicate clamp.
    # ``ratio`` is the EXACT dst→src factor (1/s on both axes) — cv2 maps
    # with the given fx/fy, not with raw/effective per axis, and the two
    # differ whenever dim*s is fractional (see stage_raw_to_bucket).
    ys = (jnp.arange(hb, dtype=jnp.float32) + 0.5) * ratio - 0.5
    xs = (jnp.arange(wb, dtype=jnp.float32) + 0.5) * ratio - 0.5
    xs = jnp.where(flip, (w - 1.0) - xs, xs)
    ys = jnp.clip(ys, 0.0, h - 1.0)
    xs = jnp.clip(xs, 0.0, w - 1.0)

    y0 = jnp.floor(ys)
    x0 = jnp.floor(xs)
    wy = (ys - y0)[:, None, None]
    wx = (xs - x0)[None, :, None]
    y0i = y0.astype(jnp.int32)
    x0i = x0.astype(jnp.int32)
    y1i = jnp.minimum(y0i + 1, hi - 1)
    x1i = jnp.minimum(x0i + 1, wi - 1)

    img = raw.astype(jnp.float32)
    r0 = img[y0i]                     # (Hb, Wb_raw, 3)
    r1 = img[y1i]
    top = r0[:, x0i] * (1.0 - wx) + r0[:, x1i] * wx
    bot = r1[:, x0i] * (1.0 - wx) + r1[:, x1i] * wx
    v = top * (1.0 - wy) + bot * wy

    v = (v - mean) / std              # affine: commutes with the resample

    ehi = im_info[0].astype(jnp.int32)
    ewi = im_info[1].astype(jnp.int32)
    valid = ((jnp.arange(hb) < ehi)[:, None]
             & (jnp.arange(wb) < ewi)[None, :])
    v = jnp.where(valid[:, :, None], v, 0.0)

    if s2d:  # mirror data/image.py::space_to_depth2 (channel order di,dj,c)
        c = v.shape[-1]
        v = (v.reshape(hb // 2, 2, wb // 2, 2, c)
             .transpose(0, 2, 1, 3, 4)
             .reshape(hb // 2, wb // 2, 4 * c))
    return v.astype(out_dtype)


class DevicePrep:
    """Owns the jitted preprocess program and the loader/trainer glue.

    ``put`` is the k=1 producer-thread hook (replaces ``jax.device_put``);
    ``put_stacked`` preps a k-stacked group batch for the
    ``--steps-per-dispatch`` wrap path.  Both consume the raw sidecar keys
    and emit the exact batch layout the host path produces (``images``
    float32/bf16 + ``im_info`` + gt keys), so every downstream consumer —
    train step, grouping, telemetry shape accounting — is unchanged.
    """

    def __init__(self, cfg, registry=None):
        net = cfg.network
        self.cfg = cfg
        self._registry = registry
        self._mean = jnp.asarray(net.PIXEL_MEANS, jnp.float32)
        self._std = jnp.asarray(net.PIXEL_STDS, jnp.float32)
        self._s2d = bool(net.HOST_S2D)
        dt = getattr(cfg.tpu, "DEVICE_PREP_DTYPE", "float32")
        if dt not in ("float32", "bfloat16"):
            raise ValueError(f"DEVICE_PREP_DTYPE must be float32 or "
                             f"bfloat16, got {dt!r}")
        self.out_dtype = jnp.bfloat16 if dt == "bfloat16" else jnp.float32
        if registry is not None:
            registry.register(KIND, self._build)
            self._fn = registry.lookup(KIND)
        else:
            self._fn = self._build()

    def _build(self):
        mean, std, s2d, dt = self._mean, self._std, self._s2d, self.out_dtype

        def batch_prep(raw, raw_hw, ratio, im_info, flip):
            one = lambda r, hw, rt, ii, f: _prep_one(r, hw, rt, ii, f,
                                                     mean, std, s2d, dt)
            return jax.vmap(one)(raw, raw_hw, ratio, im_info, flip)

        return jax.jit(batch_prep)

    # -- hooks -----------------------------------------------------------

    def _run(self, raw, raw_hw, ratio, im_info, flip):
        """Dispatch the program with registry first-seen accounting."""
        reg = self._registry
        first = reg.note_dispatch(KIND, raw.shape) if reg is not None else False
        t0 = time.perf_counter() if first else 0.0
        out = self._fn(raw, raw_hw, ratio, im_info, flip)
        if first:
            out.block_until_ready()
            reg.record_compile_seconds(KIND, raw.shape,
                                       time.perf_counter() - t0)
        return out

    def put(self, batch: dict) -> dict:
        """k=1 loader ``put`` hook: raw host batch -> final device batch."""
        batch = dict(batch)
        raw = jax.device_put(batch.pop("images"))
        raw_hw = jax.device_put(batch.pop("raw_hw"))
        ratio = jax.device_put(batch.pop("prep_ratio"))
        flip = jax.device_put(batch.pop("flip"))
        out = jax.device_put(batch)
        out["images"] = self._run(raw, raw_hw, ratio, out["im_info"], flip)
        return out

    def put_stacked(self, stacked: dict) -> dict:
        """k-group hook: leaves shaped (k, B, ...) -> prepped (k, B, ...).

        The k·B images run as ONE prep dispatch (reshape to a flat batch,
        prep, fold back) so steps-per-dispatch adds exactly one program
        per k, not per (k, position)."""
        stacked = dict(stacked)
        raw = np.asarray(stacked.pop("images"))
        raw_hw = np.asarray(stacked.pop("raw_hw"))
        ratio = np.asarray(stacked.pop("prep_ratio"))
        flip = np.asarray(stacked.pop("flip"))
        k, b = raw.shape[:2]
        out = jax.device_put(stacked)
        draw = jax.device_put(raw.reshape((k * b,) + raw.shape[2:]))
        dhw = jax.device_put(raw_hw.reshape(k * b, 2))
        drt = jax.device_put(ratio.reshape(k * b))
        dfl = jax.device_put(flip.reshape(k * b))
        dii = out["im_info"].reshape(k * b, 3)
        imgs = self._run(draw, dhw, drt, dii, dfl)
        out["images"] = imgs.reshape((k, b) + imgs.shape[1:])
        return out


def maybe_device_prep(cfg, registry=None, plan=None) -> Optional[DevicePrep]:
    """Build a DevicePrep when the config asks for one and the topology
    supports it.  Mesh plans are host-prep only for now (the prep output
    would need the plan's input sharding); callers downgrade with a
    warning rather than silently feeding raw uint8 to the step."""
    if not getattr(cfg.tpu, "DEVICE_PREP", False):
        return None
    if plan is not None:
        raise ValueError(
            "cfg.tpu.DEVICE_PREP is not supported under a mesh plan yet — "
            "strip it before building loaders (tools.common."
            "strip_device_prep_for_mesh)")
    return DevicePrep(cfg, registry=registry)
