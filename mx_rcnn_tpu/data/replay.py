"""ReplayDataset: an imdb-compatible view over mined capture shards.

Turns a ``mined-<digest>.json`` manifest (:mod:`mx_rcnn_tpu.flywheel.miner`)
into a roidb the loader can mix into the epoch plan.  Pseudo-labels come
from the serving detections: boxes at or above ``min_score`` become gt
boxes with the served class.

Coordinate contract: the served detections are in ORIGINAL image
coordinates, while the captured pixels are the staged buffer whose valid
extent is ``raw_hw`` (oversized raws were pre-shrunk host-side before
staging, see ``stage_raw_to_bucket``).  Record boxes are therefore scaled
by ``raw_hw / orig_hw`` per axis and clipped into the raw extent, so they
line up with the pixels :func:`load_replay_pixels` returns.

Pixels are loaded lazily per record from the shard npz — no handle
caching, so fork-based loader workers (PR-4) stay safe — and a corrupt or
truncated shard raises from ``np.load``, which lands in the loader's
deterministic bad-record substitution path (PR-2).
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np

from mx_rcnn_tpu.data.imdb import IMDB


class ReplayDataset(IMDB):
    """Dataset over one mined manifest.

    ``num_classes`` must match the training config's class count; served
    class ids are already in that space (the model produced them).
    Entries whose pseudo-labels all fall below ``min_score`` are dropped.
    """

    def __init__(self, manifest_path: str, num_classes: int,
                 min_score: float = 0.5):
        from mx_rcnn_tpu.flywheel.miner import load_manifest

        doc = load_manifest(manifest_path)
        digest = os.path.basename(manifest_path)
        super().__init__("replay", os.path.splitext(digest)[0],
                         "data", "data")
        self.classes = ["__background__"] + [
            f"class{i}" for i in range(1, num_classes)]
        self.manifest_path = manifest_path
        self.capture_dir = doc["capture_dir"]
        self.min_score = float(min_score)
        self._entries = doc["entries"]
        self._roidb: Optional[list] = None
        self.num_images = 0

    def gt_roidb(self) -> list:
        if self._roidb is not None:
            return self._roidb
        roidb = []
        for e in self._entries:
            rec = self._entry_record(e)
            if rec is not None:
                roidb.append(rec)
        self.num_images = len(roidb)
        self._roidb = roidb
        return roidb

    def _entry_record(self, e):
        rh, rw = int(e["raw_hw"][0]), int(e["raw_hw"][1])
        oh, ow = int(e["orig_hw"][0]), int(e["orig_hw"][1])
        sy, sx = rh / max(1, oh), rw / max(1, ow)
        boxes, classes = [], []
        for d in e["detections"]:
            if float(d["score"]) < self.min_score:
                continue
            cls = int(d["cls"])
            if not 0 < cls < self.num_classes:
                continue
            x1, y1, x2, y2 = (float(v) for v in d["bbox"])
            x1, x2 = x1 * sx, x2 * sx
            y1, y2 = y1 * sy, y2 * sy
            x1 = min(max(x1, 0.0), rw - 1)
            x2 = min(max(x2, 0.0), rw - 1)
            y1 = min(max(y1, 0.0), rh - 1)
            y2 = min(max(y2, 0.0), rh - 1)
            if x2 <= x1 or y2 <= y1:
                continue
            boxes.append((x1, y1, x2, y2))
            classes.append(cls)
        if not boxes:
            return None
        g = len(boxes)
        classes = np.asarray(classes, np.int32)
        overlaps = np.zeros((g, self.num_classes), np.float32)
        overlaps[np.arange(g), classes] = 1.0
        return {
            "image": f"replay://{e['key']}",
            "replay_npz": os.path.join(self.capture_dir, e["npz"]),
            "replay_key": e["key"],
            "replay_generation": int(e.get("generation", 0)),
            "height": rh, "width": rw,
            "boxes": np.asarray(boxes, np.float32),
            "gt_classes": classes,
            "gt_overlaps": overlaps,
            "max_classes": classes.copy(),
            "max_overlaps": np.ones((g,), np.float32),
            "flipped": False,
        }

    def evaluate_detections(self, detections) -> dict:
        raise NotImplementedError("replay shards carry pseudo-labels; "
                                  "evaluate against a real test set")


def load_replay_pixels(rec) -> np.ndarray:
    """Load a replay record's uint8 HWC pixels, cropped to the raw extent.

    Raises on a missing/corrupt/truncated shard so the loader's
    bad-record substitution path handles it deterministically.
    """
    with np.load(rec["replay_npz"], allow_pickle=False) as npz:
        px = np.asarray(npz[rec["replay_key"]])
    if px.ndim != 3 or px.dtype != np.uint8:
        raise ValueError(f"{rec['replay_npz']}:{rec['replay_key']}: "
                         f"bad pixel payload {px.dtype}{px.shape}")
    return np.ascontiguousarray(px[:rec["height"], :rec["width"]])
