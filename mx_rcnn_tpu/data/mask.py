"""Host-side gt mask rasterization for Mask R-CNN training.

Each gt instance's polygons (COCO 'segmentation', original image coords)
are rasterized ONCE per sample into a fixed (S, S) crop aligned to its gt
box.  The device-side ``ops/mask_target.py`` then resamples these crops
into each sampled RoI's frame — so the host does O(G) small rasterizations
per image, never O(R) per step (reference analogue: TuSimple-era mask
targets were computed on host per RoI per step; this split is the TPU-first
restructuring).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import cv2
import numpy as np

GT_MASK_SIZE = 112  # gt-box-frame crop resolution (4x the 28px head output)


def rasterize_gt_masks(segs: Sequence, boxes: np.ndarray, width: int,
                       flipped: bool, max_gt: int,
                       size: int = GT_MASK_SIZE) -> np.ndarray:
    """(max_gt, size, size) float32 gt-box-frame masks.

    Args:
      segs: per-gt COCO segmentation (polygon list | RLE dict | None).
      boxes: (G, 4) gt boxes in ORIGINAL image coords, already flipped if
        ``flipped`` (the roidb contract).
      width: original image width (for polygon mirroring).
      flipped: whether this record is an x-flip.
    """
    g = min(len(boxes), max_gt)
    out = np.zeros((max_gt, size, size), np.float32)
    for j in range(g):
        seg = segs[j] if segs is not None and j < len(segs) else None
        if seg is None:
            # no segmentation (e.g. VOC): box mask — full coverage
            out[j] = 1.0
            continue
        x1, y1, x2, y2 = boxes[j]
        bw = max(x2 - x1, 1e-3)
        bh = max(y2 - y1, 1e-3)
        canvas = np.zeros((size, size), np.uint8)
        if isinstance(seg, list):
            pts = []
            for poly in seg:
                p = np.asarray(poly, np.float64).reshape(-1, 2)
                if flipped:
                    p[:, 0] = width - p[:, 0] - 1
                p[:, 0] = (p[:, 0] - x1) / bw * size
                p[:, 1] = (p[:, 1] - y1) / bh * size
                if len(p) >= 3:
                    pts.append(p.round().astype(np.int32))
            if pts:
                cv2.fillPoly(canvas, pts, 1)
        elif isinstance(seg, dict):
            from mx_rcnn_tpu.eval.mask_rle import decode, string_to_counts

            rle = dict(seg)
            if isinstance(rle.get("counts"), (str, bytes)):
                rle = {"size": rle["size"],
                       "counts": string_to_counts(rle["counts"])}
            full = decode(rle)
            if flipped:
                full = full[:, ::-1]
            xi1, yi1 = int(max(x1, 0)), int(max(y1, 0))
            xi2, yi2 = int(min(x2 + 1, full.shape[1])), int(min(y2 + 1, full.shape[0]))
            crop = full[yi1:yi2, xi1:xi2]
            if crop.size:
                canvas = cv2.resize(crop.astype(np.uint8), (size, size),
                                    interpolation=cv2.INTER_NEAREST)
        out[j] = canvas.astype(np.float32)
    return out
