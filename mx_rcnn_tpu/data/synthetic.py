"""Synthetic dataset — no reference counterpart; exists so every driver
(train/test/bench/CI) runs with zero data on disk (SURVEY §7 minimum slice:
"synthetic-then-VOC").

Images are noise with solid-color rectangles at the gt boxes (class ↔ color
correlated), so a detector can genuinely overfit/learn on it — loss curves
and mAP on synthetic data are meaningful smoke signals.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from mx_rcnn_tpu.data.imdb import IMDB


class SyntheticDataset(IMDB):
    def __init__(self, num_images: int = 64, num_classes: int = 21,
                 height: int = 600, width: int = 800, max_objects: int = 6,
                 seed: int = 0):
        super().__init__("synthetic", f"n{num_images}", "data", "data")
        self.classes = ["__background__"] + [f"class{i}" for i in
                                             range(1, num_classes)]
        self.num_images = num_images
        self._h, self._w = height, width
        self._max_objects = max_objects
        self._seed = seed
        self._roidb: Optional[list] = None

    def _colors(self):
        rng = np.random.RandomState(1234)
        return rng.randint(40, 255, size=(self.num_classes, 3))

    def gt_roidb(self) -> list:
        if self._roidb is not None:
            return self._roidb
        rng = np.random.RandomState(self._seed)
        colors = self._colors()
        roidb = []
        for i in range(self.num_images):
            n = rng.randint(1, self._max_objects + 1)
            boxes = np.zeros((n, 4), np.float32)
            classes = np.zeros((n,), np.int32)
            img = (rng.randn(self._h, self._w, 3) * 20 + 127).clip(0, 255)
            for j in range(n):
                bw = rng.randint(max(self._w // 5, 8), max(self._w // 2, 16))
                bh = rng.randint(max(self._h // 5, 8), max(self._h // 2, 16))
                x1 = rng.randint(0, self._w - bw)
                y1 = rng.randint(0, self._h - bh)
                cls = rng.randint(1, self.num_classes)
                boxes[j] = (x1, y1, x1 + bw - 1, y1 + bh - 1)
                classes[j] = cls
                img[y1:y1 + bh, x1:x1 + bw] = colors[cls]
            overlaps = np.zeros((n, self.num_classes), np.float32)
            overlaps[np.arange(n), classes] = 1.0
            roidb.append({
                "image": f"synthetic://{i}",
                "image_array": img.astype(np.uint8),
                "height": self._h, "width": self._w,
                "boxes": boxes, "gt_classes": classes,
                "gt_overlaps": overlaps,
                "max_classes": classes.copy(),
                "max_overlaps": np.ones((n,), np.float32),
                "flipped": False,
            })
        self._roidb = roidb
        return roidb

    def evaluate_sds(self, detections, masks) -> dict:
        """Box AP only — synthetic gt has rectangular instances, so segm
        scoring adds nothing; masks are exercised by the coco path."""
        del masks
        return {"bbox": self.evaluate_detections(detections)}

    def evaluate_detections(self, detections) -> dict:
        """Greedy-match AP at IoU 0.5 via the VOC scorer (classes are
        synthetic but the metric math is the real one)."""
        from mx_rcnn_tpu.eval.voc_eval import voc_eval

        recs = {}
        for i, rec in enumerate(self.gt_roidb()):
            recs[i] = [{"name": self.classes[c], "difficult": 0,
                        "bbox": list(map(float, b))}
                       for b, c in zip(rec["boxes"], rec["gt_classes"])]
        aps = {}
        for k, cls in enumerate(self.classes):
            if k == 0:
                continue
            aps[cls] = voc_eval(detections[k], recs, cls, ovthresh=0.5,
                                use_07_metric=False)
        aps["mAP"] = float(np.mean(list(aps.values())))
        return aps
