"""Roidb-wide bbox regression target statistics (reference
``rcnn/processing/bbox_regression.py``: ``add_bbox_regression_targets`` /
``compute_bbox_regression_targets``).

With ``BBOX_NORMALIZATION_PRECOMPUTED`` (the default, here and in the
reference) training uses the fixed ``BBOX_MEANS``/``BBOX_STDS``; this module
provides the legacy alternative — measure the per-class delta statistics
over a proposal roidb (the ROIIter / Fast-RCNN path) and return the
(means, stds) to feed into the config.  The per-RoI target assignment and
the class-specific 4·K expansion live in ``ops/sample_rois.py`` (in-graph).
"""

from __future__ import annotations

from typing import Tuple

import numpy as np


def compute_bbox_regression_targets(rois: np.ndarray, gt_boxes: np.ndarray,
                                    gt_classes: np.ndarray,
                                    fg_thresh: float = 0.5) -> np.ndarray:
    """(R, 5) [cls, dx, dy, dw, dh] for rois vs their argmax gt (rows with
    max IoU < fg_thresh get class 0 and zero targets)."""
    from mx_rcnn_tpu.native import bbox_overlaps

    out = np.zeros((len(rois), 5), np.float32)
    if len(rois) == 0 or len(gt_boxes) == 0:
        return out
    ov = bbox_overlaps(rois.astype(np.float32), gt_boxes.astype(np.float32))
    max_ov = ov.max(axis=1)
    argmax = ov.argmax(axis=1)
    fg = max_ov >= fg_thresh
    ex, gt = rois[fg], gt_boxes[argmax[fg]]

    from mx_rcnn_tpu.ops.boxes import bbox_transform  # the canonical codec

    out[fg, 0] = gt_classes[argmax[fg]]
    if fg.any():
        out[fg, 1:] = np.asarray(bbox_transform(ex, gt))
    return out


def add_bbox_regression_targets(roidb: list, num_classes: int,
                                fg_thresh: float = 0.5
                                ) -> Tuple[np.ndarray, np.ndarray]:
    """Attach ``bbox_targets`` to each record and return (means, stds)
    measured class-agnostically over all fg targets (the reference averages
    its per-class stats when PRECOMPUTED is off; the fixed defaults
    (0, 0.1/0.2) approximate these — this recovers the measured version)."""
    sums = np.zeros(4)
    sq = np.zeros(4)
    count = 0
    for rec in roidb:
        props = rec.get("proposals", rec["boxes"])
        t = compute_bbox_regression_targets(
            np.asarray(props, np.float32), rec["boxes"], rec["gt_classes"],
            fg_thresh)
        rec["bbox_targets"] = t
        fg = t[:, 0] > 0
        sums += t[fg, 1:].sum(axis=0)
        sq += (t[fg, 1:] ** 2).sum(axis=0)
        count += int(fg.sum())
    if count == 0:
        return np.zeros(4), np.ones(4)
    means = sums / count
    stds = np.sqrt(np.maximum(sq / count - means ** 2, 1e-12))
    return means, stds
