"""Dataset base class (reference ``rcnn/dataset/imdb.py``).

The roidb contract is the reference's, verbatim: a list of per-image dicts

    {image: path, height, width,
     boxes: (G, 4) float32 [x1,y1,x2,y2],
     gt_classes: (G,) int32 (0 = background, never present in gt),
     gt_overlaps: (G, K) float32,
     max_classes: (G,), max_overlaps: (G,),
     flipped: bool}

plus ``append_flipped_images`` (x-mirror the boxes, mark flipped — doubles
the roidb; the image itself is flipped at load time) and a pickle cache.
"""

from __future__ import annotations

import os
import pickle
from typing import List, Optional

import numpy as np

from mx_rcnn_tpu.logger import logger


class IMDB:
    def __init__(self, name: str, image_set: str, root_path: str,
                 dataset_path: str):
        self.name = name + "_" + image_set
        self.image_set = image_set
        self.root_path = root_path
        self.data_path = dataset_path
        self.classes: List[str] = []
        self.num_images = 0

    @property
    def num_classes(self) -> int:
        return len(self.classes)

    @property
    def cache_path(self) -> str:
        p = os.path.join(self.root_path, "cache")
        os.makedirs(p, exist_ok=True)
        return p

    # -- to be implemented by subclasses ------------------------------------
    def gt_roidb(self) -> list:
        raise NotImplementedError

    def evaluate_detections(self, detections) -> dict:
        raise NotImplementedError

    # -- shared machinery ----------------------------------------------------
    def load_cached(self, tag: str, builder):
        cache_file = os.path.join(self.cache_path, f"{self.name}_{tag}.pkl")
        if os.path.exists(cache_file):
            with open(cache_file, "rb") as f:
                data = pickle.load(f)
            logger.info("%s %s loaded from %s", self.name, tag, cache_file)
            return data
        data = builder()
        with open(cache_file, "wb") as f:
            pickle.dump(data, f, pickle.HIGHEST_PROTOCOL)
        logger.info("%s wrote %s cache to %s", self.name, tag, cache_file)
        return data

    @staticmethod
    def sanitize_proposals(boxes, width: int, height: int) -> np.ndarray:
        """Clip external proposals into the image and repair degenerate
        rows (x2 < x1 / y2 < y1).  Real selective-search releases contain
        occasional zero-width / out-of-bounds boxes (the reference's
        merged-roidb flip would trip its assert on them); sanitizing ONCE
        at attach time keeps original and flipped records on identical
        geometry instead of special-casing the flip path."""
        boxes = np.asarray(boxes, dtype=np.float32)
        if len(boxes) == 0:
            return boxes.reshape(0, 4)
        boxes = boxes.copy()
        boxes[:, 0::2] = np.clip(boxes[:, 0::2], 0, width - 1)
        boxes[:, 1::2] = np.clip(boxes[:, 1::2], 0, height - 1)
        boxes[:, 2] = np.maximum(boxes[:, 0], boxes[:, 2])
        boxes[:, 3] = np.maximum(boxes[:, 1], boxes[:, 3])
        return boxes

    def append_flipped_images(self, roidb: list) -> list:
        """Double the roidb with x-flipped records (reference semantics:
        boxes mirrored on image width; loader flips pixels at read time).
        External proposals attached before flipping (the selective-search
        path) are mirrored too — the ``proposals`` key is always copied
        (possibly empty) so flipped records stay structurally uniform."""

        def mirror(boxes, w):
            boxes = boxes.copy()
            x1 = boxes[:, 0].copy()
            x2 = boxes[:, 2].copy()
            boxes[:, 0] = w - x2 - 1
            boxes[:, 2] = w - x1 - 1
            return boxes

        flipped = []
        for rec in roidb:
            boxes = mirror(rec["boxes"], rec["width"])
            assert (boxes[:, 2] >= boxes[:, 0]).all()
            new = dict(rec)
            new["boxes"] = boxes
            new["flipped"] = True
            if "proposals" in rec:
                # re-sanitize here rather than assume every attach path did:
                # a legacy roidb pickle can carry a plain empty list (shape
                # (0,)) that would crash mirror's column indexing before the
                # guiding assert fires.  Written back to the source record
                # so original and flipped halves stay on identical geometry
                # (the sanitize-ONCE invariant above).
                props = self.sanitize_proposals(
                    rec["proposals"], rec["width"], rec["height"])
                rec["proposals"] = props
                new["proposals"] = mirror(props, rec["width"]) if len(props) \
                    else props
                assert (len(new["proposals"]) == 0
                        or (new["proposals"][:, 2] >= new["proposals"][:, 0]).all()), \
                    "degenerate proposals — attach via sanitize_proposals"
            flipped.append(new)
        logger.info("%s appended %d flipped images", self.name, len(flipped))
        return list(roidb) + flipped

    @staticmethod
    def filter_roidb(roidb: list, min_gt: int = 1) -> list:
        """Drop images with no usable gt (reference train_end2end filters
        roidb entries whose fg boxes are empty)."""
        keep = [r for r in roidb if len(r["boxes"]) >= min_gt]
        logger.info("filtered roidb: %d -> %d images", len(roidb), len(keep))
        return keep
