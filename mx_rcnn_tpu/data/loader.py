"""Batch loaders (reference ``rcnn/core/loader.py``: ``AnchorLoader``,
``ROIIter``, ``TestLoader``).

Differences by design (all SURVEY §7 step-4 decisions):

* No ``feat_sym.infer_shape`` / label pre-computation — anchor and RoI
  targets are assigned *inside the jitted graph*; the loader ships
  (images, im_info, gt_boxes·scale, gt_classes, gt_valid) only.
* Static shapes: images land in per-orientation scale buckets, gt is
  padded to MAX_GT.  Aspect-ratio grouping (the reference's
  ``aspect_grouping``) both balances batches and selects the compiled
  program: one batch never mixes bucket shapes.
* Host→device overlap: a background thread prepares the next batch(es)
  while the device runs the current step (replaces MXNet's threaded
  ``PrefetchingIter``).
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

from mx_rcnn_tpu import telemetry
from mx_rcnn_tpu.config import Config
from mx_rcnn_tpu.data.image import (get_image, resize_to_bucket,
                                    space_to_depth2, stage_raw_to_bucket,
                                    transform_image)
from mx_rcnn_tpu.logger import logger

# Fault isolation (train loaders): one missing/corrupt image substitutes a
# deterministic neighbor record instead of killing the producer thread, but
# this many failures IN A ROW means the breakage is systemic (unmounted
# filesystem, wrong dataset path) and must raise, not silently retrain on
# substitutes.  Class-level so tests/operators can widen it.
MAX_CONSECUTIVE_BAD_RECORDS = 8


def prepare_image(im: np.ndarray, cfg: Config,
                  scale: Tuple[int, int]) -> Tuple[np.ndarray, np.ndarray]:
    """Raw RGB HWC image → (bucket-padded network input, im_info) — the
    image half of ``_load_record``, shared with the serve engine
    (``mx_rcnn_tpu/serve``) so an online request goes through byte-for-byte
    the same transform chain as an eval batch: pixel normalize → resize by
    the reference rule → zero-pad into the orientation's static bucket →
    optional host space-to-depth."""
    im = transform_image(im, cfg.network.PIXEL_MEANS, cfg.network.PIXEL_STDS)
    stride = max(cfg.network.IMAGE_STRIDE, cfg.network.RPN_FEAT_STRIDE)
    padded, s, (eh, ew) = resize_to_bucket(im, scale, stride)
    if cfg.network.HOST_S2D:
        padded = space_to_depth2(padded)
    return padded, np.asarray([eh, ew, s], np.float32)


def _load_record(rec: dict, cfg: Config, scale: Tuple[int, int],
                 with_masks: bool = False) -> dict:
    """roidb record → one transformed sample (host numpy).

    ``with_masks``: rasterize gt masks (train loaders under HAS_MASK only —
    eval and proposal loaders never consume them)."""
    device_prep = getattr(cfg.tpu, "DEVICE_PREP", False)
    flipped = bool(rec.get("flipped", False))
    if "replay_npz" in rec:  # flywheel replay shard (data/replay.py)
        from mx_rcnn_tpu.data.replay import load_replay_pixels

        # raises on a corrupt/truncated shard — train loaders land in the
        # bad-record substitution path below, eval loaders stay strict
        im = load_replay_pixels(rec)
        if flipped and not device_prep:
            im = im[:, ::-1, :]
    elif "image_array" in rec:  # synthetic dataset ships pixels inline
        im = rec["image_array"]
        if flipped and not device_prep:  # device prep mirrors on device
            im = im[:, ::-1, :]
    else:
        im = get_image(rec["image"], flipped=flipped and not device_prep)
    if device_prep:
        # ship raw uint8 staged into the output bucket; the jitted
        # device_prep program does resize/flip/normalize/pad (+ s2d).
        # The pixel key stays "images" so every shape/dtype-agnostic
        # consumer (worker shm handover, group assembly, _stack) flows
        # unchanged; the sidecar keys are consumed by DevicePrep hooks.
        stride = max(cfg.network.IMAGE_STRIDE, cfg.network.RPN_FEAT_STRIDE)
        padded, raw_hw, ratio, im_info = stage_raw_to_bucket(
            np.ascontiguousarray(im), scale, stride)
    else:
        padded, im_info = prepare_image(im, cfg, scale)
    s = float(im_info[2])

    g = cfg.tpu.MAX_GT
    boxes = np.zeros((g, 4), np.float32)
    classes = np.zeros((g,), np.int32)
    valid = np.zeros((g,), bool)
    n = min(len(rec["boxes"]), g)
    if n:
        boxes[:n] = rec["boxes"][:n] * s  # gt scaled into the resized frame
        classes[:n] = rec["gt_classes"][:n]
        valid[:n] = True
    out = dict(images=padded, im_info=im_info,
               gt_boxes=boxes, gt_classes=classes, gt_valid=valid)
    if device_prep:
        out["raw_hw"] = raw_hw
        out["prep_ratio"] = ratio
        out["flip"] = np.bool_(flipped)
    if with_masks and cfg.network.HAS_MASK:
        from mx_rcnn_tpu.data.mask import rasterize_gt_masks

        out["gt_masks"] = rasterize_gt_masks(
            rec.get("segmentation"), rec["boxes"], rec["width"],
            rec.get("flipped", False), g)
    return out


def _load_record_isolated(roidb: list, i: int, cfg: Config,
                          scale: Tuple[int, int], with_masks: bool = False,
                          state: Optional[list] = None) -> Tuple[int, dict]:
    """``_load_record`` with fault isolation for TRAIN loaders: a failing
    record (missing/corrupt image) substitutes the next roidb record
    deterministically instead of killing the producer thread, bumping the
    ``loader/bad_record`` telemetry counter per failure.

    ``state`` is a single-element mutable list holding the CONSECUTIVE
    failure count across calls from one producer generator — it resets on
    every success, and crossing ``MAX_CONSECUTIVE_BAD_RECORDS`` raises
    (systemic breakage must not silently train on substitutes).

    Returns ``(actual_index, sample)`` so callers that pair the sample
    with other per-record data (ROIIter's proposals) stay consistent
    with the substituted record.  Eval loaders stay strict: a bad record
    in evaluation silently changes the metric and must raise.
    """
    n = len(roidb)
    state = state if state is not None else [0]
    attempt = 0
    while True:
        j = (i + attempt) % n
        try:
            out = _load_record(roidb[j], cfg, scale, with_masks=with_masks)
            state[0] = 0
            return j, out
        except Exception as e:  # noqa: BLE001 — isolate, count, bound
            state[0] += 1
            telemetry.get().counter("loader/bad_record")
            if state[0] >= MAX_CONSECUTIVE_BAD_RECORDS:
                telemetry.get().dump_flight(
                    "loader_systemic", consecutive_bad=state[0],
                    last_index=j, error=f"{type(e).__name__}: {e}"[:500])
                raise RuntimeError(
                    f"{state[0]} consecutive roidb records failed to load "
                    f"(last: index {j}, {type(e).__name__}: {e}) — this "
                    f"looks systemic (wrong dataset path? unmounted "
                    f"filesystem?), not a stray corrupt image") from e
            logger.warning("bad roidb record %d (%s: %s) — substituting "
                           "record %d [loader/bad_record]",
                           j, type(e).__name__, e, (j + 1) % n)
            attempt += 1


def _stack(samples: List[dict]) -> Dict[str, np.ndarray]:
    return {k: np.stack([s[k] for s in samples]) for k in samples[0]}


def _iter_samples(roidb: list, cfg: Config, plan, part_fn, pool,
                  with_masks: bool = False) -> Iterator[Tuple[int, dict]]:
    """Yield ``(actual_index, sample)`` for every row this process owns,
    in plan order — through the multi-worker ``pool`` when one is set, on
    the calling (producer) thread otherwise.  Both paths run the same
    ``_load_record_isolated`` per task, so the output stream is identical
    sample for sample; only the consecutive-bad-record budget is scoped
    differently (per epoch serially, per worker with a pool — either way
    ``MAX_CONSECUTIVE_BAD_RECORDS`` failures in a row on one producer is
    systemic and raises)."""
    tasks = [(int(i), scale) for chunk, scale in plan
             for i in part_fn(chunk)]
    if pool is not None:
        yield from pool.imap_records(tasks, with_masks=with_masks)
        return
    fail_state = [0]
    for i, scale in tasks:
        yield _load_record_isolated(roidb, i, cfg, scale,
                                    with_masks=with_masks, state=fail_state)


class _Prefetcher:
    """Runs a batch-producing generator in a daemon thread with a bounded
    queue (depth = cfg.tpu.PREFETCH).  Closing (or GC of) the iterator stops
    the producer — an abandoned consumer must not leave a thread parked on a
    full queue pinning batches.

    ``put``: optional callable applied to each batch ON THE PRODUCER THREAD
    before it is queued — the device double-buffering hook (round-2 weakness
    3: preparing host numpy but transferring synchronously inside step
    dispatch leaves the transfer on the critical path).  ``fit`` installs
    ``jax.device_put`` (with the mesh sharding when data-parallel) here, so
    the host→device copy is in flight while the previous step computes;
    ``device_put`` only enqueues the transfer, so the producer thread never
    blocks on the device.

    Telemetry (active sink at construction; the no-op sink costs one
    attribute check per batch): producer-side ``loader/produce`` (host
    batch assembly), ``loader/put_transfer`` (the ``put`` hook — the
    device transfer when double-buffering) and ``loader/queue_full_wait``
    (producer blocked on a full queue = consumer is the bottleneck);
    consumer-side ``loader/queue_depth`` gauge sampled at every get (a
    persistently empty queue = producer is the bottleneck).

    ``watchdog_s``: consumer-side timeout on the blocking get — a producer
    stuck past it (hung filesystem read, deadlocked ``put`` hook) raises a
    diagnostic naming the producer state instead of hanging the training
    loop forever.  The timeout is measured from the producer's last
    HEARTBEAT (one per queued batch), so a slow-but-advancing producer is
    never killed.  <= 0 disables."""

    def __init__(self, gen, depth: int, put=None, watchdog_s: float = 600.0):
        self._q: queue.Queue = queue.Queue(maxsize=max(depth, 1))
        self._err = None
        self._stop = threading.Event()
        self._tel = telemetry.get()
        self._watchdog_s = watchdog_s
        self._beat = time.monotonic()

        def enqueue(item) -> bool:
            """Blocking put that honors close(); False once stopped."""
            while not self._stop.is_set():
                try:
                    self._q.put(item, timeout=0.2)
                    self._beat = time.monotonic()
                    return True
                except queue.Full:
                    self._beat = time.monotonic()  # blocked-on-full is alive
                    continue
            return False

        def run():
            # Re-stamp the heartbeat the moment the producer THREAD starts:
            # the watchdog clock otherwise runs from __init__, and a slow
            # epoch boundary (worker-pool spawn, scheduler delay between
            # construction and thread start) would count against the budget
            # and trip a spurious prefetch_watchdog flight dump on a fresh
            # prefetcher.
            self._beat = time.monotonic()
            tel = self._tel
            try:
                if not tel.enabled:  # untimed hot path: one check per epoch
                    for item in gen:
                        if put is not None:
                            item = put(item)
                        if not enqueue(item):
                            return
                else:
                    t_prod = time.perf_counter()
                    for item in gen:
                        dt_prod = time.perf_counter() - t_prod
                        tel.add("loader/produce", dt_prod)
                        tel.observe("loader/produce", dt_prod)
                        if put is not None:
                            with tel.span("loader/put_transfer"):
                                item = put(item)
                        t_full = time.perf_counter()
                        if not enqueue(item):
                            return
                        tel.add("loader/queue_full_wait",
                                time.perf_counter() - t_full)
                        t_prod = time.perf_counter()
            except BaseException as e:  # surfaced on the consumer side
                self._err = e
            finally:
                while True:  # sentinel must land even on a full queue
                    try:
                        self._q.put(None, timeout=0.2)
                        break
                    except queue.Full:
                        if self._stop.is_set():
                            break
                        continue

        self._t = threading.Thread(target=run, daemon=True)
        self._t.start()

    def close(self, timeout: float = 5.0):
        """Stop the producer AND join its thread (bounded) — repeated
        ``fit()`` calls over one loader must not accumulate daemon threads
        parked in ``enqueue``.  Draining the queue first unblocks a
        producer waiting on a full queue so the join is fast."""
        self._stop.set()
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        self._t.join(timeout=timeout)
        if self._t.is_alive():
            logger.warning("prefetch producer thread did not exit within "
                           "%.1fs of close() — still parked in the source "
                           "generator?", timeout)

    def __del__(self):
        self._stop.set()  # no join in GC: finalizers must not block

    def _get(self):
        """Blocking get with the producer watchdog (see class docstring)."""
        if self._watchdog_s <= 0:
            return self._q.get()
        poll = min(self._watchdog_s, 5.0)
        while True:
            try:
                return self._q.get(timeout=poll)
            except queue.Empty:
                age = time.monotonic() - self._beat
                if age < self._watchdog_s and self._t.is_alive():
                    continue  # slow but advancing (or just started)
                telemetry.get().dump_flight(
                    "prefetch_watchdog", age_s=round(age, 1),
                    producer_alive=self._t.is_alive())
                raise RuntimeError(
                    f"prefetch queue empty with no producer heartbeat for "
                    f"{age:.0f}s (watchdog {self._watchdog_s:.0f}s) — "
                    f"producer thread "
                    f"{'alive' if self._t.is_alive() else 'DEAD'}, "
                    f"stop_requested={self._stop.is_set()}, "
                    f"qsize={self._q.qsize()}: the producer is stuck (hung "
                    f"filesystem read? deadlocked put hook?) or died "
                    f"without delivering its end-of-epoch sentinel") \
                    from None

    def __iter__(self):
        tel = self._tel
        try:
            while True:
                if tel.enabled:
                    # sampled BEFORE the blocking get: a persistently-zero
                    # depth means the consumer outruns the producer
                    tel.gauge("loader/queue_depth", self._q.qsize())
                item = self._get()
                if item is None:
                    if self._err is not None:
                        raise self._err
                    return
                yield item
        finally:
            self.close()


class AnchorLoader:
    """End-to-end / RPN training loader (reference ``AnchorLoader``).

    Iterable over epochs; each pass yields dict batches.  ``batch_size`` is
    the GLOBAL images-per-step (the trainer shards over the mesh data axis).
    Incomplete trailing groups are wrapped by re-sampling from the group
    (reference pads the last batch by wrapping indices).

    ``num_parts``/``part_index`` (the MXNet ``mx.io.DataIter`` partition
    kwargs used with ``KVStore('dist_sync')``) make the loader multi-host:
    the FULL epoch schedule — shuffle, aspect buckets, scale choice,
    wrap-padding — is computed from the (replicated) roidb with the shared
    seed, identical on every process, and each process then loads and
    yields only rows ``[part_index·B/num_parts, (part_index+1)·B/num_parts)``
    of every global batch.  Identical schedules are what keep all
    processes dispatching the same compiled program in lockstep;
    ``parallel.assert_loader_partition`` checks the slice matches the mesh
    row shards this process owns.  ``batch_size`` and ``steps_per_epoch``
    keep their GLOBAL meaning.
    """

    def __init__(self, roidb: list, cfg: Config, batch_size: int,
                 shuffle: bool = True, seed: int = 0,
                 num_parts: int = 1, part_index: int = 0,
                 replay_roidb: Optional[list] = None,
                 replay_ratio: float = 0.0):
        if not roidb:
            raise ValueError("empty roidb")
        if not (0 <= part_index < num_parts):
            raise ValueError(f"part_index {part_index} not in [0, {num_parts})")
        if batch_size % num_parts:
            raise ValueError(f"batch_size {batch_size} does not divide over "
                             f"{num_parts} parts")
        if not (0.0 <= replay_ratio < 1.0):
            raise ValueError(f"replay_ratio must be in [0, 1), "
                             f"got {replay_ratio}")
        # flywheel replay mixing (data/replay.py): mined records append
        # AFTER the base roidb; the epoch schedule (groups, steps, wrap)
        # is computed from the base alone, and each assembled batch then
        # substitutes ~replay_ratio of its slots with same-orientation
        # replay records.  All draws come from self._rng at plan time, so
        # the mix is bit-reproducible under advance_epochs/skip_next.
        replay_roidb = list(replay_roidb) if replay_roidb else []
        base_n = len(roidb)
        self.roidb = list(roidb) + replay_roidb
        self.replay_ratio = replay_ratio if replay_roidb else 0.0
        self._replay_groups = [
            [base_n + i for i, r in enumerate(replay_roidb)
             if r["width"] >= r["height"]],
            [base_n + i for i, r in enumerate(replay_roidb)
             if r["width"] < r["height"]],
        ]
        self.replay_substituted = 0  # cumulative slots replaced
        self.cfg = cfg
        self.batch_size = batch_size
        self.num_parts = num_parts
        self.part_index = part_index
        self.shuffle = shuffle
        # device double-buffering hook: when set (``fit`` installs the
        # plan-aware device_put), batches arrive on-device, transfer
        # overlapped with the previous step's compute
        self.put = None
        # generator transform applied around the producer ON ITS THREAD
        # (before ``put``): ``fit`` installs the steps_per_dispatch group
        # assembler here so k-batch stacking + transfer overlap the device
        # just like the k=1 ``put`` path (round-4 weakness 2: consumer-side
        # stacking shipped each group synchronously)
        self.wrap = None
        # multi-worker host pipeline (cfg.tpu.LOADER_WORKERS > 0): created
        # lazily on first iteration, REUSED across epochs (the shm ring is
        # allocated once), torn down by close_workers()/GC
        self._pool = None
        self._rng = np.random.RandomState(seed)
        self._skip = 0  # one-shot batch skip armed by skip_next()
        # aspect grouping: horizontal (w>=h) vs vertical image index pools
        self._groups = [
            [i for i, r in enumerate(roidb) if r["width"] >= r["height"]],
            [i for i, r in enumerate(roidb) if r["width"] < r["height"]],
        ]
        self._len = sum(len(g) // batch_size + (1 if len(g) % batch_size else 0)
                        for g in self._groups if g)

    def __len__(self) -> int:
        return self._len

    @property
    def steps_per_epoch(self) -> int:
        return self._len

    def _epoch_indices(self) -> List[np.ndarray]:
        batches = []
        for gi, g in enumerate(self._groups):
            if not g:
                continue
            idx = np.asarray(g)
            if self.shuffle:
                self._rng.shuffle(idx)
            pool = (self._replay_groups[gi]
                    if self.replay_ratio > 0 else [])
            for i in range(0, len(idx), self.batch_size):
                chunk = idx[i:i + self.batch_size]
                if len(chunk) < self.batch_size:  # wrap like the reference
                    extra = self._rng.choice(idx, self.batch_size - len(chunk))
                    chunk = np.concatenate([chunk, extra])
                if pool:
                    # replay substitution, drawn from the SAME RandomState
                    # as the rest of the plan (never wall clock) — the mix
                    # replays bit-identically on resume
                    mask = self._rng.rand(len(chunk)) < self.replay_ratio
                    k = int(mask.sum())
                    if k:
                        chunk = chunk.copy()
                        chunk[mask] = self._rng.choice(pool, size=k)
                        self.replay_substituted += k
                        telemetry.get().counter("flywheel/replayed", k)
                batches.append(chunk)
        if self.shuffle:
            order = self._rng.permutation(len(batches))
            batches = [batches[i] for i in order]
        return batches

    def _epoch_plan(self) -> List[Tuple[np.ndarray, Tuple[int, int]]]:
        """(batch indices, scale bucket) for one epoch.

        Multi-scale training: one scale bucket per BATCH (upstream
        py-faster-rcnn samples cfg.TRAIN.SCALES per image; with
        BATCH_IMAGES=1 per-batch ≡ per-image, and for larger batches it
        preserves the one-bucket-per-batch static-shape invariant — each
        (scale, orientation) pair is its own compiled program).
        Deterministic loaders (shuffle=False: eval, proposal dumps) pin
        SCALES[0] like the reference's single-scale TEST path.

        All RNG draws happen here, on the caller's thread at epoch start —
        the producer generator must stay RNG-free because an abandoned
        prefetch thread can overlap a re-iteration's new thread, and the
        shared RandomState is not thread-safe.
        """
        batches = self._epoch_indices()
        scales = self.cfg.tpu.SCALES
        if self.shuffle and len(scales) > 1:
            chosen = [scales[self._rng.randint(len(scales))] for _ in batches]
        else:
            chosen = [scales[0]] * len(batches)
        return list(zip(batches, chosen))

    # -- deterministic fast-forward (fit(auto_resume) mid-epoch resume) ---

    def advance_epochs(self, n: int) -> None:
        """Draw-and-discard ``n`` epoch plans, advancing the shared
        RandomState exactly as ``n`` real iterations would — epoch k's
        plan depends on the k prior epochs' draws, so resuming at epoch k
        must burn the first k plans to reproduce the original schedule."""
        for _ in range(n):
            self._epoch_plan()

    def skip_next(self, n: int) -> None:
        """Arm a one-shot skip: the NEXT iteration drops its first ``n``
        batches (consumed before the interruption).  The full plan is
        still generated first — RNG draws are position-dependent, so the
        tail batches come out identical to the uninterrupted epoch."""
        if n < 0:
            raise ValueError(f"skip_next: n must be >= 0, got {n}")
        self._skip = n

    def _take_epoch_plan(self) -> List[Tuple[np.ndarray, Tuple[int, int]]]:
        """One epoch's plan with any armed skip applied (and disarmed)."""
        plan = self._epoch_plan()  # full draw FIRST: keeps RNG in sequence
        skip, self._skip = self._skip, 0
        if skip:
            if skip > len(plan):
                raise ValueError(
                    f"skip_next({skip}) exceeds the epoch's {len(plan)} "
                    f"batches — resume position does not match this "
                    f"loader's schedule (different seed or batch size?)")
            plan = plan[skip:]
        return plan

    def _part(self, chunk: np.ndarray) -> np.ndarray:
        """This process's contiguous row slice of a global batch."""
        bl = self.batch_size // self.num_parts
        return chunk[self.part_index * bl:(self.part_index + 1) * bl]

    def _ensure_pool(self):
        """Create the worker pool on first use (consumer thread — forking
        from the prefetch producer thread would snapshot mid-mutation
        state).  workers=0 (the default) keeps today's serial producer,
        bit for bit."""
        workers = int(getattr(self.cfg.tpu, "LOADER_WORKERS", 0))
        if workers > 0 and self._pool is None:
            from mx_rcnn_tpu.data.workers import WorkerPool

            self._pool = WorkerPool(self.cfg, self.roidb,
                                    num_workers=workers)
        return self._pool

    def close_workers(self):
        """Tear down the worker pool (processes + shm segment).  Idempotent;
        the next iteration recreates it."""
        if self._pool is not None:
            self._pool.close()
            self._pool = None

    def __del__(self):
        try:
            self.close_workers()
        except Exception:
            pass

    def _produce(self, plan) -> Iterator[Dict[str, np.ndarray]]:
        bl = self.batch_size // self.num_parts
        samples: List[dict] = []
        for _, s in _iter_samples(self.roidb, self.cfg, plan, self._part,
                                  self._pool, with_masks=True):
            samples.append(s)
            if len(samples) == bl:
                yield _stack(samples)
                samples = []

    def __iter__(self):
        plan = self._take_epoch_plan()  # RNG on the consumer thread only
        self._ensure_pool()
        gen = self._produce(plan)
        if self.wrap is not None:
            gen = self.wrap(gen)
        return iter(_Prefetcher(gen, self.cfg.tpu.PREFETCH, put=self.put,
                                watchdog_s=self.cfg.tpu.PREFETCH_WATCHDOG_S))


class TestLoader:
    """Eval loader (reference ``TestLoader``): sequential, no shuffle, no gt
    needed; batch padded with repeats of the last image (mask via
    ``batch_valid``)."""

    __test__ = False  # not a pytest class

    def __init__(self, roidb: list, cfg: Config, batch_size: int = 1,
                 prefetch: Optional[int] = None,
                 device_prep: bool = False):
        self.roidb = roidb
        if getattr(cfg.tpu, "DEVICE_PREP", False) and not device_prep:
            # opt-in per loader: a train cfg with DEVICE_PREP on reaches
            # here from drivers whose consumer installs no prep hook
            # (proposal dumps, bench oracles) — those stay on the
            # bit-identical host transform.  ``device_prep=True`` (test.py
            # --device-prep) keeps the sidecars; the Predictor's
            # ``batch_put`` then preps on device (same jitted kernel and
            # host-bilinear parity pin as train; mesh plans raise at
            # Predictor construction).
            import dataclasses as _dc

            cfg = _dc.replace(cfg, tpu=_dc.replace(cfg.tpu,
                                                   DEVICE_PREP=False))
        self.cfg = cfg
        self.batch_size = batch_size
        # prefetch depth override: the overlapped evaluator keeps more
        # batches in flight than the train default assumes, so the decode
        # pipeline must stay ahead of the wider dispatch window
        self.prefetch = (int(prefetch) if prefetch is not None
                         else cfg.tpu.PREFETCH)
        # double-buffering hook (Predictor.batch_put): transfers the
        # device-bound keys from the prefetch thread, keeps indices/
        # batch_valid host-side
        self.put = None

    def __len__(self) -> int:
        n = len(self.roidb)
        return (n + self.batch_size - 1) // self.batch_size

    def __iter__(self):
        def produce():
            scale = self.cfg.tpu.SCALES[0]
            n = len(self.roidb)
            for start in range(0, n, self.batch_size):
                idx = list(range(start, min(start + self.batch_size, n)))
                pad = self.batch_size - len(idx)
                samples = [_load_record(self.roidb[i], self.cfg, scale)
                           for i in idx]
                samples += [samples[-1]] * pad
                batch = _stack(samples)
                batch["indices"] = np.asarray(idx + [idx[-1]] * pad, np.int32)
                batch["batch_valid"] = np.asarray([True] * len(idx) + [False] * pad)
                yield batch

        # strict loads by design (no fault isolation): a silently
        # substituted record would corrupt the eval metric
        return iter(_Prefetcher(
            produce(), self.prefetch, put=self.put,
            watchdog_s=self.cfg.tpu.PREFETCH_WATCHDOG_S))


class ROIIter:
    """Fast-RCNN training loader over cached proposals (reference
    ``ROIIter`` — alternate-training steps 3/6).  Each roidb record carries a
    ``proposals`` (P, 4) array dumped by ``eval.generate_proposals``; they
    are padded/truncated to ``cfg.TRAIN.RPN_POST_NMS_TOP_N`` rows and
    sampled in-graph by ``rcnn_train``."""

    def __init__(self, roidb: list, cfg: Config, batch_size: int,
                 shuffle: bool = True, seed: int = 0,
                 num_parts: int = 1, part_index: int = 0):
        self._inner = AnchorLoader(roidb, cfg, batch_size, shuffle, seed,
                                   num_parts=num_parts, part_index=part_index)
        self.cfg = cfg
        self.batch_size = batch_size
        self.num_parts = num_parts
        self.part_index = part_index
        self.put = None  # same double-buffering hook as AnchorLoader
        self.wrap = None  # same producer-thread group-assembly hook
        cap = cfg.TRAIN.RPN_POST_NMS_TOP_N
        over = sum(len(r.get("proposals", ())) > cap for r in roidb)
        if over:
            from mx_rcnn_tpu.logger import logger

            logger.warning(
                "%d/%d images carry more than TRAIN.RPN_POST_NMS_TOP_N=%d "
                "proposals; ROIIter keeps the FIRST %d rows — fine for "
                "score-sorted RPN caches, lossy for unranked sources like "
                "selective search (raise the cap if the tail matters)",
                over, len(roidb), cap, cap)

    def __len__(self) -> int:
        return len(self._inner)

    @property
    def steps_per_epoch(self) -> int:
        return len(self._inner)

    def advance_epochs(self, n: int) -> None:
        self._inner.advance_epochs(n)

    def skip_next(self, n: int) -> None:
        self._inner.skip_next(n)

    def close_workers(self):
        self._inner.close_workers()

    def __iter__(self):
        cfg = self.cfg
        p_max = cfg.TRAIN.RPN_POST_NMS_TOP_N
        # same per-batch scale-bucket plan as AnchorLoader (upstream samples
        # TRAIN.SCALES in the Fast-RCNN path too); proposals are in the
        # original image frame and rescale by each batch's own im_scale
        plan = self._inner._take_epoch_plan()
        pool = self._inner._ensure_pool()
        roidb = self._inner.roidb
        bl = self.batch_size // self.num_parts

        def produce():
            samples = []
            for j, s in _iter_samples(roidb, cfg, plan, self._inner._part,
                                      pool):
                # the substituted index pairs the sample with ITS OWN
                # proposals — mixing record j's pixels with record i's
                # rois would train on garbage.  Proposal attach stays in
                # the parent (workers ship pixels + gt only; proposal
                # arrays live in the parent's roidb either way)
                rec = roidb[j]
                props = np.asarray(rec.get("proposals",
                                           np.zeros((0, 4))), np.float32)
                rois = np.zeros((p_max, 4), np.float32)
                rvalid = np.zeros((p_max,), bool)
                n = min(len(props), p_max)
                if n:
                    rois[:n] = props[:n] * s["im_info"][2]
                    rvalid[:n] = True
                s["rois"] = rois
                s["roi_valid"] = rvalid
                samples.append(s)
                if len(samples) == bl:
                    yield _stack(samples)
                    samples = []

        gen = produce()
        if self.wrap is not None:
            gen = self.wrap(gen)
        return iter(_Prefetcher(gen, cfg.tpu.PREFETCH, put=self.put,
                                watchdog_s=cfg.tpu.PREFETCH_WATCHDOG_S))
