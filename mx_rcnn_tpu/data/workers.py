"""Multi-worker host input pipeline: process pool + shared-memory handover.

The device step is heavily optimized (chain dispatch, donated state, bf16,
host s2d), but every pixel still used to be decoded/resized/normalized by
ONE Python producer thread (``loader._Prefetcher``) — the ``loader_wait``
the PR-1 telemetry exposes.  This module scales that hot path across
``cfg.tpu.LOADER_WORKERS`` OS processes, the same producer/consumer
decoupling tf.data and PyTorch's multi-worker DataLoader exist for:

* Each worker runs the per-sample hot path (``_load_record_isolated`` /
  ``prepare_image``: imread, resize, normalize, flip, bucket pad, host
  s2d) and writes the finished pixel array into a preallocated
  ``multiprocessing.shared_memory`` ring slot — ZERO pickle copies for
  pixel data; only small metadata (im_info, gt targets, shapes) crosses
  the result queue.  Under ``cfg.tpu.DEVICE_PREP`` the "pixels" are the
  raw uint8 staging buffer instead (``stage_raw_to_bucket``) — same
  ``images`` key, same bucket extents, strictly smaller than the float
  slot the ring is sized for — and the prep sidecars (``raw_hw``,
  ``prep_ratio``, ``flip``) ride the metadata path; nothing here is
  shape- or dtype-special-cased for it.
* The parent's order-preserving collector hands samples back IN TASK
  ORDER regardless of worker skew, so batches assemble exactly as the
  serial producer would have built them and the existing prefetch queue /
  ``device_put`` double-buffering hooks run unchanged downstream.

Determinism is load-bearing: all RNG (shuffle, scale choice, wrap
padding, flip plan) stays in the loader's seeded epoch plan on the
consumer side; workers are pure functions of (roidb index, scale).  Tasks
are sharded to workers by sequence number (``seq % N``), so the schedule
— and therefore ``advance_epochs``/``skip_next`` exact mid-epoch resume —
is identical with workers on or off, batch for batch.

Fault isolation mirrors the PR-2 bad-record contract: a crashed worker
(segfault, OOM-kill) is respawned with a fresh task queue and its
in-flight tasks reissued (``loader/worker_respawn`` counter); crossing
``MAX_WORKER_RESPAWNS`` marks the pool broken and raises — systemic
breakage must not silently grind on respawns.  Bad records inside a
worker keep the per-producer consecutive-failure budget and surface the
same systemic RuntimeError through the result queue.

Telemetry (active sink only): ``loader/assembly_wait`` (collector blocked
on the next in-order sample = workers are the bottleneck),
``loader/worker_busy`` (fraction of workers with work in flight),
per-worker ``loader/worker{N}/produce`` spans (skew triage), and the
``loader/bad_record`` / ``loader/worker_respawn`` recovery counters.
``scripts/telemetry_report.py`` folds all of these.

The serve engine reuses the same pool for ``prepare_image`` (the caller-
thread resize is the serving ingest bottleneck at high offered load):
``prepare()`` ships the raw image in by pickle (small, uint8) and the
prepared float32 bucket array back through the shm ring.
"""

from __future__ import annotations

import collections
import os
import queue
import threading
import time
import traceback
from multiprocessing import shared_memory
from typing import Iterable, List, Optional, Tuple

import numpy as np

from mx_rcnn_tpu import telemetry
from mx_rcnn_tpu.logger import logger

# Total respawns a pool tolerates before declaring the breakage systemic
# (a worker that dies on every task would otherwise respawn forever).
# Module-level so tests/operators can widen it — the MAX_CONSECUTIVE_BAD_
# RECORDS recipe.
MAX_WORKER_RESPAWNS = 8

# Fault injection (tests / script smoke): crash a worker with os._exit(3)
# when it is asked to load this roidb index...
_ENV_CRASH_IDX = "MXR_FAULT_WORKER_CRASH_IDX"
# ...unless this marker file already exists (created atomically by the
# first crash) — "crash exactly once", the respawn-recovers case.
_ENV_CRASH_ONCE = "MXR_FAULT_WORKER_CRASH_ONCE"
# "worker_id:seconds" — that worker sleeps per task (slow-worker skew).
_ENV_SLOW = "MXR_FAULT_WORKER_SLOW"


def _mp_context():
    """fork where available (Linux: no re-import, roidb shared COW),
    overridable via MXR_LOADER_MP_START for spawn-only platforms."""
    import multiprocessing as mp

    method = os.environ.get("MXR_LOADER_MP_START")
    if not method:
        method = ("fork" if "fork" in mp.get_all_start_methods()
                  else "spawn")
    return mp.get_context(method)


def slot_bytes_for(cfg) -> int:
    """Ring-slot size: the largest single prepared sample the config can
    emit — max over scale buckets of H*W*3 float32 bytes (host s2d
    regroups channels but conserves the element count, and portrait/
    landscape buckets have equal area)."""
    from mx_rcnn_tpu.data.image import bucket_shape

    stride = max(cfg.network.IMAGE_STRIDE, cfg.network.RPN_FEAT_STRIDE)
    best = 0
    for scale in cfg.tpu.SCALES:
        hb, wb = bucket_shape(scale, stride, landscape=True)
        best = max(best, hb * wb * 3 * 4)
    return best


def _attach_shm(name: str) -> shared_memory.SharedMemory:
    """Attach to the pool's segment WITHOUT registering it with this
    process's resource tracker: on 3.10 every attach registers for
    unlink-at-exit, so a worker exiting would tear the segment down (or
    at least warn) under the parent still using it (bpo-39959; fixed by
    track=False in 3.13)."""
    try:
        from multiprocessing import resource_tracker

        orig = resource_tracker.register
        resource_tracker.register = lambda *a, **k: None
    except Exception:  # pragma: no cover — tracker API is CPython detail
        return shared_memory.SharedMemory(name=name)
    try:
        return shared_memory.SharedMemory(name=name)
    finally:
        resource_tracker.register = orig


def _maybe_crash(index: int):
    """Env-driven hard-crash injection (see module constants)."""
    want = os.environ.get(_ENV_CRASH_IDX)
    if want is None or int(want) != int(index):
        return
    marker = os.environ.get(_ENV_CRASH_ONCE)
    if marker:
        try:  # atomic create: exactly one crash across all workers
            fd = os.open(marker, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            os.close(fd)
        except FileExistsError:
            return
    os._exit(3)


def _worker_main(worker_id: int, cfg, roidb, shm_name: str, slot_bytes: int,
                 task_q, result_q):
    """One decode/augment worker.  Pure consumer of task messages
    ``(seq, kind, payload, scale, with_masks, slot)``:

    * kind "record": payload is a roidb index → ``_load_record_isolated``
      (bad-record substitution included), pixels into the shm slot,
      metadata (actual index, gt targets, im_info, produce span) back.
    * kind "image": payload is a raw RGB array (serving ingest) →
      ``prepare_image``, pixels into the slot, im_info back.

    None is the shutdown sentinel.
    """
    import signal

    # the parent handles SIGINT for everyone (a Ctrl-C must not kill the
    # workers before the parent decides whether to checkpoint), and a
    # forked child must not run the parent's preemption handlers
    signal.signal(signal.SIGINT, signal.SIG_IGN)
    try:
        signal.signal(signal.SIGTERM, signal.SIG_DFL)
    except (OSError, ValueError):  # pragma: no cover
        pass
    try:
        import cv2

        cv2.setNumThreads(0)  # N workers × cv2's own pool oversubscribes
    except Exception:
        pass
    # a fork inherits the parent's open telemetry stream — a worker
    # writing (or closing) it would interleave garbage into the JSONL
    telemetry.reset_null()

    from mx_rcnn_tpu.data import loader as loader_mod

    shm = _attach_shm(shm_name)
    fail_state = [0]  # consecutive bad records, per worker (PR-2 budget)
    slow_s = 0.0
    slow = os.environ.get(_ENV_SLOW)
    if slow:
        wid, _, sec = slow.partition(":")
        if int(wid) == worker_id:
            slow_s = float(sec)
    try:
        while True:
            msg = task_q.get()
            if msg is None:
                return
            seq, kind, payload, scale, with_masks, slot = msg
            t0 = time.perf_counter()
            try:
                if slow_s:
                    time.sleep(slow_s)
                if kind == "record":
                    index = int(payload)
                    _maybe_crash(index)
                    j, sample = loader_mod._load_record_isolated(
                        roidb, index, cfg, scale, with_masks=with_masks,
                        state=fail_state)
                    img = sample.pop("images")
                    meta = {"index": j, "sample": sample,
                            "bad": (j - index) % len(roidb)}
                else:  # "image" (serving ingest)
                    img, im_info = loader_mod.prepare_image(
                        np.asarray(payload), cfg, scale)
                    meta = {"im_info": im_info, "bad": 0}
                view = np.ndarray(
                    img.shape, img.dtype,
                    buffer=shm.buf[slot * slot_bytes:
                                   slot * slot_bytes + img.nbytes])
                view[...] = img
                meta["shape"] = tuple(img.shape)
                meta["dtype"] = img.dtype.str
                meta["dur_s"] = time.perf_counter() - t0
                result_q.put(("ok", seq, worker_id, meta))
            except BaseException as e:  # surfaced at the collector
                result_q.put(("err", seq, worker_id,
                              f"{type(e).__name__}: {e}\n"
                              f"{traceback.format_exc()}"))
    finally:
        shm.close()


class _Pending:
    __slots__ = ("worker", "msg", "done", "meta", "error")

    def __init__(self, worker: int, msg: tuple):
        self.worker = worker
        self.msg = msg
        self.done = False
        self.meta = None
        self.error: Optional[str] = None


class WorkerPool:
    """``num_workers`` decode/augment processes over one shared-memory
    slot ring.  One pool per loader (or serve engine); epochs REUSE the
    pool — slots cycle, the segment is allocated exactly once and
    unlinked at ``close()``.

    ``roidb`` may be None for image-only pools (serving)."""

    def __init__(self, cfg, roidb: Optional[list] = None,
                 num_workers: int = 1, n_slots: Optional[int] = None,
                 max_respawns: Optional[int] = None):
        if num_workers < 1:
            raise ValueError(f"num_workers must be >= 1, got {num_workers}")
        self.cfg = cfg
        self.roidb = roidb
        self.num_workers = int(num_workers)
        self.slot_bytes = slot_bytes_for(cfg)
        # in-flight window: enough for every worker to be busy with one
        # task and have the next queued, plus headroom for out-of-order
        # completions parked at the collector
        self.n_slots = int(n_slots) if n_slots else max(
            2 * self.num_workers + 2, 4)
        self.max_respawns = (MAX_WORKER_RESPAWNS if max_respawns is None
                             else int(max_respawns))
        self._ctx = _mp_context()
        self._shm = shared_memory.SharedMemory(
            create=True, size=self.n_slots * self.slot_bytes)
        self._result_q = self._ctx.Queue()
        self._task_qs = [self._ctx.Queue() for _ in range(self.num_workers)]
        self._free: queue.Queue = queue.Queue()
        for s in range(self.n_slots):
            self._free.put(s)
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._pending: dict = {}  # seq -> _Pending
        self._seq = 0
        self._closed = False
        self._broken: Optional[BaseException] = None
        self.respawns = 0
        self._procs = [self._spawn(w) for w in range(self.num_workers)]
        self._collector = threading.Thread(target=self._collect_loop,
                                           name="loader-pool-collector",
                                           daemon=True)
        self._collector.start()

    # -- lifecycle -------------------------------------------------------

    def _spawn(self, worker_id: int):
        p = self._ctx.Process(
            target=_worker_main,
            args=(worker_id, self.cfg, self.roidb, self._shm.name,
                  self.slot_bytes, self._task_qs[worker_id], self._result_q),
            name=f"loader-worker-{worker_id}", daemon=True)
        p.start()
        return p

    def close(self, timeout: float = 5.0):
        """Stop workers, join the collector, free the shm segment.
        Idempotent; safe from ``__del__``."""
        with self._cond:
            if self._closed:
                return
            self._closed = True
            self._cond.notify_all()
        for q_ in self._task_qs:
            try:
                q_.put(None)
            except (ValueError, OSError):
                pass
        for p in self._procs:
            p.join(timeout=timeout)
            if p.is_alive():
                p.terminate()
                p.join(timeout=1.0)
        if self._collector.is_alive():
            self._collector.join(timeout=timeout)
        for q_ in self._task_qs + [self._result_q]:
            try:
                q_.close()
                q_.cancel_join_thread()
            except (ValueError, OSError):
                pass
        try:
            self._shm.close()
            self._shm.unlink()
        except FileNotFoundError:
            pass

    def __del__(self):  # no join storms in GC — close() bounds every wait
        try:
            self.close(timeout=1.0)
        except Exception:
            pass

    # -- submission / collection ----------------------------------------

    def _take_slot(self) -> int:
        while True:
            try:
                return self._free.get(timeout=0.2)
            except queue.Empty:
                with self._lock:
                    if self._broken is not None:
                        raise RuntimeError(str(self._broken))
                    if self._closed:
                        raise RuntimeError("worker pool closed")

    def _submit(self, kind: str, payload, scale, with_masks: bool) -> int:
        slot = self._take_slot()  # blocks: bounds in-flight to n_slots
        with self._cond:
            if self._broken is not None:
                self._free.put(slot)
                raise RuntimeError(str(self._broken))
            if self._closed:
                self._free.put(slot)
                raise RuntimeError("worker pool closed")
            seq = self._seq
            self._seq += 1
            # deterministic shard-by-index: the same plan always lands on
            # the same workers, so worker-local state (bad-record budget)
            # and failure attribution are reproducible
            w = seq % self.num_workers
            msg = (seq, kind, payload, tuple(scale), bool(with_masks), slot)
            self._pending[seq] = _Pending(w, msg)
        self._task_qs[w].put(msg)
        return seq

    def _wait(self, seq: int) -> Tuple[np.ndarray, dict]:
        """Block for ticket ``seq``; copy its pixels out of the ring slot,
        recycle the slot, return (pixels, metadata)."""
        tel = telemetry.get()
        t0 = time.perf_counter()
        with self._cond:
            while True:
                t = self._pending.get(seq)
                if t is None:
                    raise RuntimeError(f"unknown pool ticket {seq}")
                if t.done:
                    del self._pending[seq]
                    break
                if self._broken is not None:
                    raise RuntimeError(str(self._broken))
                if not self._cond.wait(timeout=0.5):
                    self._check_workers_locked()
            if tel.enabled:
                in_flight = {p.worker for p in self._pending.values()
                             if not p.done}
                tel.gauge("loader/worker_busy",
                          len(in_flight) / self.num_workers)
        slot = t.msg[5]
        if t.error is not None:
            self._free.put(slot)
            raise RuntimeError(
                f"loader worker {t.worker} task failed: {t.error}")
        meta = t.meta
        view = np.ndarray(
            meta["shape"], np.dtype(meta["dtype"]),
            buffer=self._shm.buf[slot * self.slot_bytes:
                                 slot * self.slot_bytes + self.slot_bytes])
        img = np.array(view, copy=True)  # slot freed below — must own
        self._free.put(slot)
        if tel.enabled:
            dt_wait = time.perf_counter() - t0
            tel.add("loader/assembly_wait", dt_wait)
            tel.observe("loader/assembly_wait", dt_wait)
            tel.add(f"loader/worker{t.worker}/produce", meta["dur_s"])
            if meta.get("bad"):
                tel.counter("loader/bad_record", meta["bad"])
        return img, meta

    def _collect_loop(self):
        while True:
            with self._lock:
                if self._closed:
                    return
            try:
                status, seq, worker_id, payload = self._result_q.get(
                    timeout=0.2)
            except queue.Empty:
                with self._cond:
                    self._check_workers_locked()
                continue
            except (ValueError, OSError):  # queue closed mid-shutdown
                return
            with self._cond:
                t = self._pending.get(seq)
                if t is None or t.done:
                    continue  # stale (reissued task raced its original)
                t.done = True
                if status == "ok":
                    t.meta = payload
                else:
                    t.error = payload
                self._cond.notify_all()

    def _check_workers_locked(self):
        """Respawn dead workers and reissue their in-flight tasks (fresh
        task queue — the dead worker's queue may still hold unread tasks,
        and reissuing into it would duplicate seqs).  Called under the
        condition lock from both the collector and blocked waiters."""
        if self._closed or self._broken is not None:
            return
        for w, p in enumerate(self._procs):
            if p.is_alive():
                continue
            lost = sorted(s for s, t in self._pending.items()
                          if t.worker == w and not t.done)
            self.respawns += 1
            telemetry.get().counter("loader/worker_respawn")
            if self.respawns > self.max_respawns:
                err = RuntimeError(
                    f"loader worker {w} died (exit {p.exitcode}) and the "
                    f"pool exceeded {self.max_respawns} respawns — this "
                    f"looks systemic (OOM-killed decode? poisoned "
                    f"record crashing native code?), not a stray fault")
                self._broken = err
                telemetry.get().dump_flight(
                    "loader_systemic", worker=w, exitcode=p.exitcode,
                    respawns=self.respawns,
                    max_respawns=self.max_respawns)
                for s in lost:
                    self._pending[s].done = True
                    self._pending[s].error = str(err)
                self._cond.notify_all()
                return
            logger.warning(
                "loader worker %d died (exit %s) — respawning, reissuing "
                "%d in-flight task(s) [loader/worker_respawn]",
                w, p.exitcode, len(lost))
            self._task_qs[w] = self._ctx.Queue()
            self._procs[w] = self._spawn(w)
            for s in lost:
                self._task_qs[w].put(self._pending[s].msg)

    # -- high-level APIs -------------------------------------------------

    def imap_records(self, tasks: Iterable[Tuple[int, tuple]],
                     with_masks: bool = False):
        """Ordered map over ``(roidb_index, scale)`` tasks: yields
        ``(actual_index, sample)`` — the ``_load_record_isolated``
        contract — IN TASK ORDER, keeping up to ``n_slots`` tasks in
        flight.  Out-of-order completions park at the collector; the
        oldest outstanding task is always either queued on, or being run
        by, its (deterministically assigned) worker, so order-preserving
        assembly cannot deadlock."""
        tasks = list(tasks)
        tickets: collections.deque = collections.deque()
        i = 0
        try:
            while tickets or i < len(tasks):
                while i < len(tasks) and len(tickets) < self.n_slots:
                    idx, scale = tasks[i]
                    tickets.append(
                        self._submit("record", int(idx), scale, with_masks))
                    i += 1
                img, meta = self._wait(tickets.popleft())
                sample = meta["sample"]
                sample["images"] = img
                yield meta["index"], sample
        finally:
            # abandoned mid-epoch (consumer closed the prefetcher): drain
            # outstanding tickets so their ring slots return to the free
            # list — the pool outlives the epoch and must not bleed slots
            while tickets:
                try:
                    self._wait(tickets.popleft())
                except Exception:
                    pass

    def prepare(self, image: np.ndarray, scale) -> Tuple[np.ndarray,
                                                         np.ndarray]:
        """Serving ingest: run ``data.prepare_image`` in a worker process
        (raw uint8 in via the task queue, prepared float32 back through
        the shm ring).  Thread-safe; blocks the calling thread only."""
        seq = self._submit("image", np.ascontiguousarray(image), scale,
                           False)
        img, meta = self._wait(seq)
        return img, meta["im_info"]
