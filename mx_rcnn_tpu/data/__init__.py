"""Data layer — the reference's ``rcnn/io`` + ``rcnn/dataset`` +
``rcnn/core/loader.py`` tier, rebuilt for static XLA shapes:

* datasets (``imdb.py``/``pascal_voc.py``/``coco_dataset.py``) keep the
  reference's roidb contract;
* image IO (``image.py``) resizes shortest-side to scale and pads to a
  static bucket shape (replacing MutableModule executor rebinding);
* ``loader.py`` assembles padded host batches and double-buffers them to
  the device — anchor/RoI target assignment happens *in-graph* (ops layer),
  so the loader ships only images + padded gt.
"""

from mx_rcnn_tpu.data.image import get_image, transform_image, resize_to_bucket
from mx_rcnn_tpu.data.imdb import IMDB
from mx_rcnn_tpu.data.loader import (AnchorLoader, TestLoader, ROIIter,
                                     prepare_image)
from mx_rcnn_tpu.data.replay import ReplayDataset, load_replay_pixels
from mx_rcnn_tpu.data.synthetic import SyntheticDataset
