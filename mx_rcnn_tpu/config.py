"""Configuration system.

The reference keeps a global mutable ``easydict`` tree (``rcnn/config.py``:
``config``, ``default``, ``generate_config(network, dataset)``) that every
layer reads.  Field names and default values below deliberately preserve the
reference's, so a user of the reference can audit them one-to-one — but the
container is a frozen dataclass tree: immutable, hashable (so it can be a
static argument to ``jax.jit``), and assembled by a pure ``generate_config``
instead of in-place mutation.

Reference parity notes
----------------------
* ``TrainConfig`` mirrors ``config.TRAIN.*`` (BATCH_ROIS=128,
  FG_FRACTION=0.25, RPN_* anchor/NMS params, bbox normalization
  means/stds, END2END flag).
* ``TestConfig`` mirrors ``config.TEST.*`` (RPN_PRE/POST_NMS_TOP_N,
  NMS=0.3, max_per_image).
* ``generate_config(network, dataset)`` applies the network/dataset preset
  dicts exactly like the reference's, returning a new frozen config.
* TPU-specific additions are grouped in their own fields and documented as
  such (scale buckets replacing ``MutableModule`` rebinding, MAX_GT padding,
  mesh axes) — they are additive, not renames.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field, replace
from typing import Tuple


@dataclass(frozen=True)
class TrainConfig:
    """Mirrors reference ``config.TRAIN``."""

    # whether to train RPN+RCNN jointly (train_end2end.py) or staged
    END2END: bool = True
    # scale-jitter: pick a random scale index per image (reference: single scale)
    SHUFFLE: bool = True
    FLIP: bool = True

    # images per device-step (reference: per-GPU batch from --ctx split)
    BATCH_IMAGES: int = 1
    # R-CNN sampled RoIs per image
    BATCH_ROIS: int = 128
    FG_FRACTION: float = 0.25
    FG_THRESH: float = 0.5
    BG_THRESH_HI: float = 0.5
    BG_THRESH_LO: float = 0.0

    # bbox regression target normalization (folded into weights at save time,
    # see train/checkpoint.py — same contract as reference do_checkpoint)
    BBOX_NORMALIZATION_PRECOMPUTED: bool = True
    BBOX_MEANS: Tuple[float, float, float, float] = (0.0, 0.0, 0.0, 0.0)
    BBOX_STDS: Tuple[float, float, float, float] = (0.1, 0.1, 0.2, 0.2)

    # RPN anchor target assignment
    RPN_FG_FRACTION: float = 0.5
    RPN_BATCH_SIZE: int = 256
    RPN_POSITIVE_OVERLAP: float = 0.7
    RPN_NEGATIVE_OVERLAP: float = 0.3
    RPN_CLOBBER_POSITIVES: bool = False
    RPN_ALLOWED_BORDER: int = 0
    # Opt-in: store the (N, G) anchor-IoU matrix in bf16 before its three
    # reduction passes (max/argmax per anchor, max per gt), halving the HBM
    # traffic that dominates assign cost at FPN's 155 520 anchors.  IoU is
    # still COMPUTED in f32 (the cast fuses into the producer); only the
    # stored matrix and the 0.7/0.3 threshold comparisons round to bf16
    # (~3 decimal digits → marginal anchors near the thresholds may flip
    # label, a statistical not systematic change).  Divergence-ledger
    # treatment (BASELINE.md): default OFF = exact reference semantics.
    RPN_ASSIGN_IOU_BF16: bool = False

    # RPN proposal generation (training-time Proposal op params)
    CXX_PROPOSAL: bool = True  # reference flag name; here: use Pallas kernel
    RPN_NMS_THRESH: float = 0.7
    RPN_PRE_NMS_TOP_N: int = 12000
    RPN_POST_NMS_TOP_N: int = 2000
    RPN_MIN_SIZE: int = 16

    # optimizer (reference train_end2end defaults)
    LR: float = 0.001
    LR_STEP: Tuple[int, ...] = (7,)  # epochs at which lr decays 10x
    LR_FACTOR: float = 0.1
    MOMENTUM: float = 0.9
    WD: float = 0.0005
    CLIP_GRADIENT: float = 5.0
    # momentum-accumulator storage dtype ("float32" | "bfloat16").  The
    # update is HBM-bandwidth-bound (every buffer read+written once per
    # step); bf16 storage halves the momentum traffic (measured −0.26 ms
    # device on the classic step).  Update math stays f32 (the trace is
    # upcast before g + mu*t), params stay f32 master weights — only the
    # stored trace rounds.  Default is "float32" — exact reference (MXNet
    # SGD) momentum semantics; the mini-VOC fixture A/B measured bf16
    # neutral (BASELINE.md round-3 divergence ledger) but fixture
    # neutrality cannot bound a VOC07/COCO regression.  The SPEED half of
    # the claim is now a one-flag measurement — ``python bench.py --mode
    # train --opt-acc-ab`` runs the chain bench under both dtypes and
    # emits f32/bf16 ms/step plus ``delta_ms_per_step`` in one JSON row —
    # so bf16 stays opt-in until that A/B on real TPU hardware plus a
    # real-dataset accuracy run pins (or retires) the −0.26 ms figure.
    OPT_ACC_DTYPE: str = "float32"
    WARMUP: bool = False
    WARMUP_LR: float = 0.0
    WARMUP_STEP: int = 0

    # Mask R-CNN
    MASK_SIZE: int = 28


@dataclass(frozen=True)
class TestConfig:
    """Mirrors reference ``config.TEST``."""

    HAS_RPN: bool = True
    BATCH_IMAGES: int = 1
    CXX_PROPOSAL: bool = True
    RPN_NMS_THRESH: float = 0.7
    RPN_PRE_NMS_TOP_N: int = 6000
    RPN_POST_NMS_TOP_N: int = 300
    RPN_MIN_SIZE: int = 16
    # final per-class detection NMS
    NMS: float = 0.3
    # score threshold applied in pred_eval
    THRESH: float = 1e-3
    MAX_PER_IMAGE: int = 100
    # proposal-file path mode for alternate training (ROIIter)
    PROPOSAL: str = "rpn"
    # mask eval paste+RLE strategy (all three agree to ulp-at-threshold;
    # measured round 4, tunnel-attached v5e, 100-det worst case):
    #   "native": ship (R,28,28) probabilities (~313 KB/img), fused C++
    #       separable paste+RLE (no full-frame materialization) — host
    #       ~10-25 ms/img, smallest transfer; the default.
    #   "device": MXU separable paste + bit-pack on chip, ONE packed
    #       bitplane readback (~6.6 MB/img) + C++ RLE — host ~8 ms/img;
    #       wins when the chip-host link is fast and the host is weak.
    #   "host": the reference's per-detection cv2 paste (~150 ms/img) —
    #       the behavioral oracle and the no-native-lib fallback.
    MASK_PASTE: str = "native"


@dataclass(frozen=True)
class NetworkConfig:
    """Mirrors the reference's per-network preset dict
    (``config.py: network.vgg / network.resnet``)."""

    NETWORK: str = "resnet50"
    # ImageNet pretrained checkpoint (converted .npz; see utils/load_model.py)
    PRETRAINED: str = "model/pretrained"
    PRETRAINED_EPOCH: int = 0
    PIXEL_MEANS: Tuple[float, float, float] = (123.68, 116.779, 103.939)
    PIXEL_STDS: Tuple[float, float, float] = (1.0, 1.0, 1.0)
    IMAGE_STRIDE: int = 32
    RPN_FEAT_STRIDE: int = 16
    RCNN_FEAT_STRIDE: int = 16
    FIXED_PARAMS: Tuple[str, ...] = ("conv1", "bn1", "stage1", "gamma", "beta")
    FIXED_PARAMS_SHARED: Tuple[str, ...] = ("conv1", "bn1", "stage1", "stage2", "stage3", "gamma", "beta")
    ANCHOR_SCALES: Tuple[int, ...] = (8, 16, 32)
    ANCHOR_RATIOS: Tuple[float, ...] = (0.5, 1.0, 2.0)
    # FPN (capability target per BASELINE.json configs 4-5; not in classic ref)
    HAS_FPN: bool = False
    # host-side 2x2 space-to-depth: the loader ships images as
    # (H/2, W/2, 12) so the stem's s2d regroup costs zero device time
    # (~1 ms/step of lane-hostile transposes otherwise); ResNet stems only
    HOST_S2D: bool = False
    FPN_FEAT_STRIDES: Tuple[int, ...] = (4, 8, 16, 32, 64)
    FPN_ANCHOR_SCALES: Tuple[int, ...] = (8,)
    FPN_OUT_CHANNELS: int = 256
    HAS_MASK: bool = False

    @property
    def NUM_ANCHORS(self) -> int:
        """Anchors per feature cell — derived, never stored, so it cannot
        drift from the scale/ratio tuples (FPN levels use one scale each)."""
        scales = self.FPN_ANCHOR_SCALES if self.HAS_FPN else self.ANCHOR_SCALES
        return len(self.ANCHOR_RATIOS) * len(scales)


@dataclass(frozen=True)
class DatasetConfig:
    """Mirrors the reference's per-dataset preset dict."""

    DATASET: str = "PascalVOC"
    IMAGE_SET: str = "2007_trainval"
    TEST_IMAGE_SET: str = "2007_test"
    ROOT_PATH: str = "data"
    DATASET_PATH: str = "data/VOCdevkit"
    NUM_CLASSES: int = 21  # includes __background__


@dataclass(frozen=True)
class TPUConfig:
    """TPU-native additions (no reference counterpart; documented divergence).

    The reference handles variable image sizes by rebinding executors
    (``rcnn/core/module.py: MutableModule``).  Under XLA we instead bucket
    images into a small set of static padded shapes; each bucket has one
    compiled program.
    """

    # (short_side, long_side) scale buckets; first is the reference SCALES[0]
    SCALES: Tuple[Tuple[int, int], ...] = ((600, 1000),)
    # padded max gt boxes per image
    MAX_GT: int = 100
    # data-parallel mesh axis name and DCN axis for multi-slice
    MESH_AXIS_DATA: str = "data"
    MESH_AXIS_MODEL: str = "model"
    # compute dtype for the backbone (params stay f32)
    COMPUTE_DTYPE: str = "bfloat16"
    # fused Pallas assign-IoU reductions (kernels/assign_pallas.py): the
    # (N, G) anchor-IoU matrix never materializes — IoU is recomputed per
    # tile on the fly (ULP-level f32 parity; ~100x less HBM traffic at
    # FPN's 155k anchors).  Auto-falls-back off-TPU and when MAX_GT > 128.
    # MEASURED AND REJECTED as the default (round 4, on-chip).  The gate
    # is green (check_pallas.py equivalence OK on TPU v5 lite) but the
    # kernel LOSES on device time: xplane-profiled FPN step 23.15 ms
    # fused vs 21.95 ms dense (r4_tpu_session3.log), matching the chained
    # standalone microbench (4.69 vs 2.75 ms @116736x100).  Wall-clock
    # train A/Bs that showed fused ahead (41.07 vs 38.33 imgs/s) did not
    # survive an interleaved repeat (39.15 vs 39.07) — tunnel-dispatch
    # weather, which is why device profile is the deciding instrument.
    # The recompute-per-tile traffic saving is real but the recompute
    # cost exceeds it at G=100; stays available as an opt-in and as a
    # libtpu-upgrade retry candidate.
    ASSIGN_FUSED: bool = False
    # ROIAlign samples per bin axis.  Classic configs default to 1: still
    # at-or-above the reference's integer-binned ROIPooling fidelity and
    # 1.8x faster end-to-end (4x fewer gather points).  FPN/Mask presets
    # get 2 via generate_config — Mask R-CNN paper parity for the mask head.
    # NOTE: affects numerics; train and eval must use the same value (any
    # consistent generate_config call does).
    ROI_SAMPLING_RATIO: int = 1
    # RoI pooling reduction: "avg" (ROIAlign paper / torchvision), "max"
    # (max over the same continuous sample grid), or "exact" — the
    # reference's integer-binned CUDA ROIPooling semantics
    # (rounded corners, overlapping integer bins, plain max, empty-bin
    # zeros; ops/roi_align.py:_roi_pool_exact).  "exact" is the transplant
    # mode: inference on MXNet-trained weights reproduces the op those
    # weights saw.  "avg"/"max" are identical at ROI_SAMPLING_RATIO=1;
    # the A/B ledger in BASELINE.md measures the deltas.
    ROI_MODE: str = "avg"
    # host→device prefetch depth
    PREFETCH: int = 2
    # overlapped eval (eval/pipeline.py): max batches dispatched-but-not-
    # post-processed; 2 = double-buffering (forward N+1 overlaps host
    # post-process N); 0 via --eval-inflight falls back to the serial
    # reference loop
    EVAL_INFLIGHT: int = 2
    # width of the eval host post-process thread pool (decode + per-class
    # NMS + mask paste); results are index-addressed so width never
    # changes the output
    EVAL_HOST_WORKERS: int = 2
    # consumer-side watchdog on the prefetch queue: no producer heartbeat
    # for this long raises a diagnostic naming the producer state instead
    # of the training loop hanging forever on a stuck filesystem read
    # (<= 0 disables)
    PREFETCH_WATCHDOG_S: float = 600.0
    # host input pipeline worker processes (data/workers.py): 0 (default)
    # keeps the single-thread producer, bit-identical to before the pool
    # existed; N > 0 fans the per-sample decode/resize/flip hot path over
    # N processes with shared-memory handover — same batches, same order,
    # any seed (the epoch plan is drawn once on the consumer and sharded
    # by index)
    LOADER_WORKERS: int = 0
    # rematerialize the backbone stages in the backward pass
    # (nn.remat on each ResNetStage): trades recompute FLOPs for HBM
    # traffic — the B>=16 lever for the measured relu-backward
    # compare_select slowdown once per-tensor working sets pass ~40 MB
    # (BASELINE.md batch-scaling table).  Param tree and numerics are
    # unchanged; off by default pending the on-chip A/B.
    REMAT_BACKBONE: bool = False
    # device-side preprocessing (data/device_prep.py): loaders emit raw
    # bucket-staged uint8 pixels and a jitted per-bucket program does
    # resize/flip/normalize/pad (and HOST_S2D) on device, overlapped with
    # the step via the prefetch thread.  Off (default) keeps the host
    # numpy path bit-identical to before the feature existed.  Train
    # loaders honor it directly; eval opts in per TestLoader
    # (test.py --device-prep → Predictor.batch_put preps on device); the
    # serve engine's fused equivalent is --serve-e2e.  Mesh plans raise —
    # host prep only there.
    DEVICE_PREP: bool = False
    # output dtype of the device preprocess program ("float32" or
    # "bfloat16") — the host path is float32-only
    DEVICE_PREP_DTYPE: str = "float32"


@dataclass(frozen=True)
class Config:
    """Root config. Frozen + hashable → usable as a jit static arg."""

    network: NetworkConfig = field(default_factory=NetworkConfig)
    dataset: DatasetConfig = field(default_factory=DatasetConfig)
    TRAIN: TrainConfig = field(default_factory=TrainConfig)
    TEST: TestConfig = field(default_factory=TestConfig)
    tpu: TPUConfig = field(default_factory=TPUConfig)

    @property
    def NUM_CLASSES(self) -> int:
        return self.dataset.NUM_CLASSES

    def replace(self, **kw) -> "Config":
        return replace(self, **kw)


# ---------------------------------------------------------------------------
# Preset registry — the analogue of the reference's `network` / `dataset`
# easydict preset blocks applied by generate_config().
# ---------------------------------------------------------------------------

_NETWORK_PRESETS = {
    "vgg16": dict(
        NETWORK="vgg16",
        IMAGE_STRIDE=0,
        RPN_FEAT_STRIDE=16,
        RCNN_FEAT_STRIDE=16,
        FIXED_PARAMS=("conv1", "conv2"),
        FIXED_PARAMS_SHARED=("conv1", "conv2", "conv3", "conv4", "conv5"),
        HAS_FPN=False,
    ),
    # classic resnet presets are generated below — one dict per depth,
    # identical apart from NETWORK (single source of truth)
    # FPN shared trunk = backbone stages 1-4 + the neck (lateral*/post* conv
    # names), so alternate-training rounds 2 keep ALL shared features frozen
    "resnet50_fpn": dict(
        NETWORK="resnet50",
        HOST_S2D=True,
        IMAGE_STRIDE=32,
        HAS_FPN=True,
        RCNN_FEAT_STRIDE=4,
        FPN_ANCHOR_SCALES=(8,),
        FIXED_PARAMS_SHARED=("conv1", "bn1", "stage1", "stage2", "stage3",
                             "stage4", "lateral", "post", "gamma", "beta"),
    ),
    "resnet101_fpn": dict(
        NETWORK="resnet101",
        HOST_S2D=True,
        IMAGE_STRIDE=32,
        HAS_FPN=True,
        RCNN_FEAT_STRIDE=4,
        FPN_ANCHOR_SCALES=(8,),
        FIXED_PARAMS_SHARED=("conv1", "bn1", "stage1", "stage2", "stage3",
                             "stage4", "lateral", "post", "gamma", "beta"),
    ),
    "resnet101_fpn_mask": dict(
        NETWORK="resnet101",
        HOST_S2D=True,
        IMAGE_STRIDE=32,
        HAS_FPN=True,
        HAS_MASK=True,
        RCNN_FEAT_STRIDE=4,
        FPN_ANCHOR_SCALES=(8,),
        FIXED_PARAMS_SHARED=("conv1", "bn1", "stage1", "stage2", "stage3",
                             "stage4", "lateral", "post", "gamma", "beta"),
    ),
}

for _depth in ("resnet50", "resnet101", "resnet152"):
    _NETWORK_PRESETS[_depth] = dict(
        NETWORK=_depth,
        HOST_S2D=True,
        IMAGE_STRIDE=32,
        FIXED_PARAMS=("conv1", "bn1", "stage1", "gamma", "beta"),
        FIXED_PARAMS_SHARED=("conv1", "bn1", "stage1", "stage2", "stage3",
                             "gamma", "beta"),
    )

_DATASET_PRESETS = {
    "PascalVOC": dict(
        DATASET="PascalVOC",
        IMAGE_SET="2007_trainval",
        TEST_IMAGE_SET="2007_test",
        ROOT_PATH="data",
        DATASET_PATH="data/VOCdevkit",
        NUM_CLASSES=21,
    ),
    "PascalVOC0712": dict(
        DATASET="PascalVOC",
        IMAGE_SET="2007_trainval+2012_trainval",
        TEST_IMAGE_SET="2007_test",
        ROOT_PATH="data",
        DATASET_PATH="data/VOCdevkit",
        NUM_CLASSES=21,
    ),
    "coco": dict(
        DATASET="coco",
        IMAGE_SET="train2017",
        TEST_IMAGE_SET="val2017",
        ROOT_PATH="data",
        DATASET_PATH="data/coco",
        NUM_CLASSES=81,
    ),
}


def generate_config(network: str, dataset: str, **overrides) -> Config:
    """Build a frozen Config from network+dataset preset names.

    Same role as the reference's ``generate_config`` (rcnn/config.py), which
    mutates the global ``config``/``default`` easydicts in place; here it
    returns a fresh immutable tree.

    ``overrides`` may address nested fields with double-underscore paths,
    e.g. ``generate_config('resnet50', 'PascalVOC', TRAIN__BATCH_IMAGES=2)``.
    """
    if network not in _NETWORK_PRESETS:
        raise KeyError(f"unknown network '{network}'; have {sorted(_NETWORK_PRESETS)}")
    if dataset not in _DATASET_PRESETS:
        raise KeyError(f"unknown dataset '{dataset}'; have {sorted(_DATASET_PRESETS)}")

    net = NetworkConfig(**_NETWORK_PRESETS[network])
    ds = DatasetConfig(**_DATASET_PRESETS[dataset])
    train = TrainConfig()
    test = TestConfig()
    tpu = TPUConfig()

    # COCO schedules differ from VOC in the reference scripts
    if dataset == "coco":
        train = replace(train, LR_STEP=(6,), BATCH_ROIS=128)
        tpu = replace(tpu, SCALES=((800, 1333),))

    # FPN/Mask configs keep the Mask R-CNN paper's 2-sample ROIAlign
    if net.HAS_FPN:
        tpu = replace(tpu, ROI_SAMPLING_RATIO=2)

    cfg = Config(network=net, dataset=ds, TRAIN=train, TEST=test, tpu=tpu)

    # apply double-underscore-path overrides
    for key, val in overrides.items():
        parts = key.split("__")
        if len(parts) == 1:
            cfg = replace(cfg, **{parts[0]: val})
        elif len(parts) == 2:
            sub = getattr(cfg, parts[0])
            cfg = replace(cfg, **{parts[0]: replace(sub, **{parts[1]: val})})
        else:
            raise KeyError(f"override path too deep: {key}")
    return cfg


def list_networks():
    return sorted(_NETWORK_PRESETS)


def list_datasets():
    return sorted(_DATASET_PRESETS)


def config_to_dict(cfg: Config) -> dict:
    """Flatten for logging/serialization."""
    return dataclasses.asdict(cfg)
