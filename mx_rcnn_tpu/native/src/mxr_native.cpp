// Native CPU kernels for the host-side eval tier.
//
// TPU-native counterpart of the reference's in-repo native code
// (rcnn/cython/bbox.pyx, rcnn/cython/cpu_nms.pyx, and the vendored
// pycocotools C RLE ops in rcnn/pycocotools/maskApi.c — behavior
// re-implemented from the contracts pinned by tests/oracles, not copied).
// The TPU compute path never calls these; they serve pred_eval's per-class
// NMS and COCO mask IoU, which run on host.
//
// Exposed as extern "C" with raw pointers; loaded via ctypes
// (mx_rcnn_tpu/native/__init__.py). Build: `make -C mx_rcnn_tpu/native`.

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <numeric>
#include <vector>

extern "C" {

// (N,4) x (K,4) -> (N,K) IoU matrix, legacy +1 areas (bbox_overlaps).
void mxr_bbox_overlaps(const float* boxes, int64_t n, const float* query,
                       int64_t k, float* out) {
  for (int64_t j = 0; j < k; ++j) {
    const float qx1 = query[j * 4], qy1 = query[j * 4 + 1];
    const float qx2 = query[j * 4 + 2], qy2 = query[j * 4 + 3];
    const float qarea = (qx2 - qx1 + 1.f) * (qy2 - qy1 + 1.f);
    for (int64_t i = 0; i < n; ++i) {
      const float bx1 = boxes[i * 4], by1 = boxes[i * 4 + 1];
      const float bx2 = boxes[i * 4 + 2], by2 = boxes[i * 4 + 3];
      const float iw = std::min(bx2, qx2) - std::max(bx1, qx1) + 1.f;
      if (iw <= 0.f) { out[i * k + j] = 0.f; continue; }
      const float ih = std::min(by2, qy2) - std::max(by1, qy1) + 1.f;
      if (ih <= 0.f) { out[i * k + j] = 0.f; continue; }
      const float barea = (bx2 - bx1 + 1.f) * (by2 - by1 + 1.f);
      const float inter = iw * ih;
      out[i * k + j] = inter / (barea + qarea - inter);
    }
  }
}

// Greedy NMS over (N,5) [x1,y1,x2,y2,score]; writes kept indices to
// keep_out (caller allocates N), returns the kept count.
int64_t mxr_nms(const float* dets, int64_t n, float thresh,
                int64_t* keep_out) {
  std::vector<int64_t> order(n);
  std::iota(order.begin(), order.end(), 0);
  std::stable_sort(order.begin(), order.end(), [&](int64_t a, int64_t b) {
    return dets[a * 5 + 4] > dets[b * 5 + 4];
  });
  std::vector<char> removed(n, 0);
  std::vector<float> area(n);
  for (int64_t i = 0; i < n; ++i)
    area[i] = (dets[i * 5 + 2] - dets[i * 5] + 1.f) *
              (dets[i * 5 + 3] - dets[i * 5 + 1] + 1.f);
  int64_t kept = 0;
  for (int64_t oi = 0; oi < n; ++oi) {
    const int64_t i = order[oi];
    if (removed[i]) continue;
    keep_out[kept++] = i;
    const float ix1 = dets[i * 5], iy1 = dets[i * 5 + 1];
    const float ix2 = dets[i * 5 + 2], iy2 = dets[i * 5 + 3];
    for (int64_t oj = oi + 1; oj < n; ++oj) {
      const int64_t j = order[oj];
      if (removed[j]) continue;
      const float iw =
          std::min(ix2, dets[j * 5 + 2]) - std::max(ix1, dets[j * 5]) + 1.f;
      if (iw <= 0.f) continue;
      const float ih = std::min(iy2, dets[j * 5 + 3]) -
                       std::max(iy1, dets[j * 5 + 1]) + 1.f;
      if (ih <= 0.f) continue;
      const float inter = iw * ih;
      if (inter / (area[i] + area[j] - inter) > thresh) removed[j] = 1;
    }
  }
  return kept;
}

// Advance to the next run, skipping zero-length runs (each skipped run
// still toggles the value — an RLE starting with count 0 means the mask
// begins with foreground).
static inline void rle_advance(const uint32_t* c, int64_t nc, int64_t* i,
                               int64_t* cur, int* v, int64_t n) {
  do {
    ++*i;
    *cur = (*i < nc) ? (int64_t)c[*i] : n;
    *v ^= 1;
  } while (*cur == 0 && *i < nc);
}

// |A n B| for two column-major RLEs (counts arrays) over n pixels.
int64_t mxr_rle_intersect(const uint32_t* a, int64_t na, const uint32_t* b,
                          int64_t nb, int64_t n) {
  int64_t ia = 0, ib = 0, pos = 0, inter = 0;
  int64_t ca = na > 0 ? (int64_t)a[0] : n;
  int64_t cb = nb > 0 ? (int64_t)b[0] : n;
  int va = 0, vb = 0;
  if (ca == 0) rle_advance(a, na, &ia, &ca, &va, n);
  if (cb == 0) rle_advance(b, nb, &ib, &cb, &vb, n);
  while (pos < n) {
    const int64_t step = std::min(ca, cb);
    if (step <= 0) break;  // both exhausted (padding beyond counts)
    if (va && vb) inter += step;
    ca -= step; cb -= step; pos += step;
    if (ca == 0) rle_advance(a, na, &ia, &ca, &va, n);
    if (cb == 0) rle_advance(b, nb, &ib, &cb, &vb, n);
  }
  return inter;
}

// (D x G) RLE IoU with crowd semantics. Counts are flattened with offsets
// (CSR-style): d_counts/d_off (D+1), g_counts/g_off (G+1).
void mxr_rle_iou(const uint32_t* d_counts, const int64_t* d_off, int64_t D,
                 const uint32_t* g_counts, const int64_t* g_off, int64_t G,
                 const int64_t* d_area, const int64_t* g_area,
                 const uint8_t* g_crowd, int64_t n, double* out) {
  for (int64_t i = 0; i < D; ++i) {
    for (int64_t j = 0; j < G; ++j) {
      const int64_t inter =
          mxr_rle_intersect(d_counts + d_off[i], d_off[i + 1] - d_off[i],
                            g_counts + g_off[j], g_off[j + 1] - g_off[j], n);
      const double uni = g_crowd[j]
                             ? (double)d_area[i]
                             : (double)d_area[i] + g_area[j] - inter;
      out[i * G + j] = uni > 0 ? inter / uni : 0.0;
    }
  }
}

}  // extern "C"
