// Native CPU kernels for the host-side eval tier.
//
// TPU-native counterpart of the reference's in-repo native code
// (rcnn/cython/bbox.pyx, rcnn/cython/cpu_nms.pyx, and the vendored
// pycocotools C RLE ops in rcnn/pycocotools/maskApi.c — behavior
// re-implemented from the contracts pinned by tests/oracles, not copied).
// The TPU compute path never calls these; they serve pred_eval's per-class
// NMS and COCO mask IoU, which run on host.
//
// Exposed as extern "C" with raw pointers; loaded via ctypes
// (mx_rcnn_tpu/native/__init__.py). Build: `make -C mx_rcnn_tpu/native`.

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <numeric>
#include <vector>

extern "C" {

// (N,4) x (K,4) -> (N,K) IoU matrix, legacy +1 areas (bbox_overlaps).
void mxr_bbox_overlaps(const float* boxes, int64_t n, const float* query,
                       int64_t k, float* out) {
  for (int64_t j = 0; j < k; ++j) {
    const float qx1 = query[j * 4], qy1 = query[j * 4 + 1];
    const float qx2 = query[j * 4 + 2], qy2 = query[j * 4 + 3];
    const float qarea = (qx2 - qx1 + 1.f) * (qy2 - qy1 + 1.f);
    for (int64_t i = 0; i < n; ++i) {
      const float bx1 = boxes[i * 4], by1 = boxes[i * 4 + 1];
      const float bx2 = boxes[i * 4 + 2], by2 = boxes[i * 4 + 3];
      const float iw = std::min(bx2, qx2) - std::max(bx1, qx1) + 1.f;
      if (iw <= 0.f) { out[i * k + j] = 0.f; continue; }
      const float ih = std::min(by2, qy2) - std::max(by1, qy1) + 1.f;
      if (ih <= 0.f) { out[i * k + j] = 0.f; continue; }
      const float barea = (bx2 - bx1 + 1.f) * (by2 - by1 + 1.f);
      const float inter = iw * ih;
      out[i * k + j] = inter / (barea + qarea - inter);
    }
  }
}

// Greedy NMS over (N,5) [x1,y1,x2,y2,score]; writes kept indices to
// keep_out (caller allocates N), returns the kept count.
int64_t mxr_nms(const float* dets, int64_t n, float thresh,
                int64_t* keep_out) {
  std::vector<int64_t> order(n);
  std::iota(order.begin(), order.end(), 0);
  std::stable_sort(order.begin(), order.end(), [&](int64_t a, int64_t b) {
    return dets[a * 5 + 4] > dets[b * 5 + 4];
  });
  std::vector<char> removed(n, 0);
  std::vector<float> area(n);
  for (int64_t i = 0; i < n; ++i)
    area[i] = (dets[i * 5 + 2] - dets[i * 5] + 1.f) *
              (dets[i * 5 + 3] - dets[i * 5 + 1] + 1.f);
  int64_t kept = 0;
  for (int64_t oi = 0; oi < n; ++oi) {
    const int64_t i = order[oi];
    if (removed[i]) continue;
    keep_out[kept++] = i;
    const float ix1 = dets[i * 5], iy1 = dets[i * 5 + 1];
    const float ix2 = dets[i * 5 + 2], iy2 = dets[i * 5 + 3];
    for (int64_t oj = oi + 1; oj < n; ++oj) {
      const int64_t j = order[oj];
      if (removed[j]) continue;
      const float iw =
          std::min(ix2, dets[j * 5 + 2]) - std::max(ix1, dets[j * 5]) + 1.f;
      if (iw <= 0.f) continue;
      const float ih = std::min(iy2, dets[j * 5 + 3]) -
                       std::max(iy1, dets[j * 5 + 1]) + 1.f;
      if (ih <= 0.f) continue;
      const float inter = iw * ih;
      if (inter / (area[i] + area[j] - inter) > thresh) removed[j] = 1;
    }
  }
  return kept;
}

// Advance to the next run, skipping zero-length runs (each skipped run
// still toggles the value — an RLE starting with count 0 means the mask
// begins with foreground).
static inline void rle_advance(const uint32_t* c, int64_t nc, int64_t* i,
                               int64_t* cur, int* v, int64_t n) {
  do {
    ++*i;
    *cur = (*i < nc) ? (int64_t)c[*i] : n;
    *v ^= 1;
  } while (*cur == 0 && *i < nc);
}

// |A n B| for two column-major RLEs (counts arrays) over n pixels.
int64_t mxr_rle_intersect(const uint32_t* a, int64_t na, const uint32_t* b,
                          int64_t nb, int64_t n) {
  int64_t ia = 0, ib = 0, pos = 0, inter = 0;
  int64_t ca = na > 0 ? (int64_t)a[0] : n;
  int64_t cb = nb > 0 ? (int64_t)b[0] : n;
  int va = 0, vb = 0;
  if (ca == 0) rle_advance(a, na, &ia, &ca, &va, n);
  if (cb == 0) rle_advance(b, nb, &ib, &cb, &vb, n);
  while (pos < n) {
    const int64_t step = std::min(ca, cb);
    if (step <= 0) break;  // both exhausted (padding beyond counts)
    if (va && vb) inter += step;
    ca -= step; cb -= step; pos += step;
    if (ca == 0) rle_advance(a, na, &ia, &ca, &va, n);
    if (cb == 0) rle_advance(b, nb, &ib, &cb, &vb, n);
  }
  return inter;
}

// (D x G) RLE IoU with crowd semantics. Counts are flattened with offsets
// (CSR-style): d_counts/d_off (D+1), g_counts/g_off (G+1).
void mxr_rle_iou(const uint32_t* d_counts, const int64_t* d_off, int64_t D,
                 const uint32_t* g_counts, const int64_t* g_off, int64_t G,
                 const int64_t* d_area, const int64_t* g_area,
                 const uint8_t* g_crowd, int64_t n, double* out) {
  for (int64_t i = 0; i < D; ++i) {
    for (int64_t j = 0; j < G; ++j) {
      const int64_t inter =
          mxr_rle_intersect(d_counts + d_off[i], d_off[i + 1] - d_off[i],
                            g_counts + g_off[j], g_off[j + 1] - g_off[j], n);
      const double uni = g_crowd[j]
                             ? (double)d_area[i]
                             : (double)d_area[i] + g_area[j] - inter;
      out[i * G + j] = uni > 0 ? inter / uni : 0.0;
    }
  }
}

}  // extern "C"

// Streaming column-major RLE cursor: counts alternate 0-run/1-run starting
// with the leading-zero count (possibly 0) — the maskApi.c rleEncode
// contract.  Feed bits/constant spans in scan order; finish() closes the
// final run.
namespace {
struct RleCursor {
  uint32_t* out;
  int64_t nc = 0;
  uint64_t run = 0;
  int cur = 0;
  void flip() {
    out[nc++] = (uint32_t)run;
    run = 0;
    cur ^= 1;
  }
  void flat(int64_t n, int val) {  // n pixels of constant `val`
    if (n <= 0) return;
    if (cur != val) flip();
    run += (uint64_t)n;
  }
  void bits(uint64_t v, int nbits) {  // nbits LSB-first bits of v
    int off = 0;
    while (off < nbits) {
      const uint64_t t = (cur ? ~v : v) >> off;
      int step = t ? __builtin_ctzll(t) : 64;
      if (step > nbits - off) step = nbits - off;
      if (step == 0) {  // bit differs from cur: close the current run
        flip();
        continue;
      }
      run += (uint64_t)step;
      off += step;
    }
  }
  int64_t finish() {
    out[nc++] = (uint32_t)run;
    return nc;
  }
};

// Bilinear source row/column for cv2-style resize of an m-bin axis to
// `extent` pixels: pixel j samples src=(j+.5)*m/extent-.5 between bins
// i0/i0+1 (border-replicate clamp), weight f on the upper bin.
inline void lerp_coeff(int64_t j, float scale, int64_t m, int* a0, int* a1,
                       float* f) {
  const float src = ((float)j + 0.5f) * scale - 0.5f;
  const float fl = std::floor(src);
  *f = src - fl;
  int i0 = (int)fl;
  *a0 = i0 < 0 ? 0 : (i0 > m - 1 ? (int)m - 1 : i0);
  ++i0;
  *a1 = i0 < 0 ? 0 : (i0 > m - 1 ? (int)m - 1 : i0);
}
}  // namespace

extern "C" {

// Column-major COCO RLE encode of one bit-packed transposed mask
// (ops/mask_paste.py layout: w columns of Hp/8 bytes, bit y&7 of byte
// [x*Hp/8 + (y>>3)] = pixel (y, x), LSB-first; Hp % 64 == 0 so columns
// stream as little-endian u64 words).  Scans exactly h bits of the first
// w columns (padding pixels beyond h/w are never read).  Returns the
// count length; caller provides counts_out of at least h*w + 1.
int64_t mxr_rle_encode(const uint8_t* packed, int64_t hp, int64_t h,
                       int64_t w, uint32_t* counts_out) {
  RleCursor rc{counts_out};
  const int64_t col_bytes = hp / 8;
  for (int64_t x = 0; x < w; ++x) {
    const uint8_t* col = packed + x * col_bytes;
    int64_t rem = h;
    for (int64_t k = 0; rem > 0; ++k, rem -= 64) {
      uint64_t v;
      std::memcpy(&v, col + 8 * k, 8);
      rc.bits(v, rem < 64 ? (int)rem : 64);
    }
  }
  return rc.finish();
}

// Fused paste + RLE of ONE (m, m) mask probability map into the (h, w)
// full frame at box [x1,y1,x2,y2] — the tester.paste_mask contract
// (integer window [floor,ceil], cv2 bilinear, threshold >= 0.5) without
// ever materializing the frame: separable resize streams column by
// column, and everything outside the box is emitted as bulk zero spans.
// Per-column upper/lower interpolation bounds skip all-background /
// all-foreground columns without per-pixel work.  Returns the count
// length; counts_out needs h*w + 1 (worst case).
int64_t mxr_paste_rle(const float* prob, int64_t m, float x1, float y1,
                      float x2, float y2, int64_t h, int64_t w,
                      uint32_t* counts_out) {
  const int64_t xa = (int64_t)std::floor(x1), xb = (int64_t)std::ceil(x2);
  const int64_t ya = (int64_t)std::floor(y1), yb = (int64_t)std::ceil(y2);
  const int64_t bw = std::max(xb - xa + 1, (int64_t)1);
  const int64_t bh = std::max(yb - ya + 1, (int64_t)1);
  const int64_t gx0 = std::max(xa, (int64_t)0), gx1 = std::min(xb, w - 1);
  const int64_t gy0 = std::max(ya, (int64_t)0), gy1 = std::min(yb, h - 1);
  RleCursor rc{counts_out};
  if (gx1 < gx0 || gy1 < gy0) {  // box entirely outside the frame
    rc.flat(h * w, 0);
    return rc.finish();
  }
  const int64_t nvis = gy1 - gy0 + 1;
  // G^T: (m, nvis) vertically-resized probabilities for the visible rows,
  // column-contiguous so the per-x lerp streams; plus per-bin min/max for
  // the column skip test.
  std::vector<float> gt((size_t)m * nvis), vbuf((size_t)nvis);
  std::vector<float> cmax(m, -1.f), cmin(m, 2.f);
  const float yscale = (float)m / (float)bh;
  for (int64_t jv = 0; jv < nvis; ++jv) {
    int a0, a1;
    float f;
    lerp_coeff(gy0 - ya + jv, yscale, m, &a0, &a1, &f);
    const float* r0 = prob + a0 * m;
    const float* r1 = prob + a1 * m;
    for (int64_t n = 0; n < m; ++n) {
      const float v = (1.0f - f) * r0[n] + f * r1[n];
      gt[(size_t)n * nvis + jv] = v;
      cmax[n] = std::max(cmax[n], v);
      cmin[n] = std::min(cmin[n], v);
    }
  }
  rc.flat(gx0 * h, 0);  // whole columns left of the box
  const float xscale = (float)m / (float)bw;
  for (int64_t x = gx0; x <= gx1; ++x) {
    int b0, b1;
    float fx;
    lerp_coeff(x - xa, xscale, m, &b0, &b1, &fx);
    rc.flat(gy0, 0);  // rows above the box in this column
    // v is a convex combination of bins b0/b1, so bin-wise extrema bound
    // every pixel in the column
    const float ub = std::max(cmax[b0], cmax[b1]);
    const float lb = std::min(cmin[b0], cmin[b1]);
    if (ub < 0.5f) {
      rc.flat(nvis, 0);
    } else if (lb >= 0.5f) {
      rc.flat(nvis, 1);
    } else {
      const float* ca = gt.data() + (size_t)b0 * nvis;
      const float* cb = gt.data() + (size_t)b1 * nvis;
      const float wa = 1.0f - fx;
      for (int64_t j = 0; j < nvis; ++j) vbuf[j] = wa * ca[j] + fx * cb[j];
      int64_t j = 0;
      while (j < nvis) {  // pack 64 threshold bits, then run-walk them
        const int nb = (int)std::min(nvis - j, (int64_t)64);
        uint64_t v = 0;
        for (int k = 0; k < nb; ++k)
          v |= (uint64_t)(vbuf[j + k] >= 0.5f) << k;
        rc.bits(v, nb);
        j += nb;
      }
    }
    rc.flat(h - 1 - gy1, 0);  // rows below the box
  }
  rc.flat((w - 1 - gx1) * h, 0);  // whole columns right of the box
  return rc.finish();
}

}  // extern "C"
