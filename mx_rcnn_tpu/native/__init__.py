"""ctypes bindings for the native CPU eval kernels (src/mxr_native.cpp).

Mirrors the reference's native tier (``rcnn/cython`` + pycocotools C): IoU
matrix, greedy NMS, RLE intersection/IoU.  The library is built on first
use (``make`` → g++, ~1 s); every entry point has a pure-numpy fallback, so
an unbuildable environment degrades to slower eval, never to failure.

API (drop-in with the numpy versions):
  bbox_overlaps(boxes (N,4), query (K,4)) -> (N,K) f32
  nms(dets (N,5), thresh) -> list[int]
  rle_iou(dts, gts, iscrowd) -> (D,G) f64   (RLE dicts, uncompressed counts)
  available() -> bool
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import List, Optional

import numpy as np

from mx_rcnn_tpu.logger import logger

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "libmxr_native.so")
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    src = os.path.join(_DIR, "src", "mxr_native.cpp")
    stale = (not os.path.exists(_SO)
             or (os.path.exists(src)
                 and os.path.getmtime(src) > os.path.getmtime(_SO)))
    if stale:
        try:
            subprocess.run(["make", "-C", _DIR], check=True,
                           capture_output=True, timeout=120)
        except Exception as e:  # no toolchain → numpy fallback
            if not os.path.exists(_SO):
                logger.warning("native build failed (%s); using numpy "
                               "fallbacks", e)
                return None
            logger.warning("native rebuild failed (%s); using the stale "
                           "library", e)
    try:
        lib = ctypes.CDLL(_SO)
    except OSError as e:
        logger.warning("native load failed (%s); using numpy fallbacks", e)
        return None

    lib.mxr_bbox_overlaps.argtypes = [
        ctypes.POINTER(ctypes.c_float), ctypes.c_int64,
        ctypes.POINTER(ctypes.c_float), ctypes.c_int64,
        ctypes.POINTER(ctypes.c_float)]
    lib.mxr_nms.restype = ctypes.c_int64
    lib.mxr_nms.argtypes = [
        ctypes.POINTER(ctypes.c_float), ctypes.c_int64, ctypes.c_float,
        ctypes.POINTER(ctypes.c_int64)]
    try:  # absent only in a stale pre-round-4 .so that failed to rebuild
        lib.mxr_rle_encode.restype = ctypes.c_int64
        lib.mxr_rle_encode.argtypes = [
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64, ctypes.POINTER(ctypes.c_uint32)]
        lib.mxr_paste_rle.restype = ctypes.c_int64
        lib.mxr_paste_rle.argtypes = [
            ctypes.POINTER(ctypes.c_float), ctypes.c_int64,
            ctypes.c_float, ctypes.c_float, ctypes.c_float, ctypes.c_float,
            ctypes.c_int64, ctypes.c_int64, ctypes.POINTER(ctypes.c_uint32)]
    except AttributeError:
        logger.warning("stale native library has no mask RLE entry points; "
                       "mask eval uses the host fallbacks")
    lib.mxr_rle_iou.argtypes = [
        ctypes.POINTER(ctypes.c_uint32), ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int64,
        ctypes.POINTER(ctypes.c_uint32), ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
        ctypes.POINTER(ctypes.c_double)]
    _lib = lib
    return _lib


def available() -> bool:
    return _load() is not None


def _fptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def bbox_overlaps(boxes: np.ndarray, query: np.ndarray) -> np.ndarray:
    lib = _load()
    boxes = np.ascontiguousarray(boxes, np.float32)
    query = np.ascontiguousarray(query, np.float32)
    if lib is None:
        from mx_rcnn_tpu.ops.boxes import bbox_overlaps as jb

        return np.asarray(jb(boxes, query))
    n, k = len(boxes), len(query)
    out = np.empty((n, k), np.float32)
    lib.mxr_bbox_overlaps(_fptr(boxes), n, _fptr(query), k, _fptr(out))
    return out


def nms(dets: np.ndarray, thresh: float) -> List[int]:
    lib = _load()
    if lib is None or len(dets) == 0:
        from mx_rcnn_tpu.ops.nms import nms as py_nms

        return py_nms(np.asarray(dets, np.float32), thresh)
    dets = np.ascontiguousarray(dets, np.float32)
    keep = np.empty(len(dets), np.int64)
    cnt = lib.mxr_nms(_fptr(dets), len(dets), thresh,
                      keep.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
    return keep[:cnt].tolist()


_enc_buf: Optional[np.ndarray] = None  # reused across per-det encode calls


def rle_encode_packed(packed: np.ndarray, h: int, w: int) -> List[int]:
    """Bit-packed transposed mask (Wp, Hp//8) uint8 (ops/mask_paste.py
    layout) → column-major COCO RLE counts over the true (h, w) frame.

    The C++ encoder streams each column as 64-bit words (the packed layout
    puts column y-runs in sequential bytes); the numpy fallback unpacks the
    bits and reuses the oracle encoder — identical counts either way.
    """
    global _enc_buf
    packed = np.ascontiguousarray(packed, np.uint8)
    hp = packed.shape[1] * 8
    assert hp % 64 == 0, \
        f"packed height {hp} must be a multiple of 64 (C++ word streaming)"
    assert h <= hp and w <= packed.shape[0], \
        f"frame ({h}, {w}) exceeds packed capacity ({hp}, {packed.shape[0]})"
    lib = _load()
    if lib is None or not hasattr(lib, "mxr_rle_encode"):
        from mx_rcnn_tpu.eval import mask_rle

        mask = np.unpackbits(packed[:w], axis=-1, bitorder="little")
        return mask_rle.encode(mask[:, :h].T)["counts"]
    need = h * w + 1
    if _enc_buf is None or _enc_buf.size < need:
        _enc_buf = np.empty(need, np.uint32)
    n = lib.mxr_rle_encode(
        packed.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), hp, h, w,
        _enc_buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)))
    return _enc_buf[:n].tolist()


def paste_rle(prob: np.ndarray, box: np.ndarray, h: int, w: int):
    """(M, M) mask probabilities + original-frame box → full-frame
    column-major RLE counts, or None when the native library is missing
    (caller falls back to the cv2 paste_mask oracle).

    Fused C++ paste+RLE: separable bilinear resize streamed column by
    column with bulk zero spans outside the box — ~10-25 ms/img at the
    100-detection worst case vs ~150 ms for per-detection cv2 paste, and
    it only needs the 28×28 probabilities shipped from the device."""
    global _enc_buf
    lib = _load()
    if lib is None or not hasattr(lib, "mxr_paste_rle"):
        return None
    prob = np.ascontiguousarray(prob, np.float32)
    need = h * w + 1
    if _enc_buf is None or _enc_buf.size < need:
        _enc_buf = np.empty(need, np.uint32)
    n = lib.mxr_paste_rle(
        _fptr(prob), prob.shape[0],
        float(box[0]), float(box[1]), float(box[2]), float(box[3]), h, w,
        _enc_buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)))
    return _enc_buf[:n].tolist()


def _flatten_counts(rles: list):
    counts = [np.asarray(r["counts"], np.uint32) for r in rles]
    off = np.zeros(len(rles) + 1, np.int64)
    for i, c in enumerate(counts):
        off[i + 1] = off[i] + len(c)
    flat = (np.concatenate(counts) if counts else np.zeros(0, np.uint32))
    return np.ascontiguousarray(flat), off


def rle_iou(dts: list, gts: list, iscrowd: np.ndarray) -> np.ndarray:
    lib = _load()
    if lib is None:
        from mx_rcnn_tpu.eval import mask_rle

        return mask_rle.rle_iou(dts, gts, np.asarray(iscrowd, bool))
    D, G = len(dts), len(gts)
    out = np.zeros((D, G), np.float64)
    if D == 0 or G == 0:
        return out
    n = int(dts[0]["size"][0]) * int(dts[0]["size"][1])
    dc, doff = _flatten_counts(dts)
    gc, goff = _flatten_counts(gts)
    d_area = np.asarray([int(np.sum(np.asarray(r["counts"])[1::2]))
                         for r in dts], np.int64)
    g_area = np.asarray([int(np.sum(np.asarray(r["counts"])[1::2]))
                         for r in gts], np.int64)
    crowd = np.ascontiguousarray(np.asarray(iscrowd, np.uint8))
    lib.mxr_rle_iou(
        dc.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        doff.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), D,
        gc.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        goff.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), G,
        d_area.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        g_area.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        crowd.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), n,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
    return out
