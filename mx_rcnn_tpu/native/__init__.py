"""ctypes bindings for the native CPU eval kernels (src/mxr_native.cpp).

Mirrors the reference's native tier (``rcnn/cython`` + pycocotools C): IoU
matrix, greedy NMS, RLE intersection/IoU.  The library is built on first
use (``make`` → g++, ~1 s); every entry point has a pure-numpy fallback, so
an unbuildable environment degrades to slower eval, never to failure.

API (drop-in with the numpy versions):
  bbox_overlaps(boxes (N,4), query (K,4)) -> (N,K) f32
  nms(dets (N,5), thresh) -> list[int]
  rle_iou(dts, gts, iscrowd) -> (D,G) f64   (RLE dicts, uncompressed counts)
  available() -> bool
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import List, Optional

import numpy as np

from mx_rcnn_tpu.logger import logger

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "libmxr_native.so")
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    if not os.path.exists(_SO):
        try:
            subprocess.run(["make", "-C", _DIR], check=True,
                           capture_output=True, timeout=120)
        except Exception as e:  # no toolchain → numpy fallback
            logger.warning("native build failed (%s); using numpy fallbacks", e)
            return None
    try:
        lib = ctypes.CDLL(_SO)
    except OSError as e:
        logger.warning("native load failed (%s); using numpy fallbacks", e)
        return None

    lib.mxr_bbox_overlaps.argtypes = [
        ctypes.POINTER(ctypes.c_float), ctypes.c_int64,
        ctypes.POINTER(ctypes.c_float), ctypes.c_int64,
        ctypes.POINTER(ctypes.c_float)]
    lib.mxr_nms.restype = ctypes.c_int64
    lib.mxr_nms.argtypes = [
        ctypes.POINTER(ctypes.c_float), ctypes.c_int64, ctypes.c_float,
        ctypes.POINTER(ctypes.c_int64)]
    lib.mxr_rle_iou.argtypes = [
        ctypes.POINTER(ctypes.c_uint32), ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int64,
        ctypes.POINTER(ctypes.c_uint32), ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
        ctypes.POINTER(ctypes.c_double)]
    _lib = lib
    return _lib


def available() -> bool:
    return _load() is not None


def _fptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def bbox_overlaps(boxes: np.ndarray, query: np.ndarray) -> np.ndarray:
    lib = _load()
    boxes = np.ascontiguousarray(boxes, np.float32)
    query = np.ascontiguousarray(query, np.float32)
    if lib is None:
        from mx_rcnn_tpu.ops.boxes import bbox_overlaps as jb

        return np.asarray(jb(boxes, query))
    n, k = len(boxes), len(query)
    out = np.empty((n, k), np.float32)
    lib.mxr_bbox_overlaps(_fptr(boxes), n, _fptr(query), k, _fptr(out))
    return out


def nms(dets: np.ndarray, thresh: float) -> List[int]:
    lib = _load()
    if lib is None or len(dets) == 0:
        from mx_rcnn_tpu.ops.nms import nms as py_nms

        return py_nms(np.asarray(dets, np.float32), thresh)
    dets = np.ascontiguousarray(dets, np.float32)
    keep = np.empty(len(dets), np.int64)
    cnt = lib.mxr_nms(_fptr(dets), len(dets), thresh,
                      keep.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
    return keep[:cnt].tolist()


def _flatten_counts(rles: list):
    counts = [np.asarray(r["counts"], np.uint32) for r in rles]
    off = np.zeros(len(rles) + 1, np.int64)
    for i, c in enumerate(counts):
        off[i + 1] = off[i] + len(c)
    flat = (np.concatenate(counts) if counts else np.zeros(0, np.uint32))
    return np.ascontiguousarray(flat), off


def rle_iou(dts: list, gts: list, iscrowd: np.ndarray) -> np.ndarray:
    lib = _load()
    if lib is None:
        from mx_rcnn_tpu.eval import mask_rle

        return mask_rle.rle_iou(dts, gts, np.asarray(iscrowd, bool))
    D, G = len(dts), len(gts)
    out = np.zeros((D, G), np.float64)
    if D == 0 or G == 0:
        return out
    n = int(dts[0]["size"][0]) * int(dts[0]["size"][1])
    dc, doff = _flatten_counts(dts)
    gc, goff = _flatten_counts(gts)
    d_area = np.asarray([int(np.sum(np.asarray(r["counts"])[1::2]))
                         for r in dts], np.int64)
    g_area = np.asarray([int(np.sum(np.asarray(r["counts"])[1::2]))
                         for r in gts], np.int64)
    crowd = np.ascontiguousarray(np.asarray(iscrowd, np.uint8))
    lib.mxr_rle_iou(
        dc.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        doff.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), D,
        gc.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        goff.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), G,
        d_area.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        g_area.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        crowd.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), n,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
    return out
