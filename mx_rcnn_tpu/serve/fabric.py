"""Cross-host serving fabric: remote replica pools over the PR-8
contract (ISSUE 12 tentpole; ROADMAP item 2).

PR-8's supervisor/router is single-host by construction: liveness is
``waitpid``, transport is a Unix socket, recovery is fork respawn.  This
module keeps that robustness contract but makes the router/replica
relationship **transport-agnostic**:

    clients → fabric router (this module, ``serve.py --fabric``)
                ├── remote member "hostA:8001"   (joined via --join)
                ├── remote member "hostB:8001"   (from --pool-file)
                └── local members (fork children, when --replicas N > 1,
                    still owned by the PR-8 ReplicaSupervisor)

* **Membership is probe-driven, not waitpid-driven.**  A remote member
  is whatever answers ``/readyz`` at its address.  The PR-8 state
  machine carries over with one deliberate amputation: the fabric has
  *no respawn authority* over a remote host.  A crash looks like probe
  failure → the member is **evicted** (unrouted + flight-dumped), then
  re-probed on the same exponential backoff schedule, and **re-admitted**
  the moment ``/readyz`` answers 200 again.  The systemic limit becomes
  quarantine: a member that fails ``max_failures`` consecutive contact
  cycles stops being probed until an explicit ``/admin/register``.
* **Least-loaded routing** over each member's live ``queue_depth``
  gauge, sampled by the readiness probe and **timestamped at receipt**
  (the router's clock — remote clocks are never trusted).  Samples older
  than ``stale_probe_intervals × probe_interval_s`` are ignored and the
  router falls back to PR-8 round-robin: a stale gauge must never pin
  traffic on yesterday's idlest member.
* **Retry-once-on-alternate** under the PR-8 :class:`TokenBucket`
  budget, unchanged semantics: transport error or 503 retries once on a
  different member; budget exhausted → early 503.
* **Per-member circuit breakers** — consecutive data-path failures open
  the breaker (member unpicked), a cooldown later one half-open trial
  request probes it, success closes.  This is the data-path complement
  to membership probes: a member whose ``/readyz`` is healthy but whose
  ``/predict`` resets connections is exactly what breakers are for.
* **Request hedging** (``hedge_after_ms > 0``): a request still
  unanswered after the threshold is duplicated to a second member and
  the first 2xx wins.  Hedges are counted distinctly from retries
  (``hedge_fired`` / ``hedge_won``) — a hedge is a latency bet, a retry
  is a failure response.
* **Partition tolerance** — the router keeps serving whatever subset it
  can reach; when the ready fraction drops below ``partition_floor`` it
  flight-dumps ``fabric_partition`` once per transition and raises the
  ``fabric/partition`` counter.  Recovery clears the flag.
* **Rolling hot reload** across members through the same
  unroute → drain → ``POST /admin/reload`` → canary → re-ready sequence
  as PR-8, now per-address instead of per-fork-child, with the identical
  rollback-on-canary-rejection and monotonic-generation rules.

``poll(now=None)`` stays the injectable-clock test surface, and
``probe_fn`` / ``reload_fn`` / ``forward_fn`` stay injectable — the
chaos tests drive the whole fabric deterministically, then the e2e suite
re-runs the same scenarios over real localhost TCP subprocesses.
"""

from __future__ import annotations

import inspect
import json
import queue
import re
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

from mx_rcnn_tpu import telemetry
from mx_rcnn_tpu.logger import logger
from mx_rcnn_tpu.serve.frontend import (_Handler, _TCPHTTPServer,
                                        _UnixHTTPServer, address_request,
                                        address_request_raw, query_param)
from mx_rcnn_tpu.serve.supervisor import (FAILED, READY as SUP_READY,
                                          STOPPED, ReplicaSupervisor,
                                          TokenBucket)
from mx_rcnn_tpu.telemetry import tracectx
from mx_rcnn_tpu.telemetry.obs import PROM_CONTENT_TYPE, prometheus_text
from mx_rcnn_tpu.telemetry.tracectx import (NULL_SPAN, TRACE_HEADER,
                                            TraceContext)

# client-minted trace ids arrive as a ``"trace"`` doc field INSIDE the
# opaque forwarded body; with tracing on the router sniffs it without
# paying a full JSON decode of the base64 image payload
_TRACE_BODY_RE = re.compile(rb'"trace"\s*:\s*"([0-9a-fA-F\-]{8,80})"')

# remote-member states — the PR-8 replica states with respawn authority
# amputated: a fabric can only evict and re-admit, never fork
JOINING = "joining"          # registered; first successful probe pending
MEMBER_READY = "ready"       # /readyz 200 — routable unless mid-reload
EVICTED = "evicted"          # unreachable; re-probed on backoff
QUARANTINED = "quarantined"  # systemic: probing stopped until re-register
PARKED = "parked"            # autoscaler drained it; spare warm capacity


@dataclass(frozen=True)
class FabricOptions:
    probe_interval_s: float = 1.0    # membership poll period
    probe_timeout_s: float = 5.0     # one readiness probe's HTTP timeout
    evict_probes: int = 3            # consecutive misses on a READY member
    start_timeout_s: float = 600.0   # register → first 200 ceiling
    backoff_base_s: float = 0.5      # first re-probe delay after eviction
    backoff_max_s: float = 30.0      # re-probe backoff ceiling
    max_failures: int = 16           # consecutive failed contact cycles
    stable_s: float = 60.0           # ready this long forgives the history
    stale_probe_intervals: float = 2.0  # queue_depth sample TTL multiplier
    partition_floor: float = 0.5     # ready fraction below this = partition
    hedge_after_ms: float = 0.0      # 0 disables hedging
    breaker_failures: int = 3        # consecutive data-path failures → open
    breaker_cooldown_s: float = 5.0  # open → half-open trial delay
    retry_budget: int = 16           # PR-8 retry TokenBucket, unchanged
    retry_refill_per_s: float = 4.0
    drain_timeout_s: float = 30.0    # router-side in-flight wait (reload)
    reload_timeout_s: float = 120.0  # one member's /admin/reload ceiling
    forward_timeout_s: float = 600.0

    @property
    def stale_after_s(self) -> float:
        """A queue_depth sample older than this is routing-inert."""
        return self.stale_probe_intervals * self.probe_interval_s


class CircuitBreaker:
    """Per-member data-path breaker: ``threshold`` consecutive failures
    open it; after ``cooldown_s`` exactly one half-open trial is allowed
    through — success closes, failure re-opens.  ``now`` is injectable
    everywhere (the fabric's fake-clock test discipline)."""

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"

    def __init__(self, threshold: int = 3, cooldown_s: float = 5.0):
        self.threshold = int(threshold)
        self.cooldown_s = float(cooldown_s)
        self.state = self.CLOSED
        self.failures = 0
        self.open_until = 0.0
        self._trial = False
        self._lock = threading.Lock()

    def allow(self, now: Optional[float] = None) -> bool:
        now = time.monotonic() if now is None else now
        with self._lock:
            if self.state == self.CLOSED:
                return True
            if self.state == self.OPEN:
                if now >= self.open_until:
                    self.state = self.HALF_OPEN
                    self._trial = True
                    return True  # the single trial request
                return False
            # HALF_OPEN with the trial already in flight: hold the line
            return False

    def can_attempt(self, now: Optional[float] = None) -> bool:
        """Side-effect-free view of :meth:`allow`: True when a request
        COULD go through right now.  Candidate filters must use this —
        calling allow() on a member that is never actually picked burns
        the single half-open trial with no request behind it, and the
        breaker then stays open forever."""
        now = time.monotonic() if now is None else now
        with self._lock:
            if self.state == self.CLOSED:
                return True
            if self.state == self.OPEN:
                return now >= self.open_until
            return False  # HALF_OPEN: the one trial is already in flight

    def record_success(self):
        with self._lock:
            self.state = self.CLOSED
            self.failures = 0
            self._trial = False

    def record_failure(self, now: Optional[float] = None) -> bool:
        """Returns True when THIS failure opened the breaker (the caller
        counts ``breaker_open`` exactly once per transition)."""
        now = time.monotonic() if now is None else now
        with self._lock:
            self.failures += 1
            if (self.state == self.HALF_OPEN
                    or (self.state == self.CLOSED
                        and self.failures >= self.threshold)):
                opened = self.state != self.OPEN
                self.state = self.OPEN
                self.open_until = now + self.cooldown_s
                self._trial = False
                return opened
            return False


class RemoteMember:
    """One remote replica known by address — ``host:port`` for TCP or a
    filesystem path (``unix:`` prefix accepted) for same-host members.
    All mutable supervision state lives here; the pool mutates it under
    its lock, HTTP happens outside."""

    kind = "remote"

    def __init__(self, address: str, opts: FabricOptions):
        self.address = normalize_address(address)
        self.name = self.address
        self.state = JOINING
        self.routable = False
        self.reloading = False
        self.generation = 0
        self.inflight = 0
        self.requests = 0         # forward attempts routed here
        self.evictions = 0
        self.failures = 0         # consecutive failed contact cycles
        self.probe_fails = 0      # consecutive misses while READY
        self.depth = None         # last queue_depth sample ...
        self.depth_t = None       # ... and WHEN the router received it
        self.joined_t = 0.0
        self.ready_t = 0.0
        self.next_probe_t = 0.0   # eviction backoff schedule
        self.last_reload = None   # last /admin/reload response doc
        self.scale_drain = False     # autoscale park drain in progress
        self.readmit_pending = False  # register() raced that drain
        self.inflight_lock = threading.Lock()  # hedge + handler threads
        self.breaker = CircuitBreaker(opts.breaker_failures,
                                      opts.breaker_cooldown_s)

    def is_active(self) -> bool:
        # parked capacity is deliberately out of service: it must not
        # count toward the partition denominator any more than a
        # quarantined member does
        return self.state not in (QUARANTINED, PARKED)

    def is_ready(self) -> bool:
        return self.state == MEMBER_READY

    def http_raw(self, method, path, body=None, timeout=60.0,
                 headers=None):
        return address_request_raw(self.address, method, path, body=body,
                                   timeout=timeout, headers=headers)

    def http(self, method, path, doc=None, timeout=60.0):
        return address_request(self.address, method, path, doc=doc,
                               timeout=timeout)


class LocalMember:
    """A fork-child replica wrapped to the member surface.  The PR-8
    supervisor KEEPS full authority — spawn, waitpid, hang-kill, backoff,
    systemic limit; the pool only reads its state, samples its
    queue_depth, and borrows its routable/reloading/inflight flags so
    routing and rolling reloads treat both member kinds identically."""

    kind = "local"

    def __init__(self, handle, sup: ReplicaSupervisor,
                 opts: FabricOptions):
        self.handle = handle
        self.sup = sup
        self.name = f"local/{handle.index}"
        self.address = f"unix:{handle.spec.sock}"
        self.requests = 0
        self.evictions = 0
        self.depth = None
        self.depth_t = None
        self.last_reload = None
        self.inflight_lock = threading.Lock()  # hedge + handler threads
        self.breaker = CircuitBreaker(opts.breaker_failures,
                                      opts.breaker_cooldown_s)

    # supervision state is the handle's — shared, not copied
    @property
    def state(self):
        return self.handle.state

    @property
    def routable(self):
        return self.handle.routable

    @routable.setter
    def routable(self, v):
        self.handle.routable = bool(v)

    @property
    def reloading(self):
        return self.handle.reloading

    @reloading.setter
    def reloading(self, v):
        self.handle.reloading = bool(v)

    @property
    def inflight(self):
        return self.handle.inflight

    @inflight.setter
    def inflight(self, v):
        self.handle.inflight = v

    @property
    def generation(self):
        return self.handle.generation

    @generation.setter
    def generation(self, v):
        self.handle.generation = v

    @property
    def probe_fails(self):
        return self.handle.probe_fails

    def is_active(self) -> bool:
        return self.state not in (FAILED, STOPPED)

    def is_ready(self) -> bool:
        return self.state == SUP_READY

    def http_raw(self, method, path, body=None, timeout=60.0,
                 headers=None):
        return address_request_raw(self.address, method, path, body=body,
                                   timeout=timeout, headers=headers)

    def http(self, method, path, doc=None, timeout=60.0):
        return address_request(self.address, method, path, doc=doc,
                               timeout=timeout)


def normalize_address(address: str) -> str:
    """Canonical member key: ``host:port`` for TCP, ``unix:<path>`` for
    sockets — so ``/admin/register`` dedupes no matter how the address
    was spelled."""
    address = address.strip()
    if address.startswith("unix:"):
        return "unix:" + address[5:]
    if "/" in address:
        return "unix:" + address
    host, _, port = address.rpartition(":")
    if not host or not port.isdigit():
        raise ValueError(f"member address must be HOST:PORT or a unix "
                         f"socket path, got {address!r}")
    return f"{host}:{int(port)}"


class ReplicaPool:
    """Probe-driven membership over local and remote members.  Remote
    members arrive via :meth:`register` (``/admin/register`` /
    ``--join``) or :meth:`load_pool_file`; local fork children via
    :meth:`adopt_supervisor`.  ``poll(now=None)`` is one membership step
    — tests drive it with a fake clock, production wraps it in the
    monitor thread (:meth:`start`)."""

    def __init__(self, opts: Optional[FabricOptions] = None,
                 probe_fn: Optional[Callable] = None,
                 reload_fn: Optional[Callable] = None):
        self.opts = opts or FabricOptions()
        self.members: Dict[str, object] = {}  # name → member (ordered)
        self._probe_fn = probe_fn or self._default_probe
        self._reload_fn = reload_fn or self._default_reload
        self._lock = threading.Lock()
        self._gen_lock = threading.Lock()
        self._roll_lock = threading.Lock()  # one rolling reload at a time
        self.generation = 0
        self._target: Optional[dict] = None
        self._prev_target: Optional[dict] = None
        self.partition = False
        self._ever_ready = False  # gates partition alarms until first join
        self.sup: Optional[ReplicaSupervisor] = None
        self.counters = {"member_joined": 0, "member_evicted": 0,
                         "member_quarantined": 0, "partition": 0,
                         "reload": 0, "reload_rollback": 0,
                         "breaker_open": 0, "hedge_fired": 0,
                         "hedge_won": 0, "retry": 0, "retry_ok": 0,
                         "retry_budget_exhausted": 0, "no_ready": 0,
                         "transport_error": 0, "requests": 0,
                         "quality_rejected": 0, "member_parked": 0,
                         "member_unparked": 0}
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def count(self, key: str, inc: int = 1):
        """Pool counter + the matching ``fabric/*`` telemetry counter —
        one source for the JSON view, the report table, and Prometheus."""
        self.counters[key] = self.counters.get(key, 0) + inc
        telemetry.get().counter(f"fabric/{key}", inc)

    # -- membership ------------------------------------------------------

    def register(self, address: str,
                 now: Optional[float] = None) -> Tuple[object, bool]:
        """Admit (or re-admit) a remote member by address.  Explicit
        registration is the quarantine escape hatch: it resets the
        failure history and schedules an immediate probe."""
        now = time.monotonic() if now is None else now
        with self._lock:
            key = normalize_address(address)
            m = self.members.get(key)
            created = m is None
            if created:
                m = RemoteMember(key, self.opts)
                m.joined_t = now
                self.members[m.name] = m
                logger.info("fabric: member %s registered", m.name)
            elif getattr(m, "kind", "remote") == "remote" \
                    and getattr(m, "scale_drain", False):
                # the readmit raced an autoscale park drain of this very
                # address: do NOT flip any routing state mid-drain (a
                # half-routable member is worse than either outcome) —
                # park_member() honors the flag when the drain settles
                m.readmit_pending = True
                unparked = False
                logger.info("fabric: member %s re-registered mid-drain — "
                            "readmit deferred until the drain settles",
                            m.name)
            elif getattr(m, "kind", "remote") == "remote" \
                    and m.state in (EVICTED, QUARANTINED, PARKED):
                was = m.state
                m.state = JOINING
                m.failures = 0
                m.probe_fails = 0
                m.next_probe_t = 0.0
                m.joined_t = now
                unparked = was == PARKED
                logger.info("fabric: member %s re-registered (was %s)",
                            m.name, was)
            else:
                unparked = False
        if created:
            unparked = False
        if unparked:
            self.count("member_unparked")
        self._wake.set()
        return m, created

    def load_pool_file(self, path: str) -> int:
        """Seed membership from a pool file: one address per line,
        ``#`` comments and blank lines ignored."""
        n = 0
        with open(path) as f:
            for line in f:
                line = line.split("#", 1)[0].strip()
                if line:
                    self.register(line)
                    n += 1
        return n

    def adopt_supervisor(self, sup: ReplicaSupervisor):
        """Wrap every fork-child handle as a LocalMember.  The
        supervisor keeps respawn authority; the pool handles routing."""
        self.sup = sup
        with self._lock:
            for h in sup.handles:
                m = LocalMember(h, sup, self.opts)
                self.members[m.name] = m

    def adopt_handle(self, h) -> LocalMember:
        """Adopt ONE supervisor handle added after boot
        (:meth:`ReplicaSupervisor.add_replica` — the autoscaler's
        on-demand spawn): :meth:`adopt_supervisor` wraps only the
        boot-time slots, so runtime capacity needs its own door."""
        if self.sup is None and h is not None:
            raise RuntimeError("adopt_handle needs adopt_supervisor "
                               "first — the pool routes, the supervisor "
                               "owns the process")
        with self._lock:
            m = LocalMember(h, self.sup, self.opts)
            if m.name in self.members:
                return self.members[m.name]
            self.members[m.name] = m
        self._wake.set()
        return m

    def release_local(self, name: str) -> bool:
        """Forget a retired fork child's LocalMember (the supervisor
        already drained and reaped the process)."""
        with self._lock:
            m = self.members.get(name)
            if m is None or m.kind != "local":
                return False
            del self.members[name]
        return True

    # -- default probing/reload wiring -----------------------------------

    def _default_probe(self, member, path: str):
        return member.http("GET", path, timeout=self.opts.probe_timeout_s)

    def _default_reload(self, member, target: dict):
        return member.http("POST", "/admin/reload", doc=target,
                           timeout=self.opts.reload_timeout_s)

    # -- lifecycle -------------------------------------------------------

    def start(self) -> "ReplicaPool":
        assert self._thread is None, "pool already started"

        def monitor():
            while not self._stop.is_set():
                self._wake.wait(self.opts.probe_interval_s)
                self._wake.clear()
                if self._stop.is_set():
                    return
                try:
                    self.poll()
                except Exception:  # noqa: BLE001 — membership must survive
                    logger.exception("fabric poll failed")

        self._thread = threading.Thread(target=monitor,
                                        name="fabric-pool", daemon=True)
        self._thread.start()
        return self

    def stop(self, timeout: float = 10.0):
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None

    # -- the membership state machine ------------------------------------

    def poll(self, now: Optional[float] = None):
        """One membership step over every member.  Probe I/O runs
        outside the lock; state transitions inside it."""
        now = time.monotonic() if now is None else now
        with self._lock:
            members = list(self.members.values())
        for m in members:
            if m.kind == "local":
                self._poll_local(m, now)
            else:
                self._poll_remote(m, now)
        self._update_partition(now)
        tel = telemetry.get()
        tel.gauge("fabric/ready", self.ready_count())
        tel.gauge("fabric/members", len(members))
        tel.gauge("fabric/generation", self.generation)

    def _poll_local(self, m: LocalMember, now: float):
        # liveness/respawn is the supervisor's; the pool only keeps the
        # queue_depth gauge fresh for least-loaded routing
        if not (m.routable and not m.reloading):
            return
        try:
            _, doc = self._probe_fn(m, "/readyz")
        except Exception:  # noqa: BLE001 — supervisor will catch the hang
            return
        if isinstance(doc, dict) and "queue_depth" in doc:
            with self._lock:
                m.depth = doc["queue_depth"]
                m.depth_t = now

    def _poll_remote(self, m: RemoteMember, now: float):
        if m.state in (QUARANTINED, PARKED):
            # parked = deliberately idle warm capacity; probing it would
            # flip it READY and defeat the scale-down — /admin/register
            # (the autoscaler's unpark) is the only way back in
            return
        if m.state == EVICTED and now < m.next_probe_t:
            return
        kind, payload = self._try_probe(m)
        if kind in ("up", "unready") and isinstance(payload, dict) \
                and "queue_depth" in payload:
            # timestamped at RECEIPT with the router's clock — remote
            # timestamps would need cross-host clock trust we don't have
            with self._lock:
                m.depth = payload["queue_depth"]
                m.depth_t = now
        if kind == "up":
            self._on_member_up(m, payload, now)
        elif kind == "unready":
            # alive but warming/draining: never an eviction signal, but
            # not routable either (the replica itself said not-ready)
            with self._lock:
                m.probe_fails = 0
                if m.state == MEMBER_READY and not m.reloading:
                    m.routable = False
        else:
            self._on_member_down(m, payload, now)

    def _try_probe(self, m) -> Tuple[str, object]:
        try:
            status, doc = self._probe_fn(m, "/readyz")
        except Exception as e:  # noqa: BLE001 — unreachable = down
            return "down", f"{type(e).__name__}: {e}"
        if status == 200 and isinstance(doc, dict):
            return "up", doc
        if status == 503 and isinstance(doc, dict):
            return "unready", doc
        return "down", f"status {status}"

    def _on_member_up(self, m: RemoteMember, doc: dict, now: float):
        catch_up = None
        with self._lock:
            m.probe_fails = 0
            if m.state != MEMBER_READY:
                was = m.state
                m.state = MEMBER_READY
                m.ready_t = now
                m.routable = not m.reloading
                # trust the member's own generation: a restarted process
                # reports its boot weights, which drives catch-up below
                m.generation = int(doc.get("generation", 0) or 0)
                joined = True
                readmitted = was == EVICTED
            else:
                joined = False
                readmitted = False
                if m.failures and now - m.ready_t > self.opts.stable_s:
                    m.failures = 0  # stable long enough: forgiven
                if not m.routable and not m.reloading:
                    m.routable = True  # suspect cleared by probe
            if joined:
                target = self._target
                if target is not None and m.generation < self.generation:
                    catch_up = dict(target, generation=self.generation)
        if joined:
            self.count("member_joined")
            logger.info("fabric: member %s %s (generation %d)", m.name,
                        "re-admitted" if readmitted else "joined",
                        m.generation)
            if catch_up is not None:
                # a re-admitted member restarted on stale weights — catch
                # it up before clients can see yesterday's boxes
                self._reload_one(m, catch_up)

    def _on_member_down(self, m: RemoteMember, cause, now: float):
        with self._lock:
            m.probe_fails += 1
            fails = m.probe_fails
            state = m.state
        if state == MEMBER_READY:
            if fails >= self.opts.evict_probes:
                self._evict(m, now, f"unreachable ({fails} probe "
                                    f"failures: {cause})")
            else:
                with self._lock:
                    m.routable = False  # suspect until a probe clears it
        elif state == JOINING:
            if now - m.joined_t > self.opts.start_timeout_s:
                self._evict(m, now, "join timeout")
        elif state == EVICTED:
            with self._lock:
                m.failures += 1
            self._schedule_reprobe(m, now)

    def _evict(self, m: RemoteMember, now: float, reason: str):
        with self._lock:
            m.state = EVICTED
            m.routable = False
            m.probe_fails = 0
            m.failures += 1
            m.evictions += 1
            m.depth_t = None  # its gauge is history, not data
        self.count("member_evicted")
        telemetry.get().dump_flight("member_evicted", member=m.name,
                                    cause=reason, evictions=m.evictions)
        logger.warning("fabric: member %s evicted (%s) — re-probing on "
                       "backoff (no respawn authority over a remote "
                       "host: eviction and re-admission are all the "
                       "fabric can do)", m.name, reason)
        self._schedule_reprobe(m, now)

    def _schedule_reprobe(self, m: RemoteMember, now: float):
        with self._lock:
            failures = m.failures
        if failures > self.opts.max_failures:
            with self._lock:
                m.state = QUARANTINED
            self.count("member_quarantined")
            telemetry.get().dump_flight("member_quarantined",
                                        member=m.name, failures=failures)
            logger.error("fabric: member %s quarantined after %d failed "
                         "contact cycles — not probing again until it "
                         "re-registers (the PR-4/PR-8 systemic-limit "
                         "contract, minus the authority to respawn)",
                         m.name, failures)
            return
        delay = min(self.opts.backoff_base_s * (2.0 ** (failures - 1)),
                    self.opts.backoff_max_s)
        with self._lock:
            m.next_probe_t = now + delay

    def note_suspect(self, m):
        """Router feedback: a forward failed at the transport level.
        Unroute immediately; the next probe confirms or clears."""
        if m.kind == "local" and self.sup is not None:
            self.sup.note_suspect(m.handle)
        else:
            with self._lock:
                if m.state == MEMBER_READY:
                    m.routable = False
                    m.probe_fails = max(m.probe_fails, 1)
        self._wake.set()

    def _update_partition(self, now: float):
        with self._lock:
            members = list(self.members.values())
        if not members:
            return
        active = [m for m in members if m.is_active()]
        ready = sum(1 for m in members if m.routable and not m.reloading)
        if ready > 0:
            self._ever_ready = True
        if not self._ever_ready:
            return  # a pool that never formed is a boot, not a partition
        frac = ready / max(1, len(active))
        if frac < self.opts.partition_floor:
            if not self.partition:
                self.partition = True
                self.count("partition")
                telemetry.get().dump_flight(
                    "fabric_partition", ready=ready, active=len(active),
                    members=len(members), fraction=round(frac, 3))
                logger.error("fabric: PARTITION — %d/%d members "
                             "reachable (floor %.2f); serving the "
                             "reachable subset", ready, len(active),
                             self.opts.partition_floor)
        elif self.partition:
            self.partition = False
            logger.info("fabric: partition healed — %d/%d members "
                        "reachable", ready, len(active))

    # -- routing support -------------------------------------------------

    def routable_members(self) -> List[object]:
        with self._lock:
            return [m for m in self.members.values()
                    if m.routable and not m.reloading]

    def ready_count(self) -> int:
        return len(self.routable_members())

    # -- scale-decision hooks (ISSUE 18) ---------------------------------

    def park_member(self, name: str) -> bool:
        """Graceful autoscale scale-down of one remote member: the PR-8
        unroute → wait-in-flight sequence verbatim, minus the swap —
        then PARKED (spare warm capacity, not probed, re-admitted only
        by ``/admin/register``).  A concurrent register of the same
        address sets ``readmit_pending`` instead of touching routing
        state; it is honored HERE, under the lock, once the drain
        settles — the member ends either fully parked or fully back in
        rotation, never half-routable."""
        with self._lock:
            m = self.members.get(name)
            if m is None:
                try:
                    m = self.members.get(normalize_address(name))
                except ValueError:
                    m = None
            if m is None or m.kind != "remote" or not m.is_ready():
                return False
            m.scale_drain = True
            m.routable = False
            m.reloading = True  # probes must not re-route mid-drain
        try:
            self._wait_inflight_drained(m)
        finally:
            with self._lock:
                m.reloading = False
                m.scale_drain = False
                if m.readmit_pending:
                    m.readmit_pending = False
                    parked = False
                    if m.is_ready():
                        m.routable = True
                else:
                    m.state = PARKED
                    m.routable = False
                    m.depth_t = None  # its gauge is history, not data
                    parked = True
        if parked:
            self.count("member_parked")
            logger.info("fabric: member %s parked (autoscale drain "
                        "complete; warm spare)", m.name)
        else:
            logger.info("fabric: member %s park ABANDONED — a register "
                        "raced the drain and the readmit wins", m.name)
        return parked

    def parked_members(self) -> List[str]:
        """Addresses of parked (warm spare) members — the autoscaler's
        cheapest scale-up source."""
        with self._lock:
            return [m.address for m in self.members.values()
                    if m.kind == "remote" and m.state == PARKED]

    def member_state_counts(self) -> Dict[str, int]:
        """``{state: n}`` over every member, local and remote — the
        fleet-size view behind the Prometheus ``fabric_member_count``
        gauges and the autoscaler's clamps."""
        with self._lock:
            counts: Dict[str, int] = {}
            for m in self.members.values():
                counts[m.state] = counts.get(m.state, 0) + 1
        return counts

    def capacity_count(self) -> int:
        """Members holding (or warming toward) serving capacity — the
        autoscaler's fleet size.  Parked / quarantined / evicted /
        failed / stopped slots are spare or dead, not capacity."""
        spare = (PARKED, QUARANTINED, EVICTED, FAILED, STOPPED)
        with self._lock:
            return sum(1 for m in self.members.values()
                       if m.state not in spare)

    def demand(self, now: Optional[float] = None) -> float:
        """Aggregate demand over routable members: fresh queue-depth
        samples plus router in-flight, under the SAME stale-gauge
        contract as least-loaded routing (a stale sample counts zero —
        better to under-forecast than to scale on history)."""
        now = time.monotonic() if now is None else now
        total = 0.0
        with self._lock:
            for m in self.members.values():
                if not (m.routable and not m.reloading):
                    continue
                if m.depth is not None and m.depth_t is not None \
                        and now - m.depth_t <= self.opts.stale_after_s:
                    total += float(m.depth)
                total += float(m.inflight)
        return total

    # -- rolling hot reload ----------------------------------------------

    def _wait_inflight_drained(self, m) -> bool:
        deadline = time.monotonic() + self.opts.drain_timeout_s
        while m.inflight > 0:
            if time.monotonic() > deadline:
                return False
            time.sleep(0.01)
        return True

    def _reload_one(self, m, target: dict) -> bool:
        """Unroute → wait router in-flight → swap → re-route: the PR-8
        sequence verbatim, addressed to the member's transport."""
        with self._lock:
            m.routable = False
            m.reloading = True
        try:
            self._wait_inflight_drained(m)
            try:
                status, doc = self._reload_fn(m, target)
            except Exception as e:  # noqa: BLE001 — treat as rejection
                status, doc = 0, {"error": f"{type(e).__name__}: {e}"}
            if status == 200:
                with self._lock:
                    m.generation = int(target.get("generation",
                                                  m.generation))
                    m.last_reload = doc if isinstance(doc, dict) else {}
                self.count("reload")
                logger.info("fabric: member %s generation %s live "
                            "(%s recompiles during swap)", m.name,
                            doc.get("generation"),
                            doc.get("recompiles_during_swap"))
                return True
            if isinstance(doc, dict) and "quality_candidate" in doc:
                # the member-side promotion gate measured the candidate
                # below the incumbent — distinct from a canary/transport
                # rejection so a stalled flywheel is diagnosable
                self.count("quality_rejected")
            logger.error("fabric: member %s reload rejected (%s): %s",
                         m.name, status,
                         doc.get("error", doc) if isinstance(doc, dict)
                         else doc)
            return False
        finally:
            with self._lock:
                m.reloading = False
                if m.is_ready():
                    m.routable = True

    def reload_to(self, target: dict) -> bool:
        """Roll ``target`` through every ready member one at a time —
        the reachable subset keeps serving throughout.  Mid-roll canary
        rejection aborts and rolls already-swapped members back to the
        previous target; the pool generation is monotonic and only
        advances on a fully-rolled fabric."""
        with self._roll_lock:
            with self._gen_lock:
                gen = self.generation + 1
            target = dict(target, generation=gen)
            swapped: List[object] = []
            # snapshot under the lock: a concurrent /admin/register
            # mutates the dict mid-roll otherwise, and _reload_one
            # blocks far too long to hold a live dict iterator across
            with self._lock:
                victims = [m for m in self.members.values()
                           if m.is_ready()]
            if not victims:
                logger.warning("fabric reload_to: no ready members")
                return False
            for m in victims:
                if not m.is_ready():
                    continue  # evicted mid-roll; catch-up on re-admission
                if self._reload_one(m, target):
                    swapped.append(m)
                    continue
                self.count("reload_rollback")
                telemetry.get().dump_flight("reload_roll_aborted",
                                            member=m.name, generation=gen)
                prev = self._target
                if prev is not None:
                    back = dict(prev, generation=self.generation)
                    for ms in swapped:
                        self._reload_one(ms, back)
                elif swapped:
                    logger.error(
                        "fabric reload_to: generation %d rejected on %s "
                        "AFTER %d member(s) swapped with no prior target "
                        "to roll back to — fabric is mixed until the "
                        "next good save", gen, m.name, len(swapped))
                return False
            with self._gen_lock:
                self.generation = max(self.generation, gen)
            self._prev_target, self._target = self._target, target
            # anyone who joined or re-admitted mid-roll missed the list
            with self._lock:
                stragglers = list(self.members.values())
            for m in stragglers:
                if m.is_ready() and m.generation < gen:
                    self._reload_one(m, target)
            telemetry.get().gauge("fabric/generation", self.generation)
            logger.info("fabric rolling reload complete: generation %d "
                        "live on %d member(s)", self.generation,
                        len(swapped))
            return True

    # -- introspection ---------------------------------------------------

    def member_generations(self) -> dict:
        """``{name: generation}`` for every member — the fleet flywheel's
        convergence check: after a promotion all values equal the pool
        generation; after a rejection none moved."""
        with self._lock:
            return {m.name: int(m.generation)
                    for m in self.members.values()}

    def metrics(self, now: Optional[float] = None) -> dict:
        now = time.monotonic() if now is None else now
        with self._lock:
            members = {}
            for m in self.members.values():
                age = (None if m.depth_t is None
                       else round(now - m.depth_t, 3))
                members[m.name] = {
                    "kind": m.kind, "address": m.address,
                    "state": m.state, "routable": m.routable,
                    "generation": m.generation, "inflight": m.inflight,
                    "requests": m.requests, "evictions": m.evictions,
                    "probe_fails": m.probe_fails,
                    "breaker": m.breaker.state,
                    "queue_depth": m.depth,
                    # the stale-gauge contract made visible: operators
                    # (and loadgen) see exactly what least-loaded sees
                    "queue_depth_age_s": age,
                    "queue_depth_stale": (m.depth_t is None or
                                          now - m.depth_t
                                          > self.opts.stale_after_s),
                }
        return {"generation": self.generation,
                "ready": self.ready_count(),
                "members": members,
                "partition": self.partition,
                "counters": dict(self.counters)}


class FabricRouter:
    """Least-loaded request router over the pool's routable members with
    the PR-8 retry-once budget and optional hedging.  ``forward_fn(
    member, method, path, body, timeout) → (status, bytes, ctype)`` is
    injectable for tests."""

    def __init__(self, pool: ReplicaPool, forward_fn=None,
                 timeout_s: Optional[float] = None):
        self.pool = pool
        self.timeout_s = (pool.opts.forward_timeout_s
                          if timeout_s is None else timeout_s)
        self._forward = forward_fn or self._default_forward
        # trace-context propagation needs a headers kwarg on the forward
        # fn; injected test doubles keep the original 5-arg signature, so
        # sniff once here instead of TypeError-ing per request
        try:
            self._fwd_headers = ("headers"
                                 in inspect.signature(
                                     self._forward).parameters)
        except (TypeError, ValueError):
            self._fwd_headers = False
        self._rr = 0
        self._rr_lock = threading.Lock()
        self.autoscaler = None  # CapacityAuthority, when --autoscale
        self.watchtower = None  # Watchtower, when --watch/--alert-rules
        self.retry_bucket = TokenBucket(pool.opts.retry_budget,
                                        pool.opts.retry_refill_per_s)

    @staticmethod
    def _default_forward(member, method, path, body, timeout,
                         headers=None):
        return member.http_raw(method, path, body=body, timeout=timeout,
                               headers=headers)

    def _pick(self, exclude=(), now: Optional[float] = None):
        """Least-loaded over FRESH queue_depth samples; round-robin over
        everything routable when no sample is fresh.  A member whose
        gauge went stale competes round-robin rather than winning on a
        depth it reported before the world changed."""
        now = time.monotonic() if now is None else now
        cands = [m for m in self.pool.routable_members()
                 if m not in exclude and m.breaker.can_attempt(now)]
        if not cands:
            return None
        ttl = self.pool.opts.stale_after_s
        fresh = [m for m in cands
                 if m.depth_t is not None and now - m.depth_t <= ttl]
        pick_from = cands
        if fresh:
            load = min(m.depth + m.inflight for m in fresh)
            # ties rotate round-robin: an idle fabric must spread load,
            # not pin every request on the lexicographically-first member
            pick_from = [m for m in fresh if m.depth + m.inflight == load]
        # only the member actually picked consumes allow(): the filter
        # above is side-effect-free, so an unpicked half-open member
        # keeps its trial for the pick that will really send a request.
        # A breaker that raced OPEN between filter and pick costs one
        # candidate, not the whole request.
        rest = [m for m in cands if m not in pick_from]
        for group in (list(pick_from), rest):
            while group:
                with self._rr_lock:
                    m = group[self._rr % len(group)]
                    self._rr += 1
                if m.breaker.allow(now):
                    return m
                group.remove(m)
        return None

    def route_predict(self, body: bytes,
                      trace_header: Optional[str] = None) -> tuple:
        """One client request → (status, body_bytes, ctype): least-loaded
        pick (hedged past ``hedge_after_ms``), then the PR-8 retry-once-
        on-alternate under the token-bucket budget.

        With tracing on, the whole routing decision is one
        ``fabric/route`` span — pick, hedge, retry, breaker outcomes as
        attrs — and the context is forwarded to the member via
        ``X-Mxr-Trace`` (the member's frontend span chains under it).
        Context comes from the client's header, a ``"trace"`` doc field
        sniffed from the opaque body, or a fresh mint; tracing off skips
        all of it.

        With a watchtower attached the router also observes its own
        end-to-end route latency into ``fabric/route_time`` — the burn-
        rate rule's signal.  Router-observed is load-bearing: a member-
        side delay fault (``MXR_FAULT_NET_DELAY_MS``) is injected at the
        member's HTTP frontend AFTER its engine, so member engine hists
        never see it; only the router does.  Gated on the watchtower so
        watch-off keeps the telemetry JSONL stream byte-identical."""
        if self.watchtower is not None:
            t0 = time.monotonic()
            try:
                return self._route_predict_traced(body, trace_header)
            finally:
                telemetry.get().observe("fabric/route_time",
                                        time.monotonic() - t0)
        return self._route_predict_traced(body, trace_header)

    def _route_predict_traced(self, body: bytes,
                              trace_header: Optional[str] = None) -> tuple:
        tracer = tracectx.get()
        if not tracer.enabled:
            return self._route_predict(body, None, NULL_SPAN)
        raw_t = trace_header
        if not raw_t and body:
            match = _TRACE_BODY_RE.search(body)
            if match:
                raw_t = match.group(1).decode("ascii")
        ctx = ((TraceContext.parse(raw_t) if raw_t else None)
               or tracer.mint())
        with tracer.span(ctx, "fabric/route") as sp:
            headers = ({TRACE_HEADER: sp.ctx.to_header()}
                       if sp.ctx is not None else None)
            status, raw, ctype = self._route_predict(body, headers, sp)
            sp.set(status=status if status is not None else 0)
        return status, raw, ctype

    def _route_predict(self, body: bytes, headers: Optional[dict],
                       sp) -> tuple:
        pool = self.pool
        m = self._pick()
        if m is None:
            pool.count("no_ready")
            sp.set(shed=True)
            return self._shed(f"no routable members "
                              f"(0/{len(pool.members)} reachable) — "
                              f"retry with backoff")
        sp.set(member=m.name)
        status, raw, ctype, transport_err, hedge = \
            self._attempt_hedged(m, body, headers, sp)
        if transport_err is None and status != 503:
            return status, raw, ctype
        if not self.retry_bucket.take():
            pool.count("retry_budget_exhausted")
            sp.set(shed=True, error=transport_err)
            return self._shed("member failed and the retry budget is "
                              "exhausted — retry with backoff")
        pool.count("retry")
        sp.set(retried=True)
        exclude = (m, hedge) if hedge is not None else (m,)
        m2 = self._pick(exclude=exclude)
        if m2 is None:
            if transport_err is not None:
                sp.set(shed=True, error=transport_err)
                return self._shed(f"member {m.name} failed "
                                  f"({transport_err}) and no alternate "
                                  f"is routable — retry with backoff")
            return status, raw, ctype  # lone member's own 503 stands
        sp.set(retry_member=m2.name)
        status2, raw2, ctype2, err2 = self._forward_to(m2, body, headers)
        if err2 is None:
            pool.count("retry_ok")
            return status2, raw2, ctype2
        sp.set(error=f"{transport_err or status}; then {err2}")
        return 502, json.dumps(
            {"error": f"members failed: {transport_err or status}; "
                      f"then {err2}"}).encode(), "application/json"

    def _attempt_hedged(self, m, body, headers=None, sp=NULL_SPAN):
        """First attempt, with the tail hedge: past ``hedge_after_ms``
        the request is duplicated to a second member and the first 2xx
        wins.  Returns (status, raw, ctype, transport_err, hedge_member).
        A hedge is a latency bet against a slow member — counted apart
        from retries, which answer failures."""
        hedge_s = self.pool.opts.hedge_after_ms / 1e3
        if hedge_s <= 0:
            return self._forward_to(m, body, headers) + (None,)
        results: "queue.Queue" = queue.Queue()

        def run(member):
            results.put((member,)
                        + self._forward_to(member, body, headers))

        threading.Thread(target=run, args=(m,), daemon=True,
                         name="fabric-fwd").start()
        try:
            first = results.get(timeout=hedge_s)
        except queue.Empty:
            first = None
        if first is not None:
            return first[1:] + (None,)
        m2 = self._pick(exclude=(m,))
        if m2 is None:  # nobody to hedge to: wait the primary out
            return results.get(timeout=self.timeout_s + 10.0)[1:] + (None,)
        self.pool.count("hedge_fired")
        sp.set(hedged=True, hedge_member=m2.name)
        threading.Thread(target=run, args=(m2,), daemon=True,
                         name="fabric-hedge").start()
        def won(r):  # (member, status, raw, ctype, transport_err)
            return (r[4] is None and r[1] is not None
                    and 200 <= r[1] < 300)

        winner = results.get(timeout=self.timeout_s + 10.0)
        if not won(winner):
            other = results.get(timeout=self.timeout_s + 10.0)
            if won(other):
                winner = other
        if winner[0] is m2:
            self.pool.count("hedge_won")
            sp.set(hedge_won=True)
        return winner[1:] + (m2,)

    def _forward_to(self, m, body, headers=None):
        """(status, raw, ctype, transport_error) — in-flight counted for
        reload drains, outcome recorded on the member's breaker."""
        pool = self.pool
        # hedge threads and handler threads race on the same member; a
        # lost += / -= leaves inflight pinned nonzero and every later
        # reload of this member eats the full drain timeout
        with m.inflight_lock:
            m.inflight += 1
            m.requests += 1
        pool.counters["requests"] += 1
        try:
            if self._fwd_headers and headers:
                status, raw, ctype = self._forward(
                    m, "POST", "/predict", body, self.timeout_s,
                    headers=headers)
            else:
                status, raw, ctype = self._forward(m, "POST", "/predict",
                                                   body, self.timeout_s)
        except Exception as e:  # noqa: BLE001 — dead/hung/reset member
            pool.count("transport_error")
            pool.note_suspect(m)
            if m.breaker.record_failure():
                pool.count("breaker_open")
                logger.warning("fabric: breaker OPEN for member %s "
                               "(%d consecutive data-path failures)",
                               m.name, m.breaker.failures)
            return None, b"", "", f"{type(e).__name__}: {e}"
        finally:
            with m.inflight_lock:
                m.inflight -= 1
        if status in (500, 502, 504):
            if m.breaker.record_failure():
                pool.count("breaker_open")
                logger.warning("fabric: breaker OPEN for member %s "
                               "(%d consecutive 5xx)", m.name,
                               m.breaker.failures)
        elif status != 503:  # a shed is neither success nor fault
            m.breaker.record_success()
        return status, raw, ctype, None

    @staticmethod
    def _shed(msg: str) -> tuple:
        return (503, json.dumps({"error": msg}).encode(),
                "application/json")

    def metrics(self) -> dict:
        """Pool membership + per-member engine metrics (best-effort live
        fetch) + fabric aggregates — the operator's single pane."""
        out = {"fabric": self.pool.metrics()}
        agg: Dict[str, float] = {}
        per = {}
        for m in self.pool.routable_members():
            try:
                status, doc = m.http("GET", "/metrics", timeout=5.0)
            except Exception as e:  # noqa: BLE001 — member mid-death
                per[m.name] = {"error": f"{type(e).__name__}: {e}"}
                continue
            if status == 200 and isinstance(doc, dict):
                per[m.name] = doc
                for k, v in (doc.get("counters") or {}).items():
                    if isinstance(v, (int, float)):
                        agg[k] = agg.get(k, 0) + v
        out["engines"] = per
        out["aggregate_counters"] = agg
        out["generation"] = self.pool.generation
        if self.autoscaler is not None:
            out["autoscale"] = self.autoscaler.state()
        if self.watchtower is not None:
            out["watch"] = self.watchtower.state()
        tracer = tracectx.get()
        if tracer.enabled:
            out["trace"] = tracer.metrics()
        return out


def _point_gauge(v) -> dict:
    return {"count": 1, "mean": v, "min": v, "max": v, "last": v}


def fabric_prometheus(router: FabricRouter) -> str:
    """The fabric router's ``/metrics?format=prom`` body: the same
    ``fabric/*`` counter names as the JSON view and the telemetry
    report, through the shared exposition renderer."""
    pool = router.pool
    counters = {f"fabric/{k}": v for k, v in pool.counters.items()}
    gauges = {"fabric/ready_members": _point_gauge(pool.ready_count()),
              "fabric/members": _point_gauge(len(pool.members)),
              "fabric/generation": _point_gauge(pool.generation),
              "fabric/partition_active":
                  _point_gauge(int(pool.partition))}
    now = time.monotonic()
    with pool._lock:
        for m in pool.members.values():
            # gate on depth_t, not depth: _evict clears only depth_t
            # (the stale-gauge contract), so an evicted member keeps a
            # depth value with no receipt timestamp to age against
            if m.depth is not None and m.depth_t is not None:
                gauges[f"fabric/queue_depth/{m.name}"] = \
                    _point_gauge(m.depth)
                gauges[f"fabric/queue_depth_age_s/{m.name}"] = \
                    _point_gauge(round(now - m.depth_t, 3))
    if router.autoscaler is not None:
        a = router.autoscaler.state()
        for key in ("demand", "forecast", "slope"):
            gauges[f"autoscale/{key}"] = _point_gauge(a[key])
        for key, v in a["counters"].items():
            counters[f"autoscale/{key}"] = v
    tracer = tracectx.get()
    if tracer.enabled:
        for key, v in tracer.metrics().items():
            if key in ("spans_emitted", "spans_dropped", "tail_kept"):
                counters[f"trace/{key}"] = v
            elif isinstance(v, (int, float)):
                gauges[f"trace/{key}"] = _point_gauge(v)
    rank = telemetry.get().rank
    text = prometheus_text({rank: {"counters": counters,
                                   "gauges": gauges}})
    # aggregate fleet-size-by-state gauges (ISSUE 18): a real labeled
    # family, appended raw because the shared renderer only labels by
    # rank/stat — smoke scripts assert fleet size with one grep instead
    # of parsing the JSON membership view.  Every known state is always
    # emitted (zeros included) so an assertion on an absent state reads
    # 0, not a missing series; "ready" covers both member kinds (the
    # remote MEMBER_READY and local READY strings are one state).
    counts = pool.member_state_counts()
    known = (JOINING, MEMBER_READY, EVICTED, QUARANTINED, PARKED,
             "starting", "backoff", FAILED, STOPPED)
    lines = ["# HELP fabric_member_count members by state (local and "
             "remote)", "# TYPE fabric_member_count gauge"]
    for state in list(known) + sorted(set(counts) - set(known)):
        lines.append(f'fabric_member_count{{state="{state}"}} '
                     f'{counts.get(state, 0)}')
    if router.watchtower is not None:
        from mx_rcnn_tpu.telemetry.watch import alert_state_lines
        lines += alert_state_lines(router.watchtower)
    return text + "\n".join(lines) + "\n"


class _FabricHandler(_Handler):
    """Fabric router HTTP: ``/predict`` forwards bytes to the picked
    member, ``/admin/register`` admits remote members, ``/admin/reload``
    rolls a checkpoint across the whole fabric, ``/metrics`` is the
    folded membership+engine view (JSON or Prometheus)."""

    router: FabricRouter = None

    def do_GET(self):
        path, _, query = self.path.partition("?")
        pool = self.router.pool
        if path == "/healthz":
            self._reply(200, {"status": "ok", "role": "fabric-router",
                              "ready_members": pool.ready_count()})
        elif path == "/readyz":
            n = pool.ready_count()
            self._reply(200 if n > 0 else 503,
                        {"ready": n > 0, "ready_members": n,
                         "members": len(pool.members),
                         "partition": pool.partition,
                         "generation": pool.generation})
        elif path == "/metrics":
            accept = self.headers.get("Accept", "")
            if "format=prom" in query or "text/plain" in accept:
                self._reply_raw(200,
                                fabric_prometheus(self.router).encode(),
                                PROM_CONTENT_TYPE)
            else:
                self._reply(200, self.router.metrics())
        elif path == "/alerts" and self.router.watchtower is not None:
            self._reply(200, self.router.watchtower.alerts_doc())
        elif path == "/history" and self.router.watchtower is not None:
            metric = query_param(query, "metric")
            if not metric:
                self._reply(400, {"error": "need ?metric=NAME"})
                return
            try:
                window = float(query_param(query, "window") or 300.0)
            except ValueError:
                self._reply(400, {"error": "window must be a number "
                                           "of seconds"})
                return
            self._reply(200,
                        self.router.watchtower.history_doc(metric,
                                                           window))
        else:
            # watch-off: /alerts and /history fall through to the same
            # 404 as any unknown path — byte parity with PR-19
            self._reply(404, {"error": f"no route {self.path}"})

    def do_POST(self):
        if self.path == "/predict":
            length = int(self.headers.get("Content-Length", 0))
            body = self.rfile.read(length)
            status, raw, ctype = self.router.route_predict(
                body, trace_header=self.headers.get(TRACE_HEADER))
            self._reply_raw(status, raw, ctype or "application/json")
            return
        try:
            length = int(self.headers.get("Content-Length", 0))
            doc = json.loads(self.rfile.read(length) or b"{}")
        except (ValueError, json.JSONDecodeError) as e:
            self._reply(400, {"error": f"bad JSON body: {e}"})
            return
        if self.path == "/admin/register":
            addr = doc.get("address")
            if not addr:
                self._reply(400, {"error": "body needs 'address'"})
                return
            try:
                member, created = self.router.pool.register(addr)
            except ValueError as e:
                self._reply(400, {"error": str(e)})
                return
            self._reply(200, {"member": member.name, "created": created,
                              "state": member.state})
        elif self.path == "/admin/reload":
            ok = self.router.pool.reload_to(doc)
            self._reply(200 if ok else 409,
                        {"ok": ok,
                         "generation": self.router.pool.generation})
        else:
            self._reply(404, {"error": f"no route {self.path}"})


def make_fabric_server(router: FabricRouter, port: Optional[int] = None,
                       host: str = "127.0.0.1",
                       unix_socket: Optional[str] = None):
    """The fabric's front door — same transports as ``make_server``,
    driven by a :class:`FabricRouter`."""
    if (port is None) == (unix_socket is None):
        raise ValueError("pass exactly one of port / unix_socket")

    class Handler(_FabricHandler):
        pass

    Handler.router = router
    if unix_socket is not None:
        return _UnixHTTPServer(unix_socket, Handler)
    return _TCPHTTPServer((host, port), Handler)


def register_with_router(router_address: str, advertise: str,
                         stop: Optional[threading.Event] = None,
                         interval_s: float = 2.0,
                         timeout_s: float = 5.0) -> threading.Event:
    """Replica-side ``--join``: a daemon thread POSTs
    ``/admin/register`` (advertising ``advertise``) until the router
    acks, then exits — re-admission after an eviction is the ROUTER's
    re-probe loop, not a re-register.  Returns the stop event."""
    stop = stop or threading.Event()

    def run():
        while not stop.is_set():
            try:
                status, doc = address_request(
                    router_address, "POST", "/admin/register",
                    {"address": advertise}, timeout=timeout_s)
                if status == 200:
                    logger.info("joined fabric router %s as member %s",
                                router_address, doc.get("member"))
                    return
                logger.warning("fabric join rejected (%s): %s",
                               status, doc)
            except Exception as e:  # noqa: BLE001 — router not up yet
                logger.debug("fabric join attempt failed: %s", e)
            stop.wait(interval_s)

    threading.Thread(target=run, daemon=True,
                     name="fabric-join").start()
    return stop
