"""Parent-side half of the multi-replica serving plane: supervision +
routing (ISSUE 8 tentpole; ROADMAP item 1).

Topology (``serve.py --replicas N``):

    clients → router (this module, parent process, TCP or Unix socket)
                ├── replica 0: serve.py --replica-index 0 over unix sock
                ├── replica 1: ...
                └── replica N-1

The supervisor owns the robustness contract:

* **Probes** — per-replica liveness (``/healthz``) and readiness
  (``/readyz``, warmup complete + admissions open); a replica is only
  routable once ready, and a slow-starting replica is alive-but-unready,
  never killed.
* **Crash/hang detection** — ``waitpid`` catches crashes (kill -9);
  ``hang_probes`` consecutive probe timeouts catch a wedged-but-alive
  process, which is then SIGKILLed.
* **Respawn** — exponential backoff per replica with the PR-4
  ``MAX_WORKER_RESPAWNS``-style systemic limit: a replica that keeps
  dying is marked FAILED with a flight-recorder dump instead of
  grinding forever; when EVERY replica has failed the ``broken`` event
  fires and the driver exits nonzero.
* **Retry-once** — a request in flight on a replica that dies (transport
  error) or sheds (503) is retried ONCE on an alternate replica, under a
  token-bucket retry budget so a flapping replica cannot amplify load
  into the survivors.  Budget exhausted → early 503, the PR-6 shed
  philosophy: capacity shrank, refuse cheaply.
* **Rolling hot-reload** — ``reload_to(target)`` rolls a new checkpoint
  generation through READY replicas one at a time (unroute → wait
  in-flight → ``POST /admin/reload`` → re-route), keeping N-1 replicas
  serving throughout.  The replica-local canary (serve/replica.py)
  rejects bad weights; on rejection the roll aborts and already-swapped
  replicas are rolled back to the previous target.  The plane-wide
  generation counter only ever advances (monotonic under ``_gen_lock``)
  and is exposed on the router's ``/metrics``.

``poll(now=None)`` is the injectable-clock test surface (the
``SLOController.tick`` pattern): tests drive the whole state machine
deterministically with fake clocks, procs, and probes; production wraps
it in the monitor thread.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from mx_rcnn_tpu import telemetry
from mx_rcnn_tpu.logger import logger
from mx_rcnn_tpu.serve.frontend import (_TCPHTTPServer, _UnixHTTPServer,
                                        _Handler, unix_http_request,
                                        unix_http_request_raw)

# mirror of data/workers.py MAX_WORKER_RESPAWNS: past this many respawns
# of ONE replica the failure is systemic (bad weights, broken device,
# OOM loop) — respawning again would grind, not heal
MAX_REPLICA_RESPAWNS = 8

# replica states
STARTING = "starting"   # spawned, alive, not yet ready (warming)
READY = "ready"         # /readyz 200 — routable unless mid-reload
BACKOFF = "backoff"     # died; waiting out the respawn backoff
FAILED = "failed"       # systemic limit crossed — no more respawns
STOPPED = "stopped"     # deliberate shutdown


@dataclass(frozen=True)
class SupervisorOptions:
    probe_interval_s: float = 1.0     # monitor poll period
    probe_timeout_s: float = 5.0      # one probe's HTTP timeout
    hang_probes: int = 3              # consecutive failures = hung
    start_timeout_s: float = 600.0    # spawn → ready ceiling (compiles!)
    backoff_base_s: float = 0.5       # first respawn delay
    backoff_max_s: float = 30.0       # backoff ceiling
    max_respawns: int = MAX_REPLICA_RESPAWNS
    stable_s: float = 60.0            # ready this long resets the backoff
    retry_budget: int = 16            # token-bucket burst capacity
    retry_refill_per_s: float = 4.0   # sustained retry rate
    drain_timeout_s: float = 30.0     # router-side in-flight wait (reload)
    reload_timeout_s: float = 120.0   # one replica's /admin/reload ceiling


@dataclass
class ReplicaSpec:
    """How to launch one replica: its argv, its Unix socket, its index,
    and any extra env (device pinning group)."""
    argv: List[str]
    sock: str
    index: int
    env: Dict[str, str] = field(default_factory=dict)


class ReplicaHandle:
    """Mutable supervision state for one replica slot.  State transitions
    happen under the supervisor's lock; probes and HTTP calls happen
    outside it."""

    def __init__(self, spec: ReplicaSpec):
        self.spec = spec
        self.proc = None
        self.state = BACKOFF       # spawn_all() brings it up
        self.routable = False
        self.reloading = False     # mid-swap: suspect-clear must not route
        self.generation = 0
        self.respawns = 0          # lifetime respawn count (systemic limit)
        self.failures = 0          # consecutive failures (backoff input)
        self.probe_fails = 0       # consecutive probe misses (hang detect)
        self.inflight = 0          # router requests currently forwarded
        self.spawn_t = 0.0
        self.ready_t = 0.0
        self.next_spawn_t = 0.0    # eligible-to-respawn instant
        self.last_exit = None

    @property
    def index(self) -> int:
        return self.spec.index

    @property
    def pid(self):
        return getattr(self.proc, "pid", None)


class TokenBucket:
    """The retry budget: ``capacity`` burst tokens refilled at
    ``refill_per_s`` — a flapping replica can push at most a bounded
    retry rate into the survivors."""

    def __init__(self, capacity: int, refill_per_s: float):
        self.capacity = float(capacity)
        self.refill_per_s = float(refill_per_s)
        self._tokens = float(capacity)
        self._t = None
        self._lock = threading.Lock()

    def take(self, now: Optional[float] = None) -> bool:
        now = time.monotonic() if now is None else now
        with self._lock:
            if self._t is not None and now > self._t:
                self._tokens = min(self.capacity, self._tokens
                                   + (now - self._t) * self.refill_per_s)
            self._t = now
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return True
            return False


def build_child_argv(argv: List[str], sock: str, index: int) -> List[str]:
    """Parent argv → one replica child's argv: strip the parent-only
    transport/watch flags, keep everything else (model, checkpoint,
    engine knobs, ``--replicas`` for the obs world size), and append the
    child's Unix socket + ``--replica-index`` (which routes main() to
    the replica path before the supervisor branch can recurse)."""
    strip = {"--port": 1, "--host": 1, "--unix-socket": 1,
             "--watch-checkpoints": 1, "--watch-interval-s": 1,
             "--replica-devices": 1,
             # fabric flags are the ROUTER's business — a fork child is
             # a plain unix-socket replica even under a fabric parent
             "--fabric": 0, "--join": 1, "--advertise": 1,
             "--pool-file": 1, "--hedge-after-ms": 1,
             "--partition-floor": 1,
             # the capacity authority is the PARENT's business too — a
             # fork child must never run its own autoscaler
             "--autoscale": 0, "--autoscale-min": 1,
             "--autoscale-max": 1, "--autoscale-target-depth": 1,
             "--autoscale-interval-s": 1, "--autoscale-standby": 1}
    out = [sys.executable, argv[0]]
    i = 1
    while i < len(argv):
        arg = argv[i]
        name = arg.split("=", 1)[0]
        if name in strip:
            i += 1 + (0 if "=" in arg else strip[name])
            continue
        out.append(arg)
        i += 1
    out += ["--unix-socket", sock, "--replica-index", str(index)]
    return out


def replica_specs(argv: List[str], n: int, sock_dir: str,
                  devices: str = "") -> List[ReplicaSpec]:
    """One spec per replica: sockets under ``sock_dir``, device groups
    split from the ``--replica-devices`` semicolon list (group i → child
    env ``MXR_REPLICA_DEVICES``)."""
    groups = [g.strip() for g in devices.split(";")] if devices else []
    specs = []
    for i in range(n):
        sock = os.path.join(sock_dir, f"replica_{i}.sock")
        env = {"MXR_REPLICA_INDEX": str(i)}
        if i < len(groups) and groups[i]:
            env["MXR_REPLICA_DEVICES"] = groups[i]
        specs.append(ReplicaSpec(build_child_argv(argv, sock, i),
                                 sock, i, env))
    return specs


class ReplicaSupervisor:
    """Owns N :class:`ReplicaHandle` slots.  ``spawn_fn(spec) → proc``,
    ``probe_fn(handle, path) → (status, doc)`` and ``reload_fn(handle,
    target) → (status, doc)`` are injectable for deterministic tests;
    defaults subprocess.Popen + Unix-socket HTTP."""

    def __init__(self, specs: List[ReplicaSpec],
                 opts: Optional[SupervisorOptions] = None,
                 spawn_fn: Optional[Callable] = None,
                 probe_fn: Optional[Callable] = None,
                 reload_fn: Optional[Callable] = None):
        self.opts = opts or SupervisorOptions()
        self.handles = [ReplicaHandle(s) for s in specs]
        self._spawn_fn = spawn_fn or self._default_spawn
        self._probe_fn = probe_fn or self._default_probe
        self._reload_fn = reload_fn or self._default_reload
        self._lock = threading.Lock()
        self._gen_lock = threading.Lock()
        self._roll_lock = threading.Lock()  # one rolling reload at a time
        self.generation = 0
        self._target: Optional[dict] = None       # current generation's
        self._prev_target: Optional[dict] = None  # ...and the one before
        self.broken = threading.Event()  # every replica FAILED — systemic
        self.counters = {"spawn": 0, "respawn": 0, "systemic": 0,
                         "hang_kill": 0, "reload": 0, "reload_rollback": 0,
                         "retry": 0, "retry_ok": 0,
                         "retry_budget_exhausted": 0, "no_ready": 0,
                         "transport_error": 0, "scale_spawn": 0,
                         "scale_retire": 0}
        self.retry_bucket = TokenBucket(self.opts.retry_budget,
                                        self.opts.retry_refill_per_s)
        self._stop = threading.Event()
        self._wake = threading.Event()  # router nudge: poll soon
        self._thread: Optional[threading.Thread] = None

    # -- defaults (production wiring) ------------------------------------

    def _default_spawn(self, spec: ReplicaSpec):
        env = dict(os.environ, **spec.env)
        return subprocess.Popen(spec.argv, env=env)

    def _default_probe(self, handle: ReplicaHandle, path: str):
        return unix_http_request(handle.spec.sock, "GET", path,
                                 timeout=self.opts.probe_timeout_s)

    def _default_reload(self, handle: ReplicaHandle, target: dict):
        return unix_http_request(handle.spec.sock, "POST", "/admin/reload",
                                 target,
                                 timeout=self.opts.reload_timeout_s)

    # -- lifecycle -------------------------------------------------------

    def spawn_all(self, now: Optional[float] = None):
        now = time.monotonic() if now is None else now
        for h in self.handles:
            self._spawn(h, now)

    # -- on-demand capacity (ISSUE 18: the autoscaler's spawn API) -------

    def _next_spec_locked(self) -> ReplicaSpec:
        """Synthesize the next slot's spec from slot 0's: same argv with
        the trailing ``--unix-socket SOCK --replica-index I`` pair
        (appended last by :func:`build_child_argv`, so the positions are
        a contract) rebound to a fresh index and socket."""
        if not self.handles:
            raise RuntimeError("add_replica on an empty supervisor "
                               "needs an explicit spec — there is no "
                               "slot to template from")
        tmpl = self.handles[0].spec
        idx = max(h.index for h in self.handles) + 1
        sock = os.path.join(os.path.dirname(tmpl.sock),
                            f"replica_{idx}.sock")
        argv = list(tmpl.argv)
        argv[-3] = sock
        argv[-1] = str(idx)
        env = dict(tmpl.env, MXR_REPLICA_INDEX=str(idx))
        env.pop("MXR_REPLICA_DEVICES", None)  # device pin is per-slot
        return ReplicaSpec(argv, sock, idx, env)

    def add_replica(self, spec: Optional[ReplicaSpec] = None,
                    now: Optional[float] = None) -> ReplicaHandle:
        """Grow the plane by one slot at runtime and spawn it
        immediately — the autoscaler's scale-up actuation.  The new
        replica warms from the same shared AOT program cache as its
        siblings, so bringing it up costs a cache load, not a compile.
        Returns the new handle (callers under a fabric adopt it with
        :meth:`ReplicaPool.adopt_handle`)."""
        now = time.monotonic() if now is None else now
        with self._lock:
            if spec is None:
                spec = self._next_spec_locked()
            h = ReplicaHandle(spec)
            self.handles.append(h)
        self.counters["scale_spawn"] += 1
        telemetry.get().counter("replica/scale_spawn")
        self._spawn(h, now)
        self._wake.set()
        return h

    def retire_replica(self, h: ReplicaHandle,
                       graceful_timeout: float = 5.0) -> bool:
        """Shrink the plane by one slot — the autoscaler's scale-down
        actuation: unroute → wait out the router's in-flight requests
        (the PR-8 drain, minus the swap) → SIGTERM (the replica drains
        its own engine queue on the way out) → reap → drop the slot.
        Returns False for a handle this supervisor doesn't own."""
        with self._lock:
            if h not in self.handles:
                return False
            h.routable = False
            h.reloading = True  # suspect-clear must not re-route it
        try:
            self._wait_inflight_drained(h)
        finally:
            with self._lock:
                h.reloading = False
                h.state = STOPPED
                h.routable = False
        proc = h.proc
        if proc is not None and proc.poll() is None:
            try:
                proc.terminate()
            except OSError:
                pass
            try:
                proc.wait(timeout=graceful_timeout)
            except subprocess.TimeoutExpired:
                try:
                    proc.kill()
                    proc.wait(timeout=5.0)
                except (OSError, subprocess.TimeoutExpired):
                    pass
        try:
            os.unlink(h.spec.sock)
        except OSError:
            pass
        with self._lock:
            if h in self.handles:
                self.handles.remove(h)
        self.counters["scale_retire"] += 1
        telemetry.get().counter("replica/scale_retire")
        logger.info("replica %d: retired (scale-down drain complete)",
                    h.index)
        return True

    def start(self) -> "ReplicaSupervisor":
        assert self._thread is None, "supervisor already started"
        self.spawn_all()

        def monitor():
            while not self._stop.is_set():
                self._wake.wait(self.opts.probe_interval_s)
                self._wake.clear()
                if self._stop.is_set():
                    return
                try:
                    self.poll()
                except Exception:  # noqa: BLE001 — supervision must survive
                    logger.exception("supervisor poll failed")

        self._thread = threading.Thread(target=monitor,
                                        name="replica-supervisor",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self, timeout: float = 10.0):
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None
        self.sweep(graceful_timeout=timeout)

    def sweep(self, graceful_timeout: float = 5.0):
        """Leave no orphans: SIGTERM every live child, then SIGKILL the
        stragglers, and unlink their sockets.  Safe to call repeatedly
        and from atexit/signal paths."""
        with self._lock:
            handles = list(self.handles)
            for h in handles:
                h.state = STOPPED
                h.routable = False
        live = [h for h in handles
                if h.proc is not None and h.proc.poll() is None]
        for h in live:
            try:
                h.proc.terminate()
            except OSError:
                pass
        deadline = time.monotonic() + graceful_timeout
        for h in live:
            try:
                h.proc.wait(timeout=max(0.0, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                try:
                    h.proc.kill()
                    h.proc.wait(timeout=5.0)
                except (OSError, subprocess.TimeoutExpired):
                    pass
        for h in handles:
            try:
                os.unlink(h.spec.sock)
            except OSError:
                pass

    # -- state machine ---------------------------------------------------

    def _spawn(self, h: ReplicaHandle, now: float):
        h.proc = self._spawn_fn(h.spec)
        with self._lock:
            h.state = STARTING
            h.routable = False
            h.probe_fails = 0
            h.spawn_t = now
        self.counters["spawn"] += 1
        telemetry.get().counter("replica/spawn")
        logger.info("replica %d: spawned (pid %s)", h.index, h.pid)

    def _declare_dead(self, h: ReplicaHandle, now: float, reason: str,
                      kill: bool = False):
        """Crash/hang/start-timeout → BACKOFF (or FAILED past the
        systemic limit)."""
        if kill and h.proc is not None and h.proc.poll() is None:
            try:
                h.proc.kill()
            except OSError:
                pass
            self.counters["hang_kill"] += 1
            telemetry.get().counter("replica/hang_kill")
        rc = h.proc.poll() if h.proc is not None else None
        with self._lock:
            h.routable = False
            h.last_exit = rc
            h.failures += 1
            h.probe_fails = 0
            # the NEXT process boots on the original weights: forget the
            # handle's generation so _on_ready catches it up to the plane
            h.generation = 0
            if h.respawns >= self.opts.max_respawns:
                h.state = FAILED
                systemic = True
            else:
                h.state = BACKOFF
                delay = min(self.opts.backoff_base_s
                            * (2.0 ** (h.failures - 1)),
                            self.opts.backoff_max_s)
                h.next_spawn_t = now + delay
                systemic = False
        tel = telemetry.get()
        tel.counter("replica/down")
        tel.dump_flight("replica_down", index=h.index, cause=reason,
                        exit_code=rc, respawns=h.respawns)
        if systemic:
            self.counters["systemic"] += 1
            tel.counter("replica/systemic")
            tel.dump_flight("replica_systemic", index=h.index,
                            respawns=h.respawns, cause=reason)
            logger.error("replica %d: FAILED after %d respawns (%s) — "
                         "systemic, not respawning (the PR-4 respawn-"
                         "limit contract: a replica that keeps dying has "
                         "a cause respawning can't fix)",
                         h.index, h.respawns, reason)
            if all(x.state == FAILED for x in self.handles):
                logger.error("every replica has failed — serving plane "
                             "is down")
                self.broken.set()
        else:
            logger.warning("replica %d: down (%s, exit %s) — respawn in "
                           "%.1fs (attempt %d/%d)", h.index, reason, rc,
                           max(0.0, h.next_spawn_t - now),
                           h.failures, self.opts.max_respawns)

    def _on_ready(self, h: ReplicaHandle, now: float):
        with self._lock:
            h.state = READY
            h.routable = True
            h.ready_t = now
            h.probe_fails = 0
        logger.info("replica %d: ready (%.1fs after spawn)", h.index,
                    now - h.spawn_t)
        # a respawned replica boots on the ORIGINAL weights — catch it up
        # to the plane's current generation before clients see stale boxes
        target = self._target
        if target is not None and h.generation < self.generation:
            self._reload_one(h, dict(target,
                                     generation=self.generation))

    def note_suspect(self, h: ReplicaHandle):
        """Router feedback: a forward to this replica failed at the
        transport level.  Unroute it immediately and nudge the monitor —
        waitpid/probes confirm (or clear) on the next poll."""
        with self._lock:
            if h.state == READY:
                h.routable = False
                h.probe_fails = max(h.probe_fails, 1)
        self._wake.set()

    def poll(self, now: Optional[float] = None):
        """One supervision step over every replica (called by the monitor
        thread each ``probe_interval_s``; tests call it directly with a
        fake clock).  Probe I/O runs outside the lock."""
        now = time.monotonic() if now is None else now
        # snapshot: add_replica/retire_replica mutate the slot list from
        # the autoscaler's thread while this loop is mid-iteration
        with self._lock:
            handles = list(self.handles)
        for h in handles:
            with self._lock:
                state = h.state
            if state in (FAILED, STOPPED):
                continue
            rc = h.proc.poll() if h.proc is not None else -1
            if state in (STARTING, READY) and rc is not None:
                self._declare_dead(h, now, reason=f"exit {rc}")
                continue
            if state == STARTING:
                status = self._try_probe(h, "/readyz")
                if status == 200:
                    self._on_ready(h, now)
                elif now - h.spawn_t > self.opts.start_timeout_s:
                    self._declare_dead(h, now, reason="start timeout",
                                       kill=True)
            elif state == READY:
                status = self._try_probe(h, "/healthz")
                if status == 200:
                    with self._lock:
                        h.probe_fails = 0
                        # stable long enough → forgive the backoff history
                        if h.failures and now - h.ready_t > self.opts.stable_s:
                            h.failures = 0
                        if (not h.routable and h.state == READY
                                and not h.reloading):
                            h.routable = True  # suspect cleared by probe
                else:
                    with self._lock:
                        h.probe_fails += 1
                        fails = h.probe_fails
                    if fails >= self.opts.hang_probes:
                        self._declare_dead(
                            h, now, kill=True,
                            reason=f"hung ({fails} probe timeouts)")
            elif state == BACKOFF and now >= h.next_spawn_t:
                with self._lock:
                    h.respawns += 1
                self.counters["respawn"] += 1
                telemetry.get().counter("replica/respawn")
                self._spawn(h, now)
        tel = telemetry.get()
        tel.gauge("replica/ready", self.ready_count())
        tel.gauge("replica/generation", self.generation)

    def _try_probe(self, h: ReplicaHandle, path: str) -> Optional[int]:
        try:
            status, _ = self._probe_fn(h, path)
            return status
        except Exception:  # noqa: BLE001 — any probe failure is a miss
            return None

    # -- routing support -------------------------------------------------

    def ready_handles(self) -> List[ReplicaHandle]:
        with self._lock:
            return [h for h in self.handles
                    if h.state == READY and h.routable]

    def ready_count(self) -> int:
        return len(self.ready_handles())

    # -- rolling hot reload ----------------------------------------------

    def _wait_inflight_drained(self, h: ReplicaHandle) -> bool:
        deadline = time.monotonic() + self.opts.drain_timeout_s
        while h.inflight > 0:
            if time.monotonic() > deadline:
                return False
            time.sleep(0.01)
        return True

    def _reload_one(self, h: ReplicaHandle, target: dict) -> bool:
        """Unroute → wait router in-flight → swap → re-route.  The
        replica's own drain handles requests already inside its engine;
        this handles the ones on the wire."""
        with self._lock:
            h.routable = False
            h.reloading = True
        try:
            self._wait_inflight_drained(h)
            try:
                status, doc = self._reload_fn(h, target)
            except Exception as e:  # noqa: BLE001 — treat as rejection
                status, doc = 0, {"error": f"{type(e).__name__}: {e}"}
            if status == 200:
                with self._lock:
                    h.generation = int(target.get("generation",
                                                  h.generation))
                self.counters["reload"] += 1
                telemetry.get().counter("replica/reload")
                logger.info("replica %d: generation %s live "
                            "(%s recompiles during swap)", h.index,
                            doc.get("generation"),
                            doc.get("recompiles_during_swap"))
                return True
            logger.error("replica %d: reload rejected (%s): %s", h.index,
                         status, doc.get("error", doc))
            return False
        finally:
            with self._lock:
                h.reloading = False
                if h.state == READY:
                    h.routable = True

    def reload_to(self, target: dict) -> bool:
        """Roll ``target`` through every READY replica one at a time —
        N-1 replicas keep serving throughout, so a rolling swap drops
        zero 2xx-eligible requests.  On a mid-roll rejection (canary):
        abort, roll already-swapped replicas back to the previous
        target, and leave the plane generation unchanged.  Returns
        overall success; the generation counter is monotonic — it only
        ever advances, and only on a fully-rolled plane."""
        with self._roll_lock:
            with self._gen_lock:
                gen = self.generation + 1
            target = dict(target, generation=gen)
            swapped: List[ReplicaHandle] = []
            victims = [h for h in self.handles if h.state == READY]
            if not victims:
                logger.warning("reload_to: no ready replicas to roll")
                return False
            for h in victims:
                if h.state != READY:
                    continue  # died mid-roll; catch-up reload on respawn
                if self._reload_one(h, target):
                    swapped.append(h)
                    continue
                # rejection: the replica rolled ITSELF back; undo the
                # already-swapped ones so the plane stays one-generation
                self.counters["reload_rollback"] += 1
                tel = telemetry.get()
                tel.counter("replica/reload_rollback")
                tel.dump_flight("reload_roll_aborted", index=h.index,
                                generation=gen)
                prev = self._target
                if prev is not None:
                    back = dict(prev, generation=self.generation)
                    for hs in swapped:
                        self._reload_one(hs, back)
                elif swapped:
                    logger.error(
                        "reload_to: generation %d rejected on replica %d "
                        "AFTER %d replica(s) swapped, and there is no "
                        "prior reload target to roll back to (they hold "
                        "boot weights on disk only) — plane is mixed "
                        "until the next good save", gen, h.index,
                        len(swapped))
                return False
            with self._gen_lock:
                self.generation = max(self.generation, gen)
            self._prev_target, self._target = self._target, target
            # a replica that respawned DURING the roll came back on its
            # boot weights and wasn't in the victim list — catch it up now
            for h in self.handles:
                if h.state == READY and h.generation < gen:
                    self._reload_one(h, target)
            telemetry.get().gauge("replica/generation", self.generation)
            logger.info("rolling reload complete: generation %d live on "
                        "%d replica(s)", self.generation, len(swapped))
            return True

    # -- introspection ---------------------------------------------------

    def metrics(self) -> dict:
        with self._lock:
            replicas = {
                str(h.index): {
                    "state": h.state, "pid": h.pid,
                    "routable": h.routable, "generation": h.generation,
                    "respawns": h.respawns, "inflight": h.inflight,
                    "probe_fails": h.probe_fails,
                    "last_exit": h.last_exit,
                } for h in self.handles}
        return {"generation": self.generation,
                "ready": self.ready_count(),
                "replicas": replicas,
                "counters": dict(self.counters),
                "broken": self.broken.is_set()}


class ReplicaRouter:
    """Round-robin request router over the supervisor's READY replicas,
    with retry-once-on-alternate under the retry budget.  Forward I/O is
    byte-level passthrough (no image re-encode); ``forward_fn(handle,
    method, path, body, timeout) → (status, bytes, ctype)`` is
    injectable for tests."""

    def __init__(self, sup: ReplicaSupervisor, forward_fn=None,
                 timeout_s: float = 600.0):
        self.sup = sup
        self.timeout_s = timeout_s
        self._forward = forward_fn or self._default_forward
        self._rr = 0
        self._rr_lock = threading.Lock()

    def _default_forward(self, handle, method, path, body, timeout):
        return unix_http_request_raw(handle.spec.sock, method, path,
                                     body=body, timeout=timeout)

    def _pick(self, exclude=()):
        ready = [h for h in self.sup.ready_handles() if h not in exclude]
        if not ready:
            return None
        with self._rr_lock:
            h = ready[self._rr % len(ready)]
            self._rr += 1
        return h

    def route_predict(self, body: bytes) -> tuple:
        """One client request → (status, body_bytes, ctype).  Transport
        failure or a shed (503: draining/queue-full) retries ONCE on an
        alternate replica under the retry budget; no ready replica at
        all is the graceful-degradation early 503."""
        sup = self.sup
        h = self._pick()
        if h is None:
            sup.counters["no_ready"] += 1
            telemetry.get().counter("replica/no_ready")
            return self._shed(f"no ready replicas "
                              f"(0/{len(sup.handles)} up) — retry with "
                              f"backoff")
        status, raw, ctype, transport_err = self._forward_to(h, body)
        if transport_err is None and status != 503:
            return status, raw, ctype
        # first attempt failed in a retryable way — alternate, budget
        # permitting (retry-once: a second failure is the client's 50x)
        if not sup.retry_bucket.take():
            sup.counters["retry_budget_exhausted"] += 1
            telemetry.get().counter("replica/retry_budget_exhausted")
            return self._shed("replica failed and the retry budget is "
                              "exhausted — retry with backoff")
        sup.counters["retry"] += 1
        telemetry.get().counter("replica/retry")
        h2 = self._pick(exclude=(h,))
        if h2 is None:
            if transport_err is not None:
                return self._shed(f"replica {h.index} failed "
                                  f"({transport_err}) and no alternate is "
                                  f"ready — retry with backoff")
            return status, raw, ctype  # lone replica's own 503 stands
        status2, raw2, ctype2, err2 = self._forward_to(h2, body)
        if err2 is None:
            sup.counters["retry_ok"] += 1
            telemetry.get().counter("replica/retry_ok")
            return status2, raw2, ctype2
        return 502, json.dumps(
            {"error": f"both replicas failed: {transport_err or status}; "
                      f"then {err2}"}).encode(), "application/json"

    def _forward_to(self, h, body):
        """(status, raw, ctype, transport_error) — counts in-flight so a
        rolling reload can wait out requests on the wire."""
        h.inflight += 1
        try:
            status, raw, ctype = self._forward(h, "POST", "/predict",
                                               body, self.timeout_s)
            return status, raw, ctype, None
        except Exception as e:  # noqa: BLE001 — dead/hung replica
            self.sup.counters["transport_error"] += 1
            telemetry.get().counter("replica/transport_error")
            self.sup.note_suspect(h)
            return None, b"", "", f"{type(e).__name__}: {e}"
        finally:
            h.inflight -= 1

    @staticmethod
    def _shed(msg: str) -> tuple:
        return (503, json.dumps({"error": msg}).encode(),
                "application/json")

    def metrics(self) -> dict:
        """Supervisor state + per-replica engine metrics (best-effort
        live fetch) + plane aggregates — the single pane the smoke
        script and operators read."""
        out = {"supervisor": self.sup.metrics()}
        agg: Dict[str, float] = {}
        per = {}
        for h in self.sup.ready_handles():
            try:
                status, doc = unix_http_request(h.spec.sock, "GET",
                                                "/metrics", timeout=5.0)
            except Exception as e:  # noqa: BLE001 — replica mid-death
                per[str(h.index)] = {"error": f"{type(e).__name__}: {e}"}
                continue
            if status == 200 and isinstance(doc, dict):
                per[str(h.index)] = doc
                for k, v in (doc.get("counters") or {}).items():
                    if isinstance(v, (int, float)):
                        agg[k] = agg.get(k, 0) + v
        out["engines"] = per
        out["aggregate_counters"] = agg
        out["generation"] = self.sup.generation
        return out


class _RouterHandler(_Handler):
    """Router-side HTTP: /predict forwards bytes, /healthz is the
    ROUTER's liveness, /readyz means ≥1 replica is routable, /metrics is
    the folded plane view.  (No engine attribute — this handler never
    touches one.)"""
    router: ReplicaRouter = None

    def do_GET(self):
        path, _, _ = self.path.partition("?")
        sup = self.router.sup
        if path == "/healthz":
            self._reply(200, {"status": "ok", "role": "router",
                              "ready_replicas": sup.ready_count()})
        elif path == "/readyz":
            n = sup.ready_count()
            self._reply(200 if n > 0 else 503,
                        {"ready": n > 0, "ready_replicas": n,
                         "replicas": len(sup.handles),
                         "generation": sup.generation})
        elif path == "/metrics":
            self._reply(200, self.router.metrics())
        else:
            self._reply(404, {"error": f"no route {self.path}"})

    def do_POST(self):
        if self.path != "/predict":
            self._reply(404, {"error": f"no route {self.path}"})
            return
        length = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(length)
        status, raw, ctype = self.router.route_predict(body)
        self._reply_raw(status, raw, ctype or "application/json")


def make_router_server(router: ReplicaRouter, port: Optional[int] = None,
                       host: str = "127.0.0.1",
                       unix_socket: Optional[str] = None):
    """The plane's front door — same transports as make_server, driven
    by a :class:`ReplicaRouter` instead of an engine."""
    if (port is None) == (unix_socket is None):
        raise ValueError("pass exactly one of port / unix_socket")

    class Handler(_RouterHandler):
        pass

    Handler.router = router
    if unix_socket is not None:
        return _UnixHTTPServer(unix_socket, Handler)
    return _TCPHTTPServer((host, port), Handler)
