"""Streaming serving: per-stream temporal state over the shared batcher.

Request/response serving treats every image as independent; a 30 fps
camera therefore pays the full prep → forward → NMS cost 30 times a
second even when nothing in the scene moved.  This module adds the two
wins that workload class leaves on the table:

* **Cross-stream temporal coalescing** — frames from *different* streams
  route into the one :class:`~mx_rcnn_tpu.serve.engine.ServeEngine`
  bucket batcher (``submit(..., stream=...)``), so same-bucket frames
  from many cameras share one ``serve_e2e`` dispatch.  The engine's
  flush bookkeeping counts how often that happens
  (``stream_coalesced_batches`` / batch occupancy on ``/metrics``).
* **Frame-delta skip** — an ON-DEVICE gate (registry kind
  ``frame_delta``, one tiny program per bucket, AOT-warm like
  ``device_prep``) computes the mean absolute pixel delta between the
  incoming staged uint8 frame and the stream's *reference* frame (the
  last frame that took the full path).  Below ``skip_thresh`` the
  stream's cached detections answer immediately — no batch, no forward,
  ZERO ``serve_e2e`` counter deltas (the 1/1/1 contract is untouched)
  and no ``serve/service_time`` observation (the SLO controller never
  sees a skip).  Above it — or on bucket change, generation change
  (weight hot-reload), or after ``max_skip`` consecutive skips — the
  frame takes the normal fused path and becomes the new reference.

Accuracy caveat: a skipped frame returns the reference frame's
detections verbatim.  ``skip_thresh`` is in mean-absolute uint8 units
over the whole staged bucket (padding included — a size change reads as
motion, which is the safe direction); 0 disables the gate entirely, and
a gate-off stream is byte-for-byte the ``/predict`` path (pinned by
``tests/test_stream.py``).

Ordering: one stream's frames are serialized by a per-stream lock and a
strictly-increasing ``seq`` (stale/duplicate seqs raise
:class:`StaleSeqError` — the frontend's 409), so per-stream response
order holds no matter how frames from other streams interleave in the
batcher.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Dict, Optional

import numpy as np

from mx_rcnn_tpu import telemetry
from mx_rcnn_tpu.data.image import stage_raw_to_bucket
from mx_rcnn_tpu.logger import logger
from mx_rcnn_tpu.serve.engine import RejectedError, ServeEngine
from mx_rcnn_tpu.telemetry import Hist, tracectx

KIND = "frame_delta"


class StaleSeqError(ValueError):
    """Frame ``seq`` not strictly greater than the stream's last — the
    frontend's 409 (a reconnecting client must resume past its high
    -water mark, not replay)."""


def _build_frame_delta():
    """The gate program: mean |a - b| over two staged uint8 buffers of
    one bucket shape, as a float32 scalar.  uint8 in, one scalar out —
    the readback is 4 bytes."""
    import jax
    import jax.numpy as jnp

    def delta(a, b):
        return jnp.mean(jnp.abs(a.astype(jnp.float32)
                                - b.astype(jnp.float32)))

    return jax.jit(delta)


@dataclass(frozen=True)
class StreamOptions:
    """Stream knobs (CLI: ``--stream-skip-thresh`` / ``--stream-max-skip``)."""

    # mean absolute uint8 pixel delta below which a frame skips the
    # forward and answers with the reference frame's cached detections;
    # <= 0 disables the gate (pure coalescing, byte-identical results)
    skip_thresh: float = 0.0
    # forced refresh cadence: after this many CONSECUTIVE skips the next
    # frame takes the full path regardless of its delta, bounding how
    # stale a static scene's detections can get
    max_skip: int = 30
    # stream-table cap: a frame for a NEW stream beyond this is rejected
    # (503) once no idle stream can be evicted
    max_streams: int = 256
    # streams idle this long are evictable when the table is full
    idle_ttl_s: float = 300.0

    def __post_init__(self):
        if self.max_skip < 1:
            raise ValueError(f"max_skip must be >= 1, got {self.max_skip}")
        if self.max_streams < 1:
            raise ValueError(
                f"max_streams must be >= 1, got {self.max_streams}")


class FrameResult:
    """Completion handle for one stream frame.  ``skipped`` frames share
    the REFERENCE frame's future (usually already resolved — the skip
    answers without touching the engine); forwarded frames carry their
    own live :class:`~mx_rcnn_tpu.serve.engine.ServeFuture`."""

    __slots__ = ("stream_id", "seq", "skipped", "delta", "_future")

    def __init__(self, stream_id, seq, skipped, delta, future):
        self.stream_id = stream_id
        self.seq = seq
        self.skipped = skipped
        self.delta = delta  # gate measurement (None when the gate is off
        # or the frame could not be compared — first frame, bucket change)
        self._future = future

    def done(self) -> bool:
        return self._future.done()

    def result(self, timeout: Optional[float] = None):
        """Detections records — the reference frame's when skipped."""
        return self._future.result(timeout)

    @property
    def queue_wait_s(self) -> Optional[float]:
        return None if self.skipped else self._future.queue_wait_s

    def cascade(self) -> Optional[dict]:
        """Cascade provenance of the frame that produced these records —
        the REFERENCE frame's when skipped — or None when the stream is
        not cascade-routed.  Call after :meth:`result`."""
        prov = getattr(self._future, "provenance", None)
        return prov() if prov is not None else None


class _StreamState:
    __slots__ = ("stream_id", "last_seq", "bucket", "ref_dev", "ref_future",
                 "generation", "skip_run", "last_used", "lock")

    def __init__(self, stream_id: str):
        self.stream_id = stream_id
        self.last_seq = 0
        self.bucket = None      # (H, W) bucket of the reference frame
        self.ref_dev = None     # reference staged uint8, ON DEVICE
        self.ref_future = None  # the reference frame's ServeFuture
        self.generation = -1    # engine generation the reference was served at
        self.skip_run = 0       # consecutive skips since the last forward
        self.last_used = time.monotonic()
        self.lock = threading.Lock()  # serializes one stream's frames


class StreamManager:
    """Per-stream state over a started :class:`ServeEngine`.

    Attaching (construction) sets ``engine.stream`` so ``/metrics`` grows
    the ``stream`` section and the dispatcher's flush bookkeeping counts
    cross-stream batch sharing.  ``registry`` defaults to the engine's
    (a real Predictor's ProgramRegistry); without one the gate falls back
    to a local jit — same math, no AOT markers."""

    def __init__(self, engine: ServeEngine,
                 options: Optional[StreamOptions] = None, registry=None,
                 cascade=None):
        self.engine = engine
        # a CascadeRouter (attached to this engine's model as the SMALL
        # side): forwarded frames route through it, so a hard frame's
        # answer escalates to the big model exactly like /predict.  The
        # frame-delta skip gate is untouched — a skip replays the
        # reference frame's (possibly escalated) records.
        self.cascade = cascade
        self.opts = options or StreamOptions()
        self._streams: Dict[str, _StreamState] = {}
        self._lock = threading.Lock()  # guards _streams + counters
        self.counters = {"frames": 0, "forwarded": 0, "skipped": 0,
                         "delta_dispatches": 0, "refreshes": 0,
                         "bucket_switches": 0, "stale_seq": 0, "evicted": 0}
        # skip-response latency lives in its OWN hist: skips must never
        # pollute serve/service_time or serve/request_time (the SLO
        # controller's signals measure real forwards only)
        self.hists: Dict[str, Hist] = {"stream/skip_time": Hist()}
        self._registry = registry if registry is not None else engine.registry
        if self._registry is not None:
            self._registry.register(KIND, _build_frame_delta)
            self._fn = self._registry.lookup(KIND)
        else:
            self._fn = _build_frame_delta()
        self._stride = max(engine.cfg.network.IMAGE_STRIDE,
                           engine.cfg.network.RPN_FEAT_STRIDE)
        engine.stream = self

    @property
    def gate_enabled(self) -> bool:
        return self.opts.skip_thresh > 0

    # -- the on-device gate ----------------------------------------------

    def _dispatch_delta(self, a_dev, b_dev, shape) -> float:
        """One gate dispatch with registry first-seen accounting (the
        ``device_prep`` recipe: note_dispatch + compile-seconds on first,
        AOT markers so a warm boot loads instead of compiling)."""
        reg = self._registry
        first = reg.note_dispatch(KIND, shape) if reg is not None else False
        t0 = time.perf_counter() if first else 0.0
        out = self._fn(a_dev, b_dev)
        if first:
            out.block_until_ready()
            reg.record_compile_seconds(KIND, shape,
                                       time.perf_counter() - t0)
        with self._lock:
            self.counters["delta_dispatches"] += 1
        telemetry.get().counter("stream/delta_dispatches")
        return float(out)

    def warmup(self) -> int:
        """Register + ready one ``frame_delta`` program per orientation
        bucket (gate on only), so steady-state streaming never compiles —
        and a warm AOT cache boots with ``aot_hit == programs`` covering
        the gate like every other program.  Returns the number of
        programs first-dispatched."""
        if not self.gate_enabled:
            return 0
        import jax

        reg = self._registry
        before = reg.counters["programs"] if reg is not None else 0
        short, long_ = self.engine._scale
        t0 = time.perf_counter()
        n = 0
        for h, w in ((short, long_), (long_, short)):
            staged, _, _, _ = stage_raw_to_bucket(
                np.zeros((h, w, 3), np.uint8), self.engine._scale,
                self._stride)
            dev = jax.device_put(staged)
            self._dispatch_delta(dev, dev, tuple(staged.shape))
            n += 1
        compiled = (reg.counters["programs"] - before
                    if reg is not None else n)
        logger.info("stream warmup: %d frame_delta program(s) ready in "
                    "%.1fs (skip_thresh=%g, max_skip=%d)", compiled,
                    time.perf_counter() - t0, self.opts.skip_thresh,
                    self.opts.max_skip)
        return compiled

    # -- intake ----------------------------------------------------------

    def _state(self, stream_id: str) -> _StreamState:
        now = time.monotonic()
        with self._lock:
            st = self._streams.get(stream_id)
            if st is None:
                if len(self._streams) >= self.opts.max_streams:
                    for sid, s in list(self._streams.items()):
                        if now - s.last_used > self.opts.idle_ttl_s:
                            del self._streams[sid]
                            self.counters["evicted"] += 1
                if len(self._streams) >= self.opts.max_streams:
                    raise RejectedError(
                        f"stream table full ({len(self._streams)}/"
                        f"{self.opts.max_streams} active) — retire idle "
                        f"streams or raise --max-streams")
                st = self._streams[stream_id] = _StreamState(stream_id)
            st.last_used = now
            return st

    def submit_frame(self, stream_id: str, seq: int, image: np.ndarray,
                     deadline_ms: Optional[float] = None,
                     trace=None) -> FrameResult:
        """One sequenced frame → :class:`FrameResult`.  Raises
        :class:`StaleSeqError` on a non-increasing ``seq`` and lets the
        engine's :class:`RejectedError`/deadline semantics pass through
        unchanged — a stream frame is an ordinary request plus state.
        ``trace`` (a TraceContext) records the skip-vs-forward verdict as
        a ``stream/gate`` span and rides forwarded frames into the
        engine's batch-causality spans; None (the default) is inert."""
        tel = telemetry.get()
        state = self._state(stream_id)
        with state.lock:
            if seq <= state.last_seq:
                with self._lock:
                    self.counters["stale_seq"] += 1
                tel.counter("stream/stale_seq")
                raise StaleSeqError(
                    f"stream {stream_id!r}: seq {seq} <= last accepted "
                    f"{state.last_seq} (frames must arrive with strictly "
                    f"increasing seq)")
            state.last_seq = seq
            with self._lock:
                self.counters["frames"] += 1
            tel.counter("stream/frames")
            return self._gate_and_submit(state, seq, image, deadline_ms,
                                         tel, trace)

    def _gate_and_submit(self, state: _StreamState, seq: int, image,
                         deadline_ms, tel, trace=None) -> FrameResult:
        t0 = time.perf_counter()
        key = cur_dev = staged = None
        delta = None
        if self.gate_enabled:
            import jax

            raw8 = np.asarray(image)
            if raw8.dtype != np.uint8:
                raw8 = np.clip(raw8, 0, 255).astype(np.uint8)
            staged, _, _, _ = stage_raw_to_bucket(
                raw8, self.engine._scale, self._stride)
            key = self.engine.bucket_key(image.shape[0], image.shape[1])
            if state.bucket is not None and state.bucket != key:
                with self._lock:
                    self.counters["bucket_switches"] += 1
                tel.counter("stream/bucket_switches")
            ref_ok = (state.ref_dev is not None and state.bucket == key
                      and state.ref_future is not None
                      and state.ref_future._error is None
                      and state.generation == self.engine.generation)
            if ref_ok and state.skip_run >= self.opts.max_skip:
                # forced refresh: the scene may be static, but cached
                # detections must not outlive the skip budget
                ref_ok = False
                with self._lock:
                    self.counters["refreshes"] += 1
                tel.counter("stream/refreshes")
            if ref_ok:
                cur_dev = jax.device_put(staged)
                delta = self._dispatch_delta(cur_dev, state.ref_dev,
                                             tuple(staged.shape))
                if delta < self.opts.skip_thresh:
                    # the skip fast path: cached detections, zero engine
                    # work — serve_e2e counters and service_time hists
                    # see nothing (asserted by tests/test_stream.py)
                    state.skip_run += 1
                    with self._lock:
                        self.counters["skipped"] += 1
                    tel.counter("stream/skipped")
                    dt = time.perf_counter() - t0
                    self.hists["stream/skip_time"].observe(dt)
                    tel.observe("stream/skip_time", dt)
                    if trace is not None:
                        tracectx.get().record(
                            trace, "stream/gate", dt,
                            attrs={"skipped": True,
                                   "delta": round(delta, 4),
                                   "skip_run": state.skip_run,
                                   "stream": state.stream_id})
                    return FrameResult(state.stream_id, seq, True, delta,
                                       state.ref_future)
        if trace is not None:
            tracectx.get().record(
                trace, "stream/gate", time.perf_counter() - t0,
                attrs={"skipped": False,
                       "delta": round(delta, 4) if delta is not None
                       else None,
                       "stream": state.stream_id})
        # full path: an ordinary engine request, tagged with its stream
        # so the dispatcher's flush bookkeeping can count cross-stream
        # batch sharing; with a cascade attached it rides the router so
        # hard frames escalate to the big model
        if self.cascade is not None:
            fut = self.cascade.submit(image, deadline_ms=deadline_ms,
                                      stream=state.stream_id, trace=trace,
                                      model_id=self.cascade.small)
        else:
            fut = self.engine.submit(image, deadline_ms=deadline_ms,
                                     stream=state.stream_id, trace=trace)
        state.ref_future = fut
        state.generation = self.engine.generation
        state.skip_run = 0
        if self.gate_enabled:
            import jax

            state.bucket = key
            # the staged pixels become the new on-device reference —
            # reuse the gate's device_put when the delta ran
            state.ref_dev = (cur_dev if cur_dev is not None
                             else jax.device_put(staged))
        with self._lock:
            self.counters["forwarded"] += 1
        tel.counter("stream/forwarded")
        return FrameResult(state.stream_id, seq, False, delta, fut)

    # -- introspection ---------------------------------------------------

    def active_streams(self) -> int:
        with self._lock:
            return len(self._streams)

    def metrics(self) -> dict:
        """The ``/metrics`` ``stream`` section: manager counters folded
        with the engine's flush-side stream bookkeeping, the live stream
        table size, and coalesced-batch occupancy (stream frames per
        stream-carrying batch slot)."""
        with self._lock:
            c = dict(self.counters)
            active = len(self._streams)
        ec = self.engine.counters
        c["batches"] = ec.get("stream_batches", 0)
        c["batch_frames"] = ec.get("stream_batch_frames", 0)
        c["coalesced_batches"] = ec.get("stream_coalesced_batches", 0)
        occupancy = (c["batch_frames"]
                     / max(c["batches"] * self.engine.opts.batch_size, 1))
        out = {
            "active_streams": active,
            "counters": c,
            "batch_occupancy": round(occupancy, 4),
            "skip_fraction": round(c["skipped"] / max(c["frames"], 1), 4),
            "options": {"skip_thresh": self.opts.skip_thresh,
                        "max_skip": self.opts.max_skip,
                        "max_streams": self.opts.max_streams},
        }
        latency = {}
        h = self.hists["stream/skip_time"]
        for q, tag in ((0.5, "skip_time_p50_ms"), (0.99, "skip_time_p99_ms")):
            v = h.quantile(q)
            if v is not None:
                latency[tag] = round(v * 1e3, 3)
        if latency:
            out["latency"] = latency
        return out
