"""Online inference serving — the first ONLINE workload on the stack.

Layered on the eval machinery, nothing duplicated: requests go through the
loader's image-prep chain (``data.prepare_image``), the ``Predictor``'s
jitted bucket programs, and the shared ``ops/postprocess`` block that
``pred_eval`` scores with.

* ``engine``     — async queue + bucket-aware dynamic batcher (deadline
  flush, partial-batch padding, bounded-queue backpressure).
* ``frontend``   — stdlib HTTP endpoints (``/predict``, ``/healthz``,
  ``/metrics``) over TCP or a Unix socket, plus a stdio mode.
* ``warmup``     — eager compilation of every (bucket, batch) program so
  the first request never pays XLA compile.
* ``controller`` — SLO-driven admission control: adapts per-bucket flush
  batch/delay toward ``--target-p99-ms`` off the engine's own latency
  histograms and sheds load when the queue trend predicts misses.

Driver: top-level ``serve.py``; load generator: ``scripts/loadgen.py``;
throughput: ``bench.py --mode serve``; smoke: ``script/serve_smoke.sh``
and ``script/slo_smoke.sh``.
"""

from mx_rcnn_tpu.serve.controller import ControllerOptions, SLOController
from mx_rcnn_tpu.serve.engine import (DeadlineExceededError, RejectedError,
                                      ServeEngine, ServeFuture, ServeOptions)
from mx_rcnn_tpu.serve.frontend import (encode_image_payload, make_server,
                                        run_stdio, unix_http_request)
from mx_rcnn_tpu.serve.warmup import warmup

__all__ = ["ServeEngine", "ServeOptions", "ServeFuture", "RejectedError",
           "DeadlineExceededError", "SLOController", "ControllerOptions",
           "make_server", "run_stdio", "unix_http_request",
           "encode_image_payload", "warmup"]
