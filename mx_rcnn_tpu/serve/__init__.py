"""Online inference serving — the first ONLINE workload on the stack.

Layered on the eval machinery, nothing duplicated: requests go through the
loader's image-prep chain (``data.prepare_image``), the ``Predictor``'s
jitted bucket programs, and the shared ``ops/postprocess`` block that
``pred_eval`` scores with.

* ``engine``     — async queue + bucket-aware dynamic batcher (deadline
  flush, partial-batch padding, bounded-queue backpressure).
* ``frontend``   — stdlib HTTP endpoints (``/predict``, ``/healthz``,
  ``/readyz``, ``/metrics``) over TCP or a Unix socket, plus stdio.
* ``warmup``     — eager compilation of every (bucket, batch) program so
  the first request never pays XLA compile; completion = readiness.
* ``controller`` — SLO-driven admission control: adapts per-bucket flush
  batch/delay toward ``--target-p99-ms`` off the engine's own latency
  histograms and sheds load when the queue trend predicts misses.
* ``replica``    — the replica-side of the multi-replica plane: child
  main loop, zero-downtime checkpoint hot-reload with canary rollback,
  checkpoint watching, and the ``MXR_FAULT_REPLICA_*`` chaos injectors.
* ``supervisor`` — the parent-side: liveness/readiness probing, crash/
  hang detection, backoff respawn with a systemic limit, rolling
  reloads, and the retry-budgeted request router.
* ``stream``     — sequenced-frame streaming over the same batcher:
  per-stream state (reference frame + cached detections), cross-stream
  temporal coalescing (same-bucket frames from different streams share
  one ``serve_e2e`` dispatch), and an on-device ``frame_delta`` skip
  gate that answers low-motion frames from cache without any forward.
* ``pool``       — multi-model serving: N ``(config, params, Predictor)``
  entries behind one frontend (``/predict?model=...``), a single
  cross-model dispatcher interleaving per-model bucket queues by queue
  depth × SLO class, and a device weight-residency manager paging param
  trees host↔device under a byte budget (LRU, pinning, zero recompiles
  — params are runtime arguments to every program).  Also home of the
  cascade router (``--cascade small:big``): requests answer from the
  cheap model unless an on-device confidence gate — the flywheel
  miner's hardness, computed from the still-on-device detections —
  escalates them to the big model with their staged pixels reused.
* ``fabric``     — the cross-host generalization: a transport-agnostic
  replica pool (local fork children + remote TCP members that ``--join``
  or are registered by address), HTTP-probe-driven membership with
  eviction/re-admission instead of respawn, least-loaded routing over
  freshness-checked queue-depth gauges, per-member circuit breakers,
  request hedging, partition-tolerant degraded serving, and rolling
  hot-reload across remote members.
* ``autoscaler`` — the capacity authority over the fabric: forecasts
  demand from the pool's queue-depth gauges (PR-6 least-squares slope),
  scales the fleet between configured bounds through existing surfaces
  only (supervisor on-demand spawn/retire, member park/unpark via the
  register path, model-pool residency rebalance), with hysteresis,
  per-direction cooldowns, a thrash-freeze guard, and a zero-recompile
  assertion over registry counters on every scale event.

Driver: top-level ``serve.py`` (``--replicas N`` for the plane);
load generator: ``scripts/loadgen.py``; throughput: ``bench.py --mode
serve``; smoke: ``script/serve_smoke.sh``, ``script/slo_smoke.sh``, and
``script/replica_smoke.sh``.
"""

from mx_rcnn_tpu.serve.autoscaler import (AutoscalerOptions,
                                          CapacityAuthority,
                                          fleet_compile_counters,
                                          fleet_compiled_programs)
from mx_rcnn_tpu.serve.controller import ControllerOptions, SLOController
from mx_rcnn_tpu.serve.engine import (DeadlineExceededError, RejectedError,
                                      ServeEngine, ServeFuture, ServeOptions)
from mx_rcnn_tpu.serve.fabric import (CircuitBreaker, FabricOptions,
                                      FabricRouter, LocalMember, RemoteMember,
                                      ReplicaPool, make_fabric_server,
                                      normalize_address, register_with_router)
from mx_rcnn_tpu.serve.frontend import (address_request, address_request_raw,
                                        encode_image_payload, make_server,
                                        parse_address, run_stdio,
                                        run_stream_stdio,
                                        tcp_http_request, tcp_http_request_raw,
                                        unix_http_request,
                                        unix_http_request_raw)
from mx_rcnn_tpu.serve.pool import (FIDELITY_CLASSES, CascadeFuture,
                                    CascadeRouter, ModelEntry, ModelPool,
                                    param_nbytes)
from mx_rcnn_tpu.serve.replica import (CheckpointWatcher, NetFaults,
                                       ReplicaFaults, make_reloader,
                                       reload_engine_params,
                                       scan_checkpoints, serve_replica)
from mx_rcnn_tpu.serve.supervisor import (ReplicaRouter, ReplicaSpec,
                                          ReplicaSupervisor,
                                          SupervisorOptions,
                                          make_router_server, replica_specs)
from mx_rcnn_tpu.serve.stream import (FrameResult, StaleSeqError,
                                      StreamManager, StreamOptions)
from mx_rcnn_tpu.serve.warmup import warmup

__all__ = ["ServeEngine", "ServeOptions", "ServeFuture", "RejectedError",
           "DeadlineExceededError", "SLOController", "ControllerOptions",
           "make_server", "run_stdio", "unix_http_request",
           "unix_http_request_raw", "encode_image_payload", "warmup",
           "CheckpointWatcher", "ReplicaFaults", "make_reloader",
           "reload_engine_params", "scan_checkpoints", "serve_replica",
           "ReplicaRouter", "ReplicaSpec", "ReplicaSupervisor",
           "SupervisorOptions", "make_router_server", "replica_specs",
           "CircuitBreaker", "FabricOptions", "FabricRouter", "LocalMember",
           "RemoteMember", "ReplicaPool", "make_fabric_server",
           "normalize_address", "register_with_router", "NetFaults",
           "parse_address", "address_request", "address_request_raw",
           "tcp_http_request", "tcp_http_request_raw",
           "StreamManager", "StreamOptions", "StaleSeqError",
           "FrameResult", "run_stream_stdio",
           "ModelPool", "ModelEntry", "param_nbytes",
           "CascadeRouter", "CascadeFuture", "FIDELITY_CLASSES",
           "AutoscalerOptions", "CapacityAuthority",
           "fleet_compile_counters", "fleet_compiled_programs"]
