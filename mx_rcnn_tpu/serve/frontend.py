"""Dependency-free serving frontends over :class:`ServeEngine`.

Three transports, one JSON contract:

* TCP HTTP (``make_server(engine, port=...)``) — the production-shaped
  endpoint ``scripts/loadgen.py`` drives.
* Unix-socket HTTP (``make_server(engine, unix_socket=path)``) — same
  handler over ``AF_UNIX``; what the tier-1 tests round-trip (no port
  allocation races on shared CI hosts).  ``unix_http_request`` is the
  matching client.
* stdio (``run_stdio``) — newline-delimited JSON over stdin/stdout for
  debugging and pipe-based harnesses.

Endpoints:

* ``POST /predict`` — body ``{"shape": [h, w, 3], "data": <base64 raw
  uint8 RGB bytes>}`` (or ``"pixels"``: nested lists), optional
  ``"deadline_ms"``.  200 → ``{"detections": [{"cls", "score", "bbox"}...],
  "queue_wait_ms"}``; 503 queue full (backpressure — retry with backoff);
  504 deadline exceeded; 400 malformed.
* ``POST /stream`` — sequenced-frame streaming (only when the server was
  built with a ``stream`` manager; 404 otherwise).  Body is NDJSON: one
  frame per line, each a predict payload plus ``"stream_id"`` (str) and
  ``"seq"`` (strictly increasing int per stream).  The connection is
  persistent (HTTP/1.1 keep-alive) and a body may carry many frames —
  all frames are submitted BEFORE any is waited on, so one client's
  pipeline fills batches alongside other streams (cross-stream
  coalescing).  Response is NDJSON in submit order, each line
  ``{"status", "stream_id", "seq", "skipped", "detections",
  "queue_wait_ms"}``; per-frame statuses mirror ``/predict`` (400/503/
  504), plus 409 for a stale ``seq``.  The HTTP envelope is 200 as long
  as the body parsed.
* ``GET /healthz`` — liveness: 200 once the engine thread is up (a
  warming or draining replica still answers — backward-compatible).
* ``GET /readyz`` — readiness: 200 only once warmup has registered every
  program AND admissions are open (not draining for a weight swap);
  503 otherwise.  What the replica supervisor and smoke scripts gate
  routing on — liveness and readiness are deliberately distinct.
* ``POST /admin/reload`` — replica-local checkpoint hot-swap (only when
  the server was built with a ``reloader`` callback; 404 otherwise).
  Body is a reload target doc; 200 → new generation live, 409 → load or
  canary failure, previous weights restored.
* ``GET /metrics`` — engine counters + queue state as JSON; with
  ``Accept: text/plain`` or ``?format=prom``, Prometheus text exposition
  instead — rendered by ``telemetry/obs.py`` from the same registry the
  ``--obs-port`` server scrapes (one metrics path, not two).

Everything here is stdlib (``http.server`` + ``ThreadingHTTPServer``):
request threads do the image prep in ``engine.submit`` concurrently, which
is precisely what fills batches — a single-threaded frontend would
serialize arrivals and the batcher would only ever see singletons.
"""

from __future__ import annotations

import base64
import json
import os
import socket
import sys
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import numpy as np

from mx_rcnn_tpu.logger import logger
from mx_rcnn_tpu.serve.engine import (DeadlineExceededError, RejectedError,
                                      ServeEngine)
from mx_rcnn_tpu.serve.stream import StaleSeqError, StreamManager
from mx_rcnn_tpu.telemetry import tracectx
from mx_rcnn_tpu.telemetry.obs import (PROM_CONTENT_TYPE, pool_prometheus,
                                       serve_prometheus)
from mx_rcnn_tpu.telemetry.tracectx import TRACE_HEADER, TraceContext

# result-wait ceiling for one HTTP request; the engine's own per-request
# deadline (default ServeOptions.deadline_ms) fires long before this —
# the ceiling only bounds a wedged dispatcher so handler threads can't
# accumulate forever
WAIT_TIMEOUT_S = 600.0


def decode_image_payload(doc: dict) -> np.ndarray:
    """Request JSON → (H, W, 3) uint8 RGB array.  Raises ValueError on a
    malformed payload (the handler's 400)."""
    if "pixels" in doc:
        img = np.asarray(doc["pixels"], np.uint8)
    elif "data" in doc:
        shape = doc.get("shape")
        if (not isinstance(shape, (list, tuple)) or len(shape) != 3
                or shape[2] != 3):
            raise ValueError(f"'shape' must be [h, w, 3], got {shape!r}")
        raw = base64.b64decode(doc["data"], validate=True)
        h, w, c = (int(x) for x in shape)
        if len(raw) != h * w * c:
            raise ValueError(f"'data' holds {len(raw)} bytes, shape "
                             f"{shape} needs {h * w * c}")
        img = np.frombuffer(raw, np.uint8).reshape(h, w, c)
    else:
        raise ValueError("payload needs 'data'+'shape' (base64 raw RGB "
                         "bytes) or 'pixels' (nested lists)")
    if img.ndim != 3 or img.shape[2] != 3:
        raise ValueError(f"expected (H, W, 3), got {img.shape}")
    return img


def encode_image_payload(img: np.ndarray) -> dict:
    """The client half of the contract (loadgen, tests)."""
    img = np.ascontiguousarray(img, np.uint8)
    return {"shape": list(img.shape),
            "data": base64.b64encode(img.tobytes()).decode("ascii")}


def _predict_doc(engine: ServeEngine, doc: dict, img,
                 trace, cascade=None, model_id=None) -> tuple:
    """The submit+wait core of one predict request — trace-agnostic, so
    the traced and untraced paths produce IDENTICAL response docs (the
    tracing-off byte-parity contract).  With a ``cascade`` router the
    submit routes through it instead of the engine and the 200 response
    grows a ``"cascade"`` provenance field (which model answered and
    why); cascade-off responses stay byte-for-byte."""
    try:
        if cascade is not None:
            fut = cascade.submit(img, deadline_ms=doc.get("deadline_ms"),
                                 trace=trace, model_id=model_id)
        else:
            fut = engine.submit(img, deadline_ms=doc.get("deadline_ms"),
                                trace=trace)
        dets = fut.result(timeout=WAIT_TIMEOUT_S)
    except RejectedError as e:
        return 503, {"error": str(e)}
    except DeadlineExceededError as e:
        return 504, {"error": str(e)}
    except TimeoutError as e:
        return 504, {"error": str(e)}
    except Exception as e:  # noqa: BLE001 — surface as a 500, keep serving
        logger.exception("predict failed")
        return 500, {"error": f"{type(e).__name__}: {e}"}
    qms = (fut.queue_wait_s or 0.0) * 1e3
    resp = {"detections": dets, "queue_wait_ms": round(qms, 3)}
    if cascade is not None:
        resp["cascade"] = fut.provenance()
    return 200, resp


def handle_request_doc(engine: ServeEngine, doc: dict,
                       trace_header: Optional[str] = None,
                       cascade=None, model_id=None) -> tuple:
    """One predict request → (http_status, response_doc).  Shared by all
    three transports so their status semantics cannot drift.

    Trace context comes from the forwarded ``X-Mxr-Trace`` header (the
    router's chain wins — it carries the parent span) or the ``"trace"``
    doc field (a client-minted bare trace id); with tracing enabled and
    neither present, one is minted here — the frontend is the root of
    the hop tree either way.  The trace id is echoed back as a
    ``"trace"`` response key ONLY when the client sent one or tracing is
    on, so a tracing-off ``/predict`` stays byte-for-byte."""
    try:
        img = decode_image_payload(doc)
    except (ValueError, TypeError, KeyError) as e:
        return 400, {"error": str(e)}
    tracer = tracectx.get()
    raw = trace_header or doc.get("trace")
    if not tracer.enabled:
        status, resp = _predict_doc(engine, doc, img, None,
                                    cascade=cascade, model_id=model_id)
        if raw:
            # propagation without recording: a client that minted an id
            # still gets it echoed so cross-host correlation never
            # depends on which members have tracing on
            resp["trace"] = str(raw).split("-", 1)[0]
        return status, resp
    ctx = (TraceContext.parse(raw) if raw else None) or tracer.mint()
    with tracer.span(ctx, "frontend/predict") as sp:
        status, resp = _predict_doc(engine, doc, img, sp.ctx,
                                    cascade=cascade, model_id=model_id)
        sp.set(status=status)
    resp["trace"] = ctx.trace_id
    return status, resp


def submit_stream_frame(stream: StreamManager, doc: dict,
                        trace_header: Optional[str] = None) -> tuple:
    """Validate + submit one stream frame WITHOUT waiting — the submit
    half of the pipelined ``/stream`` handler.  Returns
    ``(None, None, FrameResult)`` on acceptance or
    ``(status, error_doc, None)`` on submit-side failure.

    Tracing mirrors ``/predict``: per-frame ``"trace"`` doc field (or the
    body's forwarded header) is accepted, else one is minted when tracing
    is on; a ``frontend/frame`` span covers the gate+submit and parents
    the stream-gate / engine spans below it."""
    sid, seq = doc.get("stream_id"), doc.get("seq")
    if not isinstance(sid, str) or not sid:
        return 400, {"error": "frame needs a non-empty string "
                              "'stream_id'"}, None
    if not isinstance(seq, int) or isinstance(seq, bool):
        return 400, {"error": "frame needs an integer 'seq'",
                     "stream_id": sid}, None
    try:
        img = decode_image_payload(doc)
    except (ValueError, TypeError, KeyError) as e:
        return 400, {"error": str(e), "stream_id": sid, "seq": seq}, None
    tracer = tracectx.get()
    sp = tracectx.NULL_SPAN
    if tracer.enabled:
        raw = doc.get("trace") or trace_header
        ctx = (TraceContext.parse(raw) if raw else None) or tracer.mint()
        sp = tracer.span(ctx, "frontend/frame", stream=sid, seq=seq)
    try:
        with sp:
            res = stream.submit_frame(sid, seq, img,
                                      deadline_ms=doc.get("deadline_ms"),
                                      trace=sp.ctx)
    except StaleSeqError as e:
        return 409, {"error": str(e), "stream_id": sid, "seq": seq}, None
    except RejectedError as e:
        return 503, {"error": str(e), "stream_id": sid, "seq": seq}, None
    except Exception as e:  # noqa: BLE001 — surface as a 500, keep serving
        logger.exception("stream submit failed")
        return 500, {"error": f"{type(e).__name__}: {e}",
                     "stream_id": sid, "seq": seq}, None
    return None, None, res


def resolve_stream_frame(res) -> tuple:
    """The wait half: one accepted :class:`FrameResult` →
    ``(status, response_doc)`` with ``/predict``'s status semantics."""
    try:
        dets = res.result(timeout=WAIT_TIMEOUT_S)
    except RejectedError as e:
        return 503, {"error": str(e), "stream_id": res.stream_id,
                     "seq": res.seq}
    except (DeadlineExceededError, TimeoutError) as e:
        return 504, {"error": str(e), "stream_id": res.stream_id,
                     "seq": res.seq}
    except Exception as e:  # noqa: BLE001
        logger.exception("stream frame failed")
        return 500, {"error": f"{type(e).__name__}: {e}",
                     "stream_id": res.stream_id, "seq": res.seq}
    out = {"stream_id": res.stream_id, "seq": res.seq,
           "skipped": res.skipped, "detections": dets,
           "queue_wait_ms": round((res.queue_wait_s or 0.0) * 1e3, 3)}
    if res.delta is not None:
        out["delta"] = round(res.delta, 4)
    # cascade provenance, only for cascade-routed streams — non-cascade
    # frames (and pre-cascade fakes in tests) stay byte-for-byte
    prov = getattr(res, "cascade", None)
    prov = prov() if callable(prov) else None
    if prov is not None:
        out["cascade"] = prov
    return 200, out


def handle_stream_doc(stream: StreamManager, doc: dict,
                      trace_header: Optional[str] = None) -> tuple:
    """One frame, submit + wait → (status, response_doc).  The stdio
    transport's unit; HTTP goes through :func:`handle_stream_lines` to
    pipeline multi-frame bodies."""
    status, err, res = submit_stream_frame(stream, doc,
                                           trace_header=trace_header)
    if res is None:
        return status, err
    return resolve_stream_frame(res)


def handle_stream_lines(stream: StreamManager, lines,
                        trace_header: Optional[str] = None) -> list:
    """NDJSON body → list of (status, doc) replies in input order.
    Submits EVERY frame before resolving any, so a single connection's
    burst coalesces into shared batches instead of serializing."""
    staged = []
    for line in lines:
        line = line.strip()
        if not line:
            continue
        try:
            doc = json.loads(line)
        except json.JSONDecodeError as e:
            staged.append((400, {"error": f"bad JSON line: {e}"}, None))
            continue
        staged.append(submit_stream_frame(stream, doc,
                                          trace_header=trace_header))
    return [(status, err) if res is None else resolve_stream_frame(res)
            for status, err, res in staged]


def query_model(query: str) -> Optional[str]:
    """Extract ``model=...`` from a raw query string (None if absent)."""
    for part in query.split("&"):
        k, _, v = part.partition("=")
        if k == "model" and v:
            return v
    return None


def query_param(query: str, key: str) -> Optional[str]:
    """Extract ``key=...`` from a raw query string (None if absent) —
    URL-decoded just enough for metric names (``%2F`` → ``/``)."""
    for part in query.split("&"):
        k, _, v = part.partition("=")
        if k == key and v:
            return v.replace("%2F", "/").replace("%2f", "/")
    return None


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    engine: ServeEngine = None  # set by make_server subclassing
    stream: Optional[StreamManager] = None  # enables POST /stream
    pool = None          # optional ModelPool: enables ?model=... routing
    streams = None       # pool mode: {model_id: StreamManager}
    cascade = None       # optional CascadeRouter: /predict rides it
    reloader = None      # optional callback(doc) -> (status, doc)
    request_hook = None  # optional callback(status) after each /predict
    gate = None          # optional callback() before any handling
    net_faults = None    # optional NetFaults: intercept(path, handler)
    watch = None         # optional Watchtower: /alerts + /history + Prom

    def _resolve_engine(self, query: str, doc: Optional[dict] = None):
        """``?model=...`` (or a ``"model"`` field in the request doc) →
        ``(engine, None)`` or ``(None, (status, error_doc))``.  Without a
        pool, any explicit model selector is a 404 (multi-model routing
        is opt-in via ``--models``); with one, the id resolves to that
        model's own engine — its bucket set, programs, AOT subtree."""
        mid = query_model(query) if query else None
        if mid is None and doc is not None:
            m = doc.get("model")
            if isinstance(m, str) and m:
                mid = m
        if self.pool is None:
            if mid is not None:
                return None, (404, {"error": f"model routing not enabled "
                                             f"(requested {mid!r}; start "
                                             f"with --models)"})
            return self.engine, None
        try:
            return self.pool.engine_for(mid), None
        except KeyError as e:
            return None, (404, {"error": str(e.args[0]) if e.args
                                else str(e)})

    # -- plumbing --------------------------------------------------------

    def log_message(self, fmt, *args):  # route through our logger
        logger.debug("serve http: " + fmt, *args)

    def address_string(self):  # AF_UNIX peers have no (host, port)
        if isinstance(self.client_address, (bytes, str)):
            return "unix"
        return super().address_string()

    def _reply(self, status: int, doc: dict):
        self._reply_raw(status, json.dumps(doc).encode(),
                        "application/json")

    def _reply_raw(self, status: int, body: bytes, ctype: str):
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    # -- endpoints -------------------------------------------------------

    def do_GET(self):
        if self.gate is not None:
            self.gate()
        if self.net_faults is not None and \
                self.net_faults.intercept(self.path, self):
            return
        path, _, query = self.path.partition("?")
        if path == "/healthz":
            if self.pool is not None:
                self._reply(200, {"status": "ok",
                                  "models": self.pool.model_ids(),
                                  "queue_depth": sum(
                                      self.pool.engine_for(m).queue_depth()
                                      for m in self.pool.model_ids())})
            else:
                self._reply(200, {"status": "ok",
                                  "queue_depth":
                                      self.engine.queue_depth()})
        elif path == "/readyz":
            doc = (self.pool.readiness() if self.pool is not None
                   else self.engine.readiness())
            self._reply(200 if doc["ready"] else 503, doc)
        elif path == "/metrics":
            # content negotiation: JSON stays the default for existing
            # callers; Prometheus scrapers ask via Accept or ?format=prom
            accept = self.headers.get("Accept", "")
            if "format=prom" in query or "text/plain" in accept:
                text = (pool_prometheus(self.pool, watch=self.watch)
                        if self.pool is not None
                        else serve_prometheus(self.engine,
                                              watch=self.watch))
                self._reply_raw(200, text.encode(), PROM_CONTENT_TYPE)
            elif self.pool is not None:
                doc = self.pool.metrics()
                if self.watch is not None:
                    doc["watch"] = self.watch.state()
                self._reply(200, doc)
            else:
                doc = self.engine.metrics()
                if self.watch is not None:
                    doc["watch"] = self.watch.state()
                self._reply(200, doc)
        elif path == "/alerts" and self.watch is not None:
            self._reply(200, self.watch.alerts_doc())
        elif path == "/history" and self.watch is not None:
            metric = query_param(query, "metric")
            if not metric:
                self._reply(400, {"error": "need ?metric=NAME"})
                return
            try:
                window = float(query_param(query, "window") or 300.0)
            except ValueError:
                self._reply(400, {"error": "window must be a number "
                                           "of seconds"})
                return
            self._reply(200, self.watch.history_doc(metric, window))
        else:
            # /alerts and /history 404 when the watchtower is off —
            # byte-identical to the pre-watch unknown-path reply
            self._reply(404, {"error": f"no route {self.path}"})

    def do_POST(self):
        if self.gate is not None:
            self.gate()
        if self.net_faults is not None and \
                self.net_faults.intercept(self.path, self):
            return
        # query split mirrors do_GET: /predict?model=... must route, and
        # a bare single-model boot keeps 404-ing unknown query'd paths
        # through the explicit model-routing error below
        path, _, query = self.path.partition("?")
        if path not in ("/predict", "/admin/reload", "/stream"):
            self._reply(404, {"error": f"no route {self.path}"})
            return
        if path == "/stream":
            # pool mode: ?model=... picks that model's StreamManager (the
            # /predict routing twin); frames inside one body share it
            stream = self.stream
            if self.pool is not None:
                mid = query_model(query) or self.pool.default_model
                stream = (self.streams or {}).get(mid)
                if stream is None and mid not in self.pool.model_ids():
                    self._reply(404, {"error": f"unknown model {mid!r} "
                                      f"(have {self.pool.model_ids()})"})
                    return
            if stream is None:
                self._reply(404, {"error": "streaming not enabled "
                                           "(start with --stream)"})
                return
            try:
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length)
            except ValueError as e:
                self._reply(400, {"error": f"bad Content-Length: {e}"})
                return
            replies = handle_stream_lines(
                stream, body.decode("utf-8", "replace").splitlines(),
                trace_header=self.headers.get(TRACE_HEADER))
            payload = "".join(json.dumps({"status": s, **d}) + "\n"
                              for s, d in replies)
            self._reply_raw(200, payload.encode(), "application/x-ndjson")
            return
        try:
            length = int(self.headers.get("Content-Length", 0))
            doc = json.loads(self.rfile.read(length) or b"{}")
        except (ValueError, json.JSONDecodeError) as e:
            self._reply(400, {"error": f"bad JSON body: {e}"})
            return
        if path == "/admin/reload":
            if self.reloader is None:
                self._reply(404, {"error": "no reloader configured"})
                return
            self._reply(*self.reloader(doc))
            return
        engine, err = self._resolve_engine(query, doc)
        if engine is None:
            self._reply(*err)
            if self.request_hook is not None:
                self.request_hook(err[0])
            return
        mid = None
        if self.cascade is not None:
            # the router routes by model IDENTITY (addressed big model /
            # fidelity pin / bypass / gate), so it needs the id, not the
            # engine _resolve_engine already validated
            mid = query_model(query) if query else None
            if mid is None:
                m = doc.get("model")
                if isinstance(m, str) and m:
                    mid = m
        status, resp = handle_request_doc(
            engine, doc, trace_header=self.headers.get(TRACE_HEADER),
            cascade=self.cascade, model_id=mid)
        self._reply(status, resp)
        if self.request_hook is not None:
            self.request_hook(status)


class _TCPHTTPServer(ThreadingHTTPServer):
    # the stdlib default listen backlog (5) drops connections under the
    # very bursts the engine's backpressure exists to answer with 503s;
    # admission control is the engine's job, not the kernel's
    request_queue_size = 128


class _UnixHTTPServer(_TCPHTTPServer):
    address_family = socket.AF_UNIX

    def server_bind(self):
        # stale socket files from a killed process block bind
        if os.path.exists(self.server_address):
            os.unlink(self.server_address)
        super().server_bind()

    def client_address_string(self):
        return "unix"


def make_server(engine: ServeEngine, port: Optional[int] = None,
                host: str = "127.0.0.1",
                unix_socket: Optional[str] = None,
                reloader=None, request_hook=None, gate=None,
                net_faults=None, stream: Optional[StreamManager] = None,
                pool=None, streams: Optional[dict] = None, cascade=None,
                watch=None):
    """Build (not start) the HTTP server — exactly one of ``port`` /
    ``unix_socket``.  Caller owns ``serve_forever``/``shutdown``.

    ``reloader`` enables ``POST /admin/reload`` (the replica hot-swap
    endpoint); ``request_hook(status)`` fires after each ``/predict``
    reply and ``gate()`` before any handling — the chaos harness's
    kill-after-N / hang injection points.  ``net_faults`` (an object
    with ``intercept(path, handler) -> bool``) sits below both and can
    blackhole, delay, or reset the connection — the fabric chaos
    harness's network-layer injection point.

    ``pool`` (a :class:`~mx_rcnn_tpu.serve.pool.ModelPool`) turns on
    multi-model routing: ``?model=...`` on ``/predict``/``/stream``
    resolves to that model's engine / StreamManager (``streams``:
    model_id → manager), ``/metrics`` reports the whole fleet, and
    ``/readyz`` requires every model warm.  ``engine`` stays the default
    model's engine so single-model callers are untouched.

    ``watch`` (a :class:`~mx_rcnn_tpu.telemetry.watch.Watchtower`)
    enables ``GET /alerts`` and ``GET /history?metric=&window=`` plus
    the ``watch`` pane / ``mxr_alert_state`` family on ``/metrics``;
    None keeps every response byte-identical to the watch-less server
    (both routes 404)."""
    if (port is None) == (unix_socket is None):
        raise ValueError("pass exactly one of port / unix_socket")

    class Handler(_Handler):
        pass

    Handler.engine = engine
    Handler.stream = stream  # a StreamManager enables POST /stream
    Handler.pool = pool
    Handler.streams = streams
    Handler.cascade = cascade  # a CascadeRouter: /predict rides the gate
    # staticmethod: a plain function stored on the class would otherwise
    # bind as a method and receive the handler as a bogus first argument
    Handler.reloader = staticmethod(reloader) if reloader else None
    Handler.request_hook = (staticmethod(request_hook)
                            if request_hook else None)
    Handler.gate = staticmethod(gate) if gate else None
    Handler.net_faults = net_faults
    Handler.watch = watch  # a Watchtower enables /alerts + /history
    if unix_socket is not None:
        return _UnixHTTPServer(unix_socket, Handler)
    return _TCPHTTPServer((host, port), Handler)


def unix_http_request_raw(sock_path: str, method: str, path: str,
                          body: Optional[bytes] = None,
                          timeout: float = 60.0,
                          headers: Optional[dict] = None) -> tuple:
    """Byte-level HTTP over a Unix socket → (status, body_bytes, ctype).
    The router's forwarding primitive: request bodies pass through
    verbatim (no decode→re-encode of base64 image payloads on the
    hot path).  Raises ``OSError`` family on transport failure — a dead
    or hung replica — which is the retry-on-alternate trigger."""
    import http.client

    class Conn(http.client.HTTPConnection):
        def __init__(self):
            super().__init__("localhost", timeout=timeout)

        def connect(self):
            self.sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            self.sock.settimeout(timeout)
            self.sock.connect(sock_path)

    conn = Conn()
    try:
        hdrs = dict(headers or {})
        if body:
            hdrs.setdefault("Content-Type", "application/json")
        conn.request(method, path, body=body, headers=hdrs)
        resp = conn.getresponse()
        return (resp.status, resp.read(),
                resp.getheader("Content-Type") or "")
    finally:
        conn.close()


def unix_http_request(sock_path: str, method: str, path: str,
                      doc: Optional[dict] = None,
                      timeout: float = 60.0,
                      headers: Optional[dict] = None) -> tuple:
    """Minimal HTTP client over a Unix socket → (status, response_doc).
    The test/loadgen counterpart of ``make_server(unix_socket=...)``.
    JSON responses come back parsed; anything else (the Prometheus text
    negotiated via ``headers={"Accept": "text/plain"}``) as str."""
    body = json.dumps(doc).encode() if doc is not None else None
    status, raw, ctype = unix_http_request_raw(
        sock_path, method, path, body=body, timeout=timeout,
        headers=headers)
    if "json" in ctype:
        return status, json.loads(raw)
    return status, raw.decode()


def tcp_http_request_raw(host: str, port: int, method: str, path: str,
                         body: Optional[bytes] = None,
                         timeout: float = 60.0,
                         headers: Optional[dict] = None) -> tuple:
    """Byte-level HTTP over TCP → (status, body_bytes, ctype): the
    fabric router's forwarding primitive for remote members — the
    cross-host twin of :func:`unix_http_request_raw`, with the same
    pass-through-bytes and raise-on-transport-failure contract."""
    import http.client

    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        hdrs = dict(headers or {})
        if body:
            hdrs.setdefault("Content-Type", "application/json")
        conn.request(method, path, body=body, headers=hdrs)
        resp = conn.getresponse()
        return (resp.status, resp.read(),
                resp.getheader("Content-Type") or "")
    finally:
        conn.close()


def tcp_http_request(host: str, port: int, method: str, path: str,
                     doc: Optional[dict] = None, timeout: float = 60.0,
                     headers: Optional[dict] = None) -> tuple:
    """JSON-level HTTP over TCP → (status, response_doc) — the client
    for fabric probes, ``--join`` registration, and the smoke scripts."""
    body = json.dumps(doc).encode() if doc is not None else None
    status, raw, ctype = tcp_http_request_raw(
        host, port, method, path, body=body, timeout=timeout,
        headers=headers)
    if "json" in ctype:
        return status, json.loads(raw)
    return status, raw.decode()


def parse_address(address: str) -> tuple:
    """``host:port`` → ("tcp", host, port); a filesystem path (optional
    ``unix:`` prefix) → ("unix", path, None).  The fabric's one address
    grammar for pool files, ``--join``, and ``/admin/register``."""
    address = address.strip()
    if address.startswith("unix:"):
        return "unix", address[5:], None
    if "/" in address:
        return "unix", address, None
    host, _, port = address.rpartition(":")
    if not host or not port.isdigit():
        raise ValueError(f"address must be HOST:PORT or a unix socket "
                         f"path, got {address!r}")
    return "tcp", host, int(port)


def address_request_raw(address: str, method: str, path: str,
                        body: Optional[bytes] = None,
                        timeout: float = 60.0,
                        headers: Optional[dict] = None) -> tuple:
    """Transport-agnostic byte-level request: dispatches on
    :func:`parse_address` so fabric members are addressed identically
    whether they live across the network or across a fork."""
    scheme, host, port = parse_address(address)
    if scheme == "unix":
        return unix_http_request_raw(host, method, path, body=body,
                                     timeout=timeout, headers=headers)
    return tcp_http_request_raw(host, port, method, path, body=body,
                                timeout=timeout, headers=headers)


def address_request(address: str, method: str, path: str,
                    doc: Optional[dict] = None, timeout: float = 60.0,
                    headers: Optional[dict] = None) -> tuple:
    """JSON twin of :func:`address_request_raw`."""
    body = json.dumps(doc).encode() if doc is not None else None
    status, raw, ctype = address_request_raw(
        address, method, path, body=body, timeout=timeout,
        headers=headers)
    if "json" in ctype:
        return status, json.loads(raw)
    return status, raw.decode()


def run_stdio(engine: ServeEngine, inp=None, out=None):
    """Newline-delimited JSON over stdin/stdout: each input line is a
    predict payload, each output line ``{"status": N, ...response}``.
    Returns on EOF."""
    inp = inp if inp is not None else sys.stdin
    out = out if out is not None else sys.stdout
    for line in inp:
        line = line.strip()
        if not line:
            continue
        try:
            doc = json.loads(line)
        except json.JSONDecodeError as e:
            status, resp = 400, {"error": f"bad JSON line: {e}"}
        else:
            status, resp = handle_request_doc(engine, doc)
        out.write(json.dumps({"status": status, **resp}) + "\n")
        out.flush()


def run_stream_stdio(stream: StreamManager, inp=None, out=None):
    """Stream twin of :func:`run_stdio`: each input line is a frame doc
    (predict payload + ``stream_id``/``seq``), each output line
    ``{"status": N, ...}`` — the pipe-based stream harness the contract
    tests drive without a socket.  Returns on EOF."""
    inp = inp if inp is not None else sys.stdin
    out = out if out is not None else sys.stdout
    for line in inp:
        line = line.strip()
        if not line:
            continue
        try:
            doc = json.loads(line)
        except json.JSONDecodeError as e:
            status, resp = 400, {"error": f"bad JSON line: {e}"}
        else:
            status, resp = handle_stream_doc(stream, doc)
        out.write(json.dumps({"status": status, **resp}) + "\n")
        out.flush()
