"""SLO-driven admission controller: the loop that makes the obs plane act.

ROADMAP item 5's complaint about PR 5 is that the engine exposes
queue-depth extremes and latency counters but *nothing consumes them*.
This module closes the loop: a :class:`SLOController` reads the engine's
own latency histograms and queue state every control tick and steers
three knobs toward a ``--target-p99-ms``:

* **per-bucket flush delay** — the head-of-line latency knob.  AIMD:
  halve a bucket's ``max_delay_ms`` when windowed p99 breaches the
  target (multiplicative decrease — latency regressions need a fast
  exit), creep it back toward the configured value by 10% steps after
  ``relax_after`` consecutive healthy ticks (additive increase — give
  throughput back slowly enough not to oscillate).
* **per-bucket flush batch** — same AIMD on the flush threshold, between
  1 and ``opts.batch_size``.  Lowering it trades fill (more padding per
  forward) for queue wait; the compiled program shape never changes.
* **admission limit** — the predictive shed valve.  When the queue-depth
  trend (least-squares slope over the tick history) is growing AND the
  predicted drain time (depth / recent serve rate) exceeds
  ``shed_margin`` x target, cap admissions at the depth the engine can
  drain within budget; further submits 503 immediately
  (``serve/shed``).  A request that would have missed its deadline
  anyway is cheapest to refuse before it queues.

Every decision is first-class telemetry: ``slo/decisions`` /
``slo/tighten`` / ``slo/relax`` / ``slo/shed_on`` / ``slo/shed_off``
counters, ``slo/p99_ms`` / ``slo/queue_depth`` / ``slo/drain_rate`` /
``slo/admit_limit`` gauges, an ``slo_decision`` meta event per action
(rendered as an instant marker by the trace export), and a
flight-recorder dump on the shed-on transition — the moment an operator
will want the last seconds of context for.

The controller reads the ENGINE's histograms (:attr:`ServeEngine.hists`),
not the telemetry sink's, so it works with telemetry disabled — the same
engine-authoritative contract the counters follow.  ``tick()`` is public
and takes an injectable ``now`` so tests drive the control law
deterministically without threads or sleeps.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Optional

from mx_rcnn_tpu import telemetry
from mx_rcnn_tpu.logger import logger


@dataclass(frozen=True)
class ControllerOptions:
    """Control-law knobs (CLI: ``--target-p99-ms`` / ``--slo-interval-ms``
    / ``--slo-window-s``)."""

    # the SLO: windowed end-to-end request-time p99 to hold, milliseconds
    target_p99_ms: float = 100.0
    # control tick period; also the granularity of trend estimation
    interval_s: float = 0.5
    # trailing window the p99 is computed over — long enough to smooth a
    # batch boundary, short enough that control reacts within seconds
    window_s: float = 10.0
    # don't act on fewer observations than this per window (noise guard)
    min_samples: int = 8
    # healthy band: relax only when p99 < headroom x target (hysteresis —
    # relaxing at 0.99 x target would oscillate across the boundary)
    headroom: float = 0.8
    # consecutive healthy ticks before each additive relax step
    relax_after: int = 4
    # shed when predicted drain time exceeds this multiple of the target
    shed_margin: float = 1.5
    # how many ticks of depth history feed the trend slope
    trend_ticks: int = 8
    # tenant/model label (multi-model serving: one controller per model;
    # "" = the classic unlabeled single-model controller).  Shows up in
    # log lines, state(), and the slo_decision meta so a shed can be
    # attributed to the tenant whose traffic triggered it
    label: str = ""

    def __post_init__(self):
        if self.target_p99_ms <= 0:
            raise ValueError(
                f"target_p99_ms must be > 0, got {self.target_p99_ms}")
        if self.interval_s <= 0:
            raise ValueError(
                f"interval_s must be > 0, got {self.interval_s}")
        if not 0.0 < self.headroom < 1.0:
            raise ValueError(
                f"headroom must be in (0, 1), got {self.headroom}")


class SLOController:
    """Periodic controller over one :class:`ServeEngine`.

    ``start()`` attaches to the engine (``engine.controller = self``, so
    ``/metrics`` carries live controller state) and spawns the tick
    thread; ``stop()`` detaches and restores the engine's configured
    policy.  Tests call :meth:`tick` directly.
    """

    def __init__(self, engine, options: Optional[ControllerOptions] = None):
        self.engine = engine
        self.opts = options or ControllerOptions()
        self._lock = threading.Lock()
        self._stop_event = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._depth_hist: list = []       # [(now, depth)] trend window
        self._count_hist: list = []       # hist.count per tick (window base)
        self._admit_limit: Optional[int] = None
        self._last_served = 0             # counters["served"] at last tick
        self._last_tick_t: Optional[float] = None
        self._healthy_streak = 0
        self._shedding = False
        self.ticks = 0
        self.decisions = 0                # ticks that changed any knob
        self.last_p99_ms: Optional[float] = None
        self.last_drain_rate = 0.0
        self.last_slope = 0.0
        self.last_depth = 0

    # -- lifecycle -------------------------------------------------------

    def start(self) -> "SLOController":
        assert self._thread is None, "controller already started"
        self.engine.controller = self
        self._thread = threading.Thread(target=self._run,
                                        name="slo-controller", daemon=True)
        self._thread.start()
        logger.info("SLO controller%s on: target p99 %.1f ms, tick "
                    "%.0f ms, window %.1f s",
                    f" [{self.opts.label}]" if self.opts.label else "",
                    self.opts.target_p99_ms,
                    self.opts.interval_s * 1e3, self.opts.window_s)
        return self

    def stop(self, timeout: float = 5.0):
        self._stop_event.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None
        # restore configured policy so a stopped controller leaves no
        # residue (tightened buckets / a stale admit limit)
        for key in self.engine.known_buckets():
            self.engine.set_bucket_policy(
                key, max_batch=self.engine.opts.batch_size,
                max_delay_ms=self.engine.opts.max_delay_ms)
        self.engine.set_admit_limit(None)
        if self.engine.controller is self:
            self.engine.controller = None

    def _run(self):
        while not self._stop_event.wait(self.opts.interval_s):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — control must not kill serve
                logger.exception("SLO controller tick failed")

    # -- the control law -------------------------------------------------

    def tick(self, now: Optional[float] = None):
        """One control decision.  ``now`` is injectable (monotonic) so
        tests can drive windows and trends without real time passing."""
        o = self.opts
        now = time.monotonic() if now is None else now
        tel = telemetry.get()
        eng = self.engine

        hist = eng.hists["serve/request_time"]
        samples = hist.count
        p99 = hist.window_quantile(0.99, o.window_s, now=now)
        p99_ms = None if p99 is None else p99 * 1e3
        depth = eng.queue_depth()

        # serve rate since the last tick (requests actually completed)
        served = eng.counters["served"]
        if self._last_tick_t is not None and now > self._last_tick_t:
            rate = (served - self._last_served) / (now - self._last_tick_t)
        else:
            rate = 0.0
        self._last_served, self._last_tick_t = served, now

        # queue-depth trend: least-squares slope over the tick history
        self._depth_hist.append((now, depth))
        self._depth_hist = self._depth_hist[-o.trend_ticks:]
        slope = _slope(self._depth_hist)

        window_n = samples - self._window_base(samples, now)
        acted = []

        target_s = o.target_p99_ms / 1e3
        breach = (p99_ms is not None and window_n >= o.min_samples
                  and p99_ms > o.target_p99_ms)
        healthy = (p99_ms is None
                   or p99_ms < o.headroom * o.target_p99_ms)

        if breach:
            self._healthy_streak = 0
            for key in eng.known_buckets():
                b, d = eng.bucket_policy(key)
                nb = max(1, b - 1)
                nd = d / 2.0 if d > 0.25 else 0.0
                if (nb, nd) != (b, d):
                    eng.set_bucket_policy(key, max_batch=nb,
                                          max_delay_ms=nd)
                    acted.append(("tighten", key, nb, nd))
                    tel.counter("slo/tighten")
        elif healthy:
            self._healthy_streak += 1
            if self._healthy_streak >= o.relax_after:
                self._healthy_streak = 0
                cfg_b = eng.opts.batch_size
                cfg_d = eng.opts.max_delay_ms
                for key in eng.known_buckets():
                    b, d = eng.bucket_policy(key)
                    nb = min(cfg_b, b + 1)
                    nd = min(cfg_d, d + max(cfg_d * 0.1, 0.5))
                    if (nb, nd) != (b, d):
                        eng.set_bucket_policy(key, max_batch=nb,
                                              max_delay_ms=nd)
                        acted.append(("relax", key, nb, nd))
                        tel.counter("slo/relax")
        else:
            self._healthy_streak = 0

        # predictive shed: growing queue that cannot drain within budget
        drain_s = depth / rate if rate > 0 else (float("inf") if depth
                                                 else 0.0)
        should_shed = (depth > 0 and slope > 0
                       and drain_s > o.shed_margin * target_s)
        if should_shed:
            # admit what the engine can drain within the latency budget
            limit = max(eng.opts.batch_size,
                        int(rate * target_s * o.shed_margin))
            eng.set_admit_limit(limit)
            self._admit_limit = limit
            if not self._shedding:
                self._shedding = True
                acted.append(("shed_on", None, limit, None))
                tel.counter("slo/shed_on")
                tel.dump_flight("slo_shed", p99_ms=p99_ms, depth=depth,
                                slope=slope, drain_s=drain_s,
                                admit_limit=limit)
                logger.warning(
                    "SLO shed ON%s: depth %d growing (%.2f/s), drain "
                    "%.2fs > %.2fs budget — admissions capped at %d",
                    f" [{o.label}]" if o.label else "", depth,
                    slope, drain_s, o.shed_margin * target_s, limit)
        elif self._shedding and healthy and slope <= 0:
            self._shedding = False
            eng.set_admit_limit(None)
            self._admit_limit = None
            acted.append(("shed_off", None, None, None))
            tel.counter("slo/shed_off")
            logger.info("SLO shed OFF: queue drained, p99 back in budget")

        with self._lock:
            self.ticks += 1
            self.last_p99_ms = p99_ms
            self.last_drain_rate = rate
            self.last_slope = slope
            self.last_depth = depth
            if acted:
                self.decisions += len(acted)

        if p99_ms is not None:
            tel.gauge("slo/p99_ms", p99_ms)
        tel.gauge("slo/queue_depth", depth)
        tel.gauge("slo/drain_rate", rate)
        tel.gauge("slo/admit_limit",
                  self._admit_limit if self._admit_limit is not None else -1)
        for action, key, b, d in acted:
            tel.counter("slo/decisions")
            tel.meta("slo_decision", action=action, tenant=o.label or None,
                     bucket=None if key is None else f"{key[0]}x{key[1]}",
                     max_batch=b, max_delay_ms=d, p99_ms=p99_ms,
                     depth=depth, slope=round(slope, 4))
        return acted

    def _window_base(self, samples: int, now: float) -> int:
        # observation count outside the window = count at (now − window),
        # read from per-tick (t, count) records; 0 while the history is
        # still shorter than one window, matching ``window_quantile``'s
        # whole-history fallback
        o = self.opts
        cutoff = now - o.window_s
        self._count_hist.append((now, samples))
        keep = max(int(o.window_s / o.interval_s) + 2, 2)
        self._count_hist = self._count_hist[-keep:]
        base = 0
        for t, c in self._count_hist:
            if t > cutoff:
                break
            base = c
        return base

    # -- introspection ---------------------------------------------------

    def state(self) -> dict:
        """Live controller state for ``/metrics`` (JSON) and
        ``engine_summary`` (the ``gauges`` sub-dict folds into the
        Prometheus registry)."""
        with self._lock:
            return {
                "label": self.opts.label,
                "target_p99_ms": self.opts.target_p99_ms,
                "ticks": self.ticks,
                "decisions": self.decisions,
                "shedding": self._shedding,
                "admit_limit": self._admit_limit,
                "last_p99_ms": self.last_p99_ms,
                "gauges": {
                    "slo/target_p99_ms": self.opts.target_p99_ms,
                    "slo/last_p99_ms": self.last_p99_ms or 0.0,
                    "slo/decisions": float(self.decisions),
                    "slo/shedding": 1.0 if self._shedding else 0.0,
                    "slo/queue_depth_slope": self.last_slope,
                    "slo/drain_rate": self.last_drain_rate,
                },
            }

    def capacity_signal(self) -> dict:
        """The ISSUE-18 signal export: everything the capacity authority
        needs from this controller in one locked read — measured queue
        depth, the least-squares slope, the drain forecast, and whether
        shedding is active (shedding means demand already outran THIS
        engine's capacity: immediate scale-up pressure, no forecasting
        required)."""
        with self._lock:
            depth = self.last_depth
            rate = self.last_drain_rate
            drain_s = (depth / rate) if rate > 0 else \
                (float("inf") if depth > 0 else 0.0)
            return {"label": self.opts.label,
                    "target_p99_ms": self.opts.target_p99_ms,
                    "p99_ms": self.last_p99_ms,
                    "queue_depth": depth,
                    "slope": self.last_slope,
                    "drain_rate": rate,
                    "drain_s": drain_s,
                    "shedding": self._shedding}


def _slope(points) -> float:
    """Least-squares slope of [(t, y)] — the queue-depth trend in
    requests/second.  0 for fewer than 2 points or zero time spread."""
    n = len(points)
    if n < 2:
        return 0.0
    t0 = points[0][0]
    xs = [t - t0 for t, _ in points]
    ys = [float(y) for _, y in points]
    mx = sum(xs) / n
    my = sum(ys) / n
    denom = sum((x - mx) ** 2 for x in xs)
    if denom <= 0:
        return 0.0
    return sum((x - mx) * (y - my) for x, y in zip(xs, ys)) / denom
