"""Elastic autoscaling: the capacity authority for the serving fabric
(ISSUE 18).

The PR-12 fabric supervises whatever fleet the operator started —
membership, probes, breakers, least-loaded routing — but nothing ever
decides *how many* members there should be.  :class:`CapacityAuthority`
closes that loop.  It is a control loop in the PR-6 mold: one injectable
``tick(now=None)`` step that tests drive with a fake clock and
production wraps in a daemon monitor thread.

Signals (all pre-existing — the authority adds none of its own probes):

- fabric per-member ``queue_depth``/``inflight`` gauges, folded by
  :meth:`ReplicaPool.demand` under the same stale-gauge contract as
  least-loaded routing;
- the PR-6 SLO controller's exported :meth:`capacity_signal` (queue
  depth, least-squares slope, drain rate, shed state) for co-resident
  engines;
- the PR-15 model pool's scheduler depth, via
  :meth:`ModelPool.rebalance_residency`.

Demand is *forecast*, not just measured: the authority keeps a trailing
``(t, demand)`` window and extends it ``forecast_s`` seconds ahead with
the PR-6 least-squares ``_slope`` — a rising queue scales the fleet up
before the queue is deep, which is the only way a scale-up that takes
seconds can beat a flash crowd that takes milliseconds.

Actuation goes through existing surfaces only:

- local fork replicas: :meth:`ReplicaSupervisor.add_replica` /
  :meth:`retire_replica` (the PR-8 on-demand spawn API), adopted into
  the pool with :meth:`ReplicaPool.adopt_handle`;
- remote members: re-admission via the same ``register`` path as
  ``/admin/register`` (parked members first, then the standby list),
  and graceful scale-down via :meth:`ReplicaPool.park_member` — the
  unroute → drain-in-flight sequence from the PR-8 reload, minus the
  swap;
- model placement: :meth:`ModelPool.rebalance_residency` pages the
  hottest models resident at runtime (placement is a runtime decision,
  never a boot decision).

Hard invariant — scaling NEVER causes a recompile.  New capacity warms
from the shared AOT program cache and params stay runtime args, so the
registry's ``aot_miss`` counter must not move across a scale event.
Every scale-up snapshots the per-member registry counters
(:func:`fleet_compile_counters`, including the member about to become
routable — its boot history must not be mistaken for a fresh compile)
and re-checks each member against its own baseline once the new
capacity is ready; growth is an ``autoscale/recompile_violation``
counter plus a flight dump, not a silent regression.

A noisy signal must not flap the fleet: scale-up and scale-down have
separate cooldowns, scale-down additionally requires
``down_after_ticks`` consecutive low-load ticks below a hysteresis band
(``down_headroom``×target), and a thrash guard freezes the authority
(with a flight dump) when the scale direction flips too often inside
``thrash_window_s``.

Every decision is first-class telemetry: ``autoscale/*`` counters and
gauges, an ``autoscale_decision`` meta event per action — carrying a
PR-16 trace id when tracing is on — and ``state()`` for the fabric
``/metrics`` pane.  With ``--autoscale`` off the authority is never
constructed and the fleet behaves byte-for-byte as before (pinned by
test).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

from mx_rcnn_tpu import telemetry
from mx_rcnn_tpu.logger import logger
from mx_rcnn_tpu.serve.controller import _slope
from mx_rcnn_tpu.serve.frontend import address_request
from mx_rcnn_tpu.telemetry import tracectx


@dataclass(frozen=True)
class AutoscalerOptions:
    min_members: int = 1        # never drain below this fleet size
    max_members: int = 4        # never grow past this fleet size
    target_depth: float = 4.0   # demand (queue+inflight) per ready member
    interval_s: float = 1.0     # monitor tick period
    trend_ticks: int = 8        # demand history length for the slope
    forecast_s: float = 3.0     # look-ahead horizon (predictive scale-up)
    up_cooldown_s: float = 5.0        # min spacing between scale-ups
    down_cooldown_s: float = 20.0     # min spacing between scale-downs
    down_headroom: float = 0.5  # hysteresis band: down only below h×target
    down_after_ticks: int = 3   # consecutive low ticks before a down
    thrash_window_s: float = 60.0     # flip-counting window
    thrash_flips: int = 4       # direction flips in window → freeze
    freeze_s: float = 30.0      # how long a thrash freeze lasts
    verify_timeout_s: float = 60.0    # zero-recompile check deadline

    def __post_init__(self):
        if self.min_members < 0:
            raise ValueError("min_members must be >= 0")
        if self.max_members < max(self.min_members, 1):
            raise ValueError("max_members must be >= max(min_members, 1)")
        if self.target_depth <= 0:
            raise ValueError("target_depth must be > 0")
        if not 0.0 < self.down_headroom < 1.0:
            raise ValueError("down_headroom must be in (0, 1) — at 1.0 "
                             "the up and down thresholds touch and any "
                             "noise flaps the fleet")
        if self.down_after_ticks < 1:
            raise ValueError("down_after_ticks must be >= 1")


def _registry_misses(doc) -> Optional[int]:
    """Registry ``aot_miss`` out of one member's ``/metrics`` doc (an
    actual XLA compile — ``aot_hit`` is a cache load and costs nothing).
    ``None`` when the member has no registry (shape-fake tests): no
    registry, nothing to assert."""
    if not isinstance(doc, dict):
        return None
    compile_doc = doc.get("compile")
    if not isinstance(compile_doc, dict):
        return None
    counters = compile_doc.get("counters") or {}
    return int(counters.get("aot_miss", 0) or 0)


def fleet_compile_counters(pool, extra=()) -> Dict[str, int]:
    """Best-effort **per-member** compiled-program counters over the
    routable fleet, plus any ``extra`` addresses that are about to
    become routable (a parked member being unparked, a standby being
    admitted).  Per-member is load-bearing: a member's counter carries
    its own boot history, so a scale event that makes an old member
    routable again would shift a fleet-wide *sum* even though nothing
    compiled — each member must be diffed against itself."""
    out: Dict[str, int] = {}
    for m in pool.routable_members():
        try:
            status, doc = m.http("GET", "/metrics", timeout=5.0)
        except Exception:  # noqa: BLE001 — member mid-death; skip
            continue
        if status != 200:
            continue
        misses = _registry_misses(doc)
        if misses is not None:
            out[m.name] = misses
    for addr in extra:
        if not addr or addr in out:
            continue
        try:
            status, doc = address_request(addr, "GET", "/metrics",
                                          timeout=5.0)
        except Exception:  # noqa: BLE001 — not up yet; no history then
            continue
        if status != 200:
            continue
        misses = _registry_misses(doc)
        if misses is not None:
            out[addr] = misses
    return out


def fleet_compiled_programs(pool) -> int:
    """Fleet-wide compiled-program count: the sum over
    :func:`fleet_compile_counters`.  The scalar view for reports and
    tests; the authority's own verify diffs the per-member map."""
    return sum(fleet_compile_counters(pool).values())


class CapacityAuthority:
    """The capacity control loop over one fabric pool.

    ``tick(now=None)`` is one decision step and returns the list of
    decision docs it acted on (empty on a hold) so tests can assert the
    loop without threads.  ``start()`` wraps it in the standard daemon
    monitor; ``stop()`` joins it.

    ``supervisor`` (optional) grants local fork spawn/retire authority;
    ``model_pool`` (optional) grants residency rebalance; ``controllers``
    (optional) are co-resident :class:`SLOController` instances whose
    :meth:`capacity_signal` feeds demand and shed pressure; ``standby``
    is a list of remote addresses the authority may admit when demand
    outgrows the registered fleet.  ``compile_probe`` overrides
    :func:`fleet_compiled_programs` for deterministic tests."""

    def __init__(self, pool, supervisor=None, model_pool=None,
                 controllers=(), opts: Optional[AutoscalerOptions] = None,
                 standby=(), compile_probe: Optional[Callable] = None):
        self.pool = pool
        self.sup = supervisor
        self.model_pool = model_pool
        self.controllers = list(controllers)
        self.opts = opts or AutoscalerOptions()
        self.standby = [str(a) for a in standby]
        # None → the per-member default; injected probes may return a
        # scalar (tests) or a per-member dict — verify handles both
        self._compile_probe = compile_probe
        self._lock = threading.Lock()
        self._demand_hist: List[tuple] = []  # (t, demand) trend window
        self._low_streak = 0          # consecutive below-band ticks
        self._blocked_warned = False  # one warning per blocked episode
        self._last_up_t: Optional[float] = None
        self._last_down_t: Optional[float] = None
        self._last_direction = 0      # +1 up / -1 down (thrash input)
        self._flips: List[float] = []  # direction-change instants
        self._frozen_until = 0.0
        self._pending_verify: List[dict] = []  # open recompile checks
        self.ticks = 0
        self.last_demand = 0.0
        self.last_forecast = 0.0
        self.last_slope = 0.0
        self.counters = {"scale_up": 0, "scale_down": 0, "hold": 0,
                         "spawn": 0, "retire": 0, "unpark": 0, "park": 0,
                         "admit_standby": 0, "blocked": 0,
                         "thrash_freeze": 0, "recompile_violation": 0,
                         "recompile_check": 0, "rebalance": 0}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def count(self, key: str, inc: int = 1):
        """Authority counter + the matching ``autoscale/*`` telemetry
        counter — one source for ``state()`` and the report table."""
        self.counters[key] = self.counters.get(key, 0) + inc
        telemetry.get().counter(f"autoscale/{key}", inc)

    # -- lifecycle -------------------------------------------------------

    def start(self) -> "CapacityAuthority":
        assert self._thread is None, "autoscaler already started"

        def monitor():
            while not self._stop.wait(self.opts.interval_s):
                try:
                    self.tick()
                except Exception:  # noqa: BLE001 — capacity must survive
                    logger.exception("autoscaler tick failed")

        self._thread = threading.Thread(target=monitor,
                                        name="capacity-authority",
                                        daemon=True)
        self._thread.start()
        logger.info("autoscaler: capacity authority up (fleet %d..%d, "
                    "target depth/member %.1f, forecast %.1fs)",
                    self.opts.min_members, self.opts.max_members,
                    self.opts.target_depth, self.opts.forecast_s)
        return self

    def stop(self, timeout: float = 10.0):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None

    # -- signals ---------------------------------------------------------

    def _gather(self, now: float) -> dict:
        """One consolidated signal sample: fabric demand + co-resident
        SLO controller depth, with shed state as immediate pressure."""
        demand = float(self.pool.demand(now))
        shedding = False
        for c in self.controllers:
            try:
                sig = c.capacity_signal()
            except Exception:  # noqa: BLE001 — a dying engine is not news
                continue
            demand += max(float(sig.get("queue_depth", 0) or 0), 0.0)
            shedding = shedding or bool(sig.get("shedding"))
        with self._lock:
            self._demand_hist.append((now, demand))
            if len(self._demand_hist) > self.opts.trend_ticks:
                self._demand_hist = \
                    self._demand_hist[-self.opts.trend_ticks:]
            slope = _slope(self._demand_hist)
        forecast = max(demand + slope * self.opts.forecast_s, 0.0)
        return {"demand": demand, "slope": slope, "forecast": forecast,
                "shedding": shedding}

    # -- the decision step -----------------------------------------------

    def tick(self, now: Optional[float] = None) -> List[dict]:
        """One capacity decision.  Gather → forecast → (maybe) act →
        verify open zero-recompile checks → emit telemetry."""
        now = time.monotonic() if now is None else now
        self.ticks += 1
        o = self.opts
        sig = self._gather(now)
        fleet = self.pool.capacity_count()
        ready = self.pool.ready_count()
        per_member = sig["forecast"] / max(ready, 1)
        self.last_demand = sig["demand"]
        self.last_forecast = sig["forecast"]
        self.last_slope = sig["slope"]

        decisions: List[dict] = []
        frozen = now < self._frozen_until
        if not frozen:
            if fleet < o.min_members:
                decisions += self._scale_up(now, sig, fleet, ready,
                                            reason="below_min")
            elif (per_member > o.target_depth or sig["shedding"]) \
                    and fleet < o.max_members \
                    and self._cooled(self._last_up_t, o.up_cooldown_s,
                                     now):
                reason = "shed_pressure" if sig["shedding"] \
                    else "forecast_over_target"
                decisions += self._scale_up(now, sig, fleet, ready,
                                            reason=reason)
            elif per_member < o.down_headroom * o.target_depth \
                    and sig["slope"] <= 0 and fleet > o.min_members \
                    and ready > 0:
                self._low_streak += 1
                if self._low_streak >= o.down_after_ticks \
                        and self._cooled(self._last_down_t,
                                         o.down_cooldown_s, now):
                    decisions += self._scale_down(now, sig, fleet, ready)
            else:
                self._low_streak = 0
        if not decisions:
            self.count("hold")
        if not any(d["action"] == "blocked" for d in decisions):
            self._blocked_warned = False   # episode over; warn again next time

        self._verify_pending(now)
        if self.model_pool is not None:
            self._rebalance(now)

        tel = telemetry.get()
        tel.gauge("autoscale/demand", sig["demand"])
        tel.gauge("autoscale/forecast", sig["forecast"])
        tel.gauge("autoscale/slope", sig["slope"])
        tel.gauge("autoscale/fleet", fleet)
        tel.gauge("autoscale/ready", ready)
        tel.gauge("autoscale/per_member", round(per_member, 3))
        tel.gauge("autoscale/frozen", int(frozen))
        return decisions

    @staticmethod
    def _cooled(last_t: Optional[float], cooldown_s: float,
                now: float) -> bool:
        return last_t is None or now - last_t >= cooldown_s

    # -- actuation -------------------------------------------------------

    def _scale_up(self, now: float, sig: dict, fleet: int, ready: int,
                  reason: str) -> List[dict]:
        """Add one member, cheapest capacity first: unpark a drained
        remote (warm process, zero boot cost), then admit a standby
        address, then fork a local replica via the supervisor."""
        how, detail = None, None
        parked = self.pool.parked_members()
        standby = self._unregistered_standby()
        if parked:
            how, detail = "unpark", parked[0]
        elif standby:
            how, detail = "admit_standby", standby[0]
        elif self.sup is not None:
            how = "spawn"
        if how is not None:
            # baseline BEFORE actuation, and per-member: an unparked or
            # admitted member brings its own boot-time compile history
            # into the routable set — snapshot it now so only compiles
            # caused by THIS event can show up in the verify diff (a
            # spawned child has no pre-history; its boot misses count)
            baseline = self._probe_compiles(
                extra=(detail,) if detail else ())
        if how == "unpark":
            self.pool.register(detail, now=now)
            self.count("unpark")
        elif how == "admit_standby":
            self.pool.register(detail, now=now)
            self.count("admit_standby")
        elif how == "spawn":
            h = self.sup.add_replica(now=now)
            m = self.pool.adopt_handle(h)
            self.count("spawn")
            detail = m.name
        else:
            self.count("blocked")
            if not self._blocked_warned:
                # a fleet waiting on members to boot would otherwise
                # re-warn every tick; the counter keeps the full tally
                self._blocked_warned = True
                logger.warning("autoscaler: scale-up wanted (%s) but no "
                               "capacity source — no parked member, empty "
                               "standby list, no supervisor", reason)
            return [self._decide(now, "blocked", reason, sig, fleet,
                                 ready, member=None)]
        self._last_up_t = now
        self._note_direction(now, +1)
        self.count("scale_up")
        self._low_streak = 0
        if baseline is not None:
            self.count("recompile_check")
            self._pending_verify.append(
                {"deadline": now + self.opts.verify_timeout_s,
                 "baseline": baseline, "want_ready": ready + 1,
                 "member": detail})
        logger.info("autoscaler: scale UP via %s (%s) — %s; demand %.1f "
                    "forecast %.1f slope %.2f fleet %d→%d", how, detail,
                    reason, sig["demand"], sig["forecast"], sig["slope"],
                    fleet, fleet + 1)
        return [self._decide(now, f"scale_up:{how}", reason, sig, fleet,
                             ready, member=detail)]

    def _scale_down(self, now: float, sig: dict, fleet: int,
                    ready: int) -> List[dict]:
        """Drain one member gracefully: pick the least-loaded routable
        member (remote preferred — parking is reversible for free),
        unroute it, wait out its in-flight requests, then park (remote)
        or retire (local fork)."""
        victim = self._pick_victim(now)
        if victim is None:
            return []
        if victim.kind == "remote":
            ok = self.pool.park_member(victim.name)
            how = "park"
            if ok:
                self.count("park")
        else:
            ok = self.sup is not None \
                and self.sup.retire_replica(victim.handle)
            how = "retire"
            if ok:
                self.pool.release_local(victim.name)
                self.count("retire")
        if not ok:
            # drain raced a readmit or the handle vanished — not an
            # error, just not a scale-down; try again next tick
            self._low_streak = 0
            return []
        self._last_down_t = now
        self._note_direction(now, -1)
        self.count("scale_down")
        self._low_streak = 0
        logger.info("autoscaler: scale DOWN via %s (%s) — demand %.1f "
                    "forecast %.1f fleet %d→%d", how, victim.name,
                    sig["demand"], sig["forecast"], fleet, fleet - 1)
        return [self._decide(now, f"scale_down:{how}", "below_band", sig,
                             fleet, ready, member=victim.name)]

    def _pick_victim(self, now: float):
        """Least-loaded routable member; ties prefer remote (a parked
        remote costs nothing to bring back) and then the latest joiner."""
        stale_after = self.pool.opts.stale_after_s
        best, best_key = None, None
        for m in self.pool.routable_members():
            depth = 0.0
            if m.depth is not None and m.depth_t is not None \
                    and now - m.depth_t <= stale_after:
                depth = float(m.depth)
            key = (depth + float(m.inflight),
                   0 if m.kind == "remote" else 1, m.name)
            if best_key is None or key < best_key:
                best, best_key = m, key
        return best

    def _unregistered_standby(self) -> List[str]:
        with self.pool._lock:
            known = set(self.pool.members)
        return [a for a in self.standby if a not in known]

    # -- zero-recompile verification -------------------------------------

    def _probe_compiles(self, extra=()):
        """Snapshot compile counters: the per-member map by default
        (``extra`` = addresses this scale event is about to make
        routable, so their boot history lands in the baseline), or
        whatever an injected probe returns (scalar or map)."""
        try:
            if self._compile_probe is None:
                return fleet_compile_counters(self.pool, extra=extra)
            v = self._compile_probe()
        except Exception:  # noqa: BLE001 — probe is best-effort
            return None
        if v is None or isinstance(v, dict):
            return v
        return int(v)

    def _verify_pending(self, now: float):
        """Close out open scale events: once the fleet reaches the
        expected ready count (or the deadline passes), re-probe the
        registry counters — growth means new capacity COMPILED instead
        of warming from the shared AOT cache, which breaks the contract
        that params are runtime args and placement is free."""
        if not self._pending_verify:
            return
        still_open = []
        for check in self._pending_verify:
            ripe = self.pool.ready_count() >= check["want_ready"] \
                or now >= check["deadline"]
            if not ripe:
                still_open.append(check)
                continue
            probe = self._probe_compiles()
            base = check["baseline"]
            if probe is None:
                delta = 0
            elif isinstance(probe, dict) and isinstance(base, dict):
                # each member against ITS OWN baseline — a member newly
                # routable since the snapshot (absent key) is capacity
                # this event added, so all its misses are event-caused
                delta = sum(max(v - base.get(k, 0), 0)
                            for k, v in probe.items())
            else:
                delta = max(int(probe) - int(base), 0)
            telemetry.get().gauge("autoscale/recompiles_during_scale",
                                  delta)
            if delta > 0:
                self.count("recompile_violation", delta)
                telemetry.get().dump_flight(
                    "autoscale_recompile", member=check["member"],
                    compiled=delta, baseline=check["baseline"])
                logger.error("autoscaler: ZERO-RECOMPILE VIOLATION — "
                             "%d program(s) compiled while %s warmed "
                             "(capacity must come from the shared AOT "
                             "cache)", delta, check["member"])
        self._pending_verify = still_open

    # -- residency rebalance ---------------------------------------------

    def _rebalance(self, now: float):
        try:
            paged = self.model_pool.rebalance_residency()
        except Exception:  # noqa: BLE001 — paging races model eviction
            return
        if paged:
            self.count("rebalance", len(paged))
            telemetry.get().meta("autoscale_rebalance", models=paged)

    # -- thrash guard ----------------------------------------------------

    def _note_direction(self, now: float, direction: int):
        """A scale action in the opposite direction from the last one is
        a flip; too many flips inside the window means the signal is
        oscillating faster than capacity can follow — freeze and dump."""
        if self._last_direction and direction != self._last_direction:
            self._flips.append(now)
        self._last_direction = direction
        self._flips = [t for t in self._flips
                       if now - t <= self.opts.thrash_window_s]
        if len(self._flips) >= self.opts.thrash_flips:
            self._frozen_until = now + self.opts.freeze_s
            self._flips = []
            self.count("thrash_freeze")
            telemetry.get().dump_flight(
                "autoscale_thrash", flips=self.opts.thrash_flips,
                window_s=self.opts.thrash_window_s,
                freeze_s=self.opts.freeze_s)
            logger.error("autoscaler: THRASH — %d direction flips in "
                         "%.0fs; frozen for %.0fs (a fleet that flaps "
                         "serves worse than a fleet one member too "
                         "small)", self.opts.thrash_flips,
                         self.opts.thrash_window_s, self.opts.freeze_s)

    # -- telemetry -------------------------------------------------------

    def _decide(self, now: float, action: str, reason: str, sig: dict,
                fleet: int, ready: int, member) -> dict:
        doc = {"action": action, "reason": reason, "member": member,
               "demand": round(sig["demand"], 3),
               "forecast": round(sig["forecast"], 3),
               "slope": round(sig["slope"], 4),
               "fleet": fleet, "ready": ready}
        tracer = tracectx.get()
        if tracer.enabled:
            # decisions are first-class: each gets its own trace id so
            # the PR-16 tooling can correlate the decision with the
            # member churn it caused
            ctx = tracer.mint()
            doc["trace"] = ctx.trace_id
            with tracer.span(ctx, "autoscale_decision", action=action,
                             reason=reason, member=str(member)):
                pass
        telemetry.get().meta("autoscale_decision", **doc)
        return doc

    def state(self) -> dict:
        """JSON-able authority state for the fabric ``/metrics`` pane."""
        with self._lock:
            hist = list(self._demand_hist)
        return {"options": {
                    "min_members": self.opts.min_members,
                    "max_members": self.opts.max_members,
                    "target_depth": self.opts.target_depth,
                    "forecast_s": self.opts.forecast_s},
                "ticks": self.ticks,
                "demand": round(self.last_demand, 3),
                "forecast": round(self.last_forecast, 3),
                "slope": round(self.last_slope, 4),
                "low_streak": self._low_streak,
                "frozen": time.monotonic() < self._frozen_until,
                "pending_verify": len(self._pending_verify),
                "counters": dict(self.counters)}
