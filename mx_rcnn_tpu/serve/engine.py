"""Online serving engine: bucket-aware dynamic batcher over ``Predictor``.

The offline paths (``pred_eval``, ``bench.py --mode infer``) fill batches
from a dataset; online traffic arrives one image at a time, at arbitrary
sizes, and Faster R-CNN inference is throughput-bound on batch fill.
Iteration-level dynamic batching (the Clipper recipe, Crankshaw et al.,
NSDI 2017) is exactly what the static-shape bucket design enables: every
request is resized+padded into one of a small set of pre-compiled bucket
shapes (``data.prepare_image``, the same chain the eval loader runs), so
mixed-size traffic coalesces into full batches of a handful of jit
programs with zero steady-state recompiles.

Mechanics:

* ``submit`` preps the image ON THE CALLER'S THREAD (frontend request
  threads parallelize the cv2 resize, the host-side cost), routes it to
  its orientation bucket queue, and returns a :class:`ServeFuture`.
* One dispatcher thread owns the device: it flushes a bucket when it has
  ``batch_size`` requests, or when its oldest request has waited
  ``max_delay_ms`` (the latency/throughput knob — 0 serves singletons
  immediately, larger values trade head-of-line latency for fill).
  Partial batches are padded with repeats of the last request (the
  TestLoader recipe) and the padding rows are masked out of responses.
* Backpressure is a bounded queue: ``submit`` beyond ``max_queue``
  raises :class:`RejectedError` (the frontend's 503) instead of letting
  latency grow without bound.  Per-request deadlines are swept before
  every flush: an expired request fails with
  :class:`DeadlineExceededError` (504) without wasting a forward pass.
* Post-process is the shared ``ops/postprocess`` path — byte-for-byte
  the block ``pred_eval`` runs, so served detections can never drift
  from the eval metric for the same weights.

Telemetry (whatever sink is active): per-request ``serve/queue_wait``
spans; per-batch ``serve/forward`` / ``serve/readback`` /
``serve/postprocess`` spans and ``serve/batch_fill`` / ``serve/pad_ratio``
gauges; ``serve/requests`` / ``serve/batches`` / ``serve/rejected`` /
``serve/shed`` / ``serve/deadline_exceeded`` / ``serve/recompile``
counters.  The same counts are mirrored in :attr:`ServeEngine.counters`
so ``/metrics`` works with telemetry disabled — and likewise the engine
keeps its own latency :class:`~mx_rcnn_tpu.telemetry.Hist` instances
(queue wait / service time / end-to-end request time, plus per-bucket
request time), which is what lets ``serve/controller.py`` read live p99s
and ``/metrics`` expose histogram families in every configuration.

SLO hooks (driven by :class:`~mx_rcnn_tpu.serve.controller.SLOController`
when ``--target-p99-ms`` is set, inert otherwise):

* per-bucket policy — ``set_bucket_policy(key, max_batch, max_delay_ms)``
  lowers a bucket's flush threshold below ``opts.batch_size`` and/or its
  flush delay below ``opts.max_delay_ms``.  The COMPILED program shape is
  untouched: a smaller ``max_batch`` just flushes earlier and pads more,
  trading fill for head-of-line latency without any recompile.
* admission limit — ``set_admit_limit(n)`` sheds submits (503, counted
  as ``serve/shed``, distinct from queue-full ``serve/rejected``) once
  queue depth reaches ``n`` < ``max_queue``, so the controller can cut
  intake BEFORE the queue trend turns into deadline misses.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

from mx_rcnn_tpu import telemetry
from mx_rcnn_tpu.telemetry import Hist, tracectx
from mx_rcnn_tpu.telemetry.tracectx import TraceContext
from mx_rcnn_tpu.config import Config
from mx_rcnn_tpu.data.image import bucket_shape, stage_raw_to_bucket
from mx_rcnn_tpu.data.loader import prepare_image
from mx_rcnn_tpu.logger import logger
from mx_rcnn_tpu.ops.postprocess import (decode_image_boxes,
                                         detections_to_records,
                                         device_dets_to_per_class,
                                         per_class_nms)


class RejectedError(RuntimeError):
    """Queue full (or engine stopped) — the frontend's 503."""


class DeadlineExceededError(RuntimeError):
    """The request's deadline expired before it was served — 504."""


@dataclass(frozen=True)
class ServeOptions:
    """Engine knobs (CLI: ``--serve-batch`` / ``--max-delay-ms`` /
    ``--max-queue`` / ``--deadline-ms``)."""

    batch_size: int = 4
    # flush a partial batch once its oldest request has waited this long;
    # THE latency/throughput knob (0 = serve singletons immediately)
    max_delay_ms: float = 10.0
    # bounded-queue backpressure: submits beyond this many queued requests
    # (across all buckets) are rejected, not parked
    max_queue: int = 64
    # default per-request deadline (<= 0 disables); requests may override
    deadline_ms: float = 30000.0
    # host prep worker processes (data/workers.py shm pool, CLI
    # --loader-workers): 0 keeps prepare_image on each caller's thread;
    # N > 0 ships it to the shared pool — the serving ingest bottleneck
    # once offered load outruns one interpreter's resize throughput
    prep_workers: int = 0
    # single-dispatch serving (CLI --serve-e2e): submit() only STAGES the
    # raw uint8 into its bucket (data/image.py stage_raw_to_bucket — no
    # resize/normalize on the host), and each batch runs the fused
    # prep → forward → decode+NMS registry program ("serve_e2e"): one
    # h2d transfer, one dispatch, one (B, cap, 6) readback.  Off (the
    # default) reproduces the PR-3 host-prep + host-NMS path
    # byte-for-byte.  Staging always runs on the caller's thread — it is
    # a pad-copy, far below the prep-worker break-even.
    serve_e2e: bool = False

    def __post_init__(self):
        if self.batch_size < 1:
            raise ValueError(f"batch_size must be >= 1, got {self.batch_size}")
        if self.max_queue < self.batch_size:
            raise ValueError(
                f"max_queue ({self.max_queue}) must be >= batch_size "
                f"({self.batch_size}) or a full batch could never queue")
        if self.prep_workers < 0:
            raise ValueError(
                f"prep_workers must be >= 0, got {self.prep_workers}")


class ServeFuture:
    """Completion handle for one submitted request."""

    __slots__ = ("_event", "_result", "_error", "queue_wait_s",
                 "hardness", "request")

    def __init__(self):
        self._event = threading.Event()
        self._result = None
        self._error: Optional[BaseException] = None
        self.queue_wait_s: Optional[float] = None
        # cascade sidecars, set by the on-device gate when a CascadeRouter
        # is attached to the serving engine: the per-image hardness scalar
        # and a backlink to the request (whose staged uint8 buffer an
        # escalation reuses).  None on every non-cascade path.
        self.hardness: Optional[float] = None
        self.request = None

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None) -> List[dict]:
        """Block for the detections (records sorted by descending score:
        ``{"cls", "score", "bbox": [x1,y1,x2,y2]}`` in ORIGINAL image
        coordinates).  Raises the request's failure if it was rejected,
        expired, or the forward errored."""
        if not self._event.wait(timeout):
            raise TimeoutError("request not served within wait timeout")
        if self._error is not None:
            raise self._error
        return self._result

    def _set_result(self, result):
        self._result = result
        self._event.set()

    def _set_error(self, err: BaseException):
        self._error = err
        self._event.set()


class _Request:
    __slots__ = ("image", "im_info", "t_enqueue", "deadline", "bucket",
                 "future", "raw_hw", "ratio", "orig_hw", "staged",
                 "staged_hw", "stream", "trace", "rid")

    def __init__(self, image, im_info, t_enqueue, deadline, bucket=None,
                 raw_hw=None, ratio=None, orig_hw=None, staged=None,
                 staged_hw=None, stream=None, trace=None):
        self.image = image          # bucket-padded network input, or (in
        # serve_e2e mode) the STAGED raw uint8 bucket array
        self.im_info = im_info
        self.t_enqueue = t_enqueue  # monotonic
        self.deadline = deadline    # monotonic instant or None
        self.bucket = bucket        # (H, W) routing key, for per-bucket obs
        # serve_e2e sidecars (stage_raw_to_bucket): device prep consumes
        # them inside the fused program; None on the legacy path
        self.raw_hw = raw_hw        # (2,) int32 [h, w] of the raw image
        self.ratio = ratio          # () float32 output→input sampling ratio
        # flywheel capture sidecars: pre-staging (h, w) of the submitted
        # image (detections are in those coordinates), plus — legacy path
        # with capture on only — a staged uint8 copy and its valid extent
        # (in e2e mode ``image`` already IS the staged buffer)
        self.orig_hw = orig_hw
        self.staged = staged
        self.staged_hw = staged_hw
        self.stream = stream        # stream_id when submitted via a
        # StreamManager; lets the flush side count cross-stream coalescing
        self.trace = trace          # TraceContext when the request is part
        # of a distributed trace (tracectx); None otherwise
        self.rid = None             # per-engine request id, assigned at
        # flush time ONLY for batches carrying a traced request — the
        # batch-causality key ("my request shared a dispatch with rids X")
        self.future = ServeFuture()


class ServeEngine:
    """The dynamic batcher.  ``start()`` before submitting; ``stop()``
    fails whatever is still queued (a draining stop would hold clients
    through a full queue's worth of forwards)."""

    def __init__(self, predictor, cfg: Config,
                 options: Optional[ServeOptions] = None):
        self.predictor = predictor
        self.cfg = cfg
        self.opts = options or ServeOptions()
        # serving pins SCALES[0] exactly like the TEST path (TestLoader):
        # one (short, long) pair, two orientation buckets
        self._scale = cfg.tpu.SCALES[0]
        self._queues: Dict[Tuple[int, int], List[_Request]] = {}
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        # external-dispatch mode (multi-model ModelPool): the engine owns
        # queues, policy, and forwards, but a POOL dispatcher thread calls
        # poll()/dispatch_batch() instead of the engine spawning its own
        # loop — one device owner interleaving several models' buckets
        self._external = False
        # optional work signal for that pool dispatcher: called (with no
        # lock ordering guarantees) whenever new work or a policy change
        # may have made a flush due
        self.on_work = None
        # readiness (distinct from liveness): set by warmup() once every
        # (bucket, batch) program is registered — /readyz gates routing on
        # it while /healthz only proves the process answers
        self._ready = threading.Event()
        # drain mode (weight hot-reload): no NEW admissions, queued work
        # still flushes; _inflight counts batches handed to the predictor
        # so drain() can block until the device is quiescent
        self._draining = False
        self._inflight = 0
        # checkpoint generation serving right now (atomic under _lock;
        # bumped by the hot-reload path, exposed on /metrics and /readyz)
        self.generation = 0
        # program bookkeeping: a real Predictor carries a ProgramRegistry
        # (one key space for trainer/eval/serve, AOT hit/miss accounting
        # against the persistent cache); duck-typed predictors fall back
        # to the original local shape set.  jit caches one program per
        # input shape, so the first dispatch of each bucket shape is the
        # compile either way.
        self.registry = getattr(predictor, "registry", None)
        self._dtype = getattr(predictor, "infer_dtype", "float32")
        self._seen_shapes = set()
        self.counters = {"requests": 0, "served": 0, "batches": 0,
                         "rejected": 0, "shed": 0, "deadline_exceeded": 0,
                         "recompiles": 0, "warmup_programs": 0,
                         f"recompiles_{self._dtype}": 0,
                         # boundary-crossing accounting (the serve_e2e
                         # contract: exactly 1/1/1 per batch; the legacy
                         # path reports its own so bench can compare)
                         "h2d_transfers": 0, "dispatches": 0,
                         "readbacks": 0, "readback_bytes": 0,
                         "host_prep_ms_total": 0.0,
                         # stream-aware flush bookkeeping: batches that
                         # carried >= 1 stream frame, the frame count, and
                         # batches mixing frames from DIFFERENT streams
                         # (the cross-stream coalescing win).  Skipped
                         # frames never reach the engine, so the 1/1/1
                         # per-batch contract above is stream-agnostic.
                         "stream_batches": 0, "stream_batch_frames": 0,
                         "stream_coalesced_batches": 0}
        self._pool = None  # prep worker pool (opts.prep_workers > 0)
        # engine-authoritative latency distributions (same contract as
        # self.counters: live even with telemetry off — the controller's
        # and /metrics' source of truth); Hist has its own lock, so these
        # are observed OUTSIDE self._lock
        self.hists: Dict[str, Hist] = {
            "serve/queue_wait": Hist(),
            "serve/service_time": Hist(),
            "serve/request_time": Hist(),
            # per-request host prep/staging wall (submit-side): the cost
            # serve_e2e shrinks from a cv2 resize+normalize to a pad-copy
            "serve/host_prep": Hist(),
        }
        self._bucket_hists: Dict[str, Hist] = {}  # "HxW" -> request_time
        # SLO-controller policy overrides (None/absent = configured opts);
        # max_batch is a FLUSH THRESHOLD <= opts.batch_size — the padded
        # program shape never changes, so no recompiles
        self._bucket_batch: Dict[Tuple[int, int], int] = {}
        self._bucket_delay_ms: Dict[Tuple[int, int], float] = {}
        self._admit_limit: Optional[int] = None
        self.controller = None  # set by SLOController.start()
        # flywheel request capture: NULL sink unless a capture dir was
        # configured (serve.py --capture-dir attaches a RequestCapture).
        # Same contract as telemetry — capture-off costs one attribute
        # check per batch, and the NULL sink raises if recorded into.
        from mx_rcnn_tpu.flywheel.capture import NULL_CAPTURE
        self.capture = NULL_CAPTURE
        # distributed-tracing rid counter (see _Request.rid); only
        # advanced when tracing is enabled AND a batch carries a trace
        self._next_rid = 0
        # StreamManager attaches itself here; /metrics grows a "stream"
        # section when set.  The engine never calls into it — streaming
        # stays a layer above the batcher.
        self.stream = None
        # CascadeRouter attaches itself here (on the SMALL model's engine
        # only): each serve_e2e batch then folds its on-device detections
        # into per-image hardness before readback.  Cascade-off costs
        # exactly this one attribute check per batch — the capture /
        # telemetry contract.
        self.cascade = None

    # -- lifecycle -------------------------------------------------------

    def start(self, external: bool = False) -> "ServeEngine":
        """Spawn the dispatcher thread — or, with ``external=True``
        (multi-model pool mode), skip it: the engine is fully live for
        submits/policy/metrics but batches only flush when an external
        dispatcher calls :meth:`poll` + :meth:`dispatch_batch`."""
        assert self._thread is None and not self._external, \
            "engine already started"
        if self.opts.prep_workers > 0 and self._pool is None:
            from mx_rcnn_tpu.data.workers import WorkerPool

            # image-only pool (no roidb): submit() ships raw frames in,
            # prepared bucket arrays come back through the shm ring
            self._pool = WorkerPool(self.cfg,
                                    num_workers=self.opts.prep_workers)
        if external:
            self._external = True
            return self
        self._thread = threading.Thread(target=self._dispatch_loop,
                                        name="serve-dispatch", daemon=True)
        self._thread.start()
        return self

    def stop(self, timeout: float = 10.0):
        if self._pool is not None:
            self._pool.close(timeout=timeout)
            self._pool = None
        with self._cond:
            self._stop = True
            pending = [r for q in self._queues.values() for r in q]
            for q in self._queues.values():
                q.clear()
            self._cond.notify_all()
        for r in pending:
            r.future._set_error(RejectedError("engine stopped"))
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None
        self._external = False
        if self.on_work is not None:
            self.on_work()
        if self.capture.enabled:
            self.capture.close()

    # -- readiness / drain (replica supervision + hot reload) ------------

    def mark_ready(self):
        """Warmup's signal: every steady-state program is registered.
        Flips ``/readyz`` to 200 (once per process unless a drain is in
        progress)."""
        self._ready.set()

    def is_ready(self) -> bool:
        with self._lock:
            return (self._ready.is_set() and not self._draining
                    and not self._stop
                    and (self._thread is not None or self._external))

    def readiness(self) -> dict:
        """The ``/readyz`` payload — warmup + admission state, distinct
        from ``/healthz`` liveness (a warming or draining replica is alive
        but must not receive routed traffic)."""
        with self._lock:
            return {
                "ready": (self._ready.is_set() and not self._draining
                          and not self._stop
                          and (self._thread is not None or self._external)),
                "warmed": self._ready.is_set(),
                "draining": self._draining,
                "generation": self.generation,
                "queue_depth": sum(len(q) for q in self._queues.values()),
                "admit_limit": self._admit_limit,
            }

    def drain(self, timeout: float = 30.0) -> bool:
        """Stop admitting (503) and block until every queued request has
        flushed and no batch is on the device — the quiescent point a
        weight swap needs.  Returns False if the queue didn't empty within
        ``timeout`` (caller should resume() and retry later)."""
        deadline = time.monotonic() + timeout
        with self._cond:
            self._draining = True
            self._cond.notify_all()
            while (any(self._queues.values()) or self._inflight):
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(timeout=min(remaining, 0.05))
        return True

    def resume(self):
        """Re-open admissions after a drain()."""
        with self._cond:
            self._draining = False
            self._cond.notify_all()
        if self.on_work is not None:
            self.on_work()

    # -- intake ----------------------------------------------------------

    def bucket_key(self, h: int, w: int) -> Tuple[int, int]:
        """The static padded (H, W) bucket a raw (h, w) image routes to —
        orientation picks the compiled program, exactly like the loaders'
        aspect grouping."""
        return bucket_shape(self._scale,
                            max(self.cfg.network.IMAGE_STRIDE,
                                self.cfg.network.RPN_FEAT_STRIDE),
                            landscape=(w >= h))

    def queue_depth(self) -> int:
        with self._lock:
            return sum(len(q) for q in self._queues.values())

    # -- SLO-controller policy surface -----------------------------------

    def bucket_policy(self, key: Tuple[int, int]) -> Tuple[int, float]:
        """Effective (flush_batch, max_delay_ms) for a bucket — configured
        opts unless the controller has tightened them."""
        with self._lock:
            return (self._bucket_batch.get(key, self.opts.batch_size),
                    self._bucket_delay_ms.get(key, self.opts.max_delay_ms))

    def set_bucket_policy(self, key: Tuple[int, int],
                          max_batch: Optional[int] = None,
                          max_delay_ms: Optional[float] = None):
        """Override a bucket's flush threshold / delay.  ``max_batch`` is
        clamped to [1, opts.batch_size] — the compiled shape is fixed, the
        knob only flushes earlier.  ``None`` leaves a knob unchanged;
        setting the configured value drops the override."""
        with self._cond:
            if max_batch is not None:
                b = max(1, min(int(max_batch), self.opts.batch_size))
                if b == self.opts.batch_size:
                    self._bucket_batch.pop(key, None)
                else:
                    self._bucket_batch[key] = b
            if max_delay_ms is not None:
                d = max(0.0, float(max_delay_ms))
                if d == self.opts.max_delay_ms:
                    self._bucket_delay_ms.pop(key, None)
                else:
                    self._bucket_delay_ms[key] = d
            # a shorter delay may make a parked bucket due immediately
            self._cond.notify()
        if self.on_work is not None:
            self.on_work()

    def set_admit_limit(self, limit: Optional[int]):
        """Shed submits (503) at this queue depth — the controller's
        early-shed valve.  ``None`` restores plain max_queue backpressure."""
        with self._lock:
            self._admit_limit = (None if limit is None
                                 else max(1, min(int(limit),
                                                 self.opts.max_queue)))

    def known_buckets(self) -> List[Tuple[int, int]]:
        """Buckets that have ever queued a request (adaptation targets)."""
        with self._lock:
            return sorted(self._queues.keys())

    def latency_hists(self) -> Dict[str, Hist]:
        """Engine-authoritative latency histograms, global + per-bucket
        (``serve/request_time/HxW``).  The engine lock only guards the
        dict copy; Hist contents are internally locked."""
        out = dict(self.hists)
        with self._lock:
            bucket = dict(self._bucket_hists)
        out.update({f"serve/request_time/{k}": h for k, h in bucket.items()})
        return out

    def policy(self) -> Dict[str, dict]:
        """Live effective policy per known bucket (for /metrics)."""
        with self._lock:
            keys = sorted(self._queues.keys())
            out = {}
            for key in keys:
                out[f"{key[0]}x{key[1]}"] = {
                    "max_batch": self._bucket_batch.get(
                        key, self.opts.batch_size),
                    "max_delay_ms": self._bucket_delay_ms.get(
                        key, self.opts.max_delay_ms),
                }
            return out

    def submit(self, image: np.ndarray,
               deadline_ms: Optional[float] = None,
               stream: Optional[str] = None,
               trace: Optional[TraceContext] = None) -> ServeFuture:
        """Enqueue one raw RGB HWC image (uint8 or float).  Returns a
        :class:`ServeFuture`; raises :class:`RejectedError` immediately
        when the queue is full or the engine is stopped.  ``stream`` tags
        the request with its originating stream_id (StreamManager) so the
        flush side can account cross-stream batch sharing — it changes
        nothing about routing, batching, or the forward.  ``trace``
        (a :class:`~mx_rcnn_tpu.telemetry.tracectx.TraceContext`) rides
        the request so the flush side can emit batch-causality spans —
        equally inert for routing and batching."""
        if image.ndim != 3 or image.shape[2] != 3:
            raise ValueError(f"expected (H, W, 3) RGB image, "
                             f"got shape {tuple(image.shape)}")
        tel = telemetry.get()
        t_prep = time.perf_counter()
        raw_hw = ratio = None
        if self.opts.serve_e2e:
            # single-dispatch mode: no host resize/normalize — stage the
            # raw uint8 into its bucket (pad-copy; oversized raws shrink
            # host-side, see stage_raw_to_bucket) and let the fused
            # program run the prep on device
            prepared, raw_hw, ratio, im_info = stage_raw_to_bucket(
                np.asarray(image), self._scale,
                max(self.cfg.network.IMAGE_STRIDE,
                    self.cfg.network.RPN_FEAT_STRIDE))
        elif self._pool is not None:
            # host prep off the dispatcher thread either way: on the
            # caller's thread (workers=0 — concurrent frontends
            # parallelize the resize) or in the shared prep worker pool
            # (byte-identical transform, pinned by test_loader_workers),
            # so the device hot path never waits on a resize
            prepared, im_info = self._pool.prepare(np.asarray(image),
                                                   self._scale)
        else:
            prepared, im_info = prepare_image(np.asarray(image), self.cfg,
                                              self._scale)
        prep_s = time.perf_counter() - t_prep
        self.hists["serve/host_prep"].observe(prep_s)
        tel.observe("serve/host_prep", prep_s)
        orig_hw = (int(image.shape[0]), int(image.shape[1]))
        staged = staged_hw = None
        if self.capture.enabled and not self.opts.serve_e2e:
            # capture-on, legacy path: also stage the raw uint8 so the
            # flywheel logs the pixels the PII-free contract allows (the
            # e2e path's ``prepared`` already IS that buffer).  Runs on
            # the caller's thread, like the prep itself.
            raw8 = np.asarray(image)
            if raw8.dtype != np.uint8:
                raw8 = np.clip(raw8, 0, 255).astype(np.uint8)
            staged, staged_hw, _, _ = stage_raw_to_bucket(
                raw8, self._scale,
                max(self.cfg.network.IMAGE_STRIDE,
                    self.cfg.network.RPN_FEAT_STRIDE))
        # route on the LOGICAL bucket (pre-s2d padded shape) — under
        # HOST_S2D the prepared array is (H/2, W/2, 12), but orientation
        # and program identity are the bucket's, and /metrics should name
        # buckets in image coordinates
        key = self.bucket_key(image.shape[0], image.shape[1])
        now = time.monotonic()
        if deadline_ms is None:
            deadline_ms = self.opts.deadline_ms
        deadline = now + deadline_ms / 1e3 if deadline_ms > 0 else None
        req = _Request(prepared, im_info, now, deadline, bucket=key,
                       raw_hw=raw_hw, ratio=ratio, orig_hw=orig_hw,
                       staged=staged, staged_hw=staged_hw, stream=stream,
                       trace=trace)
        return self._enqueue(req, key, tel, prep_s=prep_s)

    def _enqueue(self, req: _Request, key, tel,
                 prep_s: float = 0.0) -> ServeFuture:
        """Shared admission tail of :meth:`submit` / :meth:`submit_staged`:
        backpressure + shed checks under the lock, queue insert, counters,
        work signal."""
        with self._cond:
            if self._stop:
                self.counters["rejected"] += 1
                tel.counter("serve/rejected")
                raise RejectedError("engine stopped")
            if self._draining:
                # weight swap in progress: queued work still flushes but
                # nothing new is admitted — the router retries on an
                # alternate replica, a bare client backs off briefly
                self.counters["rejected"] += 1
                tel.counter("serve/rejected")
                raise RejectedError(
                    "draining (weight swap in progress) — retry shortly")
            depth = sum(len(q) for q in self._queues.values())
            if self._admit_limit is not None and depth >= self._admit_limit:
                # controller-driven early shed: the queue is NOT full, but
                # its trend predicts deadline misses — refuse now, cheaply,
                # instead of serving a 504 after a wasted queue residence
                self.counters["shed"] += 1
                tel.counter("serve/shed")
                raise RejectedError(
                    f"load shed: SLO controller capped admissions at "
                    f"{self._admit_limit} queued requests ({depth} "
                    f"pending) — retry with backoff")
            if depth >= self.opts.max_queue:
                self.counters["rejected"] += 1
                tel.counter("serve/rejected")
                raise RejectedError(
                    f"queue full ({depth}/{self.opts.max_queue} requests "
                    f"pending) — retry with backoff")
            self._queues.setdefault(key, []).append(req)
            self.counters["requests"] += 1
            self.counters["host_prep_ms_total"] += prep_s * 1e3
            tel.counter("serve/requests")
            tel.gauge("serve/queue_depth", depth + 1)
            self._cond.notify()
        if self.on_work is not None:
            self.on_work()
        return req.future

    def submit_staged(self, staged: np.ndarray, raw_hw, ratio, im_info,
                      orig_hw,
                      deadline_ms: Optional[float] = None,
                      stream: Optional[str] = None,
                      trace: Optional[TraceContext] = None) -> ServeFuture:
        """Cascade escalation intake: enqueue an ALREADY-STAGED uint8
        bucket buffer (another engine's serve_e2e ``_Request.image``) with
        its staging sidecars, skipping ``stage_raw_to_bucket`` entirely —
        the escalated request reuses the staged pixels byte-for-byte and
        pays zero host prep.  serve_e2e mode only.  The CascadeRouter
        verified at construction that both cascade engines share bucket
        geometry; the shape is re-checked here so a config drift fails
        loudly instead of silently compiling a foreign shape."""
        if not self.opts.serve_e2e:
            raise RejectedError(
                "submit_staged requires serve_e2e mode (staged uint8 "
                "buffers are only a program input on the fused path)")
        key = self.bucket_key(int(orig_hw[0]), int(orig_hw[1]))
        if tuple(staged.shape[:2]) != key:
            raise ValueError(
                f"staged buffer {tuple(staged.shape[:2])} does not match "
                f"this engine's bucket {key} — cascade models must share "
                f"bucket geometry (SCALES + strides)")
        tel = telemetry.get()
        now = time.monotonic()
        if deadline_ms is None:
            deadline_ms = self.opts.deadline_ms
        deadline = now + deadline_ms / 1e3 if deadline_ms > 0 else None
        req = _Request(staged, im_info, now, deadline, bucket=key,
                       raw_hw=raw_hw, ratio=ratio,
                       orig_hw=(int(orig_hw[0]), int(orig_hw[1])),
                       stream=stream, trace=trace)
        return self._enqueue(req, key, tel)

    def predict(self, image: np.ndarray,
                deadline_ms: Optional[float] = None,
                timeout: Optional[float] = 60.0) -> List[dict]:
        """Synchronous convenience: ``submit`` + wait."""
        return self.submit(image, deadline_ms=deadline_ms).result(timeout)

    # -- dispatch --------------------------------------------------------

    def _sweep_expired_locked(self, now: float) -> List[_Request]:
        expired = []
        for q in self._queues.values():
            live = []
            for r in q:
                (expired if r.deadline is not None and r.deadline <= now
                 else live).append(r)
            q[:] = live
        return expired

    def _next_batch_locked(self, now: float):
        """(requests, None) when a bucket is due, else (None, wait_s).

        Full buckets flush first; among due buckets the one whose
        head-of-line request is OLDEST wins — deadline-ordered flushing,
        so no bucket's traffic can starve another's latency budget.

        "Full" and "due" are judged per bucket against the controller's
        policy overrides (flush threshold <= opts.batch_size, possibly
        shortened delay); without a controller both fall back to opts."""
        best_key, best_t, best_full = None, None, False
        wait = None
        for key, q in self._queues.items():
            if not q:
                continue
            B = self._bucket_batch.get(key, self.opts.batch_size)
            delay = self._bucket_delay_ms.get(
                key, self.opts.max_delay_ms) / 1e3
            head_t = q[0].t_enqueue
            full = len(q) >= B
            if not (full or (now - head_t) >= delay):
                remaining = delay - (now - head_t)
                wait = remaining if wait is None else min(wait, remaining)
                continue
            # full beats partial; among equals the oldest head wins
            if best_key is None or (full, -head_t) > (best_full, -best_t):
                best_key, best_t, best_full = key, head_t, full
        if best_key is not None:
            q = self._queues[best_key]
            B = self._bucket_batch.get(best_key, self.opts.batch_size)
            take, q[:] = q[:B], q[B:]
            return take, None
        return None, wait

    def _fail_expired(self, expired: List[_Request]):
        for r in expired:
            self.counters["deadline_exceeded"] += 1
            telemetry.get().counter("serve/deadline_exceeded")
            r.future._set_error(DeadlineExceededError(
                "request expired before it reached a batch (engine "
                "overloaded? raise --max-queue workers or add "
                "replicas)"))

    # -- external (pool) dispatch surface --------------------------------

    def due_state(self, now: float):
        """Lock-held peek for the ModelPool scheduler: ``(due, depth,
        wait_s)``.  ``due`` is True when a bucket would flush right now
        (full, delay elapsed) OR an expired request needs sweeping;
        ``wait_s`` is the earliest instant that could change (None when
        idle).  Purely advisory — :meth:`poll` re-judges under the lock,
        so a racing submit is at worst a missed wakeup until on_work."""
        with self._lock:
            depth = 0
            due = False
            wait = None
            for key, q in self._queues.items():
                depth += len(q)
                if not q or due:
                    continue
                B = self._bucket_batch.get(key, self.opts.batch_size)
                delay = self._bucket_delay_ms.get(
                    key, self.opts.max_delay_ms) / 1e3
                head_t = q[0].t_enqueue
                if len(q) >= B or (now - head_t) >= delay:
                    due = True
                    continue
                remaining = delay - (now - head_t)
                wait = remaining if wait is None else min(wait, remaining)
                for r in q:
                    if r.deadline is not None:
                        if r.deadline <= now:
                            due = True
                            break
                        wait = min(wait, r.deadline - now)
        return due, depth, wait

    def poll(self, now: Optional[float] = None):
        """Claim the next due batch for an external dispatcher: sweeps
        expired requests (failing them with 504) and pops one bucket's
        flush if due.  Returns ``(batch, wait_s)`` — a claimed batch
        holds an inflight slot until :meth:`dispatch_batch` releases it;
        ``(None, wait_s)`` means nothing is due for ``wait_s`` seconds
        (None = idle/stopped)."""
        with self._cond:
            if self._stop:
                return None, None
            if now is None:
                now = time.monotonic()
            expired = self._sweep_expired_locked(now)
            batch, wait = self._next_batch_locked(now)
            if batch is not None:
                self._inflight += 1
        self._fail_expired(expired)
        return batch, wait

    def dispatch_batch(self, batch: List[_Request]):
        """Run one batch claimed by :meth:`poll` (external dispatcher's
        half of ``_dispatch_loop``): forwards, fails the batch on error,
        and releases the inflight slot either way."""
        try:
            self._run_batch(batch, time.monotonic())
        except BaseException as e:  # noqa: BLE001 — fail the batch
            logger.exception("serve batch failed")
            for r in batch:
                r.future._set_error(e)
        finally:
            with self._cond:
                self._inflight -= 1
                self._cond.notify_all()  # drain() waits on this

    def _dispatch_loop(self):
        while True:
            with self._cond:
                if self._stop:
                    return
                now = time.monotonic()
                expired = self._sweep_expired_locked(now)
                batch, wait = self._next_batch_locked(now)
                if batch is not None:
                    self._inflight += 1
                if batch is None and not expired:
                    self._cond.wait(timeout=wait)
                    continue
            self._fail_expired(expired)
            if batch is not None:
                self.dispatch_batch(batch)

    def _run_batch(self, reqs: List[_Request], now: float):
        import jax

        tel = telemetry.get()
        B = self.opts.batch_size
        pad = B - len(reqs)
        for r in reqs:
            r.future.queue_wait_s = now - r.t_enqueue
            tel.add("serve/queue_wait", now - r.t_enqueue)
            self.hists["serve/queue_wait"].observe(now - r.t_enqueue)
            tel.observe("serve/queue_wait", now - r.t_enqueue)
        # pad partial batches with repeats (the TestLoader recipe); the
        # padded rows never reach a response
        images = np.stack([r.image for r in reqs]
                          + [reqs[-1].image] * pad)
        im_info = np.stack([r.im_info for r in reqs]
                           + [reqs[-1].im_info] * pad)
        tel.gauge("serve/batch_fill", len(reqs) / B)
        tel.gauge("serve/pad_ratio", pad / B)
        # distributed tracing: tracing-off costs exactly this ONE
        # attribute check per batch (the capture contract) — phases stay
        # None and every trace branch below is a no-op
        tracer = tracectx.get()
        phases = {} if tracer.enabled else None
        if self.opts.serve_e2e:
            xfer = self._forward_e2e(reqs, images, im_info, tel, phases)
        else:
            xfer = self._forward_legacy(reqs, images, im_info, tel, phases)
        # latency distributions: service time once per batch, end-to-end
        # request time once per request (global + per-bucket family) —
        # into the engine's own Hists AND the active sink, so the SLO
        # controller and /metrics see them regardless of telemetry config
        done = time.monotonic()
        service_s = done - now
        self.hists["serve/service_time"].observe(service_s)
        tel.observe("serve/service_time", service_s)
        new_bucket_hists = {}
        for r in reqs:
            req_s = done - r.t_enqueue
            self.hists["serve/request_time"].observe(req_s)
            tel.observe("serve/request_time", req_s)
            if r.bucket is not None:
                bk = f"{r.bucket[0]}x{r.bucket[1]}"
                h = self._bucket_hists.get(bk) or new_bucket_hists.get(bk)
                if h is None:
                    h = new_bucket_hists[bk] = Hist()
                h.observe(req_s)
                tel.observe(f"serve/request_time/{bk}", req_s)
        stream_ids = {r.stream for r in reqs if r.stream is not None}
        stream_frames = sum(1 for r in reqs if r.stream is not None)
        with self._lock:
            self._bucket_hists.update(new_bucket_hists)
            self.counters["batches"] += 1
            self.counters["served"] += len(reqs)
            if stream_frames:
                self.counters["stream_batches"] += 1
                self.counters["stream_batch_frames"] += stream_frames
                if len(stream_ids) > 1:
                    self.counters["stream_coalesced_batches"] += 1
            for k, v in xfer.items():
                self.counters[k] = self.counters.get(k, 0) + v
        tel.counter("serve/batches")
        tel.counter("serve/images", len(reqs))
        if stream_frames:
            tel.counter("stream/batches")
            tel.counter("stream/batch_frames", stream_frames)
            if len(stream_ids) > 1:
                tel.counter("stream/coalesced_batches")
        if tracer.enabled:
            self._emit_trace_spans(tracer, reqs, now, done, pad, B, phases)
        if self.capture.enabled:
            entries = []
            for r in reqs:
                px, hw = ((r.image, r.raw_hw) if self.opts.serve_e2e
                          else (r.staged, r.staged_hw))
                if px is not None:
                    entries.append((px, hw, r.orig_hw, r.future._result,
                                    r.trace.trace_id
                                    if r.trace is not None else None))
            self.capture.record_batch(entries, self.generation)

    def _emit_trace_spans(self, tracer, reqs: List[_Request],
                          t_start: float, t_done: float, pad: int, B: int,
                          phases: Optional[dict]):
        """The batch-causality spans.  For every traced request in the
        flush: an ``engine/request`` span (rid, batch-peer rids, queue
        position, pad fraction, bucket, occupancy) parented on the
        request's incoming context, an ``engine/dispatch`` child naming
        every rid that shared the device program run, and per-phase
        children (h2d/forward/readback/postprocess) from the measured
        batch phase durations — so a slow trace resolves to WHICH wait:
        queue residence behind peers, a cold compile in the forward, or
        a fat readback."""
        traced = [r for r in reqs if r.trace is not None and r.trace.sampled]
        if not traced:
            return
        with self._lock:
            for r in reqs:
                if r.rid is None:
                    r.rid = self._next_rid
                    self._next_rid += 1
        rids = [r.rid for r in reqs]
        bucket = reqs[0].bucket
        bname = f"{bucket[0]}x{bucket[1]}" if bucket is not None else None
        occupancy = f"{len(reqs)}/{B}"
        service_s = t_done - t_start
        for pos, r in enumerate(reqs):
            ctx = r.trace
            if ctx is None or not ctx.sampled:
                continue
            req_sid = tracer.record(
                ctx, "engine/request", t_done - r.t_enqueue,
                attrs={"rid": r.rid,
                       "peers": [i for i in rids if i != r.rid],
                       "queue_pos": pos,
                       "queue_wait_ms": round(
                           (t_start - r.t_enqueue) * 1e3, 3),
                       "pad_frac": round(pad / B, 4),
                       "bucket": bname, "occupancy": occupancy,
                       "stream": r.stream,
                       "generation": self.generation})
            if req_sid is None:
                continue
            disp_sid = tracer.record(
                TraceContext(ctx.trace_id, req_sid), "engine/dispatch",
                service_s, attrs={"batch_rids": rids, "pad": pad,
                                  "bucket": bname, "occupancy": occupancy})
            if disp_sid is None or not phases:
                continue
            pctx = TraceContext(ctx.trace_id, disp_sid)
            for ph in ("h2d", "forward", "readback", "postprocess"):
                d = phases.get(ph)
                if d is not None:
                    tracer.record(pctx, f"engine/{ph}", d)

    def _note_first_dispatch(self, shape, kind: str, tel) -> bool:
        """First-seen accounting for one batch's program (registry when
        the predictor carries one, local shape set otherwise) + the
        recompile counters/meta the SLO machinery watches."""
        if self.registry is not None:
            first = self.predictor.note_dispatch(shape, kind=kind) \
                if kind == "serve_e2e" else \
                self.predictor.note_dispatch(shape)
        else:
            first = (kind, shape) not in self._seen_shapes
            self._seen_shapes.add((kind, shape))
        if first:
            self.counters["recompiles"] += 1
            self.counters[f"recompiles_{self._dtype}"] += 1
            tel.counter("serve/recompile")
            tel.counter(f"serve/recompile/{self._dtype}")
            tel.meta("recompile", program=kind,
                     shape=[s for s in shape if not isinstance(s, str)],
                     dtype=self._dtype)
        return first

    def _forward_legacy(self, reqs: List[_Request], images, im_info,
                        tel, phases: Optional[dict] = None) -> dict:
        """PR-3 path: host-prepped batch in, full score/delta readback,
        host decode + per-class NMS.  Returns the batch's boundary-
        crossing counter increments (two h2d arrays — images and im_info
        ship separately into the jit call — one dispatch, one fat
        readback).  ``phases`` (tracing on only) collects per-phase wall
        durations for the engine's dispatch sub-spans."""
        import jax

        shape = tuple(images.shape)
        first = self._note_first_dispatch(shape, "serve_predict", tel)
        t_fwd = time.monotonic()
        t_ph = time.perf_counter() if phases is not None else 0.0
        with tel.span("serve/forward"):
            rois, roi_valid, cls_prob, bbox_deltas, _ = \
                self.predictor.predict(images, im_info)
        if phases is not None:
            t_now = time.perf_counter()
            phases["forward"] = t_now - t_ph
            t_ph = t_now
        with tel.span("serve/readback"):
            rois, roi_valid, cls_prob, bbox_deltas = jax.device_get(
                (rois, roi_valid, cls_prob, bbox_deltas))
        if phases is not None:
            t_now = time.perf_counter()
            phases["readback"] = t_now - t_ph
            t_ph = t_now
        if first and self.registry is not None:
            # first dispatch of a shape = its compile: the forward +
            # readback wall is the compile(+first run) cost this program
            # would charge a cold user request
            self.predictor.record_compile_seconds(
                shape, time.monotonic() - t_fwd)
        cfg = self.cfg
        with tel.span("serve/postprocess"):
            for b, r in enumerate(reqs):
                boxes = decode_image_boxes(rois[b], bbox_deltas[b],
                                           np.asarray(r.im_info))
                dets_pc = per_class_nms(cls_prob[b], boxes, roi_valid[b],
                                        cfg.NUM_CLASSES, cfg.TEST.THRESH,
                                        cfg.TEST.NMS,
                                        cfg.TEST.MAX_PER_IMAGE)
                r.future._set_result(detections_to_records(dets_pc))
        if phases is not None:
            phases["postprocess"] = time.perf_counter() - t_ph
        nbytes = int(sum(np.asarray(a).nbytes for a in
                         (rois, roi_valid, cls_prob, bbox_deltas)))
        return {"h2d_transfers": 2, "dispatches": 1, "readbacks": 1,
                "readback_bytes": nbytes}

    def _forward_e2e(self, reqs: List[_Request], staged, im_info,
                     tel, phases: Optional[dict] = None) -> dict:
        """Single-dispatch path (``--serve-e2e``): ONE ``device_put`` of
        the staged uint8 batch + its sidecars, ONE fused
        prep → forward → decode+NMS dispatch (registry kind
        ``serve_e2e``), ONE readback of the ``(B, cap, 6)`` detections.
        Responses come from ``device_dets_to_per_class`` — the same
        top-k-capped contract as ``--device-postprocess`` eval, so exact
        score ties at the cap may resolve differently from the host-NMS
        path (documented in ``ops.postprocess.device_postprocess``)."""
        import jax

        pad = len(staged) - len(reqs)
        raw_hw = np.stack([np.asarray(r.raw_hw) for r in reqs]
                          + [np.asarray(reqs[-1].raw_hw)] * pad
                          ).astype(np.int32)
        ratio = np.asarray([r.ratio for r in reqs]
                           + [reqs[-1].ratio] * pad, np.float32)
        flip = np.zeros(len(staged), bool)  # serve traffic never flips
        cfg = self.cfg
        mpi = int(cfg.TEST.MAX_PER_IMAGE)
        th = float(cfg.TEST.THRESH)
        shape = tuple(staged.shape) + (f"mpi={mpi}", f"th={th:g}")
        first = self._note_first_dispatch(shape, "serve_e2e", tel)
        t_fwd = time.monotonic()
        t_ph = time.perf_counter() if phases is not None else 0.0
        with tel.span("serve/h2d"):
            # the one host→device transfer: a single put of the argument
            # tuple whose only large buffer is the staged uint8 batch
            args = jax.device_put((staged, raw_hw, ratio,
                                   np.asarray(im_info, np.float32), flip))
        if phases is not None:
            t_now = time.perf_counter()
            phases["h2d"] = t_now - t_ph
            t_ph = t_now
        with tel.span("serve/forward"):
            dets, dvalid = self.predictor.predict_serve_e2e(*args, mpi, th)
        if phases is not None:
            t_now = time.perf_counter()
            phases["forward"] = t_now - t_ph
            t_ph = t_now
        if self.cascade is not None:
            # on-device confidence gate: fold the (B, cap, 6) detections
            # into per-image hardness while they are STILL device arrays —
            # the gate consumes tensors already on device and reads back
            # (B,) floats, adding zero h2d transfers to the batch
            self.cascade.gate_batch(dets, dvalid, reqs)
        with tel.span("serve/readback"):
            dets, dvalid = jax.device_get((dets, dvalid))
        if phases is not None:
            t_now = time.perf_counter()
            phases["readback"] = t_now - t_ph
            t_ph = t_now
        if first and self.registry is not None:
            self.predictor.record_compile_seconds(
                shape, time.monotonic() - t_fwd, kind="serve_e2e")
        with tel.span("serve/postprocess"):
            for b, r in enumerate(reqs):
                dets_pc = device_dets_to_per_class(dets[b], dvalid[b],
                                                   cfg.NUM_CLASSES)
                r.future._set_result(detections_to_records(dets_pc))
        if phases is not None:
            phases["postprocess"] = time.perf_counter() - t_ph
        nbytes = int(np.asarray(dets).nbytes + np.asarray(dvalid).nbytes)
        return {"h2d_transfers": 1, "dispatches": 1, "readbacks": 1,
                "readback_bytes": nbytes}

    # -- introspection ---------------------------------------------------

    def metrics(self) -> dict:
        """The ``/metrics`` payload: counters + live queue state, latency
        quantiles, effective per-bucket policy, and (when a controller is
        attached) its live state.  ``self._lock`` is NOT reentrant (the
        dispatch condition wraps it), so everything that takes its own
        lock — Hist quantiles, ``policy()``, the controller — runs after
        the engine lock is released."""
        with self._lock:
            out = {
                "counters": dict(self.counters),
                "queue_depth": sum(len(q) for q in self._queues.values()),
                "buckets": {f"{h}x{w}": len(q)
                            for (h, w), q in self._queues.items()},
                "options": {"batch_size": self.opts.batch_size,
                            "max_delay_ms": self.opts.max_delay_ms,
                            "max_queue": self.opts.max_queue,
                            "deadline_ms": self.opts.deadline_ms},
                "admit_limit": self._admit_limit,
                "generation": self.generation,
                "ready": (self._ready.is_set() and not self._draining
                          and not self._stop
                          and (self._thread is not None or self._external)),
                "draining": self._draining,
            }
        latency = {}
        for name, h in self.hists.items():
            short = name.split("/", 1)[1]
            for q, tag in ((0.5, "p50_ms"), (0.99, "p99_ms")):
                v = h.quantile(q)
                if v is not None:
                    latency[f"{short}_{tag}"] = round(v * 1e3, 3)
        out["latency"] = latency
        out["policy"] = self.policy()
        out["dtype"] = self._dtype
        if self.capture.enabled:
            out["flywheel"] = self.capture.metrics()
        tracer = tracectx.get()
        if tracer.enabled:
            out["trace"] = tracer.metrics()
        if self.stream is not None:
            out["stream"] = self.stream.metrics()
        if self.registry is not None:
            out["compile"] = self.registry.snapshot()
        ctrl = self.controller
        if ctrl is not None:
            out["controller"] = ctrl.state()
        return out
