"""Multi-model serving plane: one device, a fleet of models.

``serve.py`` historically bound one process to one ``(config, params,
Predictor)``.  :class:`ModelPool` lifts that to N models behind a single
frontend without N× device memory, N× recompiles, or one tenant's burst
destroying another's p99:

* **Model registry.**  Each entry keys a model id to its own config,
  ``Predictor`` (hence its own ``ProgramRegistry`` — program identity
  already folds the config digest, so models get disjoint program keys
  and AOT cache subtrees for free) and its own :class:`ServeEngine`
  started in external-dispatch mode.  ``/predict?model=...`` resolves
  here; requests without a model land on the default entry, preserving
  single-model semantics byte-for-byte.
* **Device weight residency.**  Param trees are paged host↔device under
  a configurable byte budget (``--weight-budget-mb``) with LRU eviction
  over last-dispatch time.  A page-out snapshots the variant-cast tree
  to host memory and deletes the device buffers; a page-in is a plain
  ``device_put`` of that snapshot — params are RUNTIME arguments to
  every registered program (the ``update_params`` hot-reload contract),
  so paging costs zero recompiles.  Pinned models are never paged out
  (their registries are also exempt from program LRU eviction).
  Counters ``serve/weight_page_in|out`` + per-model residency gauges
  make the paging observable on ``/metrics``.
* **Cross-model batch scheduling.**  ONE pool dispatcher thread owns
  the device and interleaves per-model bucket queues: among models with
  a due flush it picks the highest ``weight * (queue_depth + 1)`` score
  (weight = the model's SLO class), tie-broken by least-recently
  scheduled, so heterogeneous traffic keeps dispatch occupancy high and
  a cheap model is not starved by a heavy one.  Within a model the
  engine's own full-beats-oldest-partial bucket ordering is unchanged.
* **Tenant isolation.**  Each entry can carry its own
  :class:`~mx_rcnn_tpu.serve.controller.SLOController` (distinct
  ``--target-p99-ms``): admission shedding and flush-policy adaptation
  act on that model's engine only, so a burst on the mask model sheds
  the mask model's traffic first.

Driver: ``serve.py --models a=resnet50,b=vgg16`` (per-model overrides
via ``--model-arg``); loadgen: ``scripts/loadgen.py --models
a=0.7,b=0.3``; smoke: ``script/multimodel_smoke.sh``.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from mx_rcnn_tpu import telemetry
from mx_rcnn_tpu.logger import logger
from mx_rcnn_tpu.telemetry import Hist, tracectx

# tenant fidelity classes (--model-arg ID:fidelity=...): "cascade" routes
# the tenant's traffic through the confidence gate, "full" pins it to the
# big model unconditionally — the SLO escape hatch for tenants whose
# accuracy budget admits no small-model answers
FIDELITY_CLASSES = ("cascade", "full")


def param_nbytes(tree) -> int:
    """Total bytes of a param tree's leaves (device or host)."""
    try:
        import jax

        leaves = jax.tree_util.tree_leaves(tree)
    except Exception:
        return 0
    total = 0
    for leaf in leaves:
        nbytes = getattr(leaf, "nbytes", None)
        if nbytes is None:
            size = getattr(leaf, "size", 0)
            itemsize = getattr(getattr(leaf, "dtype", None), "itemsize", 0)
            nbytes = size * itemsize
        total += int(nbytes)
    return total


class ModelEntry:
    """One registered model: identity, compute, policy, residency."""

    __slots__ = ("model_id", "cfg", "predictor", "engine", "controller",
                 "pinned", "weight", "fidelity", "resident", "bytes",
                 "host_params", "last_use", "last_sched", "batches",
                 "page_ins", "page_outs")

    def __init__(self, model_id, cfg, predictor, engine, controller=None,
                 pinned=False, weight=1.0, fidelity="cascade"):
        self.model_id = model_id
        self.cfg = cfg
        self.predictor = predictor
        self.engine = engine
        self.controller = controller
        self.pinned = bool(pinned)
        self.weight = max(float(weight), 1e-3)
        if fidelity not in FIDELITY_CLASSES:
            raise ValueError(f"fidelity must be one of {FIDELITY_CLASSES}, "
                             f"got {fidelity!r}")
        self.fidelity = fidelity
        self.resident = True        # params arrive placed by construction
        self.bytes = param_nbytes(getattr(predictor, "params", None))
        self.host_params = None     # host snapshot while paged out
        self.last_use = time.monotonic()
        self.last_sched = 0.0
        self.batches = 0
        self.page_ins = 0
        self.page_outs = 0


class ModelPool:
    """Owns the model entries, the weight-residency manager, and the one
    cross-model dispatcher thread.  Engines must be started with
    ``start(external=True)`` before :meth:`add_model`."""

    def __init__(self, budget_bytes: int = 0, idle_poll_s: float = 0.05):
        # 0 = unbounded (no paging ever happens except explicit calls)
        self.budget_bytes = max(int(budget_bytes), 0)
        self._idle_poll_s = max(float(idle_poll_s), 1e-3)
        self._entries: "Dict[str, ModelEntry]" = {}
        self._order: List[str] = []     # registration order; [0] = default
        self._lock = threading.RLock()
        self._wake = threading.Event()
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        self._last_model: Optional[str] = None
        self.counters = {"weight_page_in": 0, "weight_page_out": 0,
                         "sched_batches": 0, "sched_switches": 0}
        # CascadeRouter, when --cascade is configured; /metrics grows a
        # "cascade" section.  The pool never calls into it — the router
        # sits a layer above the scheduler (its escalations arrive as
        # ordinary big-model submits the dispatcher interleaves).
        self.cascade = None

    # -- registry --------------------------------------------------------

    def add_model(self, model_id: str, cfg, predictor, engine,
                  controller=None, pinned: bool = False,
                  weight: float = 1.0,
                  fidelity: str = "cascade") -> ModelEntry:
        if not model_id or "/" in model_id:
            raise ValueError(f"bad model id {model_id!r}")
        entry = ModelEntry(model_id, cfg, predictor, engine,
                           controller=controller, pinned=pinned,
                           weight=weight, fidelity=fidelity)
        with self._lock:
            if model_id in self._entries:
                raise ValueError(f"model {model_id!r} already registered")
            if pinned:
                pinned_total = entry.bytes + sum(
                    e.bytes for e in self._entries.values() if e.pinned)
                if self.budget_bytes and pinned_total > self.budget_bytes:
                    raise ValueError(
                        f"pinned models need {pinned_total} bytes, over "
                        f"the {self.budget_bytes}-byte weight budget")
                reg = getattr(predictor, "registry", None)
                if reg is not None:
                    reg.pinned = True
            self._entries[model_id] = entry
            self._order.append(model_id)
        engine.on_work = self._wake.set
        # a new resident model may push the pool over budget: evict
        # colder models rather than refusing the registration
        self.ensure_resident(model_id)
        logger.info("model pool: registered %r (%d bytes, pinned=%s, "
                    "weight=%g)", model_id, entry.bytes, pinned, weight)
        return entry

    def model_ids(self) -> List[str]:
        with self._lock:
            return list(self._order)

    @property
    def default_model(self) -> Optional[str]:
        with self._lock:
            return self._order[0] if self._order else None

    def entry(self, model_id: Optional[str] = None) -> ModelEntry:
        """Resolve a model id (None = default) to its entry; raises
        ``KeyError`` for unknown ids — the frontend's 404."""
        with self._lock:
            if model_id is None:
                if not self._order:
                    raise KeyError("model pool is empty")
                model_id = self._order[0]
            e = self._entries.get(model_id)
            if e is None:
                raise KeyError(f"unknown model {model_id!r} "
                               f"(have {sorted(self._entries)})")
            return e

    def engine_for(self, model_id: Optional[str] = None):
        return self.entry(model_id).engine

    # -- lifecycle -------------------------------------------------------

    def start(self) -> "ModelPool":
        if self._thread is not None:
            return self
        self._stop = False
        self._thread = threading.Thread(target=self._dispatch_loop,
                                        name="pool-dispatch", daemon=True)
        self._thread.start()
        return self

    def stop(self, timeout: float = 10.0):
        with self._lock:
            entries = list(self._entries.values())
        for e in entries:
            if e.controller is not None:
                try:
                    e.controller.stop()
                except Exception:
                    pass
            e.engine.stop(timeout=timeout)
        self._stop = True
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None

    def is_ready(self) -> bool:
        with self._lock:
            entries = list(self._entries.values())
        return bool(entries) and all(e.engine.is_ready() for e in entries)

    def readiness(self) -> dict:
        with self._lock:
            entries = [(mid, self._entries[mid]) for mid in self._order]
        per_model = {mid: e.engine.readiness() for mid, e in entries}
        return {"ready": bool(per_model)
                and all(d["ready"] for d in per_model.values()),
                "models": per_model}

    # -- weight residency ------------------------------------------------

    def resident_bytes(self) -> int:
        with self._lock:
            return sum(e.bytes for e in self._entries.values()
                       if e.resident)

    def ensure_resident(self, model_id: str) -> None:
        """Make ``model_id``'s params device-resident, paging out LRU
        non-pinned siblings as needed to respect the byte budget.  Called
        by the dispatcher before every batch; cheap no-op when already
        resident (the steady state)."""
        with self._lock:
            e = self._entries[model_id]
            e.last_use = time.monotonic()
            if e.resident:
                self._evict_over_budget_locked(keep=model_id)
                return
            need = e.bytes
            if self.budget_bytes:
                self._evict_over_budget_locked(keep=model_id, incoming=need)
            self._page_in_locked(e)

    def _evict_over_budget_locked(self, keep: str, incoming: int = 0):
        if not self.budget_bytes:
            return
        resident = sum(e.bytes for e in self._entries.values()
                       if e.resident)
        over = resident + incoming - self.budget_bytes
        if over <= 0:
            return
        victims = sorted(
            (e for e in self._entries.values()
             if e.resident and not e.pinned and e.model_id != keep),
            key=lambda e: e.last_use)
        for v in victims:
            if over <= 0:
                break
            self._page_out_locked(v)
            over -= v.bytes
        if over > 0:
            # pinned + the incoming model alone exceed the budget; serve
            # anyway (refusing would deadlock traffic) but say so loudly
            logger.warning("model pool: weight budget %d bytes exceeded "
                           "by %d bytes even after paging (pinned set too "
                           "large?)", self.budget_bytes, over)

    def _page_out_locked(self, e: ModelEntry):
        import numpy as np

        params = getattr(e.predictor, "params", None)
        if params is None:
            e.resident = False
            return
        try:
            import jax

            host = jax.tree_util.tree_map(
                lambda x: np.array(x, copy=True), jax.device_get(params))
            for leaf in jax.tree_util.tree_leaves(params):
                delete = getattr(leaf, "delete", None)
                if delete is not None:
                    try:
                        delete()
                    except Exception:
                        pass
        except Exception:
            host = params  # duck-typed predictor: host tree already
        # host tree stays bound: an unscheduled dispatch would still be
        # CORRECT (jax transfers arguments), just unaccounted — the
        # dispatcher's ensure_resident keeps the hot path paged in
        e.predictor.params = host
        e.host_params = host
        e.resident = False
        e.page_outs += 1
        self.counters["weight_page_out"] += 1
        telemetry.get().counter("serve/weight_page_out")
        logger.info("model pool: paged OUT %r (%d bytes)", e.model_id,
                    e.bytes)

    def _page_in_locked(self, e: ModelEntry):
        host = e.host_params if e.host_params is not None \
            else getattr(e.predictor, "params", None)
        if host is not None:
            try:
                import jax

                plan = getattr(e.predictor, "plan", None)
                placed = (jax.device_put(host, plan.replicated())
                          if plan is not None else jax.device_put(host))
            except Exception:
                placed = host  # duck-typed predictor
            e.predictor.params = placed
            e.bytes = param_nbytes(placed) or e.bytes
        e.host_params = None
        e.resident = True
        e.page_ins += 1
        self.counters["weight_page_in"] += 1
        telemetry.get().counter("serve/weight_page_in")
        logger.info("model pool: paged IN %r (%d bytes)", e.model_id,
                    e.bytes)

    def residency(self) -> dict:
        """The /metrics residency doc: budget, live device bytes, and a
        per-model gauge block (also mirrored into the telemetry sink as
        ``serve/resident_bytes`` + ``serve/resident/<model>``)."""
        now = time.monotonic()
        with self._lock:
            models = {
                e.model_id: {"resident": int(e.resident),
                             "bytes": e.bytes,
                             "pinned": e.pinned,
                             "weight": e.weight,
                             "page_ins": e.page_ins,
                             "page_outs": e.page_outs,
                             "idle_s": round(now - e.last_use, 3)}
                for e in self._entries.values()}
            device_bytes = sum(e.bytes for e in self._entries.values()
                               if e.resident)
        tel = telemetry.get()
        tel.gauge("serve/resident_bytes", device_bytes)
        for mid, doc in models.items():
            tel.gauge(f"serve/resident/{mid}", doc["resident"])
        return {"budget_bytes": self.budget_bytes,
                "device_bytes": device_bytes,
                "resident_models": sum(d["resident"]
                                       for d in models.values()),
                "models": models}

    def demand_scores(self) -> dict:
        """``{model_id: weight * (queue_depth + 1)}`` — the scheduler's
        own scoring, exported as the autoscaler's placement signal
        (ISSUE 18)."""
        with self._lock:
            entries = list(self._entries.values())
        scores = {}
        for e in entries:
            try:
                depth = e.engine.queue_depth()
            except Exception:  # noqa: BLE001 — engine mid-shutdown
                depth = 0
            scores[e.model_id] = e.weight * (depth + 1)
        return scores

    def rebalance_residency(self) -> List[str]:
        """Runtime placement for the capacity authority: page the
        hottest queued-but-not-resident models in ahead of their next
        dispatch (``ensure_resident`` pages out cold LRU siblings to
        make room).  Paging is a ``device_put`` of a host snapshot —
        params are runtime args, so placement costs zero recompiles.
        Returns the model ids paged in (empty in the steady state, and
        always empty without a byte budget: everything is resident)."""
        with self._lock:
            cold = [(e.model_id, e.weight, e.engine)
                    for e in self._entries.values() if not e.resident]
        hot = []
        for mid, weight, engine in cold:
            try:
                depth = engine.queue_depth()
            except Exception:  # noqa: BLE001 — engine mid-shutdown
                continue
            if depth > 0:
                hot.append((weight * (depth + 1), mid))
        paged = []
        for _, mid in sorted(hot, reverse=True):
            try:
                self.ensure_resident(mid)
                paged.append(mid)
            except KeyError:
                continue  # removed mid-rebalance
        return paged

    # -- cross-model dispatch --------------------------------------------

    def _pick_locked(self, now: float):
        """(entry, wait_s): the due model with the best
        ``weight * (depth + 1)`` score (least-recently-scheduled breaks
        ties), or (None, soonest-deadline) when nothing is due."""
        best = None
        best_score = None
        wait = None
        for mid in self._order:
            e = self._entries[mid]
            due, depth, w = e.engine.due_state(now)
            if due:
                score = (e.weight * (depth + 1), -e.last_sched)
                if best is None or score > best_score:
                    best, best_score = e, score
            elif w is not None:
                wait = w if wait is None else min(wait, w)
        return best, wait

    def _dispatch_loop(self):
        while not self._stop:
            now = time.monotonic()
            with self._lock:
                e, wait = self._pick_locked(now)
            if e is None:
                timeout = self._idle_poll_s if wait is None \
                    else max(min(wait, self._idle_poll_s), 1e-4)
                self._wake.wait(timeout=timeout)
                self._wake.clear()
                continue
            batch, _ = e.engine.poll(now)
            if batch is None:
                # raced with a sweep/policy change; re-judge immediately
                continue
            tracer = tracectx.get()
            t_sched = time.perf_counter() if tracer.enabled else 0.0
            self.ensure_resident(e.model_id)
            with self._lock:
                switched = self._last_model not in (None, e.model_id)
                if switched:
                    self.counters["sched_switches"] += 1
                self._last_model = e.model_id
                e.last_sched = now
                e.batches += 1
                self.counters["sched_batches"] += 1
            if tracer.enabled:
                # pool/sched span per traced request in the claimed
                # batch: which model the interleaver picked, whether the
                # pick switched programs, and what residency paging cost
                # the batch paid before its dispatch
                sched_s = time.perf_counter() - t_sched
                for r in batch:
                    ctx = r.trace
                    if ctx is not None and ctx.sampled:
                        tracer.record(ctx, "pool/sched", sched_s,
                                      attrs={"model": e.model_id,
                                             "switched": switched,
                                             "batch": len(batch)})
            e.engine.dispatch_batch(batch)

    # -- introspection ---------------------------------------------------

    def metrics(self) -> dict:
        """The pool-mode ``/metrics`` payload.  Top-level ``counters``
        aggregates every model's engine counters (so single-model
        clients — loadgen's server-counter deltas — keep working), with
        the full per-model picture under ``models`` and the pool's own
        scheduling + residency state alongside."""
        with self._lock:
            order = list(self._order)
            pool_counters = dict(self.counters)
            batches = {mid: self._entries[mid].batches for mid in order}
        models = {mid: self.engine_for(mid).metrics() for mid in order}
        agg: Dict[str, float] = {}
        for doc in models.values():
            for k, v in (doc.get("counters") or {}).items():
                if isinstance(v, (int, float)):
                    agg[k] = agg.get(k, 0) + v
        out = {"multimodel": True,
               "default_model": order[0] if order else None,
               "models": models,
               "counters": agg,
               "queue_depth": sum(d.get("queue_depth", 0)
                                  for d in models.values()),
               "ready": bool(models) and all(d.get("ready")
                                             for d in models.values()),
               "pool": {"counters": pool_counters,
                        "batches": batches,
                        "last_model": self._last_model},
               "residency": self.residency()}
        if self.cascade is not None:
            out["cascade"] = self.cascade.metrics()
        return out


# ---------------------------------------------------------------------------
# Cascade serving (ISSUE 19): cheap model first, escalate the hard frames.


class CascadeFuture:
    """Completion handle for one cascade-routed request.

    Duck-compatible with :class:`~mx_rcnn_tpu.serve.engine.ServeFuture`
    (``result`` / ``done`` / ``queue_wait_s`` / ``_error``) so the
    frontend and the stream layer can hold either.  In ``gate`` mode the
    escalation decision is taken exactly once, on the first ``result``
    call, from the hardness the on-device gate stamped on the small
    model's future — concurrent resolvers agree on one decision and one
    escalated submit.
    """

    __slots__ = ("_router", "_fut", "_mode", "_model", "_reason",
                 "_deadline_ms", "_lock", "_decided", "_escalated",
                 "_big_fut", "_counted_big")

    def __init__(self, router, fut, mode, model, reason=None,
                 deadline_ms=None):
        self._router = router
        self._fut = fut              # small fut (gate) or the final fut
        self._mode = mode            # "gate" | "direct"
        self._model = model
        self._reason = reason
        self._deadline_ms = deadline_ms
        self._lock = threading.Lock()
        self._decided = False
        self._escalated = False
        self._big_fut = None
        self._counted_big = False

    def done(self) -> bool:
        with self._lock:
            big = self._big_fut
            decided = self._decided
        if big is not None:
            return big.done()
        if self._mode == "direct" or decided:
            return self._fut.done()
        return False  # gate verdict pending — result() takes it

    @property
    def _error(self):
        with self._lock:
            big = self._big_fut
        src = big if big is not None else self._fut
        return getattr(src, "_error", None)

    @property
    def queue_wait_s(self):
        """Total queue residence the client paid: the small model's wait
        plus, for escalated frames, the big model's."""
        total = self._fut.queue_wait_s
        with self._lock:
            big = self._big_fut
        if big is not None and big.queue_wait_s is not None:
            total = (total or 0.0) + big.queue_wait_s
        return total

    def result(self, timeout=None):
        if self._mode == "direct":
            return self._fut.result(timeout)
        records = self._fut.result(timeout)
        req = None
        with self._lock:
            if not self._decided:
                self._decided = True
                h = self._fut.hardness
                req = self._fut.request
                if (h is not None and req is not None
                        and self._router.should_escalate(h)):
                    try:
                        self._big_fut = self._router._escalate(
                            req, deadline_ms=self._deadline_ms)
                        self._escalated = True
                    except Exception:
                        # big model refused (queue full, draining):
                        # degrade gracefully to the small answer instead
                        # of turning a served request into a 503
                        self._router._note_escalation_rejected()
                if not self._escalated:
                    self._router._note_answered_small()
            big = self._big_fut
        if big is None:
            return records
        out = big.result(timeout)
        with self._lock:
            first = not self._counted_big
            self._counted_big = True
            req = self._fut.request
        if first and req is not None:
            self._router._note_escalated_result(req, out)
        return out

    def provenance(self) -> dict:
        """The ``cascade`` response field: which model answered and why."""
        if self._mode == "direct":
            doc = {"model": self._model, "escalated": False}
            if self._reason:
                doc["reason"] = self._reason
            return doc
        with self._lock:
            esc = self._escalated
        doc = {"model": self._router.big if esc else self._router.small,
               "escalated": esc, "thresh": self._router.thresh}
        h = self._fut.hardness
        if h is not None:
            doc["hardness"] = round(float(h), 4)
        return doc


class CascadeRouter:
    """Accuracy-aware request router over a (small, big) model pair.

    Every gated request first hits the SMALL model; the on-device
    confidence gate — the registry program ``kind="cascade_gate"``,
    AOT-markered and warm-boot loadable exactly like the stream layer's
    ``frame_delta`` — folds the small model's still-on-device
    ``(B, cap, 6)`` detections into per-image hardness (the shared
    ``flywheel/hardness.py`` definition, so serving and mining can never
    drift) and stamps it on each request's future before readback: zero
    extra h2d transfers.  Frames whose hardness clears
    ``thresh * HARDNESS_MAX`` re-submit to the BIG model through
    :meth:`~mx_rcnn_tpu.serve.engine.ServeEngine.submit_staged` — the
    staged uint8 buffer is reused byte-for-byte, never re-staged — and
    ride the ordinary pool scheduler.  Escalated frames also feed the
    flywheel capture ring tagged ``cascade_escalated`` with the big
    model's records: serving traffic mines exactly the examples the
    small model needs.

    Routing per tenant (the addressed model id): the small/default
    entry gates; the big entry is served directly ("addressed"); an
    entry with ``fidelity="full"`` pins to the big model ("fidelity" —
    the per-SLO-class escape hatch); any other pool sibling bypasses
    the cascade untouched.
    """

    KIND = "cascade_gate"

    def __init__(self, pool: ModelPool, small: str, big: str,
                 thresh: float = 0.5):
        if small == big:
            raise ValueError("--cascade needs two DISTINCT models, got "
                             f"{small!r} twice")
        if not 0.0 <= float(thresh) <= 1.0:
            raise ValueError(f"cascade thresh must be in [0, 1], got "
                             f"{thresh}")
        from mx_rcnn_tpu.flywheel.hardness import (HARDNESS_MAX,
                                                   build_device_hardness)

        self.pool = pool
        self.small = small
        self.big = big
        self.thresh = float(thresh)
        self._thresh_raw = self.thresh * HARDNESS_MAX
        self.small_entry = pool.entry(small)   # KeyError = unknown model
        self.big_entry = pool.entry(big)
        se, be = self.small_entry.engine, self.big_entry.engine
        for eng, mid in ((se, small), (be, big)):
            if not eng.opts.serve_e2e:
                raise ValueError(
                    f"--cascade requires --serve-e2e on every cascade "
                    f"model (the gate consumes the fused program's "
                    f"on-device detections); model {mid!r} is not e2e")
        # escalation reuses the small model's staged buffers, so both
        # engines must agree on bucket geometry for every orientation
        for h, w in ((100, 200), (200, 100)):
            if se.bucket_key(h, w) != be.bucket_key(h, w):
                raise ValueError(
                    f"cascade models disagree on bucket geometry "
                    f"({small}: {se.bucket_key(h, w)} vs {big}: "
                    f"{be.bucket_key(h, w)} for a {h}x{w} image) — "
                    f"escalation cannot reuse staged pixels; align "
                    f"SCALES and strides")
        self._lock = threading.Lock()
        self.counters = {"answered_small": 0, "escalated": 0,
                         "forced_big": 0, "gate_batches": 0,
                         "escalation_rejected": 0}
        self.hists = {"cascade/gate_time": Hist(),
                      "cascade/hardness": Hist()}
        # registry citizenship: the gate program registers on the SMALL
        # model's registry (it consumes that model's detections), giving
        # it AOT markers + warm-boot accounting like any other program
        self._registry = getattr(se, "registry", None)
        if self._registry is not None:
            self._registry.register(self.KIND,
                                    lambda: build_device_hardness())
            self._fn = self._registry.lookup(self.KIND)
        else:
            self._fn = build_device_hardness()
        # escalated frames feed the pool's capture ring (the sink hangs
        # off the default/small engine; NULL sink when capture is off)
        self.capture = se.capture
        se.cascade = self

    # -- the on-device gate ---------------------------------------------

    def _dispatch_gate(self, dets, dvalid):
        """Run the gate program on the still-on-device detection tensors;
        returns (hardness ndarray, wall seconds).  First-dispatch
        accounting goes through the registry like every other program."""
        import numpy as np

        reg = self._registry
        shape = tuple(dets.shape)
        first = reg.note_dispatch(self.KIND, shape) \
            if reg is not None else False
        t0 = time.perf_counter()
        hard = np.asarray(self._fn(dets, dvalid))  # (B,) readback
        dt = time.perf_counter() - t0
        if first and reg is not None:
            reg.record_compile_seconds(self.KIND, shape, dt)
        return hard, dt

    def gate_batch(self, dets, dvalid, reqs) -> None:
        """Engine hook (small model's ``_forward_e2e``): stamp per-image
        hardness + a request backlink on each future, observe gate cost,
        and emit the PR-16 trace span carrying the gate verdict."""
        hard, dt = self._dispatch_gate(dets, dvalid)
        tel = telemetry.get()
        self.hists["cascade/gate_time"].observe(dt)
        tel.observe("cascade/gate_time", dt)
        with self._lock:
            self.counters["gate_batches"] += 1
        tel.counter("cascade/gate_batches")
        tracer = tracectx.get()
        for b, r in enumerate(reqs):
            h = float(hard[b])
            r.future.hardness = h
            r.future.request = r
            self.hists["cascade/hardness"].observe(h)
            ctx = r.trace
            if tracer.enabled and ctx is not None and ctx.sampled:
                tracer.record(ctx, "cascade/gate", dt,
                              attrs={"hardness": round(h, 4),
                                     "escalate": bool(
                                         self.should_escalate(h)),
                                     "thresh": self.thresh,
                                     "small": self.small,
                                     "big": self.big})

    def should_escalate(self, hardness: float) -> bool:
        """thresh 0 escalates everything (>= comparison), 1 nothing
        (the bound is unreachable) — the threshold-sweep contract."""
        return hardness >= self._thresh_raw

    def warmup(self) -> int:
        """Compile the gate program before traffic (and before
        ``mark_ready``): one dispatch on a zeros detection tensor of the
        steady-state shape — identical for both orientation buckets, so
        one program covers them.  Returns new registry programs (0 on a
        warm boot where only the AOT marker is re-probed... the program
        still counts once per process; callers compare aot_hit)."""
        import jax
        import numpy as np

        eng = self.small_entry.engine
        B = eng.opts.batch_size
        mpi = int(self.small_entry.cfg.TEST.MAX_PER_IMAGE)
        before = self._registry.counters["programs"] \
            if self._registry is not None else 0
        dets = jax.device_put(np.zeros((B, mpi, 6), np.float32))
        dvalid = jax.device_put(np.zeros((B, mpi), bool))
        self._dispatch_gate(dets, dvalid)
        after = self._registry.counters["programs"] \
            if self._registry is not None else before
        return after - before

    # -- routing ---------------------------------------------------------

    def submit(self, image, deadline_ms=None, stream=None, trace=None,
               model_id=None) -> CascadeFuture:
        """Route one request.  Raises ``KeyError`` for an unknown model
        id (the frontend's 404) and the engine's admission errors."""
        entry = self.pool.entry(model_id)
        mid = entry.model_id
        tel = telemetry.get()
        if mid == self.big:
            fut = entry.engine.submit(image, deadline_ms=deadline_ms,
                                      stream=stream, trace=trace)
            return CascadeFuture(self, fut, "direct", mid,
                                 reason="addressed")
        if entry.fidelity == "full":
            with self._lock:
                self.counters["forced_big"] += 1
            tel.counter("cascade/forced_big")
            fut = self.big_entry.engine.submit(
                image, deadline_ms=deadline_ms, stream=stream, trace=trace)
            return CascadeFuture(self, fut, "direct", self.big,
                                 reason="fidelity")
        if mid != self.small:
            # a pool sibling outside the cascade pair: untouched
            fut = entry.engine.submit(image, deadline_ms=deadline_ms,
                                      stream=stream, trace=trace)
            return CascadeFuture(self, fut, "direct", mid, reason="bypass")
        fut = entry.engine.submit(image, deadline_ms=deadline_ms,
                                  stream=stream, trace=trace)
        return CascadeFuture(self, fut, "gate", mid,
                             deadline_ms=deadline_ms)

    # -- decision bookkeeping (called by CascadeFuture, once each) -------

    def _escalate(self, req, deadline_ms=None):
        fut = self.big_entry.engine.submit_staged(
            req.image, req.raw_hw, req.ratio, req.im_info, req.orig_hw,
            deadline_ms=deadline_ms, stream=req.stream, trace=req.trace)
        tel = telemetry.get()
        with self._lock:
            self.counters["escalated"] += 1
            rate = self._rate_locked()
        tel.counter("cascade/escalated")
        tel.gauge("cascade/escalation_rate", rate)
        return fut

    def _note_answered_small(self):
        tel = telemetry.get()
        with self._lock:
            self.counters["answered_small"] += 1
            rate = self._rate_locked()
        tel.counter("cascade/answered_small")
        tel.gauge("cascade/escalation_rate", rate)

    def _note_escalation_rejected(self):
        with self._lock:
            self.counters["escalation_rejected"] += 1
        telemetry.get().counter("cascade/escalation_rejected")

    def _note_escalated_result(self, req, records):
        """Big model answered an escalated frame: feed the capture ring,
        tagged, with the BIG model's records as the pseudo-labels — the
        small model's miss becomes its next training example."""
        cap = self.capture
        if cap is None or not cap.enabled:
            return
        trace_id = req.trace.trace_id if req.trace is not None else None
        cap.record_batch(
            [(req.image, req.raw_hw, req.orig_hw, records, trace_id,
              {"tags": ["cascade_escalated"]})],
            self.big_entry.engine.generation)

    def _rate_locked(self) -> float:
        dec = self.counters["answered_small"] + self.counters["escalated"]
        return self.counters["escalated"] / max(1, dec)

    def escalation_rate(self) -> float:
        with self._lock:
            return self._rate_locked()

    # -- introspection ---------------------------------------------------

    def metrics(self) -> dict:
        with self._lock:
            counters = dict(self.counters)
            rate = self._rate_locked()
        out = {"small": self.small, "big": self.big,
               "thresh": self.thresh,
               "counters": counters,
               "escalation_rate": round(rate, 4)}
        stats = {}
        for q, tag in ((0.5, "p50"), (0.99, "p99")):
            v = self.hists["cascade/gate_time"].quantile(q)
            if v is not None:
                stats[f"gate_time_{tag}_ms"] = round(v * 1e3, 3)
            h = self.hists["cascade/hardness"].quantile(q)
            if h is not None:
                stats[f"hardness_{tag}"] = round(h, 4)
        out["latency"] = stats
        return out
