"""Startup warmup: eagerly compile every (bucket, batch) program.

XLA compiles the forward on first dispatch of each input shape — tens of
seconds for the real backbones.  Without warmup the first user request of
each orientation pays that compile inside its latency budget (and usually
blows its deadline).  Warmup pushes one full batch of dummy pixels per
bucket through the REAL engine path — same queue, same padding, same
post-process — so every program the steady state can dispatch is compiled
before the frontend accepts traffic, and the engine's recompile counter
(the trainer's shape-keyed bookkeeping) proves it: after warmup,
``counters["recompiles"] == counters["warmup_programs"]`` must hold for
the life of the process (asserted by ``script/serve_smoke.sh`` and
``tests/test_serve.py``).
"""

from __future__ import annotations

import time

import numpy as np

from mx_rcnn_tpu import telemetry
from mx_rcnn_tpu.logger import logger


def warmup(engine) -> int:
    """Compile every (bucket, batch) program through a STARTED engine.

    Submits ``batch_size`` dummy images per orientation (full batches →
    immediate flush, no delay wait) and blocks until served.  Returns the
    number of programs compiled; stamps it into
    ``engine.counters["warmup_programs"]`` and the ``serve/warmup_programs``
    telemetry counter."""
    assert engine._thread is not None, "start() the engine before warmup"
    short, long_ = engine._scale
    t0 = time.perf_counter()
    before = engine.counters["recompiles"]
    for h, w in ((short, long_), (long_, short)):  # landscape, portrait
        dummy = np.zeros((h, w, 3), np.uint8)
        futs = [engine.submit(dummy, deadline_ms=0)  # never expire
                for _ in range(engine.opts.batch_size)]
        for f in futs:
            f.result(timeout=600.0)
    compiled = engine.counters["recompiles"] - before
    engine.counters["warmup_programs"] += compiled
    telemetry.get().counter("serve/warmup_programs", compiled)
    logger.info("serve warmup: %d program(s) compiled in %.1fs "
                "(batch=%d, scale=%s)", compiled,
                time.perf_counter() - t0, engine.opts.batch_size,
                engine._scale)
    return compiled
