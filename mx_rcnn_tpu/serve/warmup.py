"""Startup warmup: ensure every (bucket, batch) program is registered
and ready — compiled from disk (AOT warm start) or from XLA (cold).

XLA compiles the forward on first dispatch of each input shape — tens of
seconds for the real backbones.  Without warmup the first user request of
each orientation pays that compile inside its latency budget (and usually
blows its deadline).  Warmup pushes one full batch of dummy pixels per
bucket through the REAL engine path — same queue, same padding, same
post-process — so every program the steady state can dispatch is ready
before the frontend accepts traffic, and the engine's recompile counter
(the program registry's first-dispatch bookkeeping) proves it: after
warmup, ``counters["recompiles"] == counters["warmup_programs"]`` must
hold for the life of the process (asserted by ``script/serve_smoke.sh``
and ``tests/test_serve.py``).

With a persistent program cache (``MXR_PROGRAM_CACHE``), warmup is where
the AOT win lands: a second boot over a warm cache dir reports
``compile/aot_hit == warmup_programs`` and zero ``aot_miss`` — every
"compile" is a disk load, and the logged warmup wall time collapses
(asserted by ``script/aot_smoke.sh`` and ``tests/test_warmstart.py``).
"""

from __future__ import annotations

import time

import numpy as np

from mx_rcnn_tpu import telemetry
from mx_rcnn_tpu.logger import logger


def warmup(engine) -> int:
    """Register + ready every (bucket, batch) program through a STARTED
    engine.

    Submits ``batch_size`` dummy images per orientation (full batches →
    immediate flush, no delay wait) and blocks until served.  Returns the
    number of programs first-dispatched (each either an XLA compile or a
    persistent-cache load); stamps it into
    ``engine.counters["warmup_programs"]`` and the
    ``serve/warmup_programs`` telemetry counter, the warmup wall time
    into the ``serve/warmup_compile_s`` gauge, and — when the engine's
    predictor carries a :class:`~mx_rcnn_tpu.compile.ProgramRegistry` —
    logs the AOT hit/miss split for the warmed programs."""
    # pool-mode engines have no thread of their own: the ModelPool
    # dispatcher flushes them, so warmup only needs SOME dispatcher live
    assert engine._thread is not None or engine._external, \
        "start() the engine before warmup"
    short, long_ = engine._scale
    t0 = time.perf_counter()
    reg = getattr(engine, "registry", None)
    before = engine.counters["recompiles"]
    aot_before = (dict(reg.counters) if reg is not None else {})
    for h, w in ((short, long_), (long_, short)):  # landscape, portrait
        dummy = np.zeros((h, w, 3), np.uint8)
        futs = [engine.submit(dummy, deadline_ms=0)  # never expire
                for _ in range(engine.opts.batch_size)]
        for f in futs:
            f.result(timeout=600.0)
    dt = time.perf_counter() - t0
    compiled = engine.counters["recompiles"] - before
    engine.counters["warmup_programs"] += compiled
    # warmup completion IS readiness: /readyz flips to 200 here, so a
    # supervisor never routes traffic into a replica still compiling
    engine.mark_ready()
    tel = telemetry.get()
    tel.counter("serve/warmup_programs", compiled)
    tel.gauge("serve/warmup_compile_s", dt)
    if reg is not None:
        hits = reg.counters["aot_hit"] - aot_before.get("aot_hit", 0)
        misses = reg.counters["aot_miss"] - aot_before.get("aot_miss", 0)
        logger.info("serve warmup: %d program(s) ready in %.1fs — "
                    "%d AOT cache hit(s), %d compile(s) (batch=%d, "
                    "scale=%s, dtype=%s)", compiled, dt, hits, misses,
                    engine.opts.batch_size, engine._scale,
                    getattr(engine, "_dtype", "float32"))
    else:
        logger.info("serve warmup: %d program(s) compiled in %.1fs "
                    "(batch=%d, scale=%s)", compiled, dt,
                    engine.opts.batch_size, engine._scale)
    return compiled
