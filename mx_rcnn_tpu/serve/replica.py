"""Replica-side half of the multi-replica serving plane (ISSUE 8).

One replica = one supervised subprocess (``serve.py --replica-index I``)
pinned to a device or device group, running the ordinary
Predictor → ServeEngine → HTTP stack over a Unix socket the router
forwards to.  This module owns everything that happens INSIDE the
replica process:

* :func:`serve_replica` — the child's main loop: HTTP up FIRST (so
  liveness probes answer during a slow warmup), then warmup → ready,
  then park until SIGTERM.
* :func:`reload_engine_params` — the zero-downtime weight swap:
  drain → load → :meth:`Predictor.update_params` → canary probe →
  re-ready, with rollback to the previous weights when the new
  generation produces non-finite outputs on a golden image.  Because
  params are a RUNTIME argument to every registered program (PR-7
  registry), the swap reuses all compiled executables — zero
  steady-state recompiles, asserted by tests and the smoke script.
* :func:`scan_checkpoints` / :class:`CheckpointWatcher` — filesystem
  polling of the PR-2 checkpoint layout (``{prefix}/{epoch}`` +
  ``{prefix}/steps/{key}``), feeding reload targets to whoever rolls
  them (the supervisor across replicas, or the in-process path at
  ``--replicas 1``).
* :class:`ReplicaFaults` — the serve-side chaos harness: behavior is
  driven by ``MXR_FAULT_REPLICA_*`` env vars (the resilience.py
  ``MXR_FAULT_*`` precedent) so tests and ``script/replica_smoke.sh``
  inject kill -9 / hang / slow-start / corrupt-checkpoint without
  touching the code path under test.

Fault-injection env contract (each var is a comma-separated list of
``INDEX[:VALUE]`` tokens; a token applies to the replica whose
``--replica-index`` matches):

* ``MXR_FAULT_REPLICA_KILL_AFTER="0:5"``   — SIGKILL self (kill -9
  semantics) after 5 served 2xx requests.
* ``MXR_FAULT_REPLICA_HANG_AFTER="1:3"``   — wedge every subsequent
  HTTP handler (including probes) after 3 served requests: the
  crash-undetectable-by-waitpid case the supervisor's probe-timeout
  hang detection exists for.
* ``MXR_FAULT_REPLICA_SLOW_START_S="0:8"`` — sleep 8s between liveness
  and readiness (alive-but-warming), exercising the /healthz vs
  /readyz split.
* ``MXR_FAULT_REPLICA_CORRUPT_CKPT="0"``   — poison every float leaf of
  the next reloaded checkpoint with NaN, forcing the canary probe to
  reject the generation and roll back.

Network fault points (ISSUE 12) — same token grammar, applied at the
transport layer by :class:`NetFaults` so the fabric's chaos suite can
stage partitions, connection resets, and tail latency against real
sockets without touching the code under test:

* ``MXR_FAULT_NET_DROP="1:4"``      — after 4 ``/predict`` requests the
  member goes dark: EVERY handler (probes included) blackholes.  The
  router sees pure probe timeouts — a network partition, not a crash.
* ``MXR_FAULT_NET_RESET="0:3-6"``   — ``/predict`` requests number 3..6
  (1-based, inclusive; ``"0:3"`` means 3 onward forever) have their
  connections reset (RST) mid-handshake while probes stay healthy: the
  data-path-broken/control-path-fine case circuit breakers exist for.
  A bounded range lets the member RECOVER, closing the breaker.
* ``MXR_FAULT_NET_DELAY_MS="2:250"`` — every ``/predict`` response is
  delayed 250 ms: the slow-member tail that request hedging answers.
"""

from __future__ import annotations

import os
import signal
import threading
import time
from typing import Callable, Optional

import numpy as np

from mx_rcnn_tpu import telemetry
from mx_rcnn_tpu.telemetry import tracectx
from mx_rcnn_tpu.data.loader import prepare_image
from mx_rcnn_tpu.logger import logger
from mx_rcnn_tpu.serve.frontend import make_server
from mx_rcnn_tpu.serve.warmup import warmup
from mx_rcnn_tpu.train.resilience import decode_step_key

ENV_KILL_AFTER = "MXR_FAULT_REPLICA_KILL_AFTER"
ENV_HANG_AFTER = "MXR_FAULT_REPLICA_HANG_AFTER"
ENV_SLOW_START = "MXR_FAULT_REPLICA_SLOW_START_S"
ENV_CORRUPT_CKPT = "MXR_FAULT_REPLICA_CORRUPT_CKPT"
ENV_NET_DROP = "MXR_FAULT_NET_DROP"
ENV_NET_RESET = "MXR_FAULT_NET_RESET"
ENV_NET_DELAY = "MXR_FAULT_NET_DELAY_MS"
# set by the supervisor on each child; the injectors match against it
ENV_REPLICA_INDEX = "MXR_REPLICA_INDEX"
# optional device pinning: the supervisor splits --replica-devices into
# per-child groups under this var; deployment images map it onto their
# platform's visibility env (TPU_VISIBLE_CHIPS / CUDA_VISIBLE_DEVICES)
ENV_REPLICA_DEVICES = "MXR_REPLICA_DEVICES"

# how long a drain may take before the reload aborts (the queue keeps
# flushing during drain, so this only trips on a wedged dispatcher)
RELOAD_DRAIN_TIMEOUT_S = 60.0


def _fault_value(env_name: str, index: int,
                 env=os.environ) -> Optional[str]:
    """The VALUE of the ``INDEX[:VALUE]`` token matching ``index`` in
    ``env_name`` ("" for a bare-INDEX token), or None."""
    for tok in env.get(env_name, "").split(","):
        tok = tok.strip()
        if not tok:
            continue
        idx, _, value = tok.partition(":")
        try:
            if int(idx) == index:
                return value
        except ValueError:
            logger.warning("bad %s token %r (want INDEX[:VALUE])",
                           env_name, tok)
    return None


class ReplicaFaults:
    """Parsed ``MXR_FAULT_REPLICA_*`` state for one replica index, wired
    into the frontend's ``request_hook``/``gate`` and the reload path.
    With no matching env tokens every method is a cheap no-op."""

    def __init__(self, index: int, env=os.environ):
        self.index = index

        def _num(name, cast):
            v = _fault_value(name, index, env)
            return None if v in (None, "") else cast(v)

        self.kill_after = _num(ENV_KILL_AFTER, int)
        self.hang_after = _num(ENV_HANG_AFTER, int)
        self.slow_start_s = _num(ENV_SLOW_START, float) or 0.0
        self.corrupt_ckpt = _fault_value(ENV_CORRUPT_CKPT, index,
                                         env) is not None
        self._served = 0
        self._hung = False
        self._lock = threading.Lock()

    def request_hook(self, status: int):
        """After each /predict reply: count 2xx and fire kill/hang once
        the configured count is reached."""
        with self._lock:
            if 200 <= status < 300:
                self._served += 1
            served = self._served
        if self.kill_after is not None and served >= self.kill_after:
            logger.warning("FAULT replica %d: SIGKILL self after %d "
                           "served requests", self.index, served)
            os.kill(os.getpid(), signal.SIGKILL)
        if self.hang_after is not None and served >= self.hang_after:
            self._hung = True

    def gate(self):
        """Before any HTTP handling: a hung replica wedges every handler
        thread — probes included — which is exactly what the supervisor's
        probe-timeout detection must catch (waitpid never fires)."""
        if self._hung:
            logger.warning("FAULT replica %d: hanging handler thread",
                           self.index)
            time.sleep(3600.0)

    def slow_start(self):
        if self.slow_start_s > 0:
            logger.warning("FAULT replica %d: slow start %.1fs (alive, "
                           "not ready)", self.index, self.slow_start_s)
            time.sleep(self.slow_start_s)


class NetFaults:
    """Parsed ``MXR_FAULT_NET_*`` state for one member index, wired into
    the frontend as ``net_faults`` (``intercept(path, handler)`` runs
    before any handling).  With no matching tokens, ``enabled`` is False
    and the frontend never calls in — zero cost on the clean path."""

    def __init__(self, index: int, env=os.environ):
        self.index = index

        def _num(name, cast):
            v = _fault_value(name, index, env)
            return None if v is None else cast(v) if v != "" else 0
        self.drop_after = _num(ENV_NET_DROP, int)
        self.delay_ms = _num(ENV_NET_DELAY, float) or 0.0
        self.reset_from = None
        self.reset_to = None
        reset = _fault_value(ENV_NET_RESET, index, env)
        if reset:
            lo, _, hi = reset.partition("-")
            self.reset_from = int(lo)
            self.reset_to = int(hi) if hi else None
        self._predicts = 0
        self._lock = threading.Lock()

    @property
    def enabled(self) -> bool:
        return (self.drop_after is not None or self.delay_ms > 0
                or self.reset_from is not None)

    def intercept(self, path: str, handler) -> bool:
        """True = the request was consumed by a fault (blackholed or
        reset); False = continue normal handling (possibly delayed)."""
        p = path.partition("?")[0]
        with self._lock:
            if p == "/predict":
                self._predicts += 1
            n = self._predicts
        if self.drop_after is not None and n > self.drop_after:
            # partition: the member is alive but unreachable — every
            # path (probes included) blackholes, so the router sees
            # probe timeouts, not errors
            logger.warning("FAULT net %d: blackholing %s (partition)",
                           self.index, p)
            time.sleep(3600.0)
            return True
        if p != "/predict":
            return False
        if (self.reset_from is not None and n >= self.reset_from
                and (self.reset_to is None or n <= self.reset_to)):
            logger.warning("FAULT net %d: resetting /predict #%d",
                           self.index, n)
            self._reset_connection(handler)
            return True
        if self.delay_ms > 0:
            time.sleep(self.delay_ms / 1e3)
        return False

    @staticmethod
    def _reset_connection(handler):
        """Abort the TCP connection with an RST (SO_LINGER 0) so the
        client sees ConnectionResetError — a broken data path, not a
        clean HTTP error."""
        import socket
        import struct
        handler.close_connection = True
        try:
            handler.connection.setsockopt(
                socket.SOL_SOCKET, socket.SO_LINGER,
                struct.pack("ii", 1, 0))
        except OSError:
            pass
        try:
            handler.connection.close()
        except OSError:
            pass


def poison_params(params):
    """The corrupt-checkpoint injection: NaN every float leaf (dict
    pytrees and bare numbers), leaving structure intact so the swap
    itself succeeds and only the CANARY catches it — the realistic
    bad-weights failure (half-written file, diverged training run)."""
    if isinstance(params, dict):
        return {k: poison_params(v) for k, v in params.items()}
    arr = np.asarray(params)
    if np.issubdtype(arr.dtype, np.floating):
        return np.full_like(arr, np.nan)
    return params


# -- checkpoint discovery (PR-2 layout, no orbax import) -------------------

def _committed_dir(path: str) -> bool:
    """True when an int-named checkpoint dir holds at least one
    committed (non-tmp) entry.  A trainer killed mid-save can leave the
    dir itself behind empty, or holding only ``*tmp*`` payload still
    being staged — selecting either would hand the watcher a target
    whose load fails and lands on the bad list, burning the generation.
    The dir vanishing between listdir and this check (concurrent
    cleanup) is just not-committed."""
    try:
        names = os.listdir(path)
    except OSError:
        return False
    return any("tmp" not in n for n in names)


def scan_checkpoints(prefix: str) -> Optional[dict]:
    """Newest committed checkpoint under ``prefix`` as a reload target
    ``{"prefix", "kind", "epoch", "consumed"}`` — epoch dirs
    ``{prefix}/{E}`` and step dirs ``{prefix}/steps/{E*1e7+C}``, the
    furthest position winning exactly like ``latest_resume_point`` (a
    finished epoch beats its own mid-epoch saves).  Pure listdir — orbax
    commits by atomic rename, so an int-named dir is a committed save
    and in-progress ``*.orbax-checkpoint-tmp*`` names never int-parse;
    :func:`_committed_dir` additionally skips the husk a trainer killed
    mid-save leaves behind (empty or tmp-only int dir)."""
    if not os.path.isdir(prefix):
        return None
    cands = []
    for name in os.listdir(prefix):
        try:
            e = int(name)
        except ValueError:
            continue
        p = os.path.join(prefix, name)
        if os.path.isdir(p) and _committed_dir(p):
            cands.append((e, 0, "epoch"))
    steps_dir = os.path.join(prefix, "steps")
    if os.path.isdir(steps_dir):
        for name in os.listdir(steps_dir):
            try:
                key = int(name)
            except ValueError:
                continue
            p = os.path.join(steps_dir, name)
            if os.path.isdir(p) and _committed_dir(p):
                e, c = decode_step_key(key)
                cands.append((e, c, "step"))
    if not cands:
        return None
    e, c, kind = max(cands)
    return {"prefix": prefix, "kind": kind, "epoch": e, "consumed": c}


def target_key(target: dict) -> tuple:
    """Identity of a reload target for dedup/bad-list bookkeeping."""
    return (target["epoch"], target["consumed"], target["kind"])


def load_serving_params(target: dict, cfg):
    """Load a reload target's params DENORMALIZED for inference: epoch
    checkpoints via ``load_epoch(for_training=False)``; step checkpoints
    hold the RAW training parametrization, so the live-training-tracking
    path must apply ``denormalize_for_save`` itself or served boxes
    would decode against folded bbox stats."""
    from mx_rcnn_tpu.train.checkpoint import (CheckpointManager,
                                              denormalize_for_save)

    mgr = CheckpointManager(target["prefix"])
    if target["kind"] == "step":
        payload = mgr.load_step_checkpoint(target["epoch"],
                                           target["consumed"])
        return denormalize_for_save(payload["params"], cfg)
    params, _, _ = mgr.load_epoch(target["epoch"], cfg, for_training=False)
    return params


class CheckpointWatcher:
    """Polls a checkpoint prefix and fires ``reload_fn(target)`` when a
    NEWER generation appears.  Failed targets (load error, canary
    rejection) go on a bad list and are never retried — a corrupt save
    must not flap the plane; the next good save supersedes it.
    ``poll_once`` is the injectable-clock-style test surface; ``start``
    wraps it in a daemon thread for production."""

    def __init__(self, prefix: str, reload_fn: Callable[[dict], bool],
                 interval_s: float = 5.0, scan_fn=None):
        self.prefix = prefix
        self.reload_fn = reload_fn
        self.interval_s = interval_s
        self._scan = scan_fn or scan_checkpoints
        self._last: Optional[tuple] = None
        self._bad: set = set()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def prime(self):
        """Mark whatever is on disk NOW as already-served (the weights
        the replicas booted from) so the first poll doesn't redundantly
        reload the boot checkpoint onto itself."""
        tgt = self._scan(self.prefix)
        if tgt is not None:
            self._last = target_key(tgt)
        return tgt

    def poll_once(self):
        """One scan→maybe-reload step.  Returns None when nothing new,
        else ``(target, ok)``."""
        tgt = self._scan(self.prefix)
        if tgt is None:
            return None
        key = target_key(tgt)
        if key == self._last or key in self._bad:
            return None
        if self._last is not None and key < self._last:
            return None  # never roll BACKWARD off a stale dir listing
        logger.info("checkpoint watcher: new generation %s under %s",
                    key, self.prefix)
        ok = bool(self.reload_fn(tgt))
        if ok:
            self._last = key
        else:
            self._bad.add(key)
            telemetry.get().counter("replica/reload_bad_target")
            logger.warning("checkpoint watcher: target %s rejected — "
                           "skipping it until a newer save appears", key)
        return tgt, ok

    def start(self) -> "CheckpointWatcher":
        assert self._thread is None, "watcher already started"
        self.prime()

        def loop():
            while not self._stop.wait(self.interval_s):
                try:
                    self.poll_once()
                except Exception:  # noqa: BLE001 — keep watching
                    logger.exception("checkpoint watcher poll failed")

        self._thread = threading.Thread(target=loop, name="ckpt-watcher",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None


# -- the hot swap ----------------------------------------------------------

def golden_image(h: int, w: int) -> np.ndarray:
    """Deterministic canary input: a horizontal gradient (not zeros —
    constant inputs can hide scale-dependent blowups)."""
    row = np.linspace(32, 224, w).astype(np.uint8)
    return np.ascontiguousarray(
        np.broadcast_to(row[None, :, None], (h, w, 3)))


def canary_probe(engine, predictor) -> tuple:
    """Forward a golden batch at the WARMED landscape bucket shape and
    check every float output is finite — the cheap, recompile-free
    weights-sanity gate a new generation must pass before it serves.
    Probes the SAME program the engine dispatches — the fused
    ``serve_e2e`` program when the engine runs single-dispatch mode, the
    legacy forward otherwise — so the probe never first-dispatches a
    program warmup didn't register (which would break the
    ``recompiles_during_swap == 0`` pin).  Returns (ok, reason)."""
    short, long_ = engine._scale
    B = engine.opts.batch_size
    if getattr(engine.opts, "serve_e2e", False):
        from mx_rcnn_tpu.data.image import stage_raw_to_bucket

        cfg = engine.cfg
        staged, raw_hw, ratio, info = stage_raw_to_bucket(
            golden_image(short, long_), engine._scale,
            max(cfg.network.IMAGE_STRIDE, cfg.network.RPN_FEAT_STRIDE))
        dets, _ = predictor.predict_serve_e2e(
            np.stack([staged] * B), np.stack([raw_hw] * B),
            np.asarray([ratio] * B, np.float32),
            np.stack([info] * B).astype(np.float32),
            np.zeros(B, bool),
            int(cfg.TEST.MAX_PER_IMAGE), float(cfg.TEST.THRESH))
        if not np.isfinite(np.asarray(dets)).all():
            return False, "non-finite detections on golden image"
        return True, "ok"
    prepared, im_info = prepare_image(golden_image(short, long_),
                                      engine.cfg, engine._scale)
    images = np.stack([prepared] * B)
    infos = np.stack([im_info] * B)
    out = predictor.predict(images, infos)
    names = ("rois", "roi_valid", "cls_prob", "bbox_deltas")
    for name, arr in zip(names, out[:len(names)]):
        arr = np.asarray(arr)
        if (np.issubdtype(arr.dtype, np.floating)
                and not np.isfinite(arr).all()):
            return False, f"non-finite {name} on golden image"
    return True, "ok"


def reload_engine_params(engine, predictor, cfg, target: dict,
                         load_params_fn=None, faults=None) -> tuple:
    """The zero-downtime swap on one engine: drain → load → swap →
    canary → resume.  Returns ``(ok, info)``; on any failure the
    previous weights are restored verbatim (the exact pre-swap leaves,
    so rollback itself is also recompile-free) and the engine resumes
    serving them.  ``info["recompiles_during_swap"]`` pins the PR-7
    registry-reuse contract: 0 in steady state.

    A target carrying ``eval_shard`` (the fleet promotion gate, ISSUE
    17) additionally must BEAT the incumbent: the incumbent's mean
    detection agreement over the held-out shard is measured before the
    swap, the candidate's after, and a candidate scoring below
    ``incumbent - quality_slack`` is rolled back exactly like a canary
    failure — the PR-8 "finite outputs" canary extended to a measured
    quality delta.  An unreadable eval shard fails CLOSED (no swap at
    all).  The generation only advances on acceptance, so a rejected
    candidate can be retried by a later, better save.  The fabric
    unroutes a member for the whole reload, so gate probes are the only
    requests the candidate ever answers on a rejected promotion."""
    tel = telemetry.get()
    t0 = time.monotonic()
    gen = int(target.get("generation", engine.generation + 1))
    shard = quality_incumbent = None
    if target.get("eval_shard"):
        from mx_rcnn_tpu.flywheel.fleet import (eval_shard_quality,
                                                load_eval_shard)
        try:
            shard = load_eval_shard(target["eval_shard"])
        except (OSError, ValueError, KeyError) as e:
            tel.counter("flywheel/promotion_gate_reject")
            tel.dump_flight("promotion_rejected", generation=gen,
                            target=list(target_key(target)),
                            cause=f"eval shard unreadable: {e}",
                            trace_ids=target.get("trace_ids") or [])
            logger.error("promotion of %s REJECTED: eval shard "
                         "unreadable (%s) — gate fails closed",
                         target_key(target), e)
            return False, {"error": f"eval shard unreadable: {e}",
                           "rolled_back": False}
        quality_incumbent = eval_shard_quality(engine, shard)
    if not engine.drain(timeout=RELOAD_DRAIN_TIMEOUT_S):
        engine.resume()
        return False, {"error": "drain timed out — dispatcher wedged?",
                       "rolled_back": False}
    old = getattr(predictor, "params", None)
    recompiles_before = engine.counters["recompiles"]
    try:
        load = load_params_fn or load_serving_params
        params = load(target, cfg)
        if faults is not None and faults.corrupt_ckpt:
            logger.warning("FAULT: poisoning reloaded checkpoint %s with "
                           "NaN", target_key(target))
            params = poison_params(params)
        predictor.update_params(params)
        ok, reason = canary_probe(engine, predictor)
        if not ok:
            predictor.params = old  # rollback: pre-swap leaves, no cast
            tel.counter("serve/reload_rollback")
            tel.dump_flight("reload_canary_failed", generation=gen,
                            target=list(target_key(target)), cause=reason)
            logger.error("hot reload of %s REJECTED (%s) — rolled back "
                         "to generation %d", target_key(target), reason,
                         engine.generation)
            return False, {"error": f"canary failed: {reason}",
                           "rolled_back": True}
    except Exception as e:  # noqa: BLE001 — a bad save must not kill serving
        if old is not None:
            predictor.params = old
        tel.counter("serve/reload_rollback")
        logger.exception("hot reload of %s failed — rolled back",
                         target_key(target))
        return False, {"error": f"{type(e).__name__}: {e}",
                       "rolled_back": True}
    finally:
        engine.resume()
    quality_candidate = None
    if shard is not None:
        from mx_rcnn_tpu.flywheel.fleet import eval_shard_quality
        slack = float(target.get("quality_slack", 0.0))
        quality_candidate = eval_shard_quality(engine, shard)
        if quality_candidate + 1e-9 < quality_incumbent - slack:
            engine.drain(timeout=RELOAD_DRAIN_TIMEOUT_S)
            try:
                if old is not None:
                    predictor.params = old
            finally:
                engine.resume()
            tel.counter("serve/reload_rollback")
            tel.counter("flywheel/promotion_gate_reject")
            tel.dump_flight("promotion_rejected", generation=gen,
                            target=list(target_key(target)),
                            quality_candidate=round(quality_candidate, 4),
                            quality_incumbent=round(quality_incumbent, 4),
                            quality_slack=slack,
                            trace_ids=target.get("trace_ids") or [])
            logger.error("promotion of %s REJECTED by quality gate "
                         "(candidate %.4f < incumbent %.4f - slack %.4f)"
                         " — rolled back to generation %d",
                         target_key(target), quality_candidate,
                         quality_incumbent, slack, engine.generation)
            return False, {"error": "quality gate: candidate %.4f < "
                                    "incumbent %.4f - slack %.4f"
                                    % (quality_candidate,
                                       quality_incumbent, slack),
                           "rolled_back": True,
                           "quality_candidate": quality_candidate,
                           "quality_incumbent": quality_incumbent}
        tel.counter("flywheel/promotion_gate_pass")
    with engine._lock:
        engine.generation = max(engine.generation, gen)
    swap_recompiles = engine.counters["recompiles"] - recompiles_before
    tel.counter("serve/reload")
    tel.gauge("serve/generation", engine.generation)
    wall = time.monotonic() - t0
    logger.info("hot reload: generation %d live from %s in %.2fs "
                "(%d recompile(s) during swap)", engine.generation,
                target_key(target), wall, swap_recompiles)
    info = {"generation": engine.generation,
            "target": list(target_key(target)),
            "wall_s": round(wall, 3),
            "recompiles_during_swap": swap_recompiles}
    if shard is not None:
        info["quality_candidate"] = quality_candidate
        info["quality_incumbent"] = quality_incumbent
    return True, info


def make_reloader(engine, predictor, cfg, load_params_fn=None,
                  faults=None):
    """The frontend's ``POST /admin/reload`` callback: body is a reload
    target doc, 200 → new generation live, 409 → rejected + rolled
    back.  Serialized — concurrent reloads of one replica make no
    sense and would race the drain."""
    lock = threading.Lock()

    def reloader(doc: dict) -> tuple:
        required = {"prefix", "kind", "epoch", "consumed"}
        if not required.issubset(doc):
            return 400, {"error": f"reload target needs {sorted(required)}"}
        with lock:
            ok, info = reload_engine_params(
                engine, predictor, cfg, doc,
                load_params_fn=load_params_fn, faults=faults)
        return (200 if ok else 409), info

    return reloader


# -- the child main loop ---------------------------------------------------

def serve_replica(engine, cfg, sock_path: Optional[str] = None,
                  index: int = 0, predictor=None, load_params_fn=None,
                  done: Optional[threading.Event] = None,
                  port: Optional[int] = None, host: str = "127.0.0.1",
                  join: Optional[str] = None,
                  advertise: Optional[str] = None) -> None:
    """Run one replica to completion: HTTP server FIRST (liveness probes
    must answer while warmup compiles), then warmup → ready, then park
    until ``done`` (set by the driver's signal handler) — finally stop
    the server and fail whatever is still queued.  The engine must be
    ``start()``ed; ``predictor`` defaults to ``engine.predictor``.

    Transport is ``sock_path`` (a fork child behind the PR-8 supervisor)
    OR ``port``/``host`` (a fabric member on TCP).  ``join`` registers
    the member with a fabric router at that address once warm,
    advertising ``advertise`` (default ``host:port``)."""
    predictor = predictor if predictor is not None else engine.predictor
    # subprocess members inherit tracing opt-in via MXR_TRACE_DIR — a
    # no-op when the env is absent or the parent already configured one
    tracectx.configure_from_env(member=f"member{index}", rank=index)
    faults = ReplicaFaults(index)
    net = NetFaults(index)
    reloader = make_reloader(engine, predictor, cfg,
                             load_params_fn=load_params_fn, faults=faults)
    server = make_server(engine, unix_socket=sock_path, port=port,
                         host=host, reloader=reloader,
                         request_hook=faults.request_hook,
                         gate=faults.gate,
                         net_faults=net if net.enabled else None)
    th = threading.Thread(target=server.serve_forever,
                          name=f"replica-{index}-http", daemon=True)
    th.start()
    where = sock_path if sock_path is not None else f"{host}:{port}"
    logger.info("replica %d: live on %s (warming)", index, where)
    faults.slow_start()
    warmup(engine)  # sets engine readiness → /readyz flips to 200
    logger.info("replica %d: ready (generation %d)", index,
                engine.generation)
    join_stop = None
    if join:
        from mx_rcnn_tpu.serve.fabric import register_with_router
        join_stop = register_with_router(
            join, advertise or f"{host}:{port}")
    done = done or threading.Event()
    done.wait()
    if join_stop is not None:
        join_stop.set()
    server.shutdown()
    engine.stop()
