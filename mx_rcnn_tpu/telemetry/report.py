"""Fold telemetry JSONL event streams into the aggregated summary, the
human table, and ``BENCH_*.json``-compatible metric rows.

Library half of ``scripts/telemetry_report.py`` (importable so tests and
other tools fold without a subprocess).  Input is any mix of event files
and run directories; a directory expands to every ``events_rank*.jsonl``
inside it, so the multi-host case (one file per rank, mirroring the
``profile_dir`` rank-split) folds into ONE cross-rank aggregate — span
totals/counters sum over ranks, gauge extrema span all ranks.
"""

from __future__ import annotations

import glob
import json
import os
from typing import Iterable, List

from mx_rcnn_tpu.telemetry.sink import (SCHEMA_VERSION, Hist,
                                        quantile_from_counts)

# the fault-tolerance subsystem's recovery events (train/resilience.py):
# rendered as their own table section — zeros included — so "did the run
# recover from anything?" is answerable at a glance (and greppable by
# script/fault_smoke.sh) without knowing which counters might exist
RECOVERY_COUNTERS = (
    "loader/bad_record",
    "loader/worker_respawn",
    "train/nan_detected",
    "train/nan_skipped",
    "train/nan_rollback",
    "train/preempted",
    "checkpoint/retry",
)

# the serving subsystem's health counters (serve/engine.py): rendered as
# their own section — zeros included — whenever the stream carries any
# serve/* event, so "did the endpoint shed load, blow deadlines, or
# recompile after warmup?" reads off one block (script/serve_smoke.sh
# greps it the way fault_smoke.sh greps the recovery section)
SERVE_COUNTERS = (
    "serve/requests",
    "serve/images",
    "serve/batches",
    "serve/rejected",
    "serve/shed",
    "serve/deadline_exceeded",
    "serve/recompile",
    "serve/warmup_programs",
)

# the cross-host fabric's membership/routing health (serve/fabric.py):
# rendered as their own section — zeros included — whenever the stream
# carries any fabric/* event, so "did the pool evict anyone, trip a
# breaker, hedge, or declare a partition?" is one greppable block
# (script/fabric_smoke.sh reads it the way replica_smoke reads the
# supervisor counters)
FABRIC_COUNTERS = (
    "fabric/requests",
    "fabric/member_joined",
    "fabric/member_evicted",
    "fabric/member_quarantined",
    "fabric/breaker_open",
    "fabric/hedge_fired",
    "fabric/hedge_won",
    "fabric/retry",
    "fabric/retry_ok",
    "fabric/partition",
    "fabric/reload",
    "fabric/reload_rollback",
)

# the data flywheel's loop progress (flywheel/capture.py + miner.py +
# the loader's replay mixing): rendered as their own section — zeros
# included — whenever the stream carries any flywheel/* event, so "did
# traffic actually capture, mine, and replay into training?" is one
# greppable block (script/flywheel_smoke.sh reads it)
FLYWHEEL_COUNTERS = (
    "flywheel/captured",
    "flywheel/spilled_bytes",
    "flywheel/shards",
    "flywheel/spill_error",
    "flywheel/mined",
    "flywheel/skipped_unlabeled",
    "flywheel/skipped_bad_row",
    "flywheel/replayed",
    "flywheel/train_failed",
    # fleet mode (flywheel/fleet.py): merge/mine fault tolerance and the
    # gated-promotion loop — "did the fleet converge to a promoted
    # generation, and what did chaos cost?" in the same block
    "flywheel/shard_missing",
    "flywheel/manifest_dup_dropped",
    "flywheel/mine_member_failed",
    "flywheel/eval_skipped",
    "flywheel/promotion_gate_pass",
    "flywheel/promotion_gate_reject",
    "flywheel/promoted",
    "flywheel/rejected",
    "flywheel/drift_detected",
)

# the multi-model pool's paging + cross-model scheduling health
# (serve/pool.py): rendered as their own section — zeros included —
# whenever the stream carries any of these, so "did weights page under
# the budget, and did the scheduler actually interleave tenants?" is
# one greppable block (script/multimodel_smoke.sh reads it); the
# per-model variants (serve/weight_page_in/<model>, ...) render inside
# the same section
POOL_COUNTERS = (
    "serve/weight_page_in",
    "serve/weight_page_out",
    "serve/sched_batches",
    "serve/sched_switches",
)

# streaming serving's temporal-reuse progress (serve/stream.py + the
# engine's stream-aware flush bookkeeping): rendered as their own
# section — zeros included — whenever the stream carries any stream/*
# event, so "did frames actually skip, and did streams share batches?"
# is one greppable block (script/stream_smoke.sh reads it)
STREAM_COUNTERS = (
    "stream/frames",
    "stream/forwarded",
    "stream/skipped",
    "stream/delta_dispatches",
    "stream/refreshes",
    "stream/bucket_switches",
    "stream/stale_seq",
    "stream/evicted",
    "stream/batches",
    "stream/batch_frames",
    "stream/coalesced_batches",
)

# distributed request tracing (telemetry/tracectx.py): rendered as its
# own section — zeros included — whenever the stream carries any
# trace/* counter, so "did spans actually emit, and were the slow trees
# tail-kept?" is one greppable block (script/trace_smoke.sh reads it)
TRACE_COUNTERS = (
    "trace/spans_emitted",
    "trace/spans_dropped",
    "trace/tail_kept",
)

# cascade serving's routing decisions (serve/pool.py CascadeRouter):
# rendered as their own section — zeros included — whenever the stream
# carries any cascade/* event, so "did the gate actually run, and what
# fraction of traffic escalated?" is one greppable block
# (script/cascade_smoke.sh reads it)
CASCADE_COUNTERS = (
    "cascade/answered_small",
    "cascade/escalated",
    "cascade/forced_big",
    "cascade/gate_batches",
    "cascade/escalation_rejected",
)


def event_files(paths: Iterable[str]) -> List[str]:
    """Expand run dirs to their per-rank event files; pass files through.

    Distributed-trace span streams (``spans_<member>.jsonl``,
    telemetry/tracectx.py) fold alongside the per-rank files: same JSONL
    schema, ``kind: "span"`` records whose additive trace fields old
    readers ignore — so ``--trace`` output gains per-member hop tracks
    and the span table counts cross-hop work with zero extra plumbing.
    Watchtower transition logs (``alerts_<member>.jsonl``,
    telemetry/watch.py) fold the same way: ``kind: "alert"`` records
    that old readers ignore, new ones render as the alerts table."""
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            found = sorted(glob.glob(os.path.join(p, "events_rank*.jsonl")))
            found += sorted(glob.glob(os.path.join(p, "spans_*.jsonl")))
            found += sorted(glob.glob(os.path.join(p, "alerts_*.jsonl")))
            if not found:
                raise FileNotFoundError(
                    f"no events_rank*.jsonl, spans_*.jsonl, or "
                    f"alerts_*.jsonl under {p} — was the run started "
                    f"with --telemetry-dir?")
            out.extend(found)
        else:
            out.append(p)
    return out


def load_events(paths: Iterable[str]) -> List[dict]:
    events = []
    for path in event_files(paths):
        with open(path) as f:
            for ln, line in enumerate(f, 1):
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError as e:
                    raise ValueError(f"{path}:{ln}: not a JSON object "
                                     f"({e})") from None
                events.append(rec)
    return events


def aggregate(events: Iterable[dict]) -> dict:
    """Events → the ``Telemetry.summary()`` shape, cross-rank.

    The fold is the same math the live sink keeps in memory, so a
    single-rank run folds to byte-identical span/counter/gauge blocks —
    the round-trip the schema test pins.
    """
    spans: dict = {}
    counters: dict = {}
    gauges: dict = {}
    hists: dict = {}
    alerts: dict = {}
    ranks = set()
    meta: dict = {}
    pipeline: list = []
    eval_pipeline: list = []
    programs: list = []
    for e in events:
        kind = e.get("kind")
        name = e.get("name")
        ranks.add(e.get("rank", 0))
        if kind == "span":
            d = float(e["dur_s"])
            n = int(e.get("n", 1))
            s = spans.get(name)
            if s is None:
                spans[name] = [n, d, d, d]
            else:
                s[0] += n
                s[1] += d
                s[2] = min(s[2], d)
                s[3] = max(s[3], d)
        elif kind == "counter":
            counters[name] = counters.get(name, 0) + int(e["inc"])
        elif kind == "gauge":
            v = float(e["value"])
            g = gauges.get(name)
            if g is None:
                gauges[name] = [1, v, v, v, v]
            else:
                g[0] += 1
                g[1] += v
                g[2] = min(g[2], v)
                g[3] = max(g[3], v)
                g[4] = v
        elif kind == "hist":
            h = hists.get(name)
            if h is None:
                h = hists[name] = Hist()
            h.observe(float(e["value"]))
        elif kind == "alert":
            # watchtower lifecycle transitions (telemetry/watch.py
            # alerts_<member>.jsonl): per-alertname tallies + the total
            # time spent firing, cross-member — "what paged, how often,
            # for how long" off one fold
            aname = str(e.get("alert", "?"))
            a = alerts.get(aname)
            if a is None:
                a = alerts[aname] = {
                    "severity": str(e.get("severity", "warning")),
                    "pending": 0, "firing": 0, "resolved": 0,
                    "silenced": 0, "firing_s": 0.0, "members": set()}
            state = str(e.get("state", "?"))
            if state in ("pending", "firing", "resolved"):
                a[state] += 1
            if e.get("silenced"):
                a["silenced"] += 1
            fs = e.get("firing_s")
            if isinstance(fs, (int, float)):
                a["firing_s"] += float(fs)
            if e.get("member") is not None:
                a["members"].add(str(e["member"]))
        elif kind == "meta":
            if name == "run" and not meta:
                meta = dict(e.get("fields", {}))
            elif name == "pipeline_cell":
                # one row per tuning-sweep cell (train/pipeline.py —
                # also the shape bench.py --mode pipeline writes to its
                # --sweep-out JSONL, so that artifact folds here too)
                pipeline.append(dict(e.get("fields", {})))
            elif name == "eval_pipeline":
                # one row per pred_eval run (eval/pipeline.py overlap
                # breakdown: device-busy vs host post-process vs idle)
                eval_pipeline.append(dict(e.get("fields", {})))
            elif name == "compile/program":
                # one row per first-dispatched program (compile/
                # registry.py note_dispatch): kind/shape/dtype/aot — the
                # registry table below distinguishes fused serve_e2e
                # programs from legacy predict/device_prep ones
                programs.append(dict(e.get("fields", {})))
    out_extra = {"pipeline": pipeline} if pipeline else {}
    if eval_pipeline:
        out_extra["eval_pipeline"] = eval_pipeline
    if programs:
        out_extra["programs"] = programs
    if alerts:
        # additive key: a stream with no alert records folds to the
        # exact pre-watchtower summary shape
        out_extra["alerts"] = {
            k: {**{f: v for f, v in a.items() if f != "members"},
                "members": sorted(a["members"])}
            for k, a in sorted(alerts.items())}
    return {
        "schema": SCHEMA_VERSION,
        "ranks": sorted(ranks),
        "meta": meta,
        **out_extra,
        "spans": {k: {"count": c, "total_s": t, "mean_s": t / max(c, 1),
                      "min_s": lo, "max_s": hi}
                  for k, (c, t, lo, hi) in sorted(spans.items())},
        "counters": dict(sorted(counters.items())),
        "gauges": {k: {"count": c, "mean": t / max(c, 1), "min": lo,
                       "max": hi, "last": last}
                   for k, (c, t, lo, hi, last) in sorted(gauges.items())},
        "hists": {k: h.to_dict() for k, h in sorted(hists.items())},
    }


def render_table(summary: dict) -> str:
    """The human view: spans ranked by total time, then counters/gauges."""
    lines = []
    ranks = summary.get("ranks")
    if ranks:
        lines.append(f"ranks: {','.join(str(r) for r in ranks)}")
    spans = summary.get("spans", {})
    if spans:
        lines.append(f"{'span':<34}{'count':>8}{'total_s':>10}"
                     f"{'mean_ms':>10}{'max_ms':>10}")
        for name, s in sorted(spans.items(),
                              key=lambda kv: -kv[1]["total_s"]):
            lines.append(f"{name:<34}{s['count']:>8}{s['total_s']:>10.3f}"
                         f"{s['mean_s'] * 1e3:>10.3f}"
                         f"{s['max_s'] * 1e3:>10.3f}")
    counters = summary.get("counters", {})
    serving = any(k.startswith("serve/") for k in counters) or any(
        k.startswith("serve/") for k in summary.get("spans", {}))
    fabric = any(k.startswith("fabric/") for k in counters) or any(
        k.startswith("fabric/") for k in summary.get("gauges", {}))
    flywheel = any(k.startswith("flywheel/") for k in counters) or any(
        k.startswith("flywheel/") for k in summary.get("gauges", {}))
    streaming = any(k.startswith("stream/") for k in counters) or any(
        k.startswith("stream/") for k in summary.get("gauges", {}))
    pool = any(k in POOL_COUNTERS or k.startswith("serve/weight_page")
               or k.startswith("serve/sched_") for k in counters)
    tracing = any(k.startswith("trace/") for k in counters)
    cascading = any(k.startswith("cascade/") for k in counters) or any(
        k.startswith("cascade/") for k in summary.get("gauges", {}))
    pool_extra = sorted(
        n for n in counters if n not in POOL_COUNTERS
        and (n.startswith("serve/weight_page_in/")
             or n.startswith("serve/weight_page_out/")))
    if counters:
        lines.append("")
        lines.append(f"{'counter':<34}{'total':>8}")
        # dtype-labeled recompile counters (serve/recompile/bfloat16, ...)
        # and the program registry's AOT split render inside the serve
        # health block, not the general section
        serve_extra = sorted(
            n for n in counters
            if n.startswith("serve/recompile/") or n.startswith("compile/"))
        for name, v in counters.items():
            if name in RECOVERY_COUNTERS:
                continue  # recovery events get their own section below
            if serving and (name in SERVE_COUNTERS or name in serve_extra):
                continue  # ditto serve health
            if fabric and name in FABRIC_COUNTERS:
                continue  # ditto fabric health
            if flywheel and name in FLYWHEEL_COUNTERS:
                continue  # ditto the flywheel table
            if streaming and name in STREAM_COUNTERS:
                continue  # ditto the streaming table
            if pool and (name in POOL_COUNTERS or name in pool_extra):
                continue  # ditto the model-pool table
            if tracing and name in TRACE_COUNTERS:
                continue  # ditto the tracing table
            if cascading and name in CASCADE_COUNTERS:
                continue  # ditto the cascade table
            lines.append(f"{name:<34}{v:>8}")
        lines.append("")
        lines.append(f"{'recovery event':<34}{'total':>8}")
        for name in RECOVERY_COUNTERS:
            lines.append(f"{name:<34}{counters.get(name, 0):>8}")
        if serving:
            lines.append("")
            lines.append(f"{'serve health':<34}{'total':>8}")
            for name in SERVE_COUNTERS:
                lines.append(f"{name:<34}{counters.get(name, 0):>8}")
            for name in serve_extra:  # per-dtype recompiles + AOT split
                lines.append(f"{name:<34}{counters.get(name, 0):>8}")
        if fabric:
            lines.append("")
            lines.append(f"{'fabric health':<34}{'total':>8}")
            for name in FABRIC_COUNTERS:
                lines.append(f"{name:<34}{counters.get(name, 0):>8}")
        if flywheel:
            lines.append("")
            lines.append(f"{'flywheel':<34}{'total':>8}")
            for name in FLYWHEEL_COUNTERS:
                lines.append(f"{name:<34}{counters.get(name, 0):>8}")
        if streaming:
            lines.append("")
            lines.append(f"{'streaming':<34}{'total':>8}")
            for name in STREAM_COUNTERS:
                lines.append(f"{name:<34}{counters.get(name, 0):>8}")
        if pool:
            lines.append("")
            lines.append(f"{'model pool':<34}{'total':>8}")
            for name in POOL_COUNTERS:
                lines.append(f"{name:<34}{counters.get(name, 0):>8}")
            for name in pool_extra:  # per-model paging counters
                lines.append(f"{name:<34}{counters.get(name, 0):>8}")
        if tracing:
            lines.append("")
            lines.append(f"{'tracing':<34}{'total':>8}")
            for name in TRACE_COUNTERS:
                lines.append(f"{name:<34}{counters.get(name, 0):>8}")
        if cascading:
            lines.append("")
            lines.append(f"{'cascade':<34}{'total':>8}")
            for name in CASCADE_COUNTERS:
                lines.append(f"{name:<34}{counters.get(name, 0):>8}")
    gauges = summary.get("gauges", {})
    if gauges:
        lines.append("")
        lines.append(f"{'gauge':<34}{'count':>8}{'mean':>10}{'min':>10}"
                     f"{'max':>10}{'last':>10}")
        for name, g in gauges.items():
            lines.append(f"{name:<34}{g['count']:>8}{g['mean']:>10.3f}"
                         f"{g['min']:>10.3f}{g['max']:>10.3f}"
                         f"{g['last']:>10.3f}")
    pipeline = summary.get("pipeline", [])
    if pipeline:
        # tuning-sweep cells, fastest first (bench.py --mode pipeline /
        # train/pipeline.py): the full wait breakdown per cell, so "which
        # knob moved the needle and where did the time go" is one block
        lines.append("")
        lines.append(f"{'pipeline cell':<18}{'imgs/s':>10}{'loader_s':>10}"
                     f"{'assembly_s':>11}{'dispatch_s':>11}{'wait%':>8}")
        for row in sorted(pipeline,
                          key=lambda r: -(r.get("imgs_per_sec") or 0.0)):
            lines.append(
                f"{row.get('cell', '?'):<18}"
                f"{row.get('imgs_per_sec') or 0.0:>10.3f}"
                f"{row.get('loader_wait_s') or 0.0:>10.3f}"
                f"{row.get('assembly_wait_s') or 0.0:>11.3f}"
                f"{row.get('dispatch_s') or 0.0:>11.3f}"
                f"{100 * (row.get('loader_wait_frac') or 0.0):>7.1f}%")
    eval_pipeline = summary.get("eval_pipeline", [])
    if eval_pipeline:
        # one row per pred_eval run: how much host post-process time hid
        # under the device forward (overlap%), and where the main thread
        # actually waited (loader / readback / host tail)
        lines.append("")
        lines.append(f"{'eval pipeline':<20}{'imgs/s':>10}{'wall_s':>9}"
                     f"{'loader_s':>10}{'readbk_s':>10}{'post_s':>9}"
                     f"{'overlap%':>9}")
        for row in sorted(eval_pipeline,
                          key=lambda r: -(r.get("imgs_per_sec") or 0.0)):
            lines.append(
                f"{row.get('mode', '?'):<20}"
                f"{row.get('imgs_per_sec') or 0.0:>10.3f}"
                f"{row.get('wall_s') or 0.0:>9.2f}"
                f"{row.get('loader_wait_s') or 0.0:>10.3f}"
                f"{row.get('readback_wait_s') or 0.0:>10.3f}"
                f"{row.get('host_post_s') or 0.0:>9.3f}"
                f"{100 * (row.get('overlap_frac') or 0.0):>8.1f}%")
    programs = summary.get("programs", [])
    if programs:
        # the program registry table, grouped by (kind, dtype): how many
        # distinct executables each program family first-dispatched and
        # how many of them warm-started from the AOT cache — serve_e2e
        # (fused) vs predict/predict_wf (legacy) vs device_prep read off
        # separate rows
        groups: dict = {}
        for row in programs:
            key = (str(row.get("kind", "?")), str(row.get("dtype", "?")))
            g = groups.setdefault(key, [0, 0])
            g[0] += 1
            if row.get("aot") == "hit":
                g[1] += 1
        lines.append("")
        lines.append(f"{'program kind':<24}{'dtype':<16}{'programs':>9}"
                     f"{'aot_hit':>9}")
        for (kind, dtype), (n, hits) in sorted(groups.items()):
            lines.append(f"{kind:<24}{dtype:<16}{n:>9}{hits:>9}")
    hists = summary.get("hists", {})
    if hists:
        lines.append("")
        lines.append(f"{'latency':<34}{'count':>8}{'mean_ms':>10}"
                     f"{'p50_ms':>10}{'p99_ms':>10}")
        for name, h in hists.items():
            n = h.get("count", 0)
            le, buckets = h.get("le", []), h.get("buckets", [])
            p50 = quantile_from_counts(le, buckets, n, 0.50)
            p99 = quantile_from_counts(le, buckets, n, 0.99)
            mean = h.get("sum", 0.0) / max(n, 1)
            lines.append(f"{name:<34}{n:>8}{mean * 1e3:>10.3f}"
                         f"{(p50 or 0.0) * 1e3:>10.3f}"
                         f"{(p99 or 0.0) * 1e3:>10.3f}")
    alerts = summary.get("alerts", {})
    if alerts:
        # the watchtower's lifecycle, folded: how often each alert went
        # pending/firing/resolved and the total firing time — zero-firing
        # rows still render so "nothing fired" is a visible fact
        lines.append("")
        lines.append(f"{'alert':<28}{'severity':<10}{'pending':>8}"
                     f"{'firing':>8}{'resolved':>9}{'silenced':>9}"
                     f"{'firing_s':>10}")
        for name, a in sorted(alerts.items()):
            lines.append(f"{name:<28}{a.get('severity', '?'):<10}"
                         f"{a.get('pending', 0):>8}"
                         f"{a.get('firing', 0):>8}"
                         f"{a.get('resolved', 0):>9}"
                         f"{a.get('silenced', 0):>9}"
                         f"{a.get('firing_s', 0.0):>10.2f}")
    return "\n".join(lines)


def bench_rows(summary: dict) -> List[dict]:
    """Rate gauges → ``BENCH_*.json``-compatible metric rows (the
    ``{"metric", "value", "unit"}`` shape bench.py prints), so a telemetry
    run can feed the bench ledger without a separate measurement pass.
    A rate gauge is one whose name contains ``imgs_per_sec`` (the
    Speedometer feed, pred_eval's rate, and bench's own result gauge,
    whose suffixed names carry batch/network tags)."""
    rows = []
    for name, g in summary.get("gauges", {}).items():
        if "imgs_per_sec" in name:
            rows.append({"metric": name.replace("/", "_"),
                         "value": round(g["mean"], 3),
                         "unit": "imgs/sec",
                         "samples": g["count"]})
    return rows
