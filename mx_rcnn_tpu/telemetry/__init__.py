"""Telemetry layer — structured run-time instrumentation for the whole
stack (SURVEY §5's "free win" the MXNet reference never had).

Dependency-free (stdlib only — no jax import, so the data layer's
producer threads and host-only tools can emit without touching the
backend).  One module-global active sink, because the instrumented code
is cross-cutting: the trainer, the loader's prefetch thread, the
Speedometer and the eval loop all record into whatever run is active
without threading a handle through every constructor.

    from mx_rcnn_tpu import telemetry

    telemetry.configure(out_dir, rank=jax.process_index(),
                        world=jax.process_count())
    with telemetry.get().span("train/dispatch"):
        ...
    telemetry.get().counter("train/recompile")
    telemetry.shutdown()   # close the event file, restore the no-op sink

Unconfigured, ``get()`` returns the shared :data:`NULL` no-op sink —
instrumented hot paths pay one attribute check and zero allocations.
Drivers expose this as ``--telemetry-dir`` (per-rank event files on
multi-host, summary JSON from process 0 only — the ``profile_dir``
rank-split contract); ``scripts/telemetry_report.py`` folds the files
back into the human table and BENCH-compatible rows.
"""

from __future__ import annotations

from typing import Optional

from mx_rcnn_tpu.telemetry.sink import (HIST_LE, NULL, RING_SIZE,
                                        SCHEMA_VERSION, SUMMARY_NAME, Hist,
                                        NullTelemetry, Telemetry,
                                        quantile_from_counts)

__all__ = ["Telemetry", "NullTelemetry", "NULL", "RING_SIZE",
           "SCHEMA_VERSION", "SUMMARY_NAME", "Hist", "HIST_LE",
           "quantile_from_counts", "configure", "get", "reset_null",
           "shutdown"]

_active: "NullTelemetry | Telemetry" = NULL


def configure(out_dir: str, rank: int = 0, world: int = 1,
              run_meta: Optional[dict] = None, stream: bool = True,
              trace: Optional[bool] = None) -> Telemetry:
    """Open a run's sink and make it the active one.  Reconfiguring over a
    live sink closes it first (one active run per process — matching the
    one-event-file-per-rank layout).  ``stream=False`` keeps the sink
    purely in-memory (aggregates + flight ring, no event file) — the obs
    server uses it when ``--obs-port`` is set without ``--telemetry-dir``.
    ``trace`` opts span records into wall-start timestamps (default: the
    ``MXR_TELEMETRY_TRACE`` env var)."""
    global _active
    if _active.enabled:
        _active.close()
    _active = Telemetry(out_dir, rank=rank, world=world, run_meta=run_meta,
                        stream=stream, trace=trace)
    return _active


def get() -> "NullTelemetry | Telemetry":
    """The active sink (the no-op :data:`NULL` when none is configured)."""
    return _active


def reset_null():
    """Drop the active sink WITHOUT closing it — for forked children
    (loader workers) that inherit the parent's open event stream.  The
    child must stop emitting (its writes would interleave with the
    parent's JSONL through the shared fd) but must not flush/close a file
    the parent still owns."""
    global _active
    _active = NULL


def shutdown():
    """Close the active sink and restore the no-op default."""
    global _active
    _active.close()
    _active = NULL
