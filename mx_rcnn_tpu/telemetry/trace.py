"""Fold telemetry JSONL events into Chrome/Perfetto ``trace_event`` JSON.

``scripts/telemetry_report.py --trace out.json`` turns a run's event
stream into a browsable timeline: open the file in Perfetto
(https://ui.perfetto.dev) or ``chrome://tracing``.  Layout:

* one **process** per rank (``pid`` = rank — the multi-host event files
  fold into side-by-side process groups);
* one **track** (``tid``) per subsystem per rank — ``train``, ``loader``,
  ``eval``, ``serve`` — plus one per loader worker
  (``loader/worker{N}/...`` span names), so the host pipeline's per-worker
  produce spans sit on their own rows under the rank;
* spans → complete events (``ph: "X"``).  The start is the recorded
  wall-clock span start (``ts``, present when the sink ran in trace
  mode) or derived as ``t - dur_s`` (``t`` is stamped at span END).
  Within one track, containment nests exactly as Perfetto expects
  (``train/epoch`` wraps the epoch's ``train/dispatch`` spans);
* counters/gauges → counter events (``ph: "C"``): counters plot their
  cumulative total, gauges the sampled value;
* meta events (``flight_trigger``, ``nan_detected``, ``recompile`` ...)
  → instant events (``ph: "i"``) so the crash markers are visible on the
  timeline;
* **distributed-trace spans** (``tracectx`` records carrying a
  ``trace`` id) → the same complete events, but grouped under one
  process per fabric MEMBER (``spans_<member>.jsonl`` files fold
  side-by-side) with the span attrs in ``args`` — one track per hop
  (``fabric``/``frontend``/``engine``/``pool``/``stream`` prefixes) —
  plus flow arrows (``ph: "s"``/``"t"``) linking every span of one trace
  id across members, so a request's path through the fabric reads as a
  connected chain on the timeline.

Timestamps are microseconds relative to the earliest event in the fold
(absolute unix µs blows up the Perfetto axis).  Stdlib only — no jax.
"""

from __future__ import annotations

import json
import re
from typing import Iterable, List, Optional

_WORKER_RE = re.compile(r"^loader/(worker\d+)/")


def _track(name: str) -> str:
    m = _WORKER_RE.match(name)
    if m:
        return m.group(1)
    return name.split("/", 1)[0] if "/" in name else "main"


def _span_start(e: dict) -> Optional[float]:
    ts = e.get("ts")
    if ts is not None:
        return float(ts)
    t = e.get("t")
    if t is None:
        return None
    return float(t) - float(e.get("dur_s", 0.0))


def trace_events(events: Iterable[dict]) -> List[dict]:
    """Telemetry event dicts → ``trace_event`` list (see module doc)."""
    events = [e for e in events if isinstance(e, dict) and "kind" in e]
    starts = []
    for e in events:
        if e["kind"] == "span":
            s = _span_start(e)
            if s is not None:
                starts.append(s)
        elif e.get("t") is not None:
            starts.append(float(e["t"]))
    t0 = min(starts) if starts else 0.0

    out: List[dict] = []
    tids: dict = {}       # (pid, track_name) -> tid
    cum: dict = {}        # (pid, counter_name) -> running total
    member_pids: dict = {}  # member label -> synthetic pid
    flows: dict = {}      # trace id -> [span X event] (flow-arrow links)

    def tid_for(pid: int, track: str) -> int:
        key = (pid, track)
        if key not in tids:
            tids[key] = len([1 for (p, _) in tids if p == pid]) + 1
        return tids[key]

    def pid_for_member(member: str) -> int:
        # trace spans group per fabric member, not per rank — members
        # from different hosts land in side-by-side process groups with
        # no pid collision against the rank pids (offset well clear)
        if member not in member_pids:
            member_pids[member] = 1000 + len(member_pids)
        return member_pids[member]

    for e in sorted(events, key=lambda e: e.get("t", 0.0)):
        pid = int(e.get("rank", 0))
        kind = e["kind"]
        name = e.get("name", "?")
        if kind == "span":
            start = _span_start(e)
            if start is None:
                continue
            trace_id = e.get("trace")
            if trace_id is not None and e.get("member") is not None:
                pid = pid_for_member(str(e["member"]))
            ev = {"name": name, "ph": "X", "pid": pid,
                  "tid": tid_for(pid, _track(name)),
                  "ts": round((start - t0) * 1e6, 3),
                  "dur": round(float(e.get("dur_s", 0.0)) * 1e6, 3)}
            n = e.get("n", 1)
            if n != 1:  # one record standing for n dispatches
                ev["args"] = {"n": n}
            if trace_id is not None:
                args = {"trace": trace_id, "sid": e.get("sid")}
                if e.get("psid") is not None:
                    args["psid"] = e["psid"]
                args.update(e.get("attrs") or {})
                ev["args"] = args
                flows.setdefault(str(trace_id), []).append(ev)
            out.append(ev)
        elif kind == "counter":
            ckey = (pid, name)
            cum[ckey] = cum.get(ckey, 0) + e.get("inc", 1)
            out.append({"name": name, "ph": "C", "pid": pid,
                        "ts": round((float(e["t"]) - t0) * 1e6, 3),
                        "args": {"total": cum[ckey]}})
        elif kind == "gauge":
            out.append({"name": name, "ph": "C", "pid": pid,
                        "ts": round((float(e["t"]) - t0) * 1e6, 3),
                        "args": {"value": e.get("value", 0.0)}})
        elif kind == "meta":
            out.append({"name": name, "ph": "i", "s": "p", "pid": pid,
                        "tid": tid_for(pid, "main"),
                        "ts": round((float(e["t"]) - t0) * 1e6, 3),
                        "args": dict(e.get("fields") or {})})

    # flow arrows: one chain per trace id, start (ph "s") at the
    # earliest span, steps (ph "t") through every later one — binding
    # ts sits just inside each slice so Perfetto attaches the arrow to
    # the span, not the track
    for trace_id, evs in sorted(flows.items()):
        if len(evs) < 2:
            continue
        try:
            flow_id = int(trace_id[:15], 16)
        except ValueError:
            flow_id = abs(hash(trace_id)) % (1 << 60)
        evs.sort(key=lambda ev: ev["ts"])
        for i, ev in enumerate(evs):
            out.append({"name": "trace", "cat": "trace",
                        "ph": "s" if i == 0 else "t", "id": flow_id,
                        "pid": ev["pid"], "tid": ev["tid"],
                        "ts": round(ev["ts"]
                                    + min(ev["dur"] / 2, 0.5), 3)})
    rank_pids = sorted({p for (p, _) in tids
                        if p not in member_pids.values()}
                       | {int(e.get("rank", 0)) for e in events})
    for pid in rank_pids:
        out.append({"name": "process_name", "ph": "M", "pid": pid,
                    "args": {"name": f"rank {pid}"}})
    for member, pid in sorted(member_pids.items()):
        out.append({"name": "process_name", "ph": "M", "pid": pid,
                    "args": {"name": f"member {member}"}})
    for (pid, track), tid in sorted(tids.items()):
        out.append({"name": "thread_name", "ph": "M", "pid": pid,
                    "tid": tid, "args": {"name": track}})
    return out


def chrome_trace(events: Iterable[dict]) -> dict:
    """The full JSON-object trace format Perfetto/chrome accept."""
    return {"traceEvents": trace_events(events), "displayTimeUnit": "ms"}


def write_chrome_trace(events: Iterable[dict], path: str) -> int:
    """Write the trace to ``path``; returns the event count."""
    doc = chrome_trace(events)
    with open(path, "w") as f:
        json.dump(doc, f)
    return len(doc["traceEvents"])
