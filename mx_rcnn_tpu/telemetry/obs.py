"""Live observability plane: Prometheus endpoint, cross-rank snapshot
fold, and crash-time flight-recorder wiring.

PR 1's telemetry is post-hoc — events become readable after the run
closes its JSONL stream.  This module makes a live run observable:

* :func:`prometheus_text` — render per-rank :meth:`Telemetry.summary`
  dicts into the Prometheus text exposition format.  One renderer serves
  both the obs server below and serve's ``/metrics`` content negotiation
  (``frontend.py``), so there is exactly one metrics registry: the
  telemetry sink's aggregates.
* :class:`ObsServer` — a stdlib ``ThreadingHTTPServer`` on a daemon
  thread serving ``GET /metrics`` and ``GET /healthz``.  Bound only when
  a driver passes ``--obs-port`` (default off: zero network binds).
* Cross-rank fold: every rank runs a :class:`SnapshotWriter` dropping
  ``snapshot_rank{N}.json`` under the telemetry dir every couple of
  seconds (atomic tmp+rename, same contract as ``write_summary``); the
  rank-0 server folds peer snapshots into its own live summary, labeled
  ``rank="N"``, so one scrape sees the whole job.  No new transport —
  the shared filesystem the per-rank event files already require.
* :class:`ObsPlane` — the driver-facing lifecycle bundle: configures a
  sink when needed (in-memory when ``--obs-port`` is set without
  ``--telemetry-dir``), starts the writer + (rank 0) server, installs a
  ``sys.excepthook`` that flight-dumps on unhandled exceptions, and
  tears everything down (writing the rank-0 summary when it owns the
  sink) on ``close``.

Stdlib only — no jax import; safe in the loader's producer threads and
on hosts with no accelerator.
"""

from __future__ import annotations

import glob
import json
import os
import re
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from mx_rcnn_tpu import telemetry
from mx_rcnn_tpu.logger import logger
from mx_rcnn_tpu.telemetry import tracectx

SNAPSHOT_INTERVAL_S = 2.0
PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

# ServeEngine.counters key → telemetry counter name (the engine mirrors
# these into the sink when one is active; when none is, the frontend's
# Prometheus path rebuilds them from the engine so both configurations
# expose the same families)
ENGINE_COUNTER_NAMES = {
    "requests": "serve/requests",
    "served": "serve/images",
    "batches": "serve/batches",
    "rejected": "serve/rejected",
    "shed": "serve/shed",
    "deadline_exceeded": "serve/deadline_exceeded",
    "recompiles": "serve/recompile",
    "warmup_programs": "serve/warmup_programs",
}


def _prom_name(name: str) -> str:
    return re.sub(r"[^a-zA-Z0-9_:]", "_", name)


_HELP_OVERRIDES = {
    "mxr_up": "1 for every rank folded into this exposition.",
    "mxr_snapshot_age_seconds":
        "Seconds since a peer rank's snapshot file was written.",
}


def _help(fam: str, kind: str) -> str:
    """One ``# HELP`` text per family — real scrapers warn on HELP-less
    families, so every ``mxr_*`` family carries one (generic but
    truthful: the name already says what is measured)."""
    if fam in _HELP_OVERRIDES:
        return _HELP_OVERRIDES[fam]
    if kind == "counter":
        if fam.endswith("_seconds_total"):
            return "Total seconds spent, summed over calls."
        if fam.endswith("_calls_total"):
            return "Total completed calls."
        return "Monotone event count since process start."
    if kind == "histogram":
        return "Distribution in seconds (log-spaced buckets)."
    if fam.endswith("_seconds_max"):
        return "Longest single call observed, in seconds."
    return "Gauge sampled per rank (stat=last/min/max/mean)."


def prometheus_text(per_rank: dict, ages: Optional[dict] = None) -> str:
    """Render ``{rank: summary_dict}`` (the :meth:`Telemetry.summary`
    shape) as Prometheus text exposition.  Families:

    * counter ``name`` → ``mxr_<name>_total{rank="N"}``
    * span ``name`` → ``mxr_<name>_seconds_total`` +
      ``mxr_<name>_calls_total`` (counters) and
      ``mxr_<name>_seconds_max`` (gauge)
    * gauge ``name`` → ``mxr_<name>{rank="N",stat="last|min|max|mean"}``
      — the queue-depth extremes, not just the final sample
    * hist ``name`` → a native ``mxr_<name>_seconds`` histogram family:
      cumulative ``_bucket{le="..."}`` lines ending ``le="+Inf"``, plus
      ``_sum`` and ``_count`` — the shape ``histogram_quantile()`` eats
    * ``mxr_up{rank="N"} 1`` for every rank present, plus
      ``mxr_snapshot_age_seconds`` for ranks folded from snapshot files
      (``ages``: rank → seconds since the snapshot was written).
    """
    counters: dict = {}  # family -> [(rank, value)]
    gauges: dict = {}    # family -> [(rank, labels, value)]
    hists: dict = {}     # family -> [(rank, hist_dict)]
    for rank in sorted(per_rank):
        s = per_rank[rank] or {}
        gauges.setdefault("mxr_up", []).append((rank, "", 1))
        for name, h in (s.get("hists") or {}).items():
            fam = f"mxr_{_prom_name(name)}_seconds"
            hists.setdefault(fam, []).append((rank, h))
        for name, total in (s.get("counters") or {}).items():
            fam = f"mxr_{_prom_name(name)}_total"
            counters.setdefault(fam, []).append((rank, total))
        for name, sp in (s.get("spans") or {}).items():
            base = f"mxr_{_prom_name(name)}"
            counters.setdefault(f"{base}_seconds_total", []).append(
                (rank, sp.get("total_s", 0.0)))
            counters.setdefault(f"{base}_calls_total", []).append(
                (rank, sp.get("count", 0)))
            gauges.setdefault(f"{base}_seconds_max", []).append(
                (rank, "", sp.get("max_s", 0.0)))
        for name, g in (s.get("gauges") or {}).items():
            fam = f"mxr_{_prom_name(name)}"
            for stat in ("last", "min", "max", "mean"):
                gauges.setdefault(fam, []).append(
                    (rank, f',stat="{stat}"', g.get(stat, 0.0)))
    for rank, age in sorted((ages or {}).items()):
        gauges.setdefault("mxr_snapshot_age_seconds", []).append(
            (rank, "", age))

    def fmt(v):
        return repr(round(float(v), 9)) if isinstance(v, float) else str(v)

    lines = []
    for fam in sorted(counters):
        lines.append(f"# HELP {fam} {_help(fam, 'counter')}")
        lines.append(f"# TYPE {fam} counter")
        for rank, v in counters[fam]:
            lines.append(f'{fam}{{rank="{rank}"}} {fmt(v)}')
    for fam in sorted(gauges):
        lines.append(f"# HELP {fam} {_help(fam, 'gauge')}")
        lines.append(f"# TYPE {fam} gauge")
        for rank, labels, v in gauges[fam]:
            lines.append(f'{fam}{{rank="{rank}"{labels}}} {fmt(v)}')
    for fam in sorted(hists):
        lines.append(f"# HELP {fam} {_help(fam, 'histogram')}")
        lines.append(f"# TYPE {fam} histogram")
        for rank, h in hists[fam]:
            cum = 0
            for le, c in zip(h.get("le", []), h.get("buckets", [])):
                cum += int(c)
                lines.append(
                    f'{fam}_bucket{{rank="{rank}",le="{fmt(float(le))}"}}'
                    f' {cum}')
            lines.append(f'{fam}_bucket{{rank="{rank}",le="+Inf"}}'
                         f' {int(h.get("count", 0))}')
            lines.append(f'{fam}_sum{{rank="{rank}"}}'
                         f' {fmt(float(h.get("sum", 0.0)))}')
            lines.append(f'{fam}_count{{rank="{rank}"}}'
                         f' {int(h.get("count", 0))}')
    return "\n".join(lines) + "\n"


# -- cross-rank snapshots ------------------------------------------------


def snapshot_path(out_dir: str, rank: int) -> str:
    return os.path.join(out_dir, f"snapshot_rank{rank}.json")


def write_snapshot(tel=None) -> Optional[str]:
    """Atomically publish the active sink's summary for the rank-0 obs
    server to fold (peers have no HTTP server — files are the bus)."""
    tel = tel if tel is not None else telemetry.get()
    if not tel.enabled or not tel.out_dir:
        return None
    path = snapshot_path(tel.out_dir, tel.rank)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump({"t": time.time(), "summary": tel.summary()}, f)
    os.replace(tmp, path)
    return path


def read_peer_snapshots(out_dir: str, skip_rank: Optional[int] = None):
    """``(per_rank_summaries, ages)`` from ``snapshot_rank*.json`` files.
    A half-written or vanished file is skipped — the writer is atomic, so
    this only covers peers dying mid-publish."""
    per_rank: dict = {}
    ages: dict = {}
    for path in sorted(glob.glob(os.path.join(out_dir,
                                              "snapshot_rank*.json"))):
        m = re.search(r"snapshot_rank(\d+)\.json$", path)
        if not m:
            continue
        rank = int(m.group(1))
        if rank == skip_rank:
            continue
        try:
            with open(path) as f:
                doc = json.load(f)
            per_rank[rank] = doc.get("summary") or {}
            ages[rank] = max(time.time() - float(doc.get("t", 0.0)), 0.0)
        except (OSError, ValueError):
            continue
    return per_rank, ages


class SnapshotWriter(threading.Thread):
    """Daemon publishing the active sink's summary every ``interval_s``.
    ``stop()`` writes one final snapshot so even a run shorter than the
    interval leaves its rank visible to the fold."""

    def __init__(self, interval_s: float = SNAPSHOT_INTERVAL_S):
        super().__init__(name="telemetry-snapshot", daemon=True)
        self._interval = interval_s
        self._stop = threading.Event()

    def run(self):
        while not self._stop.wait(self._interval):
            try:
                write_snapshot()
            except OSError as e:  # full/unmounted disk must not kill a run
                logger.warning("telemetry snapshot write failed: %s", e)

    def stop(self):
        self._stop.set()
        try:
            write_snapshot()
        except OSError:
            pass


# -- the HTTP endpoint ---------------------------------------------------


class _ObsHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    obs: "ObsServer" = None  # set by ObsServer subclassing

    def log_message(self, fmt, *args):
        logger.debug("obs http: " + fmt, *args)

    def _reply(self, status: int, body: str, ctype: str):
        data = body.encode()
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):
        path = self.path.split("?", 1)[0]
        if path == "/healthz":
            tel = telemetry.get()
            self._reply(200, json.dumps(
                {"status": "ok", "rank": tel.rank,
                 "telemetry": bool(tel.enabled)}), "application/json")
        elif path == "/metrics":
            self._reply(200, self.obs.render_metrics(), PROM_CONTENT_TYPE)
        else:
            self._reply(404, json.dumps({"error": f"no route {path}"}),
                        "application/json")


class ObsServer:
    """The rank-0 metrics endpoint: own live summary + peer snapshots.
    ``port=0`` binds an ephemeral port (tests); read it back from
    ``self.port``."""

    def __init__(self, port: int, host: str = "127.0.0.1",
                 telemetry_dir: str = ""):
        self.telemetry_dir = telemetry_dir

        class Handler(_ObsHandler):
            pass

        Handler.obs = self
        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="obs-http", daemon=True)
        self._thread.start()

    def render_metrics(self) -> str:
        tel = telemetry.get()
        own_rank = tel.rank if tel.enabled else None
        per_rank: dict = {}
        ages: dict = {}
        if self.telemetry_dir:
            per_rank, ages = read_peer_snapshots(self.telemetry_dir,
                                                 skip_rank=own_rank)
        if tel.enabled:
            per_rank[tel.rank] = tel.summary()
        return prometheus_text(per_rank, ages)

    def close(self):
        self._httpd.shutdown()
        self._httpd.server_close()


# -- driver lifecycle ----------------------------------------------------


class ObsPlane:
    """Everything a driver needs for the live plane, in one handle.

    * inert (no sink, no threads, no binds) unless ``--obs-port`` is set
      or the driver asked it to own plain ``--telemetry-dir``
      configuration (``configure_telemetry=True`` — test/serve/bench,
      whose sinks aren't owned by ``fit``);
    * with a port: configures a sink when none is active (in-memory when
      there is no telemetry dir), starts the snapshot writer (dir set),
      binds the HTTP server on rank 0 only, and installs an excepthook
      that flight-dumps before the traceback prints;
    * ``close(extra=...)`` reverses all of it, writing the rank-0
      ``summary.json`` when the plane owns the sink and a dir is set
      (the same contract ``fit`` honors when IT owns the sink).
    """

    def __init__(self, port: int = 0, host: str = "127.0.0.1",
                 telemetry_dir: str = "", rank: int = 0, world: int = 1,
                 run_meta: Optional[dict] = None,
                 configure_telemetry: bool = False,
                 snapshot_interval_s: float = SNAPSHOT_INTERVAL_S):
        self.active = bool(port)
        self.rank = int(rank)
        self.telemetry_dir = telemetry_dir
        self.owns_sink = False
        self.server: Optional[ObsServer] = None
        self.writer: Optional[SnapshotWriter] = None
        self._prev_hook = None
        self._installed_hook = None
        need_sink = self.active or (configure_telemetry and telemetry_dir)
        if need_sink and not telemetry.get().enabled:
            telemetry.configure(telemetry_dir, rank=rank, world=world,
                                run_meta=run_meta,
                                stream=bool(telemetry_dir))
            self.owns_sink = True
        if not self.active:
            return
        if telemetry_dir:
            self.writer = SnapshotWriter(snapshot_interval_s)
            self.writer.start()
        elif world > 1 and rank == 0:
            logger.warning("--obs-port without --telemetry-dir: no "
                           "snapshot files, the scrape only sees rank 0")
        if rank == 0:
            self.server = ObsServer(port, host=host,
                                    telemetry_dir=telemetry_dir)
            logger.info("obs server on http://%s:%d (/metrics, /healthz)",
                        self.server.host, self.server.port)
        self._prev_hook = sys.excepthook
        # bind once: each `self._excepthook` access makes a fresh bound
        # method, and close() must compare by identity to restore safely
        self._installed_hook = self._excepthook
        sys.excepthook = self._installed_hook

    def _excepthook(self, exc_type, exc, tb):
        try:
            telemetry.get().dump_flight(
                "unhandled_exception", type=exc_type.__name__,
                message=str(exc)[:500])
        except Exception:  # noqa: BLE001 — never mask the real traceback
            pass
        (self._prev_hook or sys.__excepthook__)(exc_type, exc, tb)

    def close(self, extra: Optional[dict] = None):
        if self._prev_hook is not None:
            if sys.excepthook is self._installed_hook:
                sys.excepthook = self._prev_hook
            self._prev_hook = None
        if self.writer is not None:
            self.writer.stop()
            self.writer = None
        if self.server is not None:
            self.server.close()
            self.server = None
        if self.owns_sink:
            self.owns_sink = False
            tel = telemetry.get()
            if tel.enabled and self.rank == 0 and self.telemetry_dir:
                path = tel.write_summary(extra=extra)
                logger.info("wrote telemetry summary to %s", path)
            telemetry.shutdown()


# -- serve frontend bridge -----------------------------------------------


def engine_summary(engine) -> dict:
    """A summary-shaped dict for a :class:`ServeEngine`: the active
    sink's aggregates (when one is on) with the engine's own counters and
    live queue depth folded over them — the engine is authoritative for
    ``serve/*`` (its counters exist even with telemetry off)."""
    tel = telemetry.get()
    base = tel.summary() if tel.enabled else {}
    m = engine.metrics()
    counters = dict(base.get("counters") or {})
    for key, name in ENGINE_COUNTER_NAMES.items():
        if key in m.get("counters", {}):
            counters[name] = m["counters"][key]
    gauges = dict(base.get("gauges") or {})
    # flywheel capture state (engine.metrics() carries it only when a
    # RequestCapture is attached): counters as flywheel/*, plus the
    # serving generation — both needed by the smoke script's "did the
    # loop advance?" probe on the Prometheus path
    fly = m.get("flywheel") or {}
    for key, v in fly.items():
        if key == "sample_every":
            gauges["flywheel/sample_every"] = {
                "count": 1, "mean": v, "min": v, "max": v, "last": v}
        else:
            counters[f"flywheel/{key}"] = v
    # streaming state (engine.metrics() carries it only when a
    # StreamManager is attached): skip/forward/coalescing counters as
    # stream/*, table size and batch occupancy as gauges — same
    # one-metrics-path contract as the flywheel fold above
    st = m.get("stream") or {}
    for key, v in (st.get("counters") or {}).items():
        counters[f"stream/{key}"] = v
    for key in ("active_streams", "batch_occupancy", "skip_fraction"):
        v = st.get(key)
        if isinstance(v, (int, float)):
            gauges[f"stream/{key}"] = {
                "count": 1, "mean": v, "min": v, "max": v, "last": v}
    # distributed-tracing counters (tracectx, attached when tracing is
    # on): spans emitted/dropped and tail-kept trees as trace/* —
    # rendered as mxr_trace_* by the Prometheus exposition, same
    # one-metrics-path contract as the flywheel/stream folds above
    tracer = tracectx.get()
    if tracer.enabled:
        for key, v in tracer.metrics().items():
            if key in ("spans_emitted", "spans_dropped", "tail_kept"):
                counters[f"trace/{key}"] = v
            elif isinstance(v, (int, float)):
                gauges[f"trace/{key}"] = {
                    "count": 1, "mean": v, "min": v, "max": v, "last": v}
    gen = m.get("generation", 0)
    gauges.setdefault("serve/generation", {
        "count": 1, "mean": gen, "min": gen, "max": gen, "last": gen})
    depth = m.get("queue_depth", 0)
    live = gauges.get("serve/queue_depth", {})
    gauges["serve/queue_depth"] = {
        "count": live.get("count", 0) + 1,
        "mean": live.get("mean", depth),
        "min": min(live.get("min", depth), depth),
        "max": max(live.get("max", depth), depth),
        "last": depth,
    }
    # the engine is authoritative for its latency distributions too — its
    # Hists observe every request even with telemetry off
    hists = dict(base.get("hists") or {})
    for name, h in getattr(engine, "latency_hists", lambda: {})().items():
        hists[name] = h.to_dict() if hasattr(h, "to_dict") else dict(h)
    # live SLO-controller state (per-bucket flush batch / max delay and
    # the admission limit) as point-in-time gauges
    for name, v in (m.get("controller") or {}).get("gauges", {}).items():
        gauges[name] = {"count": 1, "mean": v, "min": v, "max": v,
                        "last": v}
    for key, pol in (m.get("policy") or {}).items():
        for stat, v in (("max_batch", pol.get("max_batch")),
                        ("max_delay_ms", pol.get("max_delay_ms"))):
            if v is None:
                continue
            name = f"slo/bucket_{key}/{stat}"
            gauges[name] = {"count": 1, "mean": v, "min": v, "max": v,
                            "last": v}
    return {"spans": base.get("spans") or {}, "counters": counters,
            "gauges": gauges, "hists": hists}


def serve_prometheus(engine, watch=None) -> str:
    """The frontend's ``/metrics?format=prom`` body — same renderer and
    registry as the obs server (one metrics path, not two).  ``watch``
    (a :class:`~mx_rcnn_tpu.telemetry.watch.Watchtower`, when alerting
    is on) appends the ``mxr_alert_state`` family; None appends nothing
    — byte parity with the watch-less exposition."""
    rank = telemetry.get().rank
    text = prometheus_text({rank: engine_summary(engine)})
    if watch is not None:
        from mx_rcnn_tpu.telemetry.watch import alert_state_lines
        text += "\n".join(alert_state_lines(watch)) + "\n"
    return text


def pool_summary(pool) -> dict:
    """Summary-shaped dict for the ModelPool's own state: paging and
    cross-model scheduling counters plus residency gauges — the block
    ``/metrics?format=prom`` renders under the synthetic ``pool`` rank
    alongside each model's per-rank engine summary."""
    res = pool.residency()
    counters = {"serve/weight_page_in": pool.counters["weight_page_in"],
                "serve/weight_page_out": pool.counters["weight_page_out"],
                "serve/sched_batches": pool.counters["sched_batches"],
                "serve/sched_switches": pool.counters["sched_switches"]}

    def point(v):
        return {"count": 1, "mean": v, "min": v, "max": v, "last": v}

    gauges = {"serve/weight_budget_bytes": point(res["budget_bytes"]),
              "serve/resident_bytes": point(res["device_bytes"]),
              "serve/resident_models": point(res["resident_models"])}
    for mid, doc in res["models"].items():
        gauges[f"serve/resident/{mid}"] = point(doc["resident"])
        gauges[f"serve/weight_bytes/{mid}"] = point(doc["bytes"])
        counters[f"serve/weight_page_in/{mid}"] = doc["page_ins"]
        counters[f"serve/weight_page_out/{mid}"] = doc["page_outs"]
    # cascade routing state (pool.metrics() carries it only when a
    # CascadeRouter is attached): decision counters as cascade/*, the
    # live escalation rate and gate-cost quantiles as gauges — the same
    # one-metrics-path contract as the flywheel/stream folds, and what
    # the smoke script's escalation_rate-in-(0,1) probe scrapes
    cas = pool.cascade.metrics() if pool.cascade is not None else None
    if cas:
        for key, v in (cas.get("counters") or {}).items():
            counters[f"cascade/{key}"] = v
        gauges["cascade/escalation_rate"] = point(cas["escalation_rate"])
        gauges["cascade/thresh"] = point(cas["thresh"])
        for key, v in (cas.get("latency") or {}).items():
            gauges[f"cascade/{key}"] = point(v)
    return {"spans": {}, "counters": counters, "gauges": gauges,
            "hists": {}}


def pool_prometheus(pool, watch=None) -> str:
    """Multi-model ``/metrics?format=prom``: one rank per MODEL ID (each
    model's engine summary renders under ``rank="<model>"``) plus the
    pool's paging/scheduling block under ``rank="pool"`` — per-model
    families without inventing a second label scheme.  ``watch``
    appends ``mxr_alert_state`` exactly as in :func:`serve_prometheus`."""
    per_rank = {mid: engine_summary(pool.engine_for(mid))
                for mid in pool.model_ids()}
    per_rank["pool"] = pool_summary(pool)
    text = prometheus_text(per_rank)
    if watch is not None:
        from mx_rcnn_tpu.telemetry.watch import alert_state_lines
        text += "\n".join(alert_state_lines(watch)) + "\n"
    return text
